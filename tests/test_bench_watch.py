"""Unit tests for the bench_watch capture state machine (tools/bench_watch
.CaptureWatcher) with a stubbed prober and fake capture commands.

The watcher is the round's only path to opportunistic TPU evidence, and
its window logic (relay windows last minutes and die mid-suite) is pure
state-machine: stage ordering, once-per-window banking, dark-window
resets. Those invariants are asserted here without touching sockets,
subprocesses, git, or the real bench.
"""

import json
import os
import sys

import pytest

sys.path.insert(
    0,
    os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                 "tools"),
)

import bench_watch  # noqa: E402
from bench_watch import CaptureWatcher  # noqa: E402


class FakeReport:
    def __init__(self, ok=True, backend="axon"):
        self.ok = ok
        self.backend = backend
        self.last_stage = "ready" if ok else "claim"
        self.error = "" if ok else "boom"


class Rig:
    """A watcher with everything stubbed: scripted scan results, a fake
    prober, and a capture log recording (kind, ok) in call order."""

    def __init__(self, tmp_path, capture_ok=None, probe_ok=True,
                 probe_backend="axon"):
        self.calls = []
        self.capture_ok = dict(capture_ok or {})
        self.ports = [8080]
        self.commit = "c0ffee1"
        self.clock_now = 1000.0
        proof = tmp_path / "pallas_proof.py"
        proof.write_text("# proof stub\n")
        self.watcher = CaptureWatcher(
            scan=lambda: list(self.ports),
            probe=lambda: FakeReport(ok=probe_ok, backend=probe_backend),
            capture=self._capture,
            head=lambda: self.commit,
            proof_path=str(proof),
            clock=lambda: self.clock_now,
            log=lambda event, **kw: None,
        )

    def _capture(self, kind, argv, timeout, extra_env=None):
        ok = self.capture_ok.get(kind, True)
        self.calls.append((kind, ok))
        return {"ok": ok, "kind": kind}

    def kinds(self):
        return [k for k, _ in self.calls]


def test_stage_order_fast_proof_full(tmp_path):
    rig = Rig(tmp_path)
    rig.watcher.cycle()
    assert rig.kinds() == ["bench-fast", "pallas_proof", "bench"]


def test_stages_bank_once_per_window(tmp_path):
    """A retrying full bench within one window must not re-spend window
    time on already-banked fast/proof stages."""
    rig = Rig(tmp_path, capture_ok={"bench": False})
    rig.watcher.cycle()
    assert rig.kinds() == ["bench-fast", "pallas_proof", "bench"]
    # Window still open (relay up, bench failed -> not closed): only the
    # full bench retries.
    rig.watcher.cycle()
    assert rig.kinds() == ["bench-fast", "pallas_proof", "bench", "bench"]
    # A successful full bench closes the window: cooldown + same commit
    # means the next cycle does nothing at all.
    rig.capture_ok["bench"] = True
    rig.watcher.cycle()
    assert rig.kinds()[-1] == "bench"
    n = len(rig.calls)
    rig.watcher.cycle()
    assert len(rig.calls) == n


def test_failed_fast_stage_does_not_block_proof(tmp_path):
    """The probe already proved a live device; a fast-stage timeout must
    not cost the window its only compiled-pallas evidence."""
    rig = Rig(tmp_path, capture_ok={"bench-fast": False, "bench": False})
    rig.watcher.cycle()
    assert rig.kinds() == ["bench-fast", "pallas_proof", "bench"]
    # ...and an unbanked fast stage retries next cycle (window still open:
    # the full bench failed) while the SUCCESSFUL proof stays banked.
    rig.watcher.cycle()
    assert rig.kinds()[3:] == ["bench-fast", "bench"]


def test_failed_proof_retries_within_window(tmp_path):
    rig = Rig(tmp_path, capture_ok={"pallas_proof": False, "bench": False})
    rig.watcher.cycle()
    rig.watcher.cycle()
    # fast banked once; proof retried (only success banks it).
    assert rig.kinds() == [
        "bench-fast", "pallas_proof", "bench", "pallas_proof", "bench",
    ]


def test_dark_window_resets_stage_markers(tmp_path):
    rig = Rig(tmp_path, capture_ok={"bench": False})
    rig.watcher.cycle()
    assert rig.watcher.window_fast_ok and rig.watcher.window_proof_done
    # Relay goes dark: markers reset, nothing captured.
    rig.ports = []
    n = len(rig.calls)
    rig.watcher.cycle()
    assert len(rig.calls) == n
    assert not rig.watcher.window_fast_ok
    assert not rig.watcher.window_proof_done
    # A new window re-banks a fresh fast number + proof.
    rig.ports = [8081]
    rig.watcher.cycle()
    assert rig.kinds()[n:] == ["bench-fast", "pallas_proof", "bench"]


def test_closed_window_reopens_on_new_commit_or_cooldown(tmp_path):
    rig = Rig(tmp_path)
    rig.watcher.cycle()
    n = len(rig.calls)
    rig.watcher.cycle()  # same commit, within cooldown: nothing
    assert len(rig.calls) == n
    rig.commit = "deadbee2"  # HEAD moved: recapture immediately
    rig.watcher.cycle()
    assert len(rig.calls) > n
    n = len(rig.calls)
    rig.clock_now += bench_watch.RECAPTURE_COOLDOWN_S + 1  # cooldown expiry
    rig.watcher.cycle()
    assert len(rig.calls) > n


def test_cpu_probe_or_failed_probe_never_captures(tmp_path):
    for kw in ({"probe_ok": False}, {"probe_backend": "cpu"}):
        rig = Rig(tmp_path, **kw)
        rig.watcher.cycle()
        assert rig.calls == []


def test_missing_proof_file_skips_proof_stage(tmp_path):
    rig = Rig(tmp_path)
    rig.watcher.proof_path = str(tmp_path / "no_such_proof.py")
    rig.watcher.cycle()
    assert rig.kinds() == ["bench-fast", "bench"]


@pytest.fixture(autouse=True)
def _no_repo_writes(monkeypatch, tmp_path):
    """Belt-and-braces: if a regression routes a stubbed watcher at the
    real log/capture helpers, write into tmp instead of the repo; the
    post-capture SLO gate scan must not read the real banked artifacts
    either."""
    monkeypatch.setattr(bench_watch, "WATCH_LOG",
                        str(tmp_path / "watch.jsonl"))
    monkeypatch.setattr(bench_watch, "CAPTURE_FILE",
                        str(tmp_path / "self.json"))
    monkeypatch.setattr(bench_watch, "_banked_simload_pairs", lambda: [])


# ---------------------------------------------------------------------------
# SLO regression gate (tools/bench_watch.slo_gate)
# ---------------------------------------------------------------------------

# The autouse fixture stubs _banked_simload_pairs (watcher tests must not
# read the real banked artifacts); the discovery test needs the original.
_REAL_BANKED_PAIRS = bench_watch._banked_simload_pairs


def _artifact(p50=20.0, p95=80.0, n=100, attribution=True):
    block = {"n": n, "p50_ms": p50, "p95_ms": p95, "p99_ms": p95 * 2,
             "max_ms": p95 * 3}
    if attribution:
        return {"latency_attribution": {"submit_to_placed_ms": block,
                                        "submit_to_running_ms": {"n": 0}}}
    # Pre-r08 shape: plan latency only (same event anchors).
    return {"plan_latency_ms": block}


def test_slo_gate_passes_inside_threshold():
    """Inside the objective, the gate never fails — even 2x slower than
    the baseline (latency headroom is the SLO's to spend)."""
    verdict = bench_watch.slo_gate(_artifact(p95=200.0),
                                   _artifact(p95=80.0))
    assert verdict["ok"] is True
    placed = next(c for c in verdict["checks"]
                  if c["objective"] == "submit_to_placed_p95_ms")
    assert placed["met"] is True and placed["regressed"] is False
    assert placed["baseline_ms"] == 80.0


def test_slo_gate_fails_newly_broken_objective():
    """An objective the baseline met that the new run misses is a
    regression, full stop."""
    verdict = bench_watch.slo_gate(_artifact(p95=300.0),
                                   _artifact(p95=200.0))
    assert verdict["ok"] is False
    placed = next(c for c in verdict["checks"]
                  if c["objective"] == "submit_to_placed_p95_ms")
    assert placed["regressed"] is True


def test_slo_gate_tolerance_when_both_outside():
    """Both runs outside the objective: only a >tolerance worsening
    fails (the gate hunts regressions, not pre-existing debt)."""
    base = _artifact(p95=400.0)
    within = bench_watch.slo_gate(_artifact(p95=450.0), base)
    assert within["ok"] is True  # 12.5% worse, inside the 25% tolerance
    beyond = bench_watch.slo_gate(_artifact(p95=600.0), base)
    assert beyond["ok"] is False  # 50% worse


def test_slo_gate_pre_r08_baseline_fallback():
    """A banked r07 artifact has no latency_attribution; its
    plan_latency_ms (the same submit→placed event anchors) still gates
    the placed objectives."""
    verdict = bench_watch.slo_gate(
        _artifact(p95=300.0), _artifact(p95=100.0, attribution=False))
    placed = next(c for c in verdict["checks"]
                  if c["objective"] == "submit_to_placed_p95_ms")
    assert placed["baseline_ms"] == 100.0
    assert placed["regressed"] is True
    # Unobservable objectives (no running samples either side) are
    # reported, never failed.
    running = next(c for c in verdict["checks"]
                   if c["objective"] == "submit_to_running_p95_ms")
    assert running["met"] is None and running["regressed"] is False


def test_slo_gate_scan_logs_per_family(tmp_path, monkeypatch):
    new = tmp_path / "SIMLOAD_x_s42_r08.json"
    old = tmp_path / "SIMLOAD_x_s42_r07.json"
    new.write_text(json.dumps(_artifact(p95=300.0)))
    old.write_text(json.dumps(_artifact(p95=100.0)))
    monkeypatch.setattr(
        bench_watch, "_banked_simload_pairs",
        lambda: [("x_s42", str(new), str(old))])
    logged = []

    def fake_log(event, **kw):
        logged.append({"event": event, **kw})

    assert bench_watch.slo_gate_scan(log=fake_log) is False
    assert logged == [{
        "event": "slo-gate", "family": "x_s42",
        "new": new.name, "baseline": old.name, "ok": False,
        "regressed": ["submit_to_placed_p95_ms"],
    }]


def test_banked_pair_discovery_orders_rounds(tmp_path, monkeypatch):
    for name in ("SIMLOAD_steady_s42.json", "SIMLOAD_steady_s42_r06.json",
                 "SIMLOAD_steady_s42_r08.json", "SIMLOAD_lone_s7.json",
                 "not_a_simload.json"):
        (tmp_path / name).write_text("{}")
    monkeypatch.setattr(bench_watch, "REPO", str(tmp_path))
    pairs = _REAL_BANKED_PAIRS()
    # Single-round families (a freshly banked scenario) pair with None:
    # the scan gates them absolutely instead of skipping them.
    assert pairs == [
        ("lone_s7", str(tmp_path / "SIMLOAD_lone_s7.json"), None),
        ("steady_s42",
         str(tmp_path / "SIMLOAD_steady_s42_r08.json"),
         str(tmp_path / "SIMLOAD_steady_s42_r06.json")),
    ]


def test_slo_gate_absolute_for_first_round_family():
    """A first-round family (no banked baseline — the overdrive-100k
    introduction case) gates absolutely: observed objectives must be met
    outright; unobserved ones are reported, not failed."""
    good = bench_watch.slo_gate_absolute(_artifact(p95=200.0))
    assert good["ok"] is True
    bad = bench_watch.slo_gate_absolute(_artifact(p95=300.0))
    assert bad["ok"] is False
    placed = next(c for c in bad["checks"]
                  if c["objective"] == "submit_to_placed_p95_ms")
    assert placed["regressed"] is True and placed["baseline_ms"] is None
    running = next(c for c in bad["checks"]
                   if c["objective"] == "submit_to_running_p95_ms")
    assert running["regressed"] is False  # unobserved (n=0)


def test_slo_gate_express_family_absolute(tmp_path, monkeypatch):
    """An artifact carrying express observations gates on the express
    objective too (absolute: express_placed_p50_ms < 1ms), while
    express-free families keep the default objective set."""
    express_good = _artifact(p95=100.0)
    express_good["latency_attribution"]["express_placed_ms"] = {
        "n": 300, "p50_ms": 0.7, "p95_ms": 0.9, "max_ms": 1.4}
    express_bad = _artifact(p95=100.0)
    express_bad["latency_attribution"]["express_placed_ms"] = {
        "n": 300, "p50_ms": 1.8, "p95_ms": 3.6, "max_ms": 80.0}

    assert bench_watch._objectives_for(_artifact()) is None
    objs = bench_watch._objectives_for(express_good)
    assert objs is not None and "express_placed_p50_ms" in objs

    good = bench_watch.slo_gate_absolute(
        express_good, bench_watch._objectives_for(express_good))
    assert good["ok"] is True
    bad = bench_watch.slo_gate_absolute(
        express_bad, bench_watch._objectives_for(express_bad))
    assert bad["ok"] is False
    check = next(c for c in bad["checks"]
                 if c["objective"] == "express_placed_p50_ms")
    assert check["observed_ms"] == 1.8 and check["regressed"] is True

    # Through the scan: the express family picks up its objective.
    lone = tmp_path / "SIMLOAD_express-mix_s42_r12.json"
    lone.write_text(json.dumps(express_bad))
    monkeypatch.setattr(
        bench_watch, "_banked_simload_pairs",
        lambda: [("express-mix_s42", str(lone), None)])
    logged = []
    assert bench_watch.slo_gate_scan(
        log=lambda event, **kw: logged.append(kw)) is False
    assert "express_placed_p50_ms" in logged[0]["regressed"]


def test_slo_gate_scan_absolute_arm(tmp_path, monkeypatch):
    lone = tmp_path / "SIMLOAD_over_s42_r09.json"
    lone.write_text(json.dumps(_artifact(p95=100.0)))
    monkeypatch.setattr(
        bench_watch, "_banked_simload_pairs",
        lambda: [("over_s42", str(lone), None)])
    logged = []
    assert bench_watch.slo_gate_scan(
        log=lambda event, **kw: logged.append({"event": event, **kw}))
    assert logged[0]["baseline"] == "<absolute>"
    assert logged[0]["ok"] is True


# ---------------------------------------------------------------------------
# scenario-scoped objectives + recovery gate
# ---------------------------------------------------------------------------


def test_objectives_for_scenario_scoped_family():
    """churn-fragmentation is judged against its declared scenario
    objective (the probe wave races a stop storm by design), not the
    250ms cell SLO — and the r13 bank honestly meets it."""
    from nomad_tpu.slo import SCENARIO_OBJECTIVES

    art = _artifact(p95=455.0)
    art["scenario"] = "churn-fragmentation"
    objectives = bench_watch._objectives_for(art)
    assert objectives == SCENARIO_OBJECTIVES["churn-fragmentation"]
    verdict = bench_watch.slo_gate_absolute(art, objectives)
    assert verdict["ok"] is True
    # The same artifact against the DEFAULT objectives fails — the
    # scenario scoping is load-bearing, not cosmetic.
    assert bench_watch.slo_gate_absolute(art, None)["ok"] is False


def _restart_artifact(survived=True, rate=60.0, tts=1000.0, p95=2000.0):
    art = _artifact(p95=p95)
    art["scenario"] = "restart-under-load"
    art["raft"] = {
        "enabled": True,
        "restart": {"placements_survived": survived,
                    "pre_kill_placements": 400,
                    "surviving_placements": 400 if survived else 399},
        "recovery": {"cold_start": True, "entries_replayed": 20,
                     "replay_entries_per_s": rate,
                     "time_to_serving_ms": tts},
    }
    return art


def test_recovery_gate_absolute_on_survival():
    """Digest/placement survival gates ABSOLUTELY, baseline or not."""
    good = bench_watch.recovery_gate(_restart_artifact(), None)
    assert good["ok"] is True
    bad = bench_watch.recovery_gate(_restart_artifact(survived=False),
                                    None)
    assert bad["ok"] is False
    assert [c["check"] for c in bad["checks"]
            if c["regressed"]] == ["placements_survived"]
    # Non-restart artifacts are not this gate's business.
    assert bench_watch.recovery_gate(_artifact(), None) is None


def test_recovery_gate_newest_vs_previous_tolerance():
    """Replay rate and time-to-serving gate newest-vs-previous at 50%
    tolerance: inside it passes, beyond it fails."""
    base = _restart_artifact(rate=60.0, tts=1000.0)
    within = bench_watch.recovery_gate(
        _restart_artifact(rate=40.0, tts=1400.0), base)
    assert within["ok"] is True
    slow_replay = bench_watch.recovery_gate(
        _restart_artifact(rate=20.0, tts=1000.0), base)
    assert slow_replay["ok"] is False
    assert [c["check"] for c in slow_replay["checks"] if c["regressed"]] \
        == ["replay_entries_per_s"]
    slow_serving = bench_watch.recovery_gate(
        _restart_artifact(rate=60.0, tts=2000.0), base)
    assert slow_serving["ok"] is False
    assert [c["check"] for c in slow_serving["checks"]
            if c["regressed"]] == ["time_to_serving_ms"]


def test_recovery_gate_rides_the_scan(tmp_path, monkeypatch):
    new = tmp_path / "SIMLOAD_restart-under-load_s42_r16.json"
    old = tmp_path / "SIMLOAD_restart-under-load_s42_r15.json"
    new.write_text(json.dumps(_restart_artifact(rate=20.0)))
    old.write_text(json.dumps(_restart_artifact(rate=60.0)))
    monkeypatch.setattr(
        bench_watch, "_banked_simload_pairs",
        lambda: [("restart-under-load_s42", str(new), str(old))])
    logged = []
    ok = bench_watch.slo_gate_scan(
        log=lambda event, **kw: logged.append({"event": event, **kw}))
    assert ok is False
    rec = next(r for r in logged if r["event"] == "recovery-gate")
    assert rec["ok"] is False
    assert rec["regressed"] == ["replay_entries_per_s"]


# ---------------------------------------------------------------------------
# Read gate (tools/bench_watch.read_gate)
# ---------------------------------------------------------------------------


def _reads_artifact(p95=10.0, staleness_p99=0.0, enabled=True):
    art = _artifact()
    art["scenario"] = "read-storm"
    art["reads"] = {
        "enabled": enabled,
        "endpoints": {
            "/v1/jobs": {"latency_ms": {"p95": p95}},
            "/v1/nodes": {"latency_ms": {"p95": p95 / 2}},
        },
        "freshness": {"staleness_entries": {"p99": staleness_p99}},
    }
    return art


def test_read_gate_scoped_to_read_carrying_families():
    """No reads section / reads disabled → not this gate's business;
    first-round read-carrying families report without failing (there is
    no declared absolute read-latency bound)."""
    assert bench_watch.read_gate(_artifact(), None) is None
    assert bench_watch.read_gate(_reads_artifact(enabled=False),
                                 None) is None
    first = bench_watch.read_gate(_reads_artifact(p95=40.0), None)
    assert first["ok"] is True
    lat = next(c for c in first["checks"]
               if c["check"] == "read_latency_p95_ms")
    assert lat["value"] == 40.0 and lat["baseline"] is None


def test_read_gate_newest_vs_previous_tolerance():
    """Worst-route p95 gates at 50% relative; the staleness p99 carries
    a 2-entry absolute slack on top (a healthy single-member cell sits
    at 0-1 entries, where a pure relative bar would fail on noise)."""
    base = _reads_artifact(p95=10.0, staleness_p99=0.0)
    within = bench_watch.read_gate(_reads_artifact(p95=14.0), base)
    assert within["ok"] is True
    slow = bench_watch.read_gate(_reads_artifact(p95=20.0), base)
    assert slow["ok"] is False
    assert [c["check"] for c in slow["checks"] if c["regressed"]] \
        == ["read_latency_p95_ms"]
    # Staleness: 0 → 2 rides the slack; 0 → 3 is a regression.
    noisy = bench_watch.read_gate(
        _reads_artifact(staleness_p99=2.0), base)
    assert noisy["ok"] is True
    stale = bench_watch.read_gate(
        _reads_artifact(staleness_p99=3.0), base)
    assert stale["ok"] is False
    assert [c["check"] for c in stale["checks"] if c["regressed"]] \
        == ["staleness_age_p99_entries"]
    # A reads-disabled baseline gives the new run a first-round pass,
    # not a divide-by-baseline surprise.
    off_base = _reads_artifact(enabled=False)
    assert bench_watch.read_gate(_reads_artifact(p95=99.0),
                                 off_base)["ok"] is True


def test_read_gate_rides_the_scan(tmp_path, monkeypatch):
    new = tmp_path / "SIMLOAD_read-storm_s42_r16.json"
    old = tmp_path / "SIMLOAD_read-storm_s42_r15.json"
    new.write_text(json.dumps(_reads_artifact(p95=30.0)))
    old.write_text(json.dumps(_reads_artifact(p95=10.0)))
    monkeypatch.setattr(
        bench_watch, "_banked_simload_pairs",
        lambda: [("read-storm_s42", str(new), str(old))])
    logged = []
    ok = bench_watch.slo_gate_scan(
        log=lambda event, **kw: logged.append({"event": event, **kw}))
    assert ok is False
    rec = next(r for r in logged if r["event"] == "read-gate")
    assert rec["ok"] is False
    assert rec["regressed"] == ["read_latency_p95_ms"]


def _lanes_artifact(share=1.0, age_p95=700.0, bound=5000.0,
                    violations=0, stamp_missing=0, members=3,
                    plan_p50=950.0, contrast_p50=820.0, enabled=True):
    """The r19+ read-storm shape: a lanes verdict section plus the
    leader-only contrast arm's plan books."""
    art = _artifact(attribution=False)
    art["scenario"] = "read-storm"
    art["plan_latency_ms"]["p50_ms"] = plan_p50
    art["reads"] = {"enabled": enabled, "lanes": {
        "enabled": enabled, "members": members,
        "follower_serve_share": share, "stale_bound_ms": bound,
        "stale_age_ms": {"n": 100, "p95": age_p95},
        "linear_violations": violations, "stamp_missing": stamp_missing,
    }}
    art["contrast"] = {"plan_latency_ms": {"p50_ms": contrast_p50},
                       "digest_matches": True,
                       "reads": {"enabled": False,
                                 "lanes": {"enabled": False}}}
    return art


def test_read_lane_gate_scoped_to_lane_carrying_artifacts():
    """No lanes section (pre-r19 banks) or lanes disabled (the contrast
    arm itself, single-member dev runs) → not this gate's business."""
    assert bench_watch.read_lane_gate(_artifact()) is None
    assert bench_watch.read_lane_gate(
        _lanes_artifact(enabled=False)) is None


def test_read_lane_gate_contract_rows():
    """The four absolute lane-contract rows plus the plan-p50 ceiling:
    a healthy r19-shaped artifact passes outright; each broken promise
    flips exactly its own row."""
    good = bench_watch.read_lane_gate(_lanes_artifact())
    assert good["ok"] is True
    assert [c["check"] for c in good["checks"]] == [
        "follower_serve_share", "stale_age_p95_bound_ratio",
        "linear_violations", "stamp_missing",
        "leader_plan_p50_vs_contrast_ms"]

    def regressed(art):
        v = bench_watch.read_lane_gate(art)
        return [c["check"] for c in v["checks"] if c["regressed"]]

    assert regressed(_lanes_artifact(share=0.5)) \
        == ["follower_serve_share"]
    assert regressed(_lanes_artifact(age_p95=6000.0)) \
        == ["stale_age_p95_bound_ratio"]
    assert regressed(_lanes_artifact(violations=1)) \
        == ["linear_violations"]
    assert regressed(_lanes_artifact(stamp_missing=3)) \
        == ["stamp_missing"]
    # A single-member cell cannot route around the leader: the share
    # row reports unjudged instead of failing a lane that cannot exist.
    solo = bench_watch.read_lane_gate(_lanes_artifact(members=1))
    share_row = next(c for c in solo["checks"]
                     if c["check"] == "follower_serve_share")
    assert share_row["regressed"] is False


def test_read_lane_gate_plan_ceiling_is_cliff_scaled():
    """The leader-relief row: plan p50 inside contrast*1.25 + 50ms
    passes (the tolerance prices the observatory-ON main arm, measured
    ~19% at r16/r19); a pile-up multiple fails it."""
    inside = bench_watch.read_lane_gate(
        _lanes_artifact(plan_p50=1000.0, contrast_p50=820.0))
    assert inside["ok"] is True
    cliff = bench_watch.read_lane_gate(
        _lanes_artifact(plan_p50=2500.0, contrast_p50=820.0))
    assert cliff["ok"] is False
    assert [c["check"] for c in cliff["checks"] if c["regressed"]] \
        == ["leader_plan_p50_vs_contrast_ms"]


def test_topology_change_rebanks_the_family(tmp_path, monkeypatch):
    """A round that changes the family's cell topology (read-storm went
    single-member -> 3-member when the follower read plane landed) is
    judged ABSOLUTELY against its declared objectives, never
    newest-vs-previous across different machinery — and the re-bank is
    logged, not silent."""
    new_art = _lanes_artifact()
    # Would regress 50%-relative vs the old bank, but meets the
    # scenario's declared 5s replicated-cell bound.
    new_art["plan_latency_ms"]["p95_ms"] = 3100.0
    old_art = _artifact(attribution=False)
    old_art["scenario"] = "read-storm"
    old_art["plan_latency_ms"]["p95_ms"] = 300.0
    new = tmp_path / "SIMLOAD_read-storm_s42_r19.json"
    old = tmp_path / "SIMLOAD_read-storm_s42_r16.json"
    new.write_text(json.dumps(new_art))
    old.write_text(json.dumps(old_art))
    monkeypatch.setattr(
        bench_watch, "_banked_simload_pairs",
        lambda: [("read-storm_s42", str(new), str(old))])
    logged = []
    ok = bench_watch.slo_gate_scan(
        log=lambda event, **kw: logged.append({"event": event, **kw}))
    assert ok is True
    rebank = next(r for r in logged if r["event"] == "slo-gate-rebank")
    assert rebank["new_members"] == 3
    assert rebank["baseline_members"] == 1
    gate = next(r for r in logged if r["event"] == "slo-gate")
    assert gate["baseline"] == "<absolute>"
    lane = next(r for r in logged if r["event"] == "read-lane-gate")
    assert lane["ok"] is True
