"""Differential fuzz: block-columnar state vs object state over job
lifecycles.

The same scheduler logic runs against two state representations of
identical clusters — one committing plans columnar (StoredAllocBlock, the
FSM posture) and one materializing everything to object rows (the
reference posture). After every lifecycle step the two worlds must agree
on placement totals, per-node distribution, per-node resource usage, and
job version — proving the block-native reconcile/update paths
(tpu/solver.py _block_reconcile, AllocUpdateBatch src_* columns) are
semantically invisible. Reference oracle: the five-way diff + inplace
update semantics (util.go:54-131, 265-302, 316-398)."""

import copy
import logging
import os

import numpy as np
import pytest

from nomad_tpu import mock, structs
from nomad_tpu.scheduler import new_scheduler
from nomad_tpu.server.plan_apply import evaluate_plan
from nomad_tpu.state import StateStore
from nomad_tpu.structs import (
    Evaluation,
    Resources,
    allocs_fit,
    generate_uuid,
)

N_SEEDS = int(os.environ.get("NOMAD_TPU_FUZZ_SEEDS", 8))
BATCH = 300


class _Committer:
    """Applies evaluated plans to state; columnar or materializing."""

    def __init__(self, state, columnar: bool):
        self.state = state
        self.columnar = columnar
        self._index = 10_000

    def submit_plan(self, plan):
        self._index += 1
        result = evaluate_plan(self.state.snapshot(), plan)
        result.alloc_index = self._index
        allocs = []
        for lst in result.node_update.values():
            allocs.extend(lst)
        for lst in result.node_allocation.values():
            allocs.extend(lst)
        allocs.extend(result.failed_allocs)
        if self.columnar:
            if allocs:
                self.state.upsert_allocs(self._index, allocs)
            if result.alloc_batches:
                self.state.upsert_alloc_blocks(
                    self._index, result.alloc_batches
                )
            if result.update_batches:
                self.state.apply_update_batches(
                    self._index, result.update_batches
                )
        else:
            for b in result.alloc_batches:
                allocs.extend(b.materialize())
            for b in result.update_batches:
                b.resolve(self.state.snapshot())
                allocs.extend(b.materialize())
            if allocs:
                self.state.upsert_allocs(self._index, allocs)
        return result, None

    def update_eval(self, ev):
        pass

    def create_eval(self, ev):
        pass


def _mk_world(n_nodes):
    state = StateStore()
    for i in range(n_nodes):
        node = mock.node()
        node.id = f"node-{i:03d}"
        state.upsert_node(i + 1, node)
    return state


def _process(state, planner, job):
    ev = Evaluation(
        id=generate_uuid(), priority=job.priority, type=job.type,
        triggered_by=structs.EVAL_TRIGGER_JOB_REGISTER, job_id=job.id,
    )
    sched = new_scheduler("tpu-batch", state.snapshot(), planner,
                         logging.getLogger("fuzz"))
    sched.process(ev)


def _world_view(state, job_id):
    """Comparable summary of a job's live allocations."""
    live = [a for a in state.allocs_by_job(job_id)
            if a.desired_status == structs.ALLOC_DESIRED_STATUS_RUN]
    per_node = {}
    usage = {}
    for a in live:
        per_node[a.node_id] = per_node.get(a.node_id, 0) + 1
        vec = np.asarray(a.resources.as_vector(), dtype=np.int64)
        usage[a.node_id] = usage.get(a.node_id, 0) + vec
    versions = {a.job.modify_index for a in live}
    return len(live), per_node, {k: tuple(int(x) for x in v)
                                 for k, v in usage.items()}, versions


@pytest.mark.parametrize("seed", range(N_SEEDS))
def test_block_vs_object_lifecycle(seed):
    rng = np.random.default_rng(31_000 + seed)
    n_nodes = int(rng.choice([6, 10, 16]))
    count = int(rng.choice([BATCH, BATCH + 50]))

    state_b = _mk_world(n_nodes)
    state_o = _mk_world(n_nodes)
    planner_b = _Committer(state_b, columnar=True)
    planner_o = _Committer(state_o, columnar=False)

    job = mock.job()
    job.type = structs.JOB_TYPE_BATCH
    tg = job.task_groups[0]
    tg.count = count
    tg.tasks[0].resources = Resources(
        cpu=int(rng.integers(20, 40)), memory_mb=int(rng.integers(32, 64))
    )
    tg.tasks[0].resources.networks = []

    idx = 5000
    state_b.upsert_job(idx, copy.deepcopy(job))
    state_o.upsert_job(idx, copy.deepcopy(job))
    _process(state_b, planner_b, job)
    _process(state_o, planner_o, job)

    steps = int(rng.integers(1, 4))
    for _ in range(steps):
        op = rng.choice(["grow", "shrink_res", "scale_up", "env"])
        job = copy.deepcopy(job)
        tg = job.task_groups[0]
        if op == "grow":
            tg.tasks[0].resources.memory_mb += int(rng.integers(1, 16))
        elif op == "shrink_res":
            tg.tasks[0].resources.cpu = max(
                1, tg.tasks[0].resources.cpu - int(rng.integers(1, 10))
            )
        elif op == "scale_up":
            tg.count += int(rng.integers(1, 40))
        else:  # destructive
            tg.tasks[0].env = {"V": str(int(rng.integers(0, 1000)))}
        idx += 1
        state_b.upsert_job(idx, copy.deepcopy(job))
        state_o.upsert_job(idx, copy.deepcopy(job))
        _process(state_b, planner_b, job)
        _process(state_o, planner_o, job)

        n_b, per_node_b, usage_b, ver_b = _world_view(state_b, job.id)
        n_o, per_node_o, usage_o, ver_o = _world_view(state_o, job.id)
        assert n_b == n_o, (seed, op, n_b, n_o)
        assert per_node_b == per_node_o, (seed, op)
        assert usage_b == usage_o, (seed, op)
        assert ver_b == ver_o, (seed, op, ver_b, ver_o)

        # Soundness in the columnar world: no node overcommitted.
        for node in state_b.nodes():
            live = [a for a in state_b.allocs_by_node(node.id)
                    if a.desired_status == structs.ALLOC_DESIRED_STATUS_RUN]
            fit, _dim, _u = allocs_fit(node, live)
            assert fit, (seed, op, node.id)

        # The O(1) live-object counter must equal a full scan at every
        # step (it gates the block-level reconcile).
        t = state_b._t
        scan = {}
        for a in t.allocs.values():
            if not a.terminal_status():
                scan[a.job_id] = scan.get(a.job_id, 0) + 1
        assert scan == t.live_objs_by_job, (seed, op)


@pytest.mark.parametrize("seed", range(4))
def test_no_lost_wakeup_under_concurrent_bulk_commits(seed):
    """Stress the watch fast path's ordering contract: watcher threads
    continuously run the register -> re-check -> wait loop (the
    blocking-query pattern) against random nodes while a writer commits
    columnar blocks. Every watcher must observe the final allocs index
    promptly — a lost wakeup (member items skipped for a waiter that
    registered mid-commit without post-write visibility) would strand a
    watcher until its deadline."""
    import threading
    import time as _time

    from nomad_tpu.state.store import item_alloc_node
    from nomad_tpu.structs import AllocBatch, Resources, generate_uuid

    def _mk_batch(job, node_ids, counts, eval_id):
        n = sum(counts)
        return AllocBatch(
            eval_id=eval_id, job=job, tg_name=job.task_groups[0].name,
            resources=Resources(cpu=1, memory_mb=1),
            node_ids=list(node_ids), node_counts=list(counts),
            name_idx=list(range(n)),
            ids_hex="".join(
                generate_uuid().replace("-", "") for _ in range(n)
            ),
        )

    rng = np.random.default_rng(90_000 + seed)
    store = StateStore()
    nodes = [mock.node() for _ in range(12)]
    for i, n in enumerate(nodes):
        store.upsert_node(i + 1, n)
    job = mock.job()
    store.upsert_job(100, job)

    N_COMMITS = 30
    final_index = 100 + N_COMMITS
    errors = []
    observed = []

    def watcher(widx):
        # Per-thread RNG: np.random.Generator is not thread-safe, and a
        # shared one would make seeded failures unreproducible.
        wrng = np.random.default_rng(90_000 + seed * 100 + widx)
        node = nodes[int(wrng.integers(0, len(nodes)))]
        deadline = _time.monotonic() + 30.0
        last = 0
        while _time.monotonic() < deadline:
            ticket = store.watch.register([item_alloc_node(node.id)])
            try:
                idx = store.snapshot().get_index("allocs")
                if idx >= final_index:
                    observed.append((widx, idx))
                    return
                if idx == last:
                    # Park with a SHORT timeout: a lost wakeup shows up
                    # as systematically timing out instead of waking.
                    store.watch.wait(ticket, timeout=0.5)
                last = idx
            finally:
                store.watch.unregister(ticket)
        errors.append(f"watcher {widx} never saw index {final_index}")

    threads = [threading.Thread(target=watcher, args=(i,)) for i in range(6)]
    for t in threads:
        t.start()
    for c in range(N_COMMITS):
        k = int(rng.integers(1, len(nodes) + 1))
        sel = rng.choice(len(nodes), size=k, replace=False)
        batch = _mk_batch(
            job, [nodes[i].id for i in sel], [1] * k,
            eval_id=f"gen-{seed}-{c}",
        )
        store.upsert_alloc_blocks(101 + c, [batch])
        _time.sleep(0.002)
    for t in threads:
        t.join(35.0)
    assert not errors, errors
    assert len(observed) == 6
