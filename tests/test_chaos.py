"""Chaos scenario compiler + crash-recovery hardening
(nomad_tpu/simcluster/chaos.py, the journal checksum/torn-tail path in
nomad_tpu/raft/node.py, faults.py flap windows, and the heartbeat
wheel's batched mass expiry)."""

import json
import os
import pickle
import time

import pytest

from nomad_tpu import faults, mock, slo, structs
from nomad_tpu.raft.node import RaftConfig, RaftNode
from nomad_tpu.raft_observe import fsm_state_digest
from nomad_tpu.rpc import ConnPool, RPCServer
from nomad_tpu.server import ServerConfig
from nomad_tpu.server.cluster import (
    ClusterServer,
    form_cluster,
    wait_for_leader,
)
from nomad_tpu.simcluster.chaos import (
    FAMILIES,
    ChaosSpec,
    ChaosSpecError,
    RackFillInjector,
)
from nomad_tpu.simcluster.scenario import SCENARIOS
from tests.cluster_util import relaxed_cluster_cfg, retry_write


def _wait(predicate, timeout=10.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {msg}")


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.get_registry().clear()
    yield
    faults.get_registry().clear()


# ---------------------------------------------------------------------------
# Journal torn-tail recovery (satellite: truncate-corrupt-tail restart)
# ---------------------------------------------------------------------------

class KVFSM:
    def __init__(self):
        self.data = {}

    def apply(self, index, msg_type, payload):
        self.data[payload["k"]] = payload["v"]

    def snapshot_bytes(self):
        return pickle.dumps(self.data)

    def restore_bytes(self, data):
        self.data = pickle.loads(data)


def _raft_node(tmp_path, node_id="a"):
    rpc = RPCServer()
    rpc.start()
    cfg = RaftConfig(
        node_id=node_id, peers={node_id: rpc.addr},
        data_dir=str(tmp_path), snapshot_threshold=10_000,
        bootstrap_expect=1,
    )
    fsm = KVFSM()
    return RaftNode(cfg, fsm, rpc, pool=ConnPool(timeout=2.0)), rpc, fsm


def _write_entries(tmp_path, n=12):
    node, rpc, fsm = _raft_node(tmp_path)
    node.start()
    try:
        _wait(lambda: node.is_leader, msg="leadership")
        for i in range(n):
            node.apply("kv", {"k": f"k{i}", "v": i}).result(5.0)
        applied = node.applied_index
    finally:
        node.shutdown()
        rpc.shutdown()
    return applied


def test_journal_torn_tail_truncated_and_replayed(tmp_path):
    """A crash mid-append leaves a half-written last line: restart must
    replay cleanly to the last whole checksummed entry, count the
    truncation (never crash), and rewrite the journal so the next
    restart is clean."""
    applied = _write_entries(tmp_path, n=12)
    log_path = os.path.join(str(tmp_path), "raft-log.jsonl")
    raw = open(log_path).read().rstrip("\n")
    lines = raw.split("\n")
    # 12 kv entries plus the leader's no-op (paper 5.4.2) on election.
    assert len(lines) == 13
    # Tear the tail: keep 12 whole lines, half of the 13th, no newline.
    torn = "\n".join(lines[:12]) + "\n" + lines[12][: len(lines[12]) // 2]
    with open(log_path, "w") as f:
        f.write(torn)

    node2, rpc2, fsm2 = _raft_node(tmp_path)
    try:
        assert node2.recovery["journal_truncated_tail"] == 1
        node2.start()
        _wait(lambda: node2.applied_index >= applied - 1, msg="replay")
        # The torn entry is gone; every whole entry replayed.
        assert fsm2.data == {f"k{i}": i for i in range(11)}
    finally:
        node2.shutdown()
        rpc2.shutdown()

    # The clean prefix was rewritten: a THIRD load sees no truncation.
    # (12 replayed entries plus the no-op node2 committed on winning
    # its own election.)
    node3, rpc3, fsm3 = _raft_node(tmp_path)
    try:
        assert node3.recovery["journal_truncated_tail"] == 0
        assert node3.recovery["log_entries_loaded"] == 13
    finally:
        node3.shutdown()
        rpc3.shutdown()


def test_journal_bitflip_truncates_from_corrupt_line(tmp_path):
    """A flipped byte inside an entry body fails the per-line crc32:
    replay stops at the last entry BEFORE the corruption, even though
    the line is whole and later lines parse."""
    _write_entries(tmp_path, n=10)
    log_path = os.path.join(str(tmp_path), "raft-log.jsonl")
    lines = open(log_path).read().rstrip("\n").split("\n")
    # Corrupt entry 7's body (a digit inside the JSON), keep the frame.
    body = lines[6]
    pos = len(body) - 2
    flipped = body[:pos] + ("0" if body[pos] != "0" else "1") + body[pos:][1:]
    lines[6] = flipped
    with open(log_path, "w") as f:
        f.write("\n".join(lines) + "\n")

    node2, rpc2, fsm2 = _raft_node(tmp_path)
    try:
        assert node2.recovery["journal_truncated_tail"] == 1
        assert node2.recovery["log_entries_loaded"] == 6
    finally:
        node2.shutdown()
        rpc2.shutdown()


def test_journal_accepts_legacy_unchecksummed_lines(tmp_path):
    """Pre-checksum journals (lines starting at ``{``) still load — the
    upgrade path replays old journals unchanged."""
    _write_entries(tmp_path, n=6)
    log_path = os.path.join(str(tmp_path), "raft-log.jsonl")
    lines = open(log_path).read().rstrip("\n").split("\n")
    legacy = [ln[9:] if not ln.startswith("{") else ln for ln in lines]
    with open(log_path, "w") as f:
        f.write("\n".join(legacy) + "\n")
    node2, rpc2, _ = _raft_node(tmp_path)
    try:
        assert node2.recovery["journal_truncated_tail"] == 0
        assert node2.recovery["log_entries_loaded"] == 7
    finally:
        node2.shutdown()
        rpc2.shutdown()


# ---------------------------------------------------------------------------
# Follower crash + rejoin via chunked InstallSnapshot (satellite: digest
# equality under live write load)
# ---------------------------------------------------------------------------

def _member(name, peers, data_root, bind_port=0):
    cfg = ServerConfig(scheduler_backend="host", num_schedulers=1,
                       node_name=name)
    ccfg = relaxed_cluster_cfg(
        node_id=name, peers=peers, bootstrap_expect=3,
        bind_port=bind_port,
        raft_data_dir=os.path.join(data_root, name),
        snapshot_threshold=12, trailing_logs=4,
        snapshot_chunk_bytes=2048, suspicion_threshold=1000,
    )
    return ClusterServer(cfg, ccfg)


@pytest.mark.slow
def test_follower_crash_rejoin_fsm_digest_equal(tmp_path):
    """A follower killed mid-load and restarted past the leader's
    snapshot threshold rejoins via chunked InstallSnapshot while writes
    keep landing; afterwards its fsm_state_digest equals the leader's."""
    peers = {}
    servers = [_member(f"server-{i}", peers, str(tmp_path))
               for i in range(3)]
    restarted = None
    try:
        for s in servers:
            s.start()
        leader = wait_for_leader(servers, timeout=30.0)
        nodes = [mock.node() for _ in range(12)]
        for n in nodes:
            retry_write(lambda n=n: leader.node_register(n))
        job = mock.job()
        job.task_groups[0].count = 4
        eval_id, _ = retry_write(lambda: leader.job_register(job))
        leader.wait_for_eval(eval_id, timeout=30.0)

        follower = next(s for s in servers if s is not leader)
        fname = follower.cluster.node_id
        fport = int(follower.rpc_addr.rsplit(":", 1)[1])
        commit_at_kill = leader.raft.commit_index
        follower.shutdown()

        # Write load during the outage: enough applies to push the
        # leader's compaction past the downed follower's log position.
        for round_ in range(3):
            for n in nodes:
                retry_write(lambda n=n: leader.node_register(n))
        _wait(lambda: leader.raft.snapshot_index > commit_at_kill,
              timeout=30.0, msg="leader compaction past the kill point")

        restarted = _member(fname, peers, str(tmp_path), bind_port=fport)
        restarted.start()
        # Keep writing WHILE the snapshot install races live appends.
        for n in nodes[:6]:
            retry_write(lambda n=n: leader.node_register(n))
        _wait(lambda: restarted.raft.applied_index
              >= leader.raft.applied_index, timeout=45.0,
              msg="follower catch-up")
        assert restarted.raft.snapshot_chunks_received >= 2, (
            "rejoin should ride the chunked InstallSnapshot path")

        # Digest equality at a matched applied index (the leader may
        # still tick; retry until a stable pair is observed).
        def digests_match():
            la = leader.raft.applied_index
            if restarted.raft.applied_index < la:
                return False
            d1 = fsm_state_digest(leader.state_store)
            d2 = fsm_state_digest(restarted.state_store)
            return d1 == d2 and leader.raft.applied_index == la
        _wait(digests_match, timeout=30.0, msg="fsm digest equality")
    finally:
        for s in servers:
            if s.cluster.node_id != (restarted.cluster.node_id
                                     if restarted else None):
                try:
                    s.shutdown()
                except Exception:
                    pass
        if restarted is not None:
            restarted.shutdown()


# ---------------------------------------------------------------------------
# Flap windows (satellite: scheduled armed/disarmed timelines)
# ---------------------------------------------------------------------------

def test_flap_windows_deterministic_layout():
    flap = {"period": 1.0, "duty": 0.4, "count": 3, "jitter": 0.1}
    a = faults.FaultRule("raft.append", mode="drop", flap=dict(flap), seed=9)
    b = faults.FaultRule("raft.append", mode="drop", flap=dict(flap), seed=9)
    c = faults.FaultRule("raft.append", mode="drop", flap=dict(flap), seed=10)
    assert a.windows == b.windows
    assert a.windows != c.windows
    assert len(a.windows) == 3
    for i, (start, end) in enumerate(a.windows):
        assert i * 1.0 <= start <= i * 1.0 + 0.1
        assert abs((end - start) - 0.4) < 1e-6


def test_flap_transitions_booked_from_timeline():
    """Transition books are timeline-derived: a sparse check cadence
    (no decide() landing inside a disarmed gap) still books the missed
    disarm+arm pair, and a snapshot read after the last window reports
    exactly 2*count transitions."""
    r = faults.FaultRule(
        "raft.append", mode="drop", probability=1.0,
        flap={"period": 0.04, "duty": 0.5, "count": 4}, seed=3)
    # Sleep past ALL windows without a single check, then observe once.
    time.sleep(0.04 * 4 + 0.05)
    assert r.decide("a->b") is False  # spent: past the last window
    assert r.transitions == 8
    assert r.to_dict()["transitions"] == 8


def test_flap_disarmed_checks_consume_no_draw():
    r = faults.FaultRule(
        "raft.append", mode="drop", probability=0.5,
        windows=[(10.0, 11.0)], seed=3)
    for _ in range(5):
        assert r.decide("a->b") is False
    # Disarmed checks consume nothing: neither the check counter nor
    # the seeded decision stream advanced.
    assert r.checked == 0
    state = r._rng.getstate()
    assert state == r._rng.getstate()


def test_flap_validation():
    with pytest.raises(ValueError):
        faults.FaultRule("raft.append", mode="drop",
                         flap={"period": 0.0, "count": 1})
    with pytest.raises(ValueError):
        faults.FaultRule("raft.append", mode="drop",
                         flap={"period": 1.0, "duty": 1.5, "count": 1})
    with pytest.raises(ValueError):
        faults.FaultRule("raft.append", mode="drop",
                         flap={"period": 1.0, "count": 0})
    with pytest.raises(ValueError):
        faults.FaultRule("raft.append", mode="drop",
                         windows=[(0, 1)], flap={"period": 1.0, "count": 1})


def test_registry_snapshot_carries_flap_books():
    faults.get_registry().load({"sites": {
        "raft.append": {"mode": "drop", "probability": 1.0,
                        "flap": {"period": 0.02, "duty": 0.5, "count": 2}},
    }})
    time.sleep(0.06)
    faults.fire("raft.append", target="a->b")
    snap = faults.get_registry().snapshot()
    rules = snap["sites"]["raft.append"]
    assert rules[0]["transitions"] == 4
    assert rules[0]["flap"] == {"period": 0.02, "duty": 0.5, "count": 2}
    assert len(rules[0]["windows"]) == 2


# ---------------------------------------------------------------------------
# Batched mass expiry (satellite: heartbeat cohort death without an
# eval storm)
# ---------------------------------------------------------------------------

def test_node_batch_expire_single_upsert_same_fanout(tmp_path):
    """node_batch_expire marks every node down and coalesces the
    re-placement evals into ONE eval_upsert, with per-node eval sets
    identical to the single-node path."""
    cfg = ServerConfig(scheduler_backend="host", num_schedulers=1)
    (srv,) = form_cluster(1, cfg, relaxed_cluster_cfg())
    try:
        wait_for_leader([srv])
        nodes = [mock.node() for _ in range(6)]
        for n in nodes:
            srv.node_register(n)
        job = mock.job()
        job.task_groups[0].count = 4
        eval_id, _ = srv.job_register(job)
        srv.wait_for_eval(eval_id, timeout=30.0)
        hosting = sorted({a.node_id for a in
                          srv.state_store.allocs_by_job(job.id)})
        assert len(hosting) >= 2
        victims = hosting[:2]

        reply = srv.node_batch_expire(victims)
        assert reply["nodes"] == 2
        # One eval per job with allocs on each dead node — the fan-out
        # the single path would produce, batched.
        assert len(reply["eval_ids"]) == 2
        assert "eval_create_index" in reply
        for nid in victims:
            node = srv.state_store.node_by_id(nid)
            assert node.status == structs.NODE_STATUS_DOWN
        evs = [srv.state_store.eval_by_id(e) for e in reply["eval_ids"]]
        assert all(e is not None and e.job_id == job.id for e in evs)
        # Idempotent on already-down nodes: no new status applies, and
        # the fan-out still builds (a retry must not lose evals).
        reply2 = srv.node_batch_expire(victims)
        assert reply2["nodes"] == 2
    finally:
        srv.shutdown()


# ---------------------------------------------------------------------------
# Chaos spec grammar: parse-time validation
# ---------------------------------------------------------------------------

def _minimal(**over):
    raw = {
        "name": "t",
        "nodes": {"count": 8},
        "phases": [{"at": 0.0, "workload": [
            {"kind": "steady", "jobs": 1, "tasks_per_job": 1, "over": 1.0},
        ]}],
    }
    raw.update(over)
    return raw


def test_chaos_spec_minimal_parses_and_compiles():
    spec = ChaosSpec.parse(_minimal()).compile()
    assert spec.n_nodes == 8
    assert spec.deterministic is True
    injs = spec.injectors(42)
    acts = [a for i in injs for a in i.actions()]
    assert [a.kind for a in acts] == ["register_job"]


def test_chaos_spec_phase_offsets_shift_workload_actions():
    raw = _minimal(phases=[{"at": 2.5, "workload": [
        {"kind": "steady", "jobs": 2, "tasks_per_job": 1, "over": 1.0},
    ]}])
    injs = ChaosSpec.parse(raw).compile().injectors(7)
    ats = sorted(a.at for i in injs for a in i.actions())
    assert ats[0] >= 2.5


def test_chaos_spec_rejects_bad_specs():
    cases = [
        # unknown top-level key
        _minimal(bogus=1),
        # racks must divide count
        _minimal(nodes={"count": 8, "racks": 3}),
        # unknown workload kind
        _minimal(phases=[{"at": 0, "workload": [{"kind": "nope"}]}]),
        # missing required workload param
        _minimal(phases=[{"at": 0, "workload": [
            {"kind": "steady", "jobs": 1}]}]),
        # two directives in one phase
        _minimal(phases=[{"at": 0, "barrier": True,
                          "expand_spares": True}]),
        # kill.follower in a single-member cell
        _minimal(phases=[{"at": 0, "kill": {"follower": 0}}]),
        # kill.rack without racks
        _minimal(phases=[{"at": 0, "kill": {"rack": 0}}]),
        # restart without a prior kill
        _minimal(cluster={"members": 3},
                 run={"durable_raft": True},
                 phases=[{"at": 0, "restart": {"follower": True}}]),
        # restart without durable raft
        _minimal(cluster={"members": 3},
                 phases=[{"at": 0, "kill": {"follower": 0}},
                         {"at": 1, "restart": {"follower": True}}]),
        # expand_spares without spares
        _minimal(phases=[{"at": 0, "expand_spares": True}]),
        # unknown assert flag
        _minimal(**{"assert": {"definitely_fine": True}}),
        # storm_transitions without a storm
        _minimal(**{"assert": {"storm_transitions": True}}),
        # role placeholders without a 3-member cell
        _minimal(storm={"sites": {"raft.append": {
            "mode": "drop", "match": "{leader}->x"}}}),
        # phases out of order
        _minimal(phases=[
            {"at": 2.0, "barrier": True},
            {"at": 1.0, "workload": [{"kind": "steady", "jobs": 1,
                                      "tasks_per_job": 1, "over": 1.0}]},
        ]),
        # bad objective name
        _minimal(objectives={"not_a_metric": 100.0}),
    ]
    for raw in cases:
        with pytest.raises((ChaosSpecError, ValueError)):
            ChaosSpec.parse(raw)


def test_rack_nodes_are_contiguous_domains():
    cspec = ChaosSpec.parse(_minimal(nodes={"count": 16, "racks": 4}))
    assert cspec.rack_size == 4
    assert cspec.rack_nodes(0) == [f"sim-{i:05d}" for i in range(4)]
    assert cspec.rack_nodes(3) == [f"sim-{i:05d}" for i in range(12, 16)]


def test_rack_fill_injector_full_node_bijection():
    inj = RackFillInjector(42, jobs=4, over=3.0)
    acts = inj.actions()
    assert len(acts) == 4
    assert acts[-1].at == pytest.approx(3.0)
    job = acts[0].payload["build"]()
    assert job.task_groups[0].count == 1
    assert job.task_groups[0].tasks[0].resources.cpu == 4000


def test_storm_horizon_paces_run_past_last_flap_window():
    # A scheduled storm must not outlive the run: the compiler emits a
    # no-op settle action past the last window's end so a fast workload
    # cannot quiesce while flap edges are still in the future (which
    # would honestly — and flakily — under-count storm transitions).
    raw = _minimal(storm={"sites": {
        "raft.append": {"mode": "drop", "probability": 1.0,
                        "flap": {"period": 1.2, "duty": 0.5, "count": 5,
                                 "jitter": 0.2}},
        "raft.vote": {"mode": "drop", "probability": 1.0},
    }})
    cspec = ChaosSpec.parse(raw)
    assert cspec.storm_horizon() == pytest.approx(6.0)
    settles = [a for i in cspec.compile().injectors(42)
               for a in i.actions() if a.kind == "settle"]
    assert len(settles) == 1
    assert settles[0].at > 6.0
    # Explicit window lists bound the horizon by their max end; pure
    # probability storms have no schedule, hence nothing to outlive.
    windowed = ChaosSpec.parse(_minimal(storm={"sites": {
        "raft.append": {"mode": "drop", "windows": [[0.5, 1.0],
                                                    [2.0, 3.5]]}}}))
    assert windowed.storm_horizon() == pytest.approx(3.5)
    unscheduled = ChaosSpec.parse(_minimal(storm={"sites": {
        "raft.append": {"mode": "drop", "probability": 0.1}}}))
    assert unscheduled.storm_horizon() is None
    assert not [a for i in unscheduled.compile().injectors(42)
                for a in i.actions() if a.kind == "settle"]


def test_shipped_families_registered():
    for raw in FAMILIES:
        name = raw["name"]
        assert name in SCENARIOS
        assert SCENARIOS[name].chaos_check is not None
        assert name in slo.SCENARIO_OBJECTIVES
        # slo.py declares the same bounds statically (so a process that
        # never imports the chaos compiler — the bench_watch slo-gate
        # scan — judges banked chaos artifacts identically). register()
        # merges the spec's bounds over DEFAULT_OBJECTIVES; the two
        # sources must agree key-for-key.
        assert slo.SCENARIO_OBJECTIVES[name] == {
            **slo.DEFAULT_OBJECTIVES, **raw.get("objectives", {})}
    assert SCENARIOS["partition-flap"].cluster_members == 3
    assert SCENARIOS["rack-failure"].cluster_members == 1
    assert SCENARIOS["follower-crash-rejoin"].durable_raft is True
    # The compiled kill schedule targets one whole rack, node-id exact.
    acts = [a for i in SCENARIOS["rack-failure"].injectors(42)
            for a in i.actions()]
    kills = [a for a in acts if a.kind == "fail_nodes"]
    assert len(kills) == 1
    assert kills[0].payload["node_ids"] == [
        f"sim-{i:05d}" for i in range(24, 32)]


# ---------------------------------------------------------------------------
# bench_watch chaos gate
# ---------------------------------------------------------------------------

def _chaos_artifact(ok=True, rejoin=1000.0, expiry_p95=500.0):
    return {"chaos": {
        "family": "follower-crash-rejoin",
        "ok": ok,
        "checks": [{"check": "rejoin_digest_equal", "ok": ok}],
        "time_to_rejoin_ms": rejoin,
        "expiry_replacement_ms": {"n": 8, "p95_ms": expiry_p95},
    }}


def test_chaos_gate_scopes_and_verdicts():
    import tools.bench_watch as bw

    assert bw.chaos_gate({"placements": {}}, None) is None
    # Absolute: invariants hold every round, baseline or not.
    v = bw.chaos_gate(_chaos_artifact(ok=True), None)
    assert v["ok"] is True
    v = bw.chaos_gate(_chaos_artifact(ok=False), None)
    assert v["ok"] is False
    # Relative: >tolerance growth in rejoin time regresses.
    v = bw.chaos_gate(_chaos_artifact(rejoin=1600.0),
                      _chaos_artifact(rejoin=1000.0))
    assert v["ok"] is False
    assert any(c["check"] == "time_to_rejoin_ms" and c["regressed"]
               for c in v["checks"])
    v = bw.chaos_gate(_chaos_artifact(rejoin=1400.0),
                      _chaos_artifact(rejoin=1000.0))
    assert v["ok"] is True
    # Expiry->replacement p95 regression trips the same way.
    v = bw.chaos_gate(_chaos_artifact(expiry_p95=900.0),
                      _chaos_artifact(expiry_p95=500.0))
    assert v["ok"] is False
