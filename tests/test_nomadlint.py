"""nomadlint self-tests: fixture snippets per rule (positive, negative,
and the allow() escape hatch), a lock-graph cycle fixture, the
LockWatchdog runtime check, and the tier-1 drift gates — the committed
baseline and lock order must match a fresh run on the current tree, so
the gate can never silently rot.

Fixtures are tiny fake repos written under tmp_path with the SAME
directory shape as the real tree (the passes scope by repo-relative
path: nomad_tpu/scheduler is a decision path, nomad_tpu/raft is a hot
path, nomad_tpu/tpu is traced code)."""

import textwrap
import threading

import pytest

from tools.nomadlint import (
    baseline as baseline_mod,
    determinism,
    excepts,
    lockorder,
    observatory,
    run_passes,
    tracehygiene,
)
from tools.nomadlint.project import Project
from tools.nomadlint.registry import Finding, RULES, parse_allow


def _project(tmp_path, files):
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    return Project(repo=str(tmp_path), roots=("nomad_tpu",))


def _rules(findings):
    return [f.rule_id for f in findings]


# -- determinism pass --------------------------------------------------------


def test_determinism_fixture_positive_and_negative(tmp_path):
    project = _project(tmp_path, {
        "nomad_tpu/scheduler/fix.py": """\
            import random
            import time
            from random import Random

            def decide(nodes, seed):
                random.shuffle(nodes)          # DET001: global stream
                deadline = time.time() + 5     # DET002: wall deadline
                s = {1, 2, 3}
                for x in s:                    # DET003: hash order
                    pass
                rng = Random(seed)             # negative: seeded stream
                rng.shuffle(nodes)
                t0 = time.monotonic()          # negative: monotonic
                for x in sorted(s):            # negative: sorted set
                    pass
                return deadline, t0
        """,
    })
    findings = determinism.run(project)
    assert sorted(_rules(findings)) == ["DET001", "DET002", "DET003"]
    by_rule = {f.rule_id: f for f in findings}
    assert "random.shuffle" in by_rule["DET001"].snippet
    assert "time.time()" in by_rule["DET002"].snippet
    assert by_rule["DET003"].snippet == "for x in s:                    # DET003: hash order"
    # Every finding carries the enclosing qualname for stable baselining.
    assert all(f.qualname.endswith("fix.decide") for f in findings)


def test_determinism_set_attribute_iteration(tmp_path):
    # The dominant shape in scheduler/server code: a set stored on self
    # in __init__, iterated in a method. The method's stamped qualname is
    # the CLASS's dotted name (its enclosing scope) — regression for the
    # lookup that made this branch dead.
    project = _project(tmp_path, {
        "nomad_tpu/server/fix.py": """\
            class Tracker:
                def __init__(self):
                    self.pending = set()
                    self.done = []

                def drain(self):
                    for x in self.pending:   # DET003: set attribute
                        pass
                    for x in sorted(self.pending):  # negative
                        pass
                    for x in self.done:      # negative: list attribute
                        pass
        """,
    })
    findings = determinism.run(project)
    assert _rules(findings) == ["DET003"]
    assert "self.pending" in findings[0].message
    assert findings[0].qualname.endswith("Tracker.drain")


def test_determinism_outside_decision_scope_only_checks_time(tmp_path):
    # api/ is not a decision path: DET001/DET003 do not apply there, and
    # it is outside TIME_SCOPE too — no findings at all.
    project = _project(tmp_path, {
        "nomad_tpu/api/fix.py": """\
            import random

            def pick(xs):
                return random.choice(xs)
        """,
    })
    assert determinism.run(project) == []


def test_allow_escape_suppresses_with_reason(tmp_path):
    project = _project(tmp_path, {
        "nomad_tpu/scheduler/fix.py": """\
            import random

            def decide(xs):
                # nomadlint: allow(DET001) -- fixture: sanctioned draw
                random.shuffle(xs)
                random.choice(xs)  # nomadlint: allow(DET001)
                # nomadlint: allow(NOPE999) -- no such rule
                return xs
        """,
    })
    # Both draws suppressed: one by a comment-line allow above, one by a
    # trailing same-line allow.
    assert determinism.run(project) == []
    # ...but the reasonless allow and the unknown-rule allow are
    # themselves findings (META001/META002): suppression is never free.
    meta = _rules(project.meta_findings())
    assert meta == ["META001", "META002"]


def test_allow_reason_parsing():
    a = parse_allow("x = 1  # nomadlint: allow(DET001, DET002) -- why", 7)
    assert a.rules == ("DET001", "DET002")
    assert a.reason == "why" and a.line == 7
    a = parse_allow("# nomadlint: allow(EXC001)", 3)
    assert a.rules == ("EXC001",) and a.reason is None
    assert parse_allow("# plain comment", 1) is None


# -- exception-hygiene pass --------------------------------------------------


def test_excepts_fixture(tmp_path):
    project = _project(tmp_path, {
        "nomad_tpu/raft/fix.py": """\
            from nomad_tpu import telemetry

            def hot(fut):
                try:
                    pass
                except Exception:      # EXC001: silently eaten
                    pass
                try:
                    pass
                except:                # EXC002: bare
                    pass
                try:
                    pass
                except Exception:      # negative: re-raises
                    raise
                try:
                    pass
                except Exception as e:  # negative: propagates into future
                    fut.set_exception(e)
                try:
                    pass
                except Exception:      # negative: counts telemetry
                    telemetry.incr_counter(("raft", "x"))
                try:
                    pass
                except ValueError:     # negative: typed
                    pass
        """,
    })
    assert sorted(_rules(excepts.run(project))) == ["EXC001", "EXC002"]


def test_excepts_ignores_cold_modules(tmp_path):
    project = _project(tmp_path, {
        "nomad_tpu/api/fix.py": """\
            def cold():
                try:
                    pass
                except Exception:
                    pass
        """,
    })
    assert excepts.run(project) == []


# -- trace-hygiene pass ------------------------------------------------------


def test_tracehygiene_fixture(tmp_path):
    project = _project(tmp_path, {
        "nomad_tpu/tpu/fix.py": """\
            import functools

            import jax

            TABLE = {}

            def grow():
                TABLE["k"] = 1

            @jax.jit
            def bad_branch(x):
                if x > 0:              # TRC001: traced branch
                    return x
                return -x

            @jax.jit
            def reads_state(x):
                return x + TABLE["k"]  # TRC003: mutated module state

            @jax.jit
            def ok_shape(x):
                if x.shape[0] > 2:     # negative: shape-level
                    return x
                return x

            @functools.partial(jax.jit, static_argnums=(1,))
            def with_static(x, n):
                for i in range(n):     # negative: n is static
                    x = x + 1
                return x

            def call_site(x):
                return with_static(x, [1, 2])  # TRC002: unhashable static
        """,
    })
    assert sorted(_rules(tracehygiene.run(project))) == [
        "TRC001", "TRC002", "TRC003",
    ]


# -- lock-order pass ---------------------------------------------------------


def test_lock_graph_cycle_fixture(tmp_path):
    project = _project(tmp_path, {
        "nomad_tpu/server/fixlocks.py": """\
            import threading

            A = threading.Lock()
            B = threading.Lock()

            def ab():
                with A:
                    with B:
                        pass

            def ba():
                with B:
                    with A:
                        pass
        """,
    })
    an = lockorder.analyze(project)
    a = "nomad_tpu.server.fixlocks.A"
    b = "nomad_tpu.server.fixlocks.B"
    assert (a, b) in an.edges and (b, a) in an.edges
    assert [a, b] in an.cycles
    # run() reports the cycle as LCK001 (plus LCK003: the real repo's
    # committed order naturally doesn't describe this fixture tree).
    assert "LCK001" in _rules(lockorder.run(project))


def test_lock_graph_order_edges_and_condition_alias(tmp_path):
    project = _project(tmp_path, {
        "nomad_tpu/server/fixlocks.py": """\
            import threading

            class C:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._cv = threading.Condition(self._lock)
                    self._inner = threading.Lock()

                def outerwork(self):
                    with self._cv:       # acquires _lock via the alias
                        self.helper()

                def helper(self):
                    with self._inner:    # transitive: _lock -> _inner
                        pass
        """,
    })
    an = lockorder.analyze(project)
    c = "nomad_tpu.server.fixlocks.C"
    assert an.aliases[f"{c}._cv"] == f"{c}._lock"
    assert (f"{c}._lock", f"{c}._inner") in an.edges
    assert an.cycles == []
    # Canonical order respects the edge.
    assert an.order.index(f"{c}._lock") < an.order.index(f"{c}._inner")
    # sites(): construction lines resolve to canonical ids (the alias
    # collapses onto its backing lock) — the LockWatchdog's runtime map.
    sites = an.sites()
    assert set(sites.values()) == {f"{c}._lock", f"{c}._inner"}


def test_lock_order_inversion_against_committed(tmp_path, monkeypatch):
    project = _project(tmp_path, {
        "nomad_tpu/server/fixlocks.py": """\
            import threading

            A = threading.Lock()
            B = threading.Lock()

            def ab():
                with A:
                    with B:
                        pass
        """,
    })
    a = "nomad_tpu.server.fixlocks.A"
    b = "nomad_tpu.server.fixlocks.B"
    committed = {"order": [b, a], "edges": [[b, a]], "aliases": {}}
    monkeypatch.setattr(lockorder, "load_committed",
                        lambda path=None: committed)
    findings = lockorder.run(project)
    inv = [f for f in findings if f.rule_id == "LCK002"]
    assert len(inv) == 1
    assert f"{a} -> {b}" in inv[0].message


# -- baseline semantics ------------------------------------------------------


def test_baseline_compare_new_and_stale():
    f = Finding("DET001", "nomad_tpu/x.py", 10, "x.f", "msg", snippet="s")
    g = Finding("DET001", "nomad_tpu/x.py", 99, "x.f", "msg", snippet="s")
    # Identity excludes the line number: g is the same finding moved.
    assert f.key() == g.key()
    new, stale = baseline_mod.compare([f], {f.key(): 1})
    assert new == [] and stale == []
    # Two occurrences against a budget of one: the second is NEW.
    new, stale = baseline_mod.compare([f, g], {f.key(): 1})
    assert new == [g] and stale == []
    # A fixed finding leaves a stale row that must be pruned.
    new, stale = baseline_mod.compare([], {f.key(): 1})
    assert new == [] and stale == [f.key()]


def test_baseline_roundtrip(tmp_path):
    f = Finding("EXC001", "nomad_tpu/y.py", 3, "y.g", "msg", snippet="t")
    path = str(tmp_path / "baseline.json")
    baseline_mod.save([f, f], path)
    assert baseline_mod.load(path) == {f.key(): 2}


# -- LockWatchdog (runtime half of the lockorder pass) -----------------------


def test_lock_watchdog_clean_and_inversion():
    from nomad_tpu.telemetry import LockWatchdog

    wd = LockWatchdog(order=["m.A", "m.B"], sites={})
    a = wd.watch(threading.Lock(), "m.A")
    b = wd.watch(threading.Lock(), "m.B")
    with a:
        with b:
            pass
    wd.assert_clean()
    assert ("m.A", "m.B") in wd.observed_edges()
    with b:
        with a:  # inverts the canonical order
            pass
    assert len(wd.violations) == 1
    v = wd.violations[0]
    assert (v.held, v.acquired) == ("m.B", "m.A")
    with pytest.raises(AssertionError, match="m.B -> m.A"):
        wd.assert_clean()


def test_lock_watchdog_install_wraps_only_known_sites(tmp_path):
    from nomad_tpu.telemetry import LockWatchdog, _WatchedLock

    src = tmp_path / "fixmod.py"
    src.write_text("import threading\n"
                   "def build():\n"
                   "    return threading.Lock(), threading.Lock()\n")
    ns = {}
    exec(compile(src.read_text(), str(src), "exec"), ns)
    wd = LockWatchdog(
        order=["fix.L"], sites={("fixmod.py", 3): "fix.L"},
        repo=str(tmp_path),
    )
    with wd:
        known, _also_line3 = ns["build"]()
        unknown = threading.Lock()  # this test file: not a known site
    assert isinstance(known, _WatchedLock)
    assert not isinstance(unknown, _WatchedLock)
    with known:
        pass
    assert threading.Lock is not None  # uninstalled cleanly
    assert wd.violations == []


# -- observatory pass --------------------------------------------------------


def test_observatory_flags_decision_path_imports(tmp_path):
    """OBS001: any import form of nomad_tpu.capacity inside the
    decision scope is a finding — module-level, function-local,
    from-import, and the `from nomad_tpu import capacity` spelling."""
    project = _project(tmp_path, {
        "nomad_tpu/scheduler/bad.py": """\
            import nomad_tpu.capacity
        """,
        "nomad_tpu/tpu/bad2.py": """\
            def solve():
                from nomad_tpu.capacity import CapacityAccountant
                return CapacityAccountant
        """,
        "nomad_tpu/server/worker_bad.py": """\
            from nomad_tpu import capacity
        """,
        "nomad_tpu/state/clean.py": """\
            import nomad_tpu.telemetry
        """,
    })
    findings = observatory.run(project)
    assert _rules(findings) == ["OBS001", "OBS001", "OBS001"]
    files = sorted(f.file for f in findings)
    assert files == ["nomad_tpu/scheduler/bad.py",
                     "nomad_tpu/server/worker_bad.py",
                     "nomad_tpu/tpu/bad2.py"]


def test_observatory_composition_root_exempt(tmp_path):
    """server/server.py is THE composition root: it constructs and
    starts the accountant with the other observers. Exempt by path."""
    project = _project(tmp_path, {
        "nomad_tpu/server/server.py": """\
            from nomad_tpu.capacity import CapacityAccountant
        """,
    })
    assert observatory.run(project) == []


def test_observatory_allow_escape_hatch(tmp_path):
    project = _project(tmp_path, {
        "nomad_tpu/scheduler/waived.py": """\
            # nomadlint: allow(OBS001) -- test fixture exercising the waiver
            import nomad_tpu.capacity
        """,
    })
    assert observatory.run(project) == []


def test_observatory_outside_scope_ignored(tmp_path):
    """api/ and bundle.py are exposition, not decisions: reading the
    observatory there is the point."""
    project = _project(tmp_path, {
        "nomad_tpu/api/http2.py": """\
            import nomad_tpu.capacity
        """,
        "nomad_tpu/bundle2.py": """\
            from nomad_tpu.capacity import CapacityAccountant
        """,
    })
    assert observatory.run(project) == []


def test_observatory_flags_raft_observe_imports(tmp_path):
    """OBS001 covers the raft observatory too: every import form of
    nomad_tpu.raft_observe inside the decision scope is a finding —
    including from raft/ itself (the node keeps plain-data books the
    observer drains; the dependency must never point back)."""
    project = _project(tmp_path, {
        "nomad_tpu/raft/bad_node.py": """\
            import nomad_tpu.raft_observe
        """,
        "nomad_tpu/server/bad_plan.py": """\
            def snapshot():
                from nomad_tpu.raft_observe import RaftObservatory
                return RaftObservatory
        """,
        "nomad_tpu/state/bad_store.py": """\
            from nomad_tpu import raft_observe
        """,
        "nomad_tpu/server/clean.py": """\
            import nomad_tpu.telemetry
        """,
    })
    findings = observatory.run(project)
    assert _rules(findings) == ["OBS001", "OBS001", "OBS001"]
    files = sorted(f.file for f in findings)
    assert files == ["nomad_tpu/raft/bad_node.py",
                     "nomad_tpu/server/bad_plan.py",
                     "nomad_tpu/state/bad_store.py"]


def test_observatory_raft_observe_composition_root_exempt(tmp_path):
    project = _project(tmp_path, {
        "nomad_tpu/server/server.py": """\
            from nomad_tpu.raft_observe import RaftObservatory
        """,
    })
    assert observatory.run(project) == []


def test_observatory_real_tree_is_clean():
    """The actual tree honors the contract (the tier-1 gate's view)."""
    project = Project()
    assert observatory.run(project) == []


# -- tier-1 drift gates: the committed artifacts match a fresh run -----------


@pytest.fixture(scope="module")
def real_project():
    project = Project()
    assert not project.errors
    return project


def test_tree_clean_against_committed_baseline(real_project):
    """The gate tier-1 enforces: a fresh run over the current tree has
    zero findings outside the committed baseline AND zero stale baseline
    rows — any drift is an explicit decision (--write-baseline), never an
    accident."""
    findings = run_passes(real_project)
    new, stale = baseline_mod.compare(findings, baseline_mod.load())
    assert not new, "new findings:\n" + "\n".join(f.render() for f in new)
    assert not stale, f"stale baseline rows: {stale}"


def test_committed_lock_order_matches_fresh_analysis(real_project):
    an = lockorder.analyze(real_project)
    assert an.cycles == [], f"lock-order cycles: {an.cycles}"
    assert lockorder.load_committed() == lockorder.committed_payload(an), \
        "lock_order.json drifted — regenerate with --write-lock-order"
    # The watchdog's runtime map is live: every construction site the
    # static pass found exists at the recorded line and builds a lock.
    import os
    for (rel, line), lock_id in sorted(an.sites().items()):
        with open(os.path.join(real_project.repo, rel)) as f:
            text = f.readlines()[line - 1]
        assert ("Lock(" in text or "Condition(" in text), (
            f"{rel}:{line} ({lock_id}) is not a lock construction site"
        )


def test_rule_table_is_stable():
    """Rule IDs referenced by baselines/allow()s/fixtures all exist and
    follow the <PASS><NNN> shape."""
    import re

    for rid, rule in RULES.items():
        assert re.fullmatch(r"[A-Z]{3,4}\d{3}", rid)
        assert rule.id == rid and rule.title and rule.description
    assert {"DET001", "DET002", "DET003", "LCK001", "LCK002", "LCK003",
            "EXC001", "EXC002", "TRC001", "TRC002", "TRC003",
            "META001", "META002"} <= set(RULES)
