"""Stored columnar blocks (state/blocks.py): the block form of a committed
batch must be observationally equal to its materialized expansion.

Reference semantics oracle: every placement behaves as an individual
Allocation row (/root/reference/nomad/state/state_store.go:91-760); the
block is purely a storage/wire optimization.
"""

import threading

import pytest

from nomad_tpu import structs
from nomad_tpu.state import StateStore
from nomad_tpu.state.store import item_alloc_node
from nomad_tpu.structs import AllocBatch, Resources, generate_uuid
from nomad_tpu import mock


def _mk_batch(job, node_ids, counts, eval_id="ev-1"):
    n = sum(counts)
    ids_hex = "".join(generate_uuid().replace("-", "") for _ in range(n))
    return AllocBatch(
        eval_id=eval_id,
        job=job,
        tg_name=job.task_groups[0].name,
        resources=Resources(cpu=100, memory_mb=128),
        node_ids=list(node_ids),
        node_counts=list(counts),
        name_idx=list(range(n)),
        ids_hex=ids_hex,
    )


def _seeded_store(n_nodes=4):
    store = StateStore()
    nodes = []
    for i in range(n_nodes):
        node = mock.node()
        node.id = f"node-{i}"
        store.upsert_node(i + 1, node)
        nodes.append(node)
    job = mock.job()
    store.upsert_job(50, job)
    return store, nodes, job


def _alloc_key(a):
    return (a.id, a.node_id, a.job_id, a.eval_id, a.name, a.task_group,
            a.desired_status, a.client_status, a.create_index, a.modify_index)


def test_block_store_equals_object_store():
    store_b, nodes, job = _seeded_store()
    store_o = StateStore()
    for i, node in enumerate(nodes):
        store_o.upsert_node(i + 1, node.copy())
    store_o.upsert_job(50, job)

    batch = _mk_batch(job, [n.id for n in nodes], [3, 2, 0, 4])
    store_b.upsert_alloc_blocks(100, [batch])
    store_o.upsert_allocs(100, batch.materialize())

    assert store_b.alloc_count() == store_o.alloc_count() == 9
    assert store_b.get_index("allocs") == store_o.get_index("allocs")
    for nid in [n.id for n in nodes]:
        got = sorted(map(_alloc_key, store_b.allocs_by_node(nid)))
        want = sorted(map(_alloc_key, store_o.allocs_by_node(nid)))
        assert got == want
    got = sorted(map(_alloc_key, store_b.allocs_by_job(job.id)))
    want = sorted(map(_alloc_key, store_o.allocs_by_job(job.id)))
    assert got == want
    assert sorted(map(_alloc_key, store_b.allocs_by_eval("ev-1"))) == \
        sorted(map(_alloc_key, store_o.allocs_by_eval("ev-1")))
    some_id = batch.alloc_id(4)
    assert _alloc_key(store_b.alloc_by_id(some_id)) == \
        _alloc_key(store_o.alloc_by_id(some_id))


def test_client_update_promotes_member():
    store, nodes, job = _seeded_store()
    batch = _mk_batch(job, [nodes[0].id, nodes[1].id], [2, 2])
    store.upsert_alloc_blocks(100, [batch])

    target = store.allocs_by_node(nodes[0].id)[0]
    upd = target.copy()
    upd.client_status = structs.ALLOC_CLIENT_STATUS_RUNNING
    upd.client_description = "up"
    store.update_alloc_from_client(101, upd)

    got = store.alloc_by_id(target.id)
    assert got.client_status == structs.ALLOC_CLIENT_STATUS_RUNNING
    assert got.modify_index == 101
    assert got.create_index == 100  # block commit index survives promotion
    # The untouched sibling still reads through the block.
    sibling = [a for a in store.allocs_by_node(nodes[0].id)
               if a.id != target.id]
    assert len(sibling) == 1
    assert sibling[0].client_status == structs.ALLOC_CLIENT_STATUS_PENDING
    assert store.alloc_count() == 4


def test_superseding_upsert_promotes_member():
    """A stop/evict row for a block member replaces it — reads must not
    show the member twice (the rolling-update path)."""
    store, nodes, job = _seeded_store()
    batch = _mk_batch(job, [nodes[0].id], [3])
    store.upsert_alloc_blocks(100, [batch])

    stop = store.allocs_by_node(nodes[0].id)[0].copy()
    stop.desired_status = structs.ALLOC_DESIRED_STATUS_EVICT
    store.upsert_allocs(101, [stop])

    on_node = store.allocs_by_node(nodes[0].id)
    assert len(on_node) == 3
    assert {a.id for a in on_node} == {batch.alloc_id(i) for i in range(3)}
    evicted = [a for a in on_node
               if a.desired_status == structs.ALLOC_DESIRED_STATUS_EVICT]
    assert len(evicted) == 1 and evicted[0].id == stop.id
    assert evicted[0].modify_index == 101


def test_delete_eval_reaps_blocks():
    store, nodes, job = _seeded_store()
    batch = _mk_batch(job, [nodes[0].id, nodes[1].id], [2, 1], eval_id="ev-gc")
    ev = mock.evaluation()
    ev.id = "ev-gc"
    ev.job_id = job.id
    store.upsert_evals(99, [ev])
    store.upsert_alloc_blocks(100, [batch])
    assert store.alloc_count() == 3

    store.delete_eval(102, ["ev-gc"], [])
    assert store.alloc_count() == 0
    assert store.allocs_by_node(nodes[0].id) == []
    assert store.allocs_by_job(job.id) == []
    assert store.eval_by_id("ev-gc") is None


def test_snapshot_isolated_from_promotion():
    store, nodes, job = _seeded_store()
    batch = _mk_batch(job, [nodes[0].id], [2])
    store.upsert_alloc_blocks(100, [batch])
    snap = store.snapshot()

    upd = store.allocs_by_node(nodes[0].id)[0].copy()
    upd.client_status = structs.ALLOC_CLIENT_STATUS_FAILED
    store.update_alloc_from_client(101, upd)

    # The earlier snapshot still sees the pristine block.
    before = snap.allocs_by_node(nodes[0].id)
    assert all(a.client_status == structs.ALLOC_CLIENT_STATUS_PENDING
               for a in before)
    after = store.allocs_by_node(nodes[0].id)
    assert any(a.client_status == structs.ALLOC_CLIENT_STATUS_FAILED
               for a in after)


def test_fsm_snapshot_roundtrip_with_blocks():
    from nomad_tpu.server.fsm import FSM

    fsm = FSM()
    store, nodes, job = _seeded_store()
    for i, node in enumerate(nodes):
        fsm.state.upsert_node(i + 1, node)
    fsm.state.upsert_job(50, job)
    batch = _mk_batch(job, [nodes[0].id, nodes[2].id], [2, 3])
    fsm.state.upsert_alloc_blocks(100, [batch])
    # One promoted member mixes object + block rows in the stream.
    upd = fsm.state.allocs_by_node(nodes[0].id)[0].copy()
    upd.client_status = structs.ALLOC_CLIENT_STATUS_RUNNING
    fsm.state.update_alloc_from_client(101, upd)

    data = fsm.snapshot_bytes()
    before = sorted(map(_alloc_key, fsm.state.allocs()))
    fsm2 = FSM()
    fsm2.restore_bytes(data)
    after = sorted(map(_alloc_key, fsm2.state.allocs()))
    assert before == after
    assert fsm2.state.alloc_count() == 5
    assert fsm2.state.get_index("allocs") == 101
    # Restored blocks stay columnar, not exploded.
    assert len(fsm2.state.alloc_blocks()) == 1


def test_plan_verification_sees_block_usage():
    """A committed block's usage must reject an overcommitting second plan
    (the optimistic-concurrency guard, plan_apply.go:229-277)."""
    from nomad_tpu.server.plan_apply import evaluate_plan
    from nomad_tpu.structs import Plan

    store, nodes, job = _seeded_store(1)
    node = nodes[0]
    cap = node.resources.cpu // 100  # how many 100-cpu tasks fit
    batch = _mk_batch(job, [node.id], [cap])
    store.upsert_alloc_blocks(100, [batch])

    job2 = mock.job()
    batch2 = _mk_batch(job2, [node.id], [1], eval_id="ev-2")
    plan = Plan(eval_id="ev-2", alloc_batches=[batch2])
    result = evaluate_plan(store.snapshot(), plan)
    assert sum(b.n for b in result.alloc_batches) == 0
    assert result.refresh_index > 0

    # Below the large-plan threshold both verify paths agree: force the
    # scalar path by checking a tiny object plan too.
    a = mock.alloc()
    a.node_id = node.id
    a.job_id = job2.id
    a.resources = Resources(cpu=100, memory_mb=64)
    plan_obj = Plan(eval_id="ev-3", node_allocation={node.id: [a]})
    result = evaluate_plan(store.snapshot(), plan_obj)
    assert result.node_allocation == {}


def test_bulk_verification_sees_block_usage():
    """Same overcommit guard through the native bulk verifier
    (>= FAST_VERIFY_THRESHOLD placements)."""
    from nomad_tpu.server.plan_apply import FAST_VERIFY_THRESHOLD, evaluate_plan
    from nomad_tpu.structs import Plan

    store, nodes, job = _seeded_store(2)
    full, free = nodes
    cap = full.resources.cpu // 100
    batch = _mk_batch(job, [full.id], [cap])
    store.upsert_alloc_blocks(100, [batch])

    job2 = mock.job()
    ask = max(FAST_VERIFY_THRESHOLD, 2)
    # Half the asks target the saturated node, half the free one: partial
    # commit must keep exactly the free node's run.
    batch2 = _mk_batch(job2, [full.id, free.id], [ask // 2, ask // 2],
                       eval_id="ev-2")
    plan = Plan(eval_id="ev-2", alloc_batches=[batch2])
    result = evaluate_plan(store.snapshot(), plan)
    committed = [b for b in result.alloc_batches]
    assert sum(b.n for b in committed) == ask // 2
    assert all(set(b.node_ids) == {free.id} for b in committed)
    assert result.refresh_index > 0


def test_block_dissolves_once_half_promoted():
    """Per-member COW exclusion is O(n^2) if it runs to completion; the
    store dissolves a block at 50% promotion so total cost stays O(n)."""
    store, nodes, job = _seeded_store()
    batch = _mk_batch(job, [nodes[0].id, nodes[1].id], [2, 2])
    store.upsert_alloc_blocks(100, [batch])

    for i in range(2):
        upd = store.alloc_by_id(batch.alloc_id(i)).copy()
        upd.client_status = structs.ALLOC_CLIENT_STATUS_RUNNING
        store.update_alloc_from_client(101 + i, upd)

    assert store.alloc_blocks() == []  # dissolved into object rows
    assert store.alloc_count() == 4
    assert len(store.allocs_objects()) == 4
    running = [a for a in store.allocs_by_job(job.id)
               if a.client_status == structs.ALLOC_CLIENT_STATUS_RUNNING]
    assert {a.id for a in running} == {batch.alloc_id(0), batch.alloc_id(1)}


def test_pickle_drops_materialize_cache():
    import pickle

    store, nodes, job = _seeded_store()
    batch = _mk_batch(job, [nodes[0].id, nodes[1].id], [250, 250])
    store.upsert_alloc_blocks(100, [batch])
    blk = store.alloc_blocks()[0]
    blk.materialize()  # fill the O(placements) cache
    data = pickle.dumps(blk)
    blk2 = pickle.loads(data)
    assert blk2._materialized is None  # cache never rides a raft snapshot
    assert sorted(map(_alloc_key, blk2.materialize())) == \
        sorted(map(_alloc_key, blk.materialize()))
    # And the cache's absence keeps the payload columnar-sized.
    assert len(data) < len(pickle.dumps(blk.materialize())) / 2


def test_small_batch_plan_on_deregistered_node_rejected():
    """A plan below the bulk threshold whose batch targets a node that was
    deregistered after the scheduler's snapshot must be rejected with a
    refresh, not committed via the evict-only shortcut."""
    from nomad_tpu.server.plan_apply import evaluate_plan
    from nomad_tpu.structs import Plan

    store, nodes, job = _seeded_store(2)
    gone = nodes[0]
    batch = _mk_batch(job, [gone.id, nodes[1].id], [1, 1])
    store.delete_node(90, gone.id)  # raced deregistration

    plan = Plan(eval_id="ev-x", alloc_batches=[batch])
    result = evaluate_plan(store.snapshot(), plan)
    committed = [nid for b in result.alloc_batches for nid in b.node_ids]
    assert gone.id not in committed
    assert committed == [nodes[1].id]
    assert result.refresh_index > 0


def test_block_commit_fires_node_watch():
    store, nodes, job = _seeded_store()
    ticket = store.watch.register([item_alloc_node(nodes[1].id)])
    batch = _mk_batch(job, [nodes[1].id], [2])
    store.upsert_alloc_blocks(100, [batch])
    assert store.watch.wait(ticket, timeout=1.0)
    store.watch.unregister(ticket)


def test_block_member_delete_fires_node_watch():
    """A client long-polling its node's allocs must wake when a block
    member is GC'd, exactly as for object-row deletions."""
    store, nodes, job = _seeded_store()
    batch = _mk_batch(job, [nodes[1].id], [2])
    store.upsert_alloc_blocks(100, [batch])
    ticket = store.watch.register([item_alloc_node(nodes[1].id)])
    store.delete_eval(101, [], [batch.alloc_id(0)])
    assert store.watch.wait(ticket, timeout=1.0)
    store.watch.unregister(ticket)
    assert store.alloc_count() == 1


def _mk_update_batch(batch, job2, cpu=200):
    from nomad_tpu.structs import AllocUpdateBatch

    return AllocUpdateBatch(
        eval_id="ev-upd",
        job=job2,
        tg_name=batch.tg_name,
        resources=Resources(cpu=cpu, memory_mb=128),
        alloc_ids=[batch.alloc_id(i) for i in range(batch.n)],
    )


def test_whole_block_inplace_update_swaps_fields():
    """An update batch covering every live member applies as ONE block
    field swap: reads show the new job/resources with bumped modify index
    and preserved create index — and the store stays columnar."""
    import copy

    store, nodes, job = _seeded_store()
    batch = _mk_batch(job, [nodes[0].id, nodes[1].id], [2, 3])
    store.upsert_alloc_blocks(100, [batch])

    job2 = copy.deepcopy(job)
    job2.priority = 77
    store.apply_update_batches(120, [_mk_update_batch(batch, job2)])

    assert len(store.alloc_blocks()) == 1  # still columnar: no dissolution
    got = store.allocs_by_job(job.id)
    assert len(got) == 5
    for a in got:
        assert a.eval_id == "ev-upd"
        assert a.job.priority == 77
        assert a.resources.cpu == 200
        assert a.modify_index == 120
        assert a.create_index == 100
        assert a.desired_status == structs.ALLOC_DESIRED_STATUS_RUN
    # Same ids and node placement as before the update.
    assert {a.id for a in got} == {batch.alloc_id(i) for i in range(5)}
    assert len(store.allocs_by_node(nodes[1].id)) == 3
    # Eval re-key: the block now indexes under the update's eval.
    assert len(store.allocs_by_eval("ev-upd")) == 5
    assert store.allocs_by_eval("ev-1") == []


def test_partial_inplace_update_promotes_members():
    """Updating a subset of a block's members promotes exactly those to
    object rows; siblings keep the old fields through the block."""
    store, nodes, job = _seeded_store()
    batch = _mk_batch(job, [nodes[0].id], [4])
    store.upsert_alloc_blocks(100, [batch])

    upd = _mk_update_batch(batch, job)
    upd.alloc_ids = upd.alloc_ids[:1]  # one member only
    store.apply_update_batches(120, [upd])

    target = store.alloc_by_id(batch.alloc_id(0))
    assert target.resources.cpu == 200 and target.modify_index == 120
    sibling = store.alloc_by_id(batch.alloc_id(2))
    assert sibling.resources.cpu == 100 and sibling.modify_index == 100
    assert store.alloc_count() == 4


def test_update_batch_wire_roundtrip_applies_on_replica():
    """The raft log form (ids + shared fields) must produce the same state
    on a replica that decodes it."""
    import copy

    from nomad_tpu.raft.log_codec import decode_payload, encode_payload

    store, nodes, job = _seeded_store()
    batch = _mk_batch(job, [nodes[0].id, nodes[1].id], [1, 2])
    store.upsert_alloc_blocks(100, [batch])

    job2 = copy.deepcopy(job)
    ub = _mk_update_batch(batch, job2, cpu=333)
    wire = encode_payload("alloc_update", {"update_batches": [ub]})
    decoded = decode_payload("alloc_update", wire)
    store.apply_update_batches(130, decoded["update_batches"])

    got = store.allocs_by_job(job.id)
    assert len(got) == 3
    assert all(a.resources.cpu == 333 and a.modify_index == 130 for a in got)
    assert len(store.alloc_blocks()) == 1


def test_block_commit_skips_member_items_only_when_unwatched(monkeypatch):
    """The bulk-commit fast path builds per-node watch items ONLY when an
    alloc_node waiter is parked (pinned by counting item_alloc_node
    constructions); a waiter registered before the commit fires, and one
    registering late sees the state on its first check (the
    register-then-run contract of blocking queries)."""
    from nomad_tpu.state import store as store_mod

    calls = {"n": 0}
    real = item_alloc_node

    def counting(nid):
        calls["n"] += 1
        return real(nid)

    monkeypatch.setattr(store_mod, "item_alloc_node", counting)

    store = StateStore()
    nodes = [mock.node() for _ in range(3)]
    for i, n in enumerate(nodes):
        store.upsert_node(i + 1, n)
    job = mock.job()
    store.upsert_job(10, job)

    def batch_for(seed):
        return _mk_batch(job, [n.id for n in nodes], [1, 1, 1],
                         eval_id=f"ev{seed}")

    # No waiters: the fast path builds ZERO per-node items.
    calls["n"] = 0
    store.upsert_alloc_blocks(11, [batch_for(1)])
    assert calls["n"] == 0, "unwatched commit built per-node items"
    # State is visible to a late-registering reader regardless.
    assert len(store.snapshot().allocs_by_node(nodes[0].id)) == 1

    # A registered waiter on a node item fires on the next commit, and
    # the per-node items were actually built.
    ticket = store.watch.register([real(nodes[1].id)])
    calls["n"] = 0
    store.upsert_alloc_blocks(12, [batch_for(2)])
    assert calls["n"] == 3, "watched commit must build per-node items"
    assert store.watch.wait(ticket, timeout=2.0), \
        "node watch did not fire on watched commit"

    # unregister drops the kind count back to zero: fast path returns.
    store.watch.unregister(ticket)
    calls["n"] = 0
    store.upsert_alloc_blocks(13, [batch_for(3)])
    assert calls["n"] == 0, "kind counter leaked a waiter"
