"""TLS on the RPC tier and the uplink tunnel, and the uplink's
challenge-response auth.

Reference posture: the optional rpcTLS listener arm + tlsutil
(/root/reference/nomad/rpc.go:104-110). Certificates are minted per test
session with the openssl CLI (CA + server keypair with a loopback SAN).
"""

import os
import socket
import subprocess
import threading
import time

import pytest

from nomad_tpu.rpc import ConnPool, RPCError, RPCServer, RPCUndeliveredError
from nomad_tpu.tlsutil import TLSConfig


@pytest.fixture(scope="module")
def certs(tmp_path_factory):
    d = tmp_path_factory.mktemp("tls")
    ca_key, ca_crt = d / "ca.key", d / "ca.crt"
    srv_key, srv_csr, srv_crt = d / "srv.key", d / "srv.csr", d / "srv.crt"
    ext = d / "san.cnf"
    ext.write_text(
        "subjectAltName=DNS:localhost,IP:127.0.0.1\n"
        "basicConstraints=CA:FALSE\n"
    )

    def run(*args):
        subprocess.run(args, check=True, capture_output=True)

    run("openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
        "-keyout", str(ca_key), "-out", str(ca_crt), "-days", "1",
        "-subj", "/CN=nomad-tpu-test-ca")
    run("openssl", "req", "-newkey", "rsa:2048", "-nodes",
        "-keyout", str(srv_key), "-out", str(srv_csr),
        "-subj", "/CN=localhost")
    run("openssl", "x509", "-req", "-in", str(srv_csr), "-CA", str(ca_crt),
        "-CAkey", str(ca_key), "-CAcreateserial", "-days", "1",
        "-extfile", str(ext), "-out", str(srv_crt))
    return {"ca": str(ca_crt), "cert": str(srv_crt), "key": str(srv_key)}


def _tls_cfg(certs, verify_incoming=True):
    # One region-shared keypair on both ends: mutual TLS, the reference's
    # VerifyIncoming deployment shape.
    return TLSConfig(
        enabled=True, ca_file=certs["ca"], cert_file=certs["cert"],
        key_file=certs["key"], verify_incoming=verify_incoming,
        verify_hostname=False,
    )


def test_rpc_roundtrip_and_mux_over_tls(certs):
    cfg = _tls_cfg(certs)
    srv = RPCServer(ssl_context=cfg.incoming_context())
    gate = threading.Event()
    srv.register("Echo.Hello", lambda args: {"hi": args["name"]})
    srv.register("Slow.Wait", lambda args: gate.wait(10) and {"done": True})
    srv.start()
    try:
        pool = ConnPool(ssl_context=cfg.outgoing_context())
        # A parked long-poll must not head-of-line block control traffic
        # on the shared TLS connection (the mux property, preserved
        # through the TLS wrap).
        results = {}
        t = threading.Thread(
            target=lambda: results.update(
                slow=pool.call(srv.addr, "Slow.Wait", {}, timeout=10)),
        )
        t.start()
        for i in range(20):
            assert pool.call(srv.addr, "Echo.Hello",
                             {"name": str(i)})["hi"] == str(i)
        gate.set()
        t.join(timeout=10)
        assert results.get("slow") == {"done": True}
        pool.shutdown()
    finally:
        srv.shutdown()


def test_plaintext_client_rejected_by_tls_server(certs):
    cfg = _tls_cfg(certs)
    srv = RPCServer(ssl_context=cfg.incoming_context())
    srv.register("Echo.Hello", lambda args: args)
    srv.start()
    try:
        pool = ConnPool(timeout=3.0)  # no client TLS
        with pytest.raises(RPCError):
            pool.call(srv.addr, "Echo.Hello", {"name": "x"})
    finally:
        srv.shutdown()


def test_certless_client_rejected_when_verify_incoming(certs):
    cfg = _tls_cfg(certs, verify_incoming=True)
    srv = RPCServer(ssl_context=cfg.incoming_context())
    srv.register("Echo.Hello", lambda args: args)
    srv.start()
    try:
        # Client trusts the CA but presents no certificate.
        anon = TLSConfig(enabled=True, ca_file=certs["ca"])
        pool = ConnPool(timeout=3.0, ssl_context=anon.outgoing_context())
        with pytest.raises((RPCError, RPCUndeliveredError)):
            pool.call(srv.addr, "Echo.Hello", {"name": "x"})
    finally:
        srv.shutdown()


def test_untrusted_server_rejected_by_client(certs, tmp_path):
    # A second, unrelated CA signs nothing the client trusts.
    other_ca = tmp_path / "other-ca.crt"
    other_key = tmp_path / "other-ca.key"
    subprocess.run(
        ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
         "-keyout", str(other_key), "-out", str(other_ca), "-days", "1",
         "-subj", "/CN=unrelated-ca"],
        check=True, capture_output=True,
    )
    cfg = _tls_cfg(certs, verify_incoming=False)
    srv = RPCServer(ssl_context=cfg.incoming_context())
    srv.register("Echo.Hello", lambda args: args)
    srv.start()
    try:
        client = TLSConfig(enabled=True, ca_file=str(other_ca))
        pool = ConnPool(timeout=3.0, ssl_context=client.outgoing_context())
        with pytest.raises(RPCUndeliveredError):
            pool.call(srv.addr, "Echo.Hello", {"name": "x"})
    finally:
        srv.shutdown()


# -- uplink: TLS tunnel + challenge-response auth ---------------------------


def _mini_http_server():
    """One-endpoint HTTP server standing in for the agent listener."""
    import http.server

    class H(http.server.BaseHTTPRequestHandler):
        def do_GET(self):
            body = b'{"ok": true}'
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):
            pass

    httpd = http.server.ThreadingHTTPServer(("127.0.0.1", 0), H)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    return httpd


def test_uplink_tls_tunnel_and_hmac_auth(certs):
    from nomad_tpu.scada import UplinkBroker, UplinkProvider

    cfg = _tls_cfg(certs, verify_incoming=False)
    httpd = _mini_http_server()
    broker = UplinkBroker(token="sekrit",
                          ssl_context=cfg.incoming_context())
    provider = UplinkProvider(
        endpoint=broker.addr, infrastructure="tls-infra", token="sekrit",
        http_addr="127.0.0.1:%d" % httpd.server_address[1],
        tls_context=cfg.outgoing_context(),
    )
    provider.start()
    try:
        deadline = time.time() + 15
        while time.time() < deadline and "tls-infra" not in broker.sessions():
            time.sleep(0.05)
        assert "tls-infra" in broker.sessions()
        resp = broker.http("tls-infra", "GET", "/anything")
        assert resp["status"] == 200 and "ok" in str(resp["body"])
    finally:
        provider.shutdown()
        broker.shutdown()
        httpd.shutdown()


def test_uplink_refuses_raw_token_handshake():
    """Legacy raw-token hellos are refused: the shared secret must never
    ride the wire (challenge-response only)."""
    import json
    import struct

    from nomad_tpu.scada import UplinkBroker

    broker = UplinkBroker(token="sekrit")
    try:
        host, port = broker.addr.rsplit(":", 1)
        sock = socket.create_connection((host, int(port)), timeout=5)
        payload = json.dumps({
            "seq": 0, "method": "handshake",
            "args": {"infrastructure": "x", "token": "sekrit"},
        }).encode()
        sock.sendall(struct.pack(">I", len(payload)) + payload)
        (length,) = struct.unpack(">I", sock.recv(4))
        resp = json.loads(sock.recv(length))
        assert "refused" in (resp.get("error") or "")
        sock.close()
    finally:
        broker.shutdown()


def test_uplink_wrong_token_fails_hmac():
    from nomad_tpu.scada import UplinkBroker, UplinkProvider

    httpd = _mini_http_server()
    broker = UplinkBroker(token="right")
    provider = UplinkProvider(
        endpoint=broker.addr, infrastructure="x", token="wrong",
        http_addr="127.0.0.1:%d" % httpd.server_address[1],
    )
    provider.start()
    try:
        time.sleep(1.5)
        assert "x" not in broker.sessions()
        assert provider.sessions == 0
    finally:
        provider.shutdown()
        broker.shutdown()
        httpd.shutdown()


def test_three_server_cluster_over_tls(certs):
    """Full cluster traffic — raft RPCs, leader forwarding, eval
    pipeline — over mutual TLS: register via a follower, the eval
    completes cluster-wide (the verdict's mux+blocking-over-TLS bar)."""
    import sys

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from cluster_util import relaxed_cluster_cfg, retry_write

    from nomad_tpu import mock, structs
    from nomad_tpu.server import ServerConfig
    from nomad_tpu.server.cluster import form_cluster, wait_for_leader

    servers = form_cluster(3, ServerConfig(
        scheduler_backend="host", num_schedulers=1,
        min_heartbeat_ttl=300.0, tls=_tls_cfg(certs),
    ), base_cluster=relaxed_cluster_cfg())
    try:
        leader = wait_for_leader(servers, timeout=20.0)
        follower = next(s for s in servers if s is not leader)
        node = mock.node()
        retry_write(lambda: follower.node_register(node))
        job = mock.job()
        job.task_groups[0].count = 2
        ev_id, _ = retry_write(lambda: follower.job_register(job))
        ev = leader.wait_for_eval(ev_id, timeout=30.0)
        assert ev.status == structs.EVAL_STATUS_COMPLETE
        assert len(leader.state_store.allocs_by_job(job.id)) == 2
    finally:
        for s in servers:
            s.shutdown()


def test_network_client_over_tls(certs, tmp_path):
    """A network client (servers list only) registers, runs a task, and
    syncs status back — with the whole client->server RPC path wrapped in
    mutual TLS. Guards the wiring gap where only the server tier got TLS
    and every client handshake failed."""
    from nomad_tpu import structs
    from nomad_tpu.client import Client, ClientConfig
    from nomad_tpu.server import ServerConfig
    from nomad_tpu.server.cluster import form_cluster, wait_for_leader

    cfg = _tls_cfg(certs)
    (srv,) = form_cluster(1, ServerConfig(
        scheduler_backend="host", num_schedulers=1,
        min_heartbeat_ttl=300.0, tls=cfg,
    ))
    try:
        wait_for_leader([srv])
        client = Client(ClientConfig(
            state_dir=str(tmp_path / "state"),
            alloc_dir=str(tmp_path / "allocs"),
            node_name="tls-client",
            servers=[srv.rpc_addr],
            options={"driver.mock_driver.enable": "1"},
            tls=cfg,
        ))
        client.start()
        try:
            deadline = time.time() + 15
            ready = False
            while time.time() < deadline and not ready:
                node = srv.state_store.node_by_id(client.node.id)
                ready = (node is not None
                         and node.status == structs.NODE_STATUS_READY)
                time.sleep(0.05)
            assert ready, "client never registered over TLS"

            from nomad_tpu.structs import (
                Job, Resources, RestartPolicy, Task, TaskGroup)

            job = Job(
                region="global", id="tls-job", name="tls-job",
                type=structs.JOB_TYPE_BATCH, priority=50,
                datacenters=["dc1"],
                task_groups=[TaskGroup(
                    name="g", count=1,
                    restart_policy=RestartPolicy(
                        attempts=0, interval=60.0, delay=1.0),
                    tasks=[Task(
                        name="m", driver="mock_driver",
                        config={"run_for": 0.1, "exit_code": 0},
                        resources=Resources(cpu=100, memory_mb=64),
                    )],
                )],
            )
            ev_id, _ = srv.job_register(job)
            ev = srv.wait_for_eval(ev_id, timeout=15.0)
            assert ev.status == structs.EVAL_STATUS_COMPLETE
            deadline = time.time() + 20
            while time.time() < deadline:
                allocs = srv.state_store.allocs_by_job(job.id)
                if allocs and (allocs[0].client_status
                               == structs.ALLOC_CLIENT_STATUS_DEAD):
                    break
                time.sleep(0.1)
            assert allocs and allocs[0].client_status == \
                structs.ALLOC_CLIENT_STATUS_DEAD
        finally:
            client.shutdown(destroy_allocs=True)
    finally:
        srv.shutdown()
