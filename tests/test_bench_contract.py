"""The bench one-line JSON contract under device-acquisition failure.

The driver keeps only the last parsed JSON line of a bench run. When the
device tier is unreachable the bench must therefore carry its CPU-fallback
measurement INSIDE that one line (``cpu_fallback`` + ``backend:
"cpu-fallback"``, non-zero ``value``) — a real measurement must never be
reduced to ``value: 0`` with the numbers lost in the stderr tail.

Runs bench.py as a real subprocess at toy scale: the suite environment pins
the cpu backend, and without NOMAD_TPU_BENCH_ALLOW_CPU the bench refuses it
exactly like a dead relay — the same device_dead error path a wedged tunnel
takes (bench.py acquire_device).
"""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_bench(extra_env):
    env = {
        **os.environ,
        # The coalesced phase warms 8 jobs x 129 tasks on dc1 (half the
        # nodes, 40 tasks/node by cpu) before the timed batch; 128 nodes
        # is the smallest comfortable fit.
        "NOMAD_TPU_BENCH_NODES": "128",
        "NOMAD_TPU_BENCH_TASKS": "512",
        "NOMAD_TPU_BENCH_RUNS": "1",
        "NOMAD_TPU_BENCH_DEVICE_WAIT": "30",
        "NOMAD_TPU_BENCH_BREAKDOWN_SCALES": "256",
        **extra_env,
    }
    proc = subprocess.run(
        [sys.executable, "bench.py"], cwd=REPO, env=env,
        capture_output=True, text=True, timeout=600,
    )
    lines = [l for l in proc.stdout.strip().splitlines() if l.strip()]
    assert len(lines) == 1, f"contract is ONE stdout line, got: {lines!r}"
    return proc, json.loads(lines[0])


def test_fallback_measurement_inside_parsed_json():
    proc, payload = _run_bench({})
    # Success rc: a valid cpu-fallback artifact was banked — rc must
    # read "no device", not "bench broken" (the device error stays
    # recorded in the JSON for the driver to distinguish).
    assert proc.returncode == 0
    assert "error" in payload
    # ...but the parsed artifact still carries the real measurement.
    assert payload["backend"] == "cpu-fallback"
    fb = payload["cpu_fallback"]
    assert fb["placements_per_sec"] > 0
    assert fb["solve_ms_p50"] > 0
    assert payload["value"] == fb["placements_per_sec"]
    assert payload["vs_baseline"] > 0
    assert fb["backend"] == "cpu"
    assert "NOT a TPU number" in fb["note"]
    assert payload["pallas"] in {"off", "untried", "proven", "fallback",
                                 "unknown"}
    _check_breakdown(fb["breakdown"])
    # The BASELINE configs ride the fallback line too: a dead relay must
    # not cost the round its config2/4/5 comparables.
    for name in ("config2", "config4", "config5", "staging_delta"):
        assert name in fb, f"fallback payload missing {name}"
        assert "error" not in fb[name], fb[name]
    _check_config5(fb["config5"])
    _check_staging_delta(fb["staging_delta"])


def _check_staging_delta(sweep):
    """The delta arm must show the roll path actually engaging: every
    measured single-node write rode a delta roll (not a rebuild of the
    warm cache), and both staging figures are real. The >=5x speedup bar
    is a full-scale (10k-node) acceptance judged from banked artifacts,
    not at smoke scale where fixed overheads dominate."""
    assert isinstance(sweep, list) and sweep, sweep
    for row in sweep:
        assert row["delta_staging_ms_p50"] > 0
        assert row["full_staging_ms_p50"] > 0
        assert row["speedup"] > 0
        assert row["delta_rolls"] >= row["runs"]
        assert row["rows_restaged"] >= row["runs"]


def _check_config5(c5):
    """Config-5 must state its rates AND its pass/fail bars; at reduced
    (smoke) scale the verdict abstains rather than judging scaled-down
    rates against full-scale bars."""
    assert c5["inplace_updates_per_sec"] > 0
    assert c5["rolled_updates_per_sec"] > 0
    assert c5["bar_inplace_updates_per_sec"] > 0
    assert c5["bar_rolled_updates_per_sec"] > 0
    if c5["n_nodes"] < 50_000:
        assert c5["pass"] is None
    else:
        assert isinstance(c5["pass"], bool)


def _check_breakdown(sweep):
    """The device-time split must attribute every phase with real numbers."""
    assert isinstance(sweep, list) and sweep, sweep
    for row in sweep:
        assert row["placed"] > 0
        assert row["transfer_bytes"] > 0
        assert row["readback_bytes"] > 0
        assert row["execute_ms_p50"] > 0
        assert row["warm_e2e_ms_p50"] > 0
        assert row["placements_per_sec_warm"] > 0


def test_allow_cpu_smoke_run_succeeds():
    proc, payload = _run_bench({"NOMAD_TPU_BENCH_ALLOW_CPU": "1"})
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert payload["value"] > 0
    assert payload["backend"] == "cpu"
    assert "error" not in payload
    _check_breakdown(payload["breakdown"])
    _check_config5(payload["config5"])
    _check_staging_delta(payload["staging_delta"])
