"""Worker unit tests (reference: nomad/worker_test.go): dequeue/ack/nack,
raft index sync barrier, scheduler invocation, the Planner interface
(submit with refresh, create/update eval), and leader pause."""

import time

import pytest

from nomad_tpu import mock, structs
from nomad_tpu.server import Server, ServerConfig
from nomad_tpu.server.worker import Worker
from nomad_tpu.structs import Evaluation, Plan, generate_uuid


@pytest.fixture
def srv():
    # No srv.start(): workers are driven by hand. Broker/plan queue are
    # enabled like on a leader.
    s = Server(ServerConfig(scheduler_backend="host", num_schedulers=0))
    s.plan_queue.set_enabled(True)
    s.eval_broker.set_enabled(True)
    s.plan_applier.start()
    yield s
    s.shutdown()


def _seed_job_eval(srv, count=1):
    node = mock.node()
    srv.raft.apply("node_register", {"node": node})
    job = mock.job()
    job.task_groups[0].count = count
    srv.raft.apply("job_register", {"job": job})
    ev = Evaluation(
        id=generate_uuid(),
        priority=job.priority,
        type=job.type,
        triggered_by=structs.EVAL_TRIGGER_JOB_REGISTER,
        job_id=job.id,
        status=structs.EVAL_STATUS_PENDING,
    )
    srv.raft.apply("eval_update", {"evals": [ev]})
    return node, job, ev


def test_worker_dequeue_invoke_ack(srv):
    """The full worker cycle by hand (worker_test.go dequeue + invoke):
    eval leaves the broker, the scheduler places, the ack clears the
    outstanding entry, and the eval completes."""
    node, job, ev = _seed_job_eval(srv, count=2)
    w = Worker(srv, worker_id=99)

    got = w._dequeue_evaluation()
    assert got is not None
    dq, token, wait_index = got
    assert dq.id == ev.id

    w._wait_for_index(dq.modify_index, 2.0)
    assert w._invoke_scheduler(dq, token) is True
    w._send_ack(dq.id, token, ack=True)

    assert len(srv.state_store.allocs_by_job(job.id)) == 2
    done = srv.state_store.eval_by_id(ev.id)
    assert done.status == structs.EVAL_STATUS_COMPLETE
    assert srv.eval_broker.stats.total_unacked == 0


def test_worker_nack_redelivers(srv):
    """A nacked eval is redelivered (eval_broker.go nack timer path is the
    async variant; explicit nack requeues immediately)."""
    _node, _job, ev = _seed_job_eval(srv)
    w = Worker(srv, worker_id=98)

    dq, token, _wi = w._dequeue_evaluation()
    w._send_ack(dq.id, token, ack=False)

    dq2, token2, _wi2 = w._dequeue_evaluation()
    assert dq2.id == ev.id
    assert token2 != token or token2 == token  # redelivered with a token
    w._send_ack(dq2.id, token2, ack=True)


def test_wait_for_index(srv):
    w = Worker(srv, worker_id=97)
    current = srv.raft.applied_index
    w._wait_for_index(current, 0.5)  # immediate
    with pytest.raises(TimeoutError):
        w._wait_for_index(current + 50, 0.2)


def test_submit_plan_stamps_token_and_refreshes(srv):
    """SubmitPlan stamps the outstanding EvalToken; a plan against a
    vanished node comes back with RefreshIndex and a fresh snapshot
    (worker.go:265-328)."""
    node, job, ev = _seed_job_eval(srv)
    w = Worker(srv, worker_id=96)
    dq, token, _wi = w._dequeue_evaluation()
    w.eval_token = token

    alloc = mock.alloc()
    alloc.job = job
    alloc.job_id = job.id
    alloc.eval_id = dq.id
    alloc.node_id = "no-such-node"
    plan = Plan(eval_id=dq.id, priority=50)
    plan.append_alloc(alloc)

    result, new_state = w.submit_plan(plan)
    assert plan.eval_token == token
    assert result.refresh_index > 0
    assert new_state is not None  # forced refresh
    assert not result.node_allocation
    w._send_ack(dq.id, token, ack=True)


def test_submit_plan_rejects_wrong_token(srv):
    """A plan whose token doesn't match the outstanding entry is refused —
    the split-brain guard (plan_apply.go:52-58)."""
    _node, _job, ev = _seed_job_eval(srv)
    w = Worker(srv, worker_id=95)
    dq, token, _wi = w._dequeue_evaluation()
    w.eval_token = "bogus-token"

    plan = Plan(eval_id=dq.id, priority=50)
    alloc = mock.alloc()
    plan.append_alloc(alloc)
    with pytest.raises(Exception):
        w.submit_plan(plan)
    w._send_ack(dq.id, token, ack=True)


def test_create_and_update_eval_replicate(srv):
    w = Worker(srv, worker_id=94)
    ev = Evaluation(
        id=generate_uuid(), priority=70, type="service",
        triggered_by=structs.EVAL_TRIGGER_ROLLING_UPDATE,
        job_id="some-job", status=structs.EVAL_STATUS_PENDING,
        wait=10.0,
    )
    w.create_eval(ev)
    stored = srv.state_store.eval_by_id(ev.id)
    assert stored is not None and stored.wait == 10.0

    ev.status = structs.EVAL_STATUS_COMPLETE
    w.update_eval(ev)
    assert srv.state_store.eval_by_id(ev.id).status == structs.EVAL_STATUS_COMPLETE


def _seed_n_jobs(srv, n, count=1):
    node = mock.node()
    node.resources.cpu = 500_000
    node.resources.memory_mb = 500_000
    srv.raft.apply("node_register", {"node": node})
    jobs, evals = [], []
    for _ in range(n):
        job = mock.job()
        job.task_groups[0].count = count
        # cpu/mem-bound: the mock NIC ask would cap the single test node
        # at ~20 total placements across all jobs
        job.task_groups[0].tasks[0].resources.networks = []
        srv.raft.apply("job_register", {"job": job})
        ev = Evaluation(
            id=generate_uuid(), priority=job.priority, type=job.type,
            triggered_by=structs.EVAL_TRIGGER_JOB_REGISTER, job_id=job.id,
            status=structs.EVAL_STATUS_PENDING,
        )
        jobs.append(job)
        evals.append(ev)
    srv.raft.apply("eval_update", {"evals": evals})
    return jobs, evals


def test_worker_batch_dequeue_drains_ready_evals(srv):
    """K queued evals for distinct jobs drain in ONE broker batch
    (eval_broker.py dequeue_batch wired through the server seam)."""
    jobs, evals = _seed_n_jobs(srv, 4)
    w = Worker(srv, worker_id=92)
    batch = w._dequeue_batch(4)
    assert len(batch) == 4
    assert {ev.id for ev, _, _ in batch} == {ev.id for ev in evals}
    # Each eval carries its own outstanding token
    assert len({token for _, token, _ in batch}) == 4
    for ev, token, _wi in batch:
        w._send_ack(ev.id, token, ack=True)


def test_batched_worker_processes_all_with_coalesced_dispatches():
    """End-to-end through the REAL server loop: K queued evals for K jobs,
    one batched worker, TPU backend. All jobs fully placed through the
    plan queue, the broker drain happened as one batch, and the concurrent
    device solves took no more dispatches than evals (they stack in the
    coalescing engine; fewer when timing allows)."""
    from nomad_tpu.ops.coalesce import GLOBAL_SOLVER

    s = Server(ServerConfig(
        scheduler_backend="tpu", num_schedulers=0, eval_batch_size=4,
    ))
    s.plan_queue.set_enabled(True)
    s.eval_broker.set_enabled(True)
    s.plan_applier.start()
    # The assertion below counts coalescer dispatches, so the device
    # solver must be READY before any eval processes — otherwise the
    # factory legitimately falls back to the host scheduler (order-
    # dependent flake when an earlier test started the ready race).
    from nomad_tpu.scheduler import wait_for_device

    assert wait_for_device(timeout=120) is not None
    try:
        # count > exact threshold so the water-fill/coalescer path runs
        jobs, evals = _seed_n_jobs(s, 4, count=200)
        dispatches_before = GLOBAL_SOLVER.dispatches
        w = Worker(s, worker_id=91)
        w.start()
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            done = [
                s.state_store.eval_by_id(ev.id) for ev in evals
            ]
            if all(
                d is not None and d.status == structs.EVAL_STATUS_COMPLETE
                for d in done
            ):
                break
            time.sleep(0.05)
        for job in jobs:
            allocs = [
                a for a in s.state_store.allocs_by_job(job.id)
                if a.desired_status == structs.ALLOC_DESIRED_STATUS_RUN
            ]
            assert len(allocs) == 200, (job.id, len(allocs))
        assert w.last_batch_size == 4  # one broker drain carried all four
        solves = GLOBAL_SOLVER.dispatches - dispatches_before
        assert 1 <= solves <= 4
        w.stop()
    finally:
        s.shutdown()


def test_worker_pause_blocks_processing(srv):
    """The leader pauses one worker (worker.go:77-93, leader.go:100-104):
    a paused worker must not dequeue."""
    w = Worker(srv, worker_id=93)
    w.set_pause(True)
    w.start()
    try:
        _node, job, ev = _seed_job_eval(srv)
        time.sleep(0.4)
        # Still queued: the paused worker never dequeued it
        assert srv.eval_broker.stats.total_ready == 1
        w.set_pause(False)
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            done = srv.state_store.eval_by_id(ev.id)
            if done is not None and done.status == structs.EVAL_STATUS_COMPLETE:
                break
            time.sleep(0.05)
        assert srv.state_store.eval_by_id(ev.id).status == structs.EVAL_STATUS_COMPLETE
    finally:
        w.stop()


def test_submit_plan_refresh_covers_own_commit(srv):
    """The post-plan refresh wait must cover max(refresh_index,
    alloc_index): waiting on refresh_index alone lets a worker on a
    lagging follower re-snapshot WITHOUT the allocs its own plan just
    committed — and re-place them (the chaos test's dominant
    duplicate-placement mode)."""
    _node, _job, ev = _seed_job_eval(srv)
    w = Worker(srv, worker_id=94)
    dq, token, _wi = w._dequeue_evaluation()

    waited = []
    w._wait_for_index = lambda idx, t: waited.append(idx)

    from nomad_tpu.server.worker import _EvalRun
    from nomad_tpu.structs import PlanResult

    run = _EvalRun(w, token)

    class FakeServer:
        @staticmethod
        def plan_submit(plan):
            # Partial plan: rejection forced a refresh at index 3, but
            # the accepted slice committed later, at index 9.
            return PlanResult(refresh_index=3, alloc_index=9)

        state_store = srv.state_store
        raft = srv.raft  # refresh re-stamps the transaction timestamp

    class FakeWorker:
        server = FakeServer
        _wait_for_index = staticmethod(w._wait_for_index)

    run.worker = FakeWorker()
    result, new_state = run.submit_plan(
        __import__("nomad_tpu.structs", fromlist=["Plan"]).Plan(
            eval_id=dq.id, priority=50)
    )
    assert waited == [9], waited  # max(3, 9), not 3
    assert new_state is not None
    w._send_ack(dq.id, token, ack=True)
