"""Block-level in-place updates: whole StoredAllocBlocks reconcile and
re-stamp without materializing a member (the src_* columnar form of
AllocUpdateBatch). Reference semantics preserved: util.go:54-131 diff,
util.go:265-302 tasksUpdated, util.go:316-398 inplaceUpdate feasibility."""

import copy
import logging

import numpy as np
import pytest

from nomad_tpu import mock, structs
from nomad_tpu.scheduler import new_scheduler
from nomad_tpu.server.plan_apply import evaluate_plan
from nomad_tpu.state import StateStore
from nomad_tpu.structs import (
    AllocUpdateBatch,
    Evaluation,
    Resources,
    generate_uuid,
)

BATCH = 300  # above TPUGenericScheduler.BATCH_PLACE_THRESHOLD


def _big_job(count=BATCH, cpu=100, mem=128):
    job = mock.job()
    job.type = structs.JOB_TYPE_BATCH
    tg = job.task_groups[0]
    tg.count = count
    tg.tasks[0].resources = Resources(cpu=cpu, memory_mb=mem)
    return job


def _eval_for(job):
    return Evaluation(
        id=generate_uuid(), priority=job.priority, type=job.type,
        triggered_by=structs.EVAL_TRIGGER_JOB_REGISTER, job_id=job.id,
    )


class _BlockPlanner:
    """Planner that commits columnar results columnar — the FSM posture
    (fsm.py applies alloc_batches via upsert_alloc_blocks and
    update_batches via apply_update_batches), unlike the Harness which
    materializes everything to objects."""

    def __init__(self, state):
        self.state = state
        self.plans = []
        self.created = []
        self._index = 1000

    def submit_plan(self, plan):
        self.plans.append(plan)
        self._index += 1
        result = evaluate_plan(self.state.snapshot(), plan)
        result.alloc_index = self._index
        allocs = []
        for lst in result.node_update.values():
            allocs.extend(lst)
        for lst in result.node_allocation.values():
            allocs.extend(lst)
        allocs.extend(result.failed_allocs)
        if allocs:
            self.state.upsert_allocs(self._index, allocs)
        if result.alloc_batches:
            self.state.upsert_alloc_blocks(self._index, result.alloc_batches)
        if result.update_batches:
            self.state.apply_update_batches(self._index, result.update_batches)
        return result, None

    def update_eval(self, ev):
        pass

    def create_eval(self, ev):
        self.created.append(ev)


def _cluster(n_nodes=10):
    state = StateStore()
    for i in range(n_nodes):
        node = mock.node()
        node.id = f"node-{i:03d}"
        state.upsert_node(i + 1, node)
    return state


def _process(state, planner, job):
    sched = new_scheduler("tpu-batch", state.snapshot(), planner,
                         logging.getLogger("test"))
    sched.process(_eval_for(job))


def test_block_inplace_update_never_materializes():
    state = _cluster()
    planner = _BlockPlanner(state)
    job = _big_job()
    state.upsert_job(500, job)
    _process(state, planner, job)
    blocks = state.job_alloc_blocks(job.id)
    assert blocks and sum(b.n for b in blocks) == BATCH
    assert not state.job_has_object_allocs(job.id)
    before_ids = {b.block_id for b in blocks}

    # Resource-only bump: tasks_updated is false, so the whole block
    # re-stamps in place through the src-columnar batch.
    job2 = copy.deepcopy(job)
    job2.task_groups[0].tasks[0].resources.cpu += 7
    state.upsert_job(501, job2)

    import nomad_tpu.state.blocks as blocks_mod

    calls = {"span": 0}
    orig = blocks_mod.StoredAllocBlock.materialize

    def spy(self):
        calls["span"] += 1
        return orig(self)

    blocks_mod.StoredAllocBlock.materialize = spy
    try:
        _process(state, planner, job2)
    finally:
        blocks_mod.StoredAllocBlock.materialize = orig

    plan = planner.plans[-1]
    assert plan.update_batches, "expected the block-columnar path"
    b = plan.update_batches[0]
    assert b.src_node_ids, "expected src-columnar form"
    assert b.n == BATCH
    assert calls["span"] == 0, "block members were materialized"
    assert not plan.node_allocation and not plan.alloc_batches

    # Store state: same blocks, swapped fields.
    after = state.job_alloc_blocks(job.id)
    assert {blk.block_id for blk in after} == before_ids
    for blk in after:
        assert blk.resources.cpu == 107
        assert blk.job.modify_index == job2.modify_index
    # Materialized view agrees.
    allocs = [a for a in state.allocs_by_job(job.id)
              if a.desired_status == "run"]
    assert len(allocs) == BATCH
    assert all(a.resources.cpu == 107 for a in allocs)


def test_block_inplace_same_version_is_noop():
    state = _cluster()
    planner = _BlockPlanner(state)
    job = _big_job()
    state.upsert_job(500, job)
    _process(state, planner, job)
    n_plans = len(planner.plans)
    # Same job version re-eval: everything is 'ignore'; the plan is a noop
    # and is never submitted.
    _process(state, planner, job)
    assert len(planner.plans) == n_plans


def test_block_inplace_overflow_falls_back():
    """Growth beyond node headroom cannot whole-block admit: the eval
    falls back to the object machinery (evict/replace or per-alloc)."""
    state = _cluster(n_nodes=4)
    planner = _BlockPlanner(state)
    # 4 mock nodes hold ~31GB schedulable memory total: 300x64MB fits,
    # 300x112MB cannot.
    job = _big_job(count=BATCH, cpu=30, mem=64)
    state.upsert_job(500, job)
    _process(state, planner, job)
    assert sum(b.n for b in state.job_alloc_blocks(job.id)) == BATCH

    job2 = copy.deepcopy(job)
    job2.task_groups[0].tasks[0].resources.memory_mb = 112
    state.upsert_job(501, job2)
    _process(state, planner, job2)
    plan = planner.plans[-1]
    # No unsound whole-block update was committed.
    for b in plan.update_batches:
        assert not b.src_node_ids or b.n < BATCH
    # Node capacity is respected post-commit.
    from nomad_tpu.structs import allocs_fit

    for i in range(4):
        nid = f"node-{i:03d}"
        node = state.node_by_id(nid)
        live = [a for a in state.allocs_by_node(nid)
                if a.desired_status == "run"]
        fit, _dim, _used = allocs_fit(node, live)
        assert fit, f"node {nid} overcommitted"


def test_block_inplace_tainted_node_falls_back():
    state = _cluster()
    planner = _BlockPlanner(state)
    job = _big_job()
    state.upsert_job(500, job)
    _process(state, planner, job)
    # Drain a node holding members: block-wise reconcile must refuse and
    # the object path must migrate those members.
    victim = state.job_alloc_blocks(job.id)[0].node_ids[0]
    node = state.node_by_id(victim).copy()
    node.drain = True
    state.upsert_node(502, node)
    job2 = copy.deepcopy(job)
    job2.task_groups[0].tasks[0].resources.cpu += 7
    state.upsert_job(503, job2)
    _process(state, planner, job2)
    live = [a for a in state.allocs_by_job(job.id)
            if a.desired_status == "run"]
    assert all(a.node_id != victim for a in live)
    assert len(live) == BATCH


def test_inplace_distinct_identity_allocs_never_overcommit():
    """After a snapshot restore every alloc carries its own Resources
    object: many single-member (node, identity) groups share each node.
    Grown in-place updates must still respect per-node headroom — the
    vectorized admission must not double-admit against un-deducted
    base rows."""
    import sys
    sys.path.insert(0, __file__.rsplit("/", 1)[0])
    from sched_harness import Harness

    h = Harness()
    for _ in range(4):
        h.state.upsert_node(h.next_index(), mock.node())
    job = _big_job(count=BATCH, cpu=30, mem=64)
    h.state.upsert_job(h.next_index(), job)
    h.process("tpu-batch", _eval_for(job))
    # Restore shape: break Resources identity sharing alloc by alloc.
    for a in h.state.allocs_by_job(job.id):
        a.resources = copy.deepcopy(a.resources)

    job2 = copy.deepcopy(job)
    job2.task_groups[0].tasks[0].resources.memory_mb = 100  # tight grow
    h.state.upsert_job(h.next_index(), job2)
    h.process("tpu-batch", _eval_for(job2))

    from nomad_tpu.structs import allocs_fit

    for node in h.state.nodes():
        live = [a for a in h.state.allocs_by_node(node.id)
                if a.desired_status == "run"]
        fit, _dim, _used = allocs_fit(node, live)
        assert fit, f"node {node.id} overcommitted"


def test_rolling_destructive_block_eviction():
    """A destructive change to a rolling-update job evicts exactly
    max_parallel block members per round (materializing only those),
    places same-index replacements at the new version, schedules the next
    rolling eval, and converges to a fully-updated job without ever
    overcommitting a node (util.go:400-416 evictAndPlace)."""
    from nomad_tpu.structs import UpdateStrategy, allocs_fit

    state = _cluster()
    planner = _BlockPlanner(state)
    job = _big_job()
    job.update = UpdateStrategy(stagger=0.01, max_parallel=50)
    state.upsert_job(500, job)
    _process(state, planner, job)
    assert sum(b.n for b in state.job_alloc_blocks(job.id)) == BATCH

    job2 = copy.deepcopy(job)
    job2.task_groups[0].tasks[0].env = {"V": "2"}
    state.upsert_job(501, job2)

    import nomad_tpu.state.blocks as blocks_mod

    calls = {"full": 0}
    orig = blocks_mod.StoredAllocBlock.materialize

    def spy(self):
        calls["full"] += 1
        return orig(self)

    blocks_mod.StoredAllocBlock.materialize = spy
    try:
        _process(state, planner, job2)
    finally:
        blocks_mod.StoredAllocBlock.materialize = orig

    plan = planner.plans[-1]
    stops = sum(len(v) for v in plan.node_update.values())
    assert stops == 50, stops
    assert calls["full"] == 0, "whole block was materialized for 50 evictions"
    assert planner.created, "rolling limit must schedule the next eval"

    def live_by_version():
        out = {}
        for a in state.allocs_by_job(job.id):
            if a.desired_status == "run":
                out[a.job.modify_index] = out.get(a.job.modify_index, 0) + 1
        return out

    v = live_by_version()
    assert v.get(job2.modify_index, 0) == 50
    assert v.get(job.modify_index, 0) == BATCH - 50

    # Drive to convergence. While the OLD block survives (the store
    # dissolves a block at 50% exclusions by design — remaining members
    # become object rows and later rounds legitimately take the object
    # path), every round must be block-wise: max_parallel stops, zero
    # whole-block materializations in the scheduler.
    block_rounds = 0
    for _ in range(10):
        if live_by_version().get(job.modify_index, 0) == 0:
            break
        old_block_alive = any(
            b.job.modify_index == job.modify_index
            for b in state.job_alloc_blocks(job.id)
        )
        calls["full"] = 0
        # Spy only the scheduler pass: the test's own allocs_by_job reads
        # legitimately materialize.
        blocks_mod.StoredAllocBlock.materialize = spy
        try:
            _process(state, planner, job2)
        finally:
            blocks_mod.StoredAllocBlock.materialize = orig
        old_alive_after = any(
            b.job.modify_index == job.modify_index
            for b in state.job_alloc_blocks(job.id)
        )
        if old_block_alive:
            block_rounds += 1
            if old_alive_after:
                assert calls["full"] == 0, (
                    "whole-block materialization while the block was live"
                )
            else:
                # The round whose exclusions crossed 50% dissolves the
                # block inside plan APPLY (store policy) — that one
                # materialization is the store's, not the scheduler's.
                assert calls["full"] <= 1, calls["full"]
        stops = sum(
            len(v) for v in planner.plans[-1].node_update.values()
        )
        assert stops <= 50, f"round evicted {stops} (> max_parallel)"
        for node in state.nodes():
            allocs = [a for a in state.allocs_by_node(node.id)
                      if a.desired_status == "run"]
            fit, _d, _u = allocs_fit(node, allocs)
            assert fit, f"node {node.id} overcommitted mid-roll"
    assert block_rounds >= 2, "expected several block-wise rolling rounds"
    v = live_by_version()
    assert v == {job2.modify_index: BATCH}, v


def test_src_update_batch_wire_roundtrip_and_filter():
    b = AllocUpdateBatch(
        eval_id="e1", job=mock.job(), tg_name="web",
        resources=Resources(cpu=107, memory_mb=128),
        metrics=None,
        alloc_ids=[f"id-{i}" for i in range(5)],
        src_node_ids=["n1", "n2"], src_node_counts=[2, 3],
        src_resources=Resources(cpu=100, memory_mb=128),
    )
    d = b.to_wire()
    back = AllocUpdateBatch.from_wire(d)
    assert back.src_node_ids == ["n1", "n2"]
    assert back.src_node_counts == [2, 3]
    assert back.src_resources.cpu == 100
    assert back.alloc_ids == b.alloc_ids
    back.resolve(None)  # no-op for src form: must not touch the snapshot

    kept = back.filter_nodes({"n1": True, "n2": False})
    assert kept.src_node_ids == ["n1"]
    assert kept.alloc_ids == ["id-0", "id-1"]
    assert kept.n == 2
