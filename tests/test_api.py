"""HTTP API + SDK tests (reference: command/agent/http_test.go,
*_endpoint_test.go, api/ package tests run against a live agent)."""

import threading
import time

import pytest

from nomad_tpu import mock, structs
from nomad_tpu.agent import Agent, AgentConfig
from nomad_tpu.api import ApiClient, ApiError, QueryOptions
from nomad_tpu.api.codec import from_dict, to_dict
from nomad_tpu.structs import Job


@pytest.fixture(scope="module")
def agent(tmp_path_factory):
    config = AgentConfig.dev()
    config.data_dir = str(tmp_path_factory.mktemp("agent"))
    config.http_port = 0  # auto-assign
    config.scheduler_backend = "host"
    a = Agent(config)
    a.start()
    yield a
    a.shutdown()


@pytest.fixture()
def client(agent):
    return ApiClient(address=agent.http.addr)


def test_codec_roundtrip():
    job = mock.job()
    data = to_dict(job)
    back = from_dict(Job, data)
    assert back.id == job.id
    assert back.task_groups[0].tasks[0].resources.cpu == 500
    assert back.task_groups[0].tasks[0].resources.networks[0].dynamic_ports == ["http"]
    assert back.constraints[0].l_target == "$attr.kernel.name"
    assert back.update.stagger == job.update.stagger
    # Unknown keys ignored
    data["bogus_field"] = 1
    from_dict(Job, data)


def test_agent_self(client, agent):
    info = client.agent().self_info()
    assert info["config"]["server_enabled"] is True
    assert info["config"]["client_enabled"] is True
    assert info["stats"]["leader"] is True
    assert client.status().leader() == agent.http.addr
    members = client.agent().members()
    assert len(members) == 1 and members[0]["leader"]
    # Device-solver health is operator-visible (silent host fallback is a
    # latency cliff): probe state + fallback count ride agent-info.
    solver = info["stats"]["server"]["scheduler"]
    assert solver["device"]["status"] in (
        "unprobed", "probing", "ready", "down"
    )
    assert "fallbacks" in solver["device"]


def test_job_lifecycle_over_http(client, agent):
    # Wait for the dev client node to be ready
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline:
        nodes, _ = client.nodes().list()
        if nodes and nodes[0]["status"] == "ready":
            break
        time.sleep(0.1)
    else:
        pytest.fail("dev node never became ready")

    job = mock.job()
    job.task_groups[0].count = 2
    job.task_groups[0].tasks[0].driver = "mock_driver"
    job.task_groups[0].tasks[0].config = {"run_for": "60", "exit_code": "0"}
    job.task_groups[0].tasks[0].resources.networks = []

    eval_id, meta = client.jobs().register(job)
    assert eval_id
    assert meta.last_index > 0

    # Poll the eval to completion through the API
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline:
        ev, _ = client.evaluations().info(eval_id)
        if ev.status == structs.EVAL_STATUS_COMPLETE:
            break
        time.sleep(0.1)
    else:
        pytest.fail(f"eval did not complete: {ev}")

    # Job visible in list + info
    jobs, _ = client.jobs().list()
    assert any(j["id"] == job.id for j in jobs)
    info, _ = client.jobs().info(job.id)
    assert info.id == job.id
    assert info.task_groups[0].count == 2

    allocs, _ = client.jobs().allocations(job.id)
    assert len(allocs) == 2

    evals, _ = client.jobs().evaluations(job.id)
    assert any(e.id == eval_id for e in evals)

    # Alloc detail incl. metrics
    alloc, _ = client.allocations().info(allocs[0]["id"])
    assert alloc.job_id == job.id
    assert alloc.metrics is not None

    # Eval allocations endpoint
    eallocs, _ = client.evaluations().allocations(eval_id)
    assert len(eallocs) == 2

    # Deregister
    client.jobs().deregister(job.id)
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline:
        allocs, _ = client.jobs().allocations(job.id)
        if all(a["desired_status"] == "stop" for a in allocs):
            break
        time.sleep(0.1)
    else:
        pytest.fail("allocs never stopped")


def test_node_endpoints(client, agent):
    nodes, meta = client.nodes().list()
    assert len(nodes) == 1
    node_id = nodes[0]["id"]

    node, _ = client.nodes().info(node_id)
    assert node.id == node_id
    assert node.resources.cpu > 0

    out, _ = client.nodes().toggle_drain(node_id, True)
    node, _ = client.nodes().info(node_id)
    assert node.drain is True
    client.nodes().toggle_drain(node_id, False)

    client.nodes().force_evaluate(node_id)


def test_errors(client):
    with pytest.raises(ApiError) as e:
        client.jobs().info("does-not-exist")
    assert e.value.code == 404

    with pytest.raises(ApiError) as e:
        client.query("/v1/bogus-endpoint")
    assert e.value.code == 404

    # Invalid job rejected with 400
    with pytest.raises(ApiError) as e:
        client.jobs().register(Job(id="bad job"))
    assert e.value.code == 400


def test_blocking_query(client, agent):
    """?index=N blocks until the table index passes N (http.go:228-250)."""
    _, meta = client.jobs().list()
    start_index = meta.last_index

    result = {}

    def blocked():
        jobs, m2 = client.jobs().list(
            QueryOptions(wait_index=start_index, wait_time="10s")
        )
        result["index"] = m2.last_index
        result["done_at"] = time.monotonic()

    t = threading.Thread(target=blocked)
    t0 = time.monotonic()
    t.start()
    time.sleep(0.3)
    # Trigger a jobs-table write
    job = mock.job()
    job.task_groups[0].tasks[0].driver = "mock_driver"
    job.task_groups[0].tasks[0].config = {"run_for": "0.1"}
    job.task_groups[0].tasks[0].resources.networks = []
    agent.server.job_register(job)
    t.join(timeout=10)
    assert not t.is_alive(), "blocking query never returned"
    assert result["index"] > start_index
    assert result["done_at"] - t0 >= 0.25  # actually blocked


def test_agent_debug_gated_and_populated(tmp_path_factory):
    """/v1/agent/debug: 404 without enable_debug; with it, the pprof-
    analog payload carries thread stacks, gc stats, and the device/pallas/
    coalescer/mirror state (ref command/agent/http.go:115-119)."""
    import json
    import urllib.error
    import urllib.request

    from nomad_tpu.agent import Agent, AgentConfig

    # Gated off by default
    cfg = AgentConfig.dev()
    cfg.data_dir = str(tmp_path_factory.mktemp("dbg-off"))
    cfg.http_port = 0
    cfg.scheduler_backend = "host"
    a = Agent(cfg)
    a.start()
    try:
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(a.http.addr + "/v1/agent/debug",
                                   timeout=10)
        assert exc.value.code == 404
    finally:
        a.shutdown()

    cfg2 = AgentConfig.dev()
    cfg2.data_dir = str(tmp_path_factory.mktemp("dbg-on"))
    cfg2.http_port = 0
    cfg2.scheduler_backend = "host"
    cfg2.enable_debug = True
    a2 = Agent(cfg2)
    a2.start()
    try:
        with urllib.request.urlopen(a2.http.addr + "/v1/agent/debug",
                                    timeout=10) as resp:
            out = json.loads(resp.read())
        assert "MainThread" in out["threads"]
        assert out["gc"]["counts"]
        assert "mode" in out["pallas"]
        assert "dispatches" in out["coalescer"]
        assert out["mirror_cache"]["capacity"] > 0
        assert "status" in out["device_probe"]
    finally:
        a2.shutdown()
