"""Scheduler testing harness.

Port of the reference harness (/root/reference/scheduler/scheduler_test.go:
32-176): a real in-memory StateStore plus a Planner that records plans and
applies them directly to state; ``RejectPlan`` forces the refresh/retry path.
This is the correctness oracle rig shared by the host solver tests and the
TPU solver differential tests.
"""

from __future__ import annotations

import logging
import threading
from typing import List, Optional

from nomad_tpu.scheduler import Factory, new_scheduler
from nomad_tpu.state import StateStore
from nomad_tpu.structs import Allocation, Evaluation, Plan, PlanResult

logger = logging.getLogger("nomad_tpu.test")


class RejectPlan:
    """Always rejects the plan and forces a state refresh
    (reference: scheduler_test.go:13-30)."""

    def __init__(self, harness: "Harness"):
        self.harness = harness

    def submit_plan(self, plan: Plan):
        result = PlanResult()
        result.refresh_index = self.harness.next_index()
        return result, self.harness.state

    def update_eval(self, ev: Evaluation) -> None:
        pass

    def create_eval(self, ev: Evaluation) -> None:
        pass


class Harness:
    """Lightweight scheduler harness (reference: scheduler_test.go:32-158)."""

    def __init__(self) -> None:
        self.state = StateStore()
        self.planner = None  # custom planner override
        self._plan_lock = threading.Lock()
        self.plans: List[Plan] = []
        self.evals: List[Evaluation] = []
        self.create_evals: List[Evaluation] = []
        self._next_index = 1
        self._index_lock = threading.Lock()

    # -- Planner interface -------------------------------------------------

    def submit_plan(self, plan: Plan):
        with self._plan_lock:
            self.plans.append(plan)
            if self.planner is not None:
                return self.planner.submit_plan(plan)

            index = self.next_index()
            result = PlanResult(
                node_update=plan.node_update,
                node_allocation=plan.node_allocation,
                alloc_batches=plan.alloc_batches,
                update_batches=plan.update_batches,
                alloc_index=index,
            )

            allocs: List[Allocation] = []
            for update_list in plan.node_update.values():
                allocs.extend(update_list)
            for alloc_list in plan.node_allocation.values():
                allocs.extend(alloc_list)
            for batch in plan.alloc_batches:
                allocs.extend(batch.materialize())
            for batch in plan.update_batches:
                allocs.extend(batch.materialize())
            allocs.extend(plan.failed_allocs)

            self.state.upsert_allocs(index, allocs)
            return result, None

    def update_eval(self, ev: Evaluation) -> None:
        with self._plan_lock:
            self.evals.append(ev)
            if self.planner is not None:
                self.planner.update_eval(ev)

    def create_eval(self, ev: Evaluation) -> None:
        with self._plan_lock:
            self.create_evals.append(ev)
            if self.planner is not None:
                self.planner.create_eval(ev)

    # -- helpers -----------------------------------------------------------

    def next_index(self) -> int:
        with self._index_lock:
            idx = self._next_index
            self._next_index += 1
            return idx

    def snapshot(self):
        return self.state.snapshot()

    def process(self, factory_name: str, ev: Evaluation) -> None:
        sched = new_scheduler(factory_name, self.snapshot(), self, logger)
        sched.process(ev)

    def assert_eval_status(self, status: str) -> None:
        assert len(self.evals) == 1, f"bad evals: {self.evals}"
        assert self.evals[0].status == status, f"bad: {self.evals[0]}"


def flatten(node_map) -> List[Allocation]:
    out: List[Allocation] = []
    for alloc_list in node_map.values():
        out.extend(alloc_list)
    return out
