"""Runtime self-observatory tests (nomad_tpu/profile_observe.py):
config parse validation, the thread-role taxonomy (pinned), golden
collapsed-stack and speedscope export formats, seeded-cadence
determinism, the lock watchdog's contention timing + closure-based
violation semantics, the byte-economy ledger (rings, mirror
bucket×dtype books, the measured-per-row 1M projection), the
bench_watch runtime gate, and the /v1/agent/profile + /v1/agent/runtime
+ SDK + bundle surfaces over a live agent."""

import json
import sys
import threading
import time
import urllib.error
import urllib.request
from collections import deque

import pytest

from nomad_tpu import mock, telemetry
from nomad_tpu.agent import Agent, AgentConfig
from nomad_tpu.api import ApiClient
from nomad_tpu.profile_observe import (
    ROLES,
    ProfileObserveConfig,
    RuntimeObservatory,
    classify_thread,
    collapse_frames,
    container_footprint,
    frame_label,
    rss_bytes,
    sample_schedule,
)


# -- config parse -------------------------------------------------------------


def test_config_defaults_and_parse():
    cfg = ProfileObserveConfig.parse(None)
    assert cfg.enabled is True
    assert cfg.sample_interval == 0.05
    cfg = ProfileObserveConfig.parse(
        {"enabled": False, "sample_interval": 0.1, "seed": 7,
         "max_depth": 8, "events_interval": 0}
    )
    assert cfg.enabled is False
    assert cfg.seed == 7
    assert cfg.max_depth == 8
    assert cfg.events_interval == 0.0


def test_config_parse_rejects_nonsense():
    with pytest.raises(ValueError, match="unknown profile config key"):
        ProfileObserveConfig.parse({"sample_intervall": 1.0})
    with pytest.raises(ValueError, match="must be a mapping"):
        ProfileObserveConfig.parse("fast")
    with pytest.raises(ValueError, match="sample_interval must be > 0"):
        ProfileObserveConfig.parse({"sample_interval": 0})
    with pytest.raises(ValueError, match=r"jitter must be in \[0, 1\)"):
        ProfileObserveConfig.parse({"jitter": 1.0})
    with pytest.raises(ValueError, match="max_stacks must be > 0"):
        ProfileObserveConfig.parse({"max_stacks": 0})
    with pytest.raises(ValueError, match="events_interval must be >= 0"):
        ProfileObserveConfig.parse({"events_interval": -1})


def test_file_config_validates_profile_block(tmp_path):
    """Typos in server { profile { } } fail config LOAD, not first
    use; telemetry { lock_watchdog } must be a real boolean."""
    from nomad_tpu.agent_config import load_config_file

    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps(
        {"server": {"enabled": True, "profile": {"sample_rate": 1}}}
    ))
    with pytest.raises(ValueError, match="unknown profile config key"):
        load_config_file(str(bad))

    bad_wd = tmp_path / "bad_wd.json"
    bad_wd.write_text(json.dumps(
        {"server": {"enabled": True},
         "telemetry": {"lock_watchdog": "yes"}}
    ))
    with pytest.raises(ValueError, match="lock_watchdog must be a bool"):
        load_config_file(str(bad_wd))

    good = tmp_path / "good.json"
    good.write_text(json.dumps(
        {"server": {"enabled": True,
                    "profile": {"sample_interval": 0.25, "seed": 9}},
         "telemetry": {"lock_watchdog": True}}
    ))
    cfg = load_config_file(str(good))
    assert cfg.server.profile == {"sample_interval": 0.25, "seed": 9}
    assert cfg.telemetry.lock_watchdog is True
    ac = AgentConfig.from_file_config(cfg)
    assert ac.profile == {"sample_interval": 0.25, "seed": 9}
    assert ac.lock_watchdog is True


# -- thread-role taxonomy (pinned) -------------------------------------------


def test_thread_role_taxonomy_pinned():
    """The role vocabulary is an artifact-schema contract: collapsed
    exports, speedscope profile names, and the prom role label all ride
    it. Every mapping here is deliberate."""
    cases = {
        "worker-0": "worker",
        "worker-13": "worker",
        "plan-pipeline": "pipeline-committer",
        "plan-pipeline-wait": "pipeline-committer",
        "raft-election-n1": "raft",
        "raft-leader-n1": "raft",
        "raft-compact-n1": "raft",
        "heartbeat-wheel": "heartbeat-wheel",
        "express-commit": "express-committer",
        "raft-observatory": "observer",      # before the raft- rule
        "read-observatory": "observer",
        "runtime-profiler": "observer",
        "capacity-accountant": "observer",
        "stats-emitter": "observer",
        "slo-monitor": "observer",
        "http-server": "http",
        "Thread-4 (process_request_thread)": "http",
        "MainThread": "main",
        "pytest-watcher": "other",
    }
    for name, role in cases.items():
        assert classify_thread(name) == role, name
    assert set(cases.values()) == set(ROLES)


# -- frame naming + stack collapse -------------------------------------------


def _here():
    return sys._getframe(0)


def test_frame_label_is_machine_independent():
    label = frame_label(_here())
    assert label == "test_profile_observe:_here"
    assert "/" not in label and ".py" not in label


def test_collapse_frames_root_first_and_truncates():
    stack = collapse_frames(_here(), max_depth=64)
    # Root-first: the leaf (the helper itself) is LAST.
    assert stack[-1] == "test_profile_observe:_here"
    assert stack.index(
        "test_profile_observe:"
        "test_collapse_frames_root_first_and_truncates"
    ) == len(stack) - 2
    short = collapse_frames(_here(), max_depth=3)
    assert len(short) == 3
    assert short[0] == "…"                     # root prefix folded
    assert short[-1] == "test_profile_observe:_here"  # leaf preserved


# -- seeded cadence -----------------------------------------------------------


def test_sample_schedule_deterministic_and_bounded():
    a = sample_schedule(42, 0.05, 0.2, 100)
    b = sample_schedule(42, 0.05, 0.2, 100)
    assert a == b                               # same seed, same schedule
    c = sample_schedule(43, 0.05, 0.2, 100)
    assert a != c                               # different seed decorrelates
    assert all(0.05 * 0.8 <= g <= 0.05 * 1.2 for g in a)
    # Jittered, not phase-locked: the gaps are not all identical.
    assert len(set(round(g, 9) for g in a)) > 1
    assert sample_schedule(42, 0.05, 0.0, 10) == [0.05] * 10


# -- golden export formats ----------------------------------------------------


def _synthetic_observatory(**cfg):
    obs = RuntimeObservatory(ProfileObserveConfig.parse(cfg or None))
    obs._ingest("worker", ("agent:main", "worker:run", "fit:solve"))
    obs._ingest("worker", ("agent:main", "worker:run", "fit:solve"))
    obs._ingest("worker", ("agent:main", "worker:run", "plan:submit"))
    obs._ingest("raft", ("agent:main", "raft:apply"))
    return obs


def test_golden_collapsed_output():
    """Byte-exact folded-stack text: semicolon-joined role-rooted
    frames, space, count, sorted — the flamegraph.pl input contract."""
    obs = _synthetic_observatory()
    assert obs.collapsed() == (
        "raft;agent:main;raft:apply 1\n"
        "worker;agent:main;worker:run;fit:solve 2\n"
        "worker;agent:main;worker:run;plan:submit 1\n"
    )


def test_golden_speedscope_document():
    obs = _synthetic_observatory()
    doc = obs.speedscope()
    assert doc["$schema"] == (
        "https://www.speedscope.app/file-format-schema.json")
    frames = [f["name"] for f in doc["shared"]["frames"]]
    assert frames == sorted(frames)             # deterministic table
    by_name = {p["name"]: p for p in doc["profiles"]}
    assert sorted(by_name) == ["raft", "worker"]
    worker = by_name["worker"]
    assert worker["type"] == "sampled"
    assert worker["weights"] == [2, 1]
    assert worker["endValue"] == 3
    # Every sample is indices into the shared frame table, leaf last.
    for s in worker["samples"]:
        assert frames[s[-1]] in ("fit:solve", "plan:submit")
    # The document round-trips through JSON (the download path).
    json.loads(json.dumps(doc))


def test_profiler_wall_shares_and_overflow():
    obs = _synthetic_observatory(max_stacks=2)
    # Third distinct stack exceeded max_stacks=2.
    view = obs.profile_view()["profiler"]
    assert view["distinct_stacks"] == 2
    assert view["stack_overflow"] == 1
    assert view["thread_samples"] == 4
    assert view["roles"]["worker"]["wall_share"] == 0.75
    assert view["roles"]["raft"]["wall_share"] == 0.25


def test_sample_once_sees_live_threads():
    obs = RuntimeObservatory(ProfileObserveConfig())
    stop = threading.Event()
    t = threading.Thread(target=stop.wait, name="worker-99", daemon=True)
    t.start()
    try:
        # The calling thread is excluded (in production the caller IS
        # the sampler thread), so only the worker is guaranteed.
        n = obs.sample_once()
        assert n >= 1
        view = obs.profile_view()["profiler"]
        assert view["samples"] == 1
        assert "worker" in view["roles"]
    finally:
        stop.set()
        t.join()


# -- byte-economy ledger ------------------------------------------------------


def test_rss_bytes_stdlib_only():
    rss = rss_bytes()
    assert rss["current_bytes"] > 0              # Linux container
    assert rss["peak_bytes"] >= rss["current_bytes"] // 2


def test_container_footprint_bounded_ring():
    ring = deque(({"id": "x" * 32, "n": i} for i in range(100)), maxlen=64)
    fp = container_footprint(ring)
    assert fp["entries"] == 64
    assert fp["capacity"] == 64
    assert fp["per_entry_bytes"] > 0
    assert fp["approx_bytes"] >= fp["per_entry_bytes"] * 64


def test_node_mirror_byte_ledger():
    from nomad_tpu.tpu.mirror import NodeMirror

    nodes = [mock.node() for _ in range(10)]
    ledger = NodeMirror(nodes).byte_ledger()
    assert ledger["rows"] == 10
    assert ledger["padded"] == 16                # bucket(10)
    # The named device buffers all report dtype + bytes.
    for name in ("total", "reserved_np", "sched_cap", "base_mask"):
        assert ledger["buffers"][name]["nbytes"] > 0
    assert ledger["total_bytes"] == (
        ledger["buffer_bytes"] + ledger["cache_bytes"])


def test_mirror_cache_ledger_projects_million_rows():
    from nomad_tpu.ops.binpack import bucket
    from nomad_tpu.tpu.mirror import MirrorCache, NodeMirror

    cache = MirrorCache()
    assert cache.byte_ledger()["per_row_bytes"] is None  # empty: no slope
    nodes = [mock.node() for _ in range(20)]
    cache._entries[("uid", 1, ("dc1",))] = (nodes, NodeMirror(nodes))
    ledger = cache.byte_ledger()
    assert ledger["mirrors"] == 1
    assert ledger["rows"] == 20
    assert ledger["padded_rows"] == 32
    assert "32" in ledger["by_bucket_dtype"]
    per_row = ledger["per_row_bytes"]
    assert per_row == round(ledger["total_bytes"] / 32, 2)
    # The 1M projection: measured slope × the padding bucket 1M lands in.
    assert ledger["projected_1m_rows"] == bucket(1_000_000) == 1_048_576
    assert ledger["projected_1m_bytes"] == int(per_row * 1_048_576)


def test_observatory_refresh_builds_ledger():
    ring = deque(range(50), maxlen=64)
    store = {"k": list(range(100))}
    obs = RuntimeObservatory(
        ProfileObserveConfig(),
        rings_getter=lambda: {"my_ring": ring},
        tables_getter=lambda: {"my_table": store},
    )
    obs.refresh()
    view = obs.runtime_view()
    ledger = view["bytes"]
    assert ledger["rings"]["my_ring"]["entries"] == 50
    assert ledger["tables"]["my_table"]["approx_bytes"] > 0
    assert ledger["rss"]["current_bytes"] > 0
    assert ledger["tracked_bytes"] > 0
    assert view["observer"]["polls"] == 1
    summary = obs.summary()
    assert summary["rss_mb"] > 0


# -- lock watchdog: timing + closure semantics -------------------------------


def test_lock_watchdog_times_contention():
    wd = telemetry.LockWatchdog(order=["a", "b"], sites={})
    lock = wd.watch(threading.Lock(), "a")

    holding = threading.Event()
    release = threading.Event()

    def holder():
        with lock:
            holding.set()
            release.wait(5)

    t = threading.Thread(target=holder, daemon=True)
    t.start()
    assert holding.wait(5)
    # Contended acquisition: blocks until the holder releases.
    timer = threading.Timer(0.05, release.set)
    timer.start()
    with lock:
        pass
    t.join(5)
    stats = wd.stats()
    assert stats["installed"] is False           # watch(), not install()
    row = next(r for r in stats["contention"] if r["lock"] == "a")
    assert row["acquisitions"] == 2
    assert row["contended"] == 1
    assert row["contention_rate"] == 0.5
    # We waited ~50ms for the holder: total wait and p95 both saw it.
    assert 10.0 < row["wait_total_ms"] < 5000.0
    assert row["wait_ms"]["p95"] >= 10.0
    # The holder held for ~50ms; hold books recorded both holds.
    assert row["hold_ms"]["max"] >= 10.0


def test_lock_watchdog_noncontended_fast_path_is_untimed():
    wd = telemetry.LockWatchdog(order=["a"], sites={})
    lock = wd.watch(threading.Lock(), "a")
    for _ in range(5):
        with lock:
            pass
    row = wd.stats()["contention"][0]
    assert row["acquisitions"] == 5
    assert row["contended"] == 0
    assert row["wait_total_ms"] == 0


def test_lock_watchdog_closure_violation_semantics():
    """With closure= the watchdog flags only inversions of statically
    PROVEN edges; pairs the analysis never related are recorded as
    observed edges, not violations (the whole-agent runtime-knob
    posture). Without closure= the rank comparison also flags
    unconstrained pairs (the strict single-subsystem test posture)."""
    order = ["a", "b", "c"]

    def drive(wd):
        la, lb = wd.watch(threading.Lock(), "a"), \
            wd.watch(threading.Lock(), "b")
        lc = wd.watch(threading.Lock(), "c")
        with lb:
            with la:                              # a while holding b
                pass
        with lc:
            with la:                              # a while holding c
                pass

    strict = telemetry.LockWatchdog(order=order, sites={})
    drive(strict)
    # Rank semantics: both inversions flagged.
    assert {(v.held, v.acquired) for v in strict.violations} == {
        ("b", "a"), ("c", "a")}

    informed = telemetry.LockWatchdog(
        order=order, sites={}, closure={("a", "b")})
    drive(informed)
    # Closure semantics: only b->a inverts the proven a->b edge; (a, c)
    # was never statically related, so c->a is just a new observation.
    assert [(v.held, v.acquired) for v in informed.violations] == [
        ("b", "a")]
    assert ("c", "a") in informed.observed_edges()
    with pytest.raises(AssertionError):
        informed.assert_clean()


def test_lock_watchdog_install_publishes_active_global():
    an_order = ["x"]
    wd = telemetry.LockWatchdog(order=an_order, sites={})
    assert telemetry.active_lock_watchdog() is None
    with wd:
        assert telemetry.active_lock_watchdog() is wd
        assert wd.stats()["installed"] is True
    assert telemetry.active_lock_watchdog() is None


def test_observatory_locks_view_reads_active_watchdog():
    obs = RuntimeObservatory(ProfileObserveConfig())
    assert obs.runtime_view()["locks"] == {"installed": False}
    wd = telemetry.LockWatchdog(order=["a"], sites={})
    with wd:
        lock = wd.watch(threading.Lock(), "a")
        with lock:
            pass
        view = obs.runtime_view()["locks"]
        assert view["installed"] is True
        assert view["contention"][0]["lock"] == "a"


# -- bench_watch runtime gate -------------------------------------------------


def _profile_artifact(rss=1000, per_row=50.0, wait_p95=1.0):
    return {"profile": {
        "enabled": True,
        "bytes": {"rss": {"peak_bytes": rss},
                  "mirror": {"per_row_bytes": per_row}},
        "locks": {"contention": [
            {"lock": "a", "wait_ms": {"p95": wait_p95}},
            {"lock": "b", "wait_ms": {"p95": wait_p95 / 2}},
        ]},
    }}


def test_runtime_gate_scoped_and_first_round():
    from tools.bench_watch import runtime_gate

    assert runtime_gate({}, None) is None
    assert runtime_gate({"profile": {"enabled": False}}, None) is None
    verdict = runtime_gate(_profile_artifact(), None)
    assert verdict["ok"] is True
    assert {c["check"] for c in verdict["checks"]} == {
        "rss_peak_bytes", "mirror_per_row_bytes", "lock_wait_p95_ms"}
    assert all(c["baseline"] is None for c in verdict["checks"])


def test_runtime_gate_regression_detection():
    from tools.bench_watch import runtime_gate

    base = _profile_artifact(rss=1000, per_row=50.0, wait_p95=1.0)
    ok = runtime_gate(_profile_artifact(rss=1400), base)
    assert ok["ok"] is True                      # within 50% tolerance
    bad = runtime_gate(_profile_artifact(rss=2000), base)
    assert bad["ok"] is False
    assert [c["check"] for c in bad["checks"] if c["regressed"]] == [
        "rss_peak_bytes"]
    worse_rows = runtime_gate(_profile_artifact(per_row=200.0), base)
    assert worse_rows["ok"] is False
    # A disabled-profile baseline gates nothing (first-round posture).
    assert runtime_gate(
        _profile_artifact(rss=9999),
        {"profile": {"enabled": False}})["ok"] is True


# -- live agent e2e -----------------------------------------------------------


@pytest.fixture(scope="module")
def agent(tmp_path_factory):
    config = AgentConfig.dev()
    config.data_dir = str(tmp_path_factory.mktemp("agent"))
    config.http_port = 0
    config.scheduler_backend = "host"
    config.lock_watchdog = True
    # Fast cadences so the module's tests see samples, ledger polls and
    # a Runtime event within a second.
    config.profile = {"sample_interval": 0.02, "ledger_interval": 0.2,
                      "events_interval": 0.3}
    a = Agent(config)
    a.start()
    yield a
    a.shutdown()


def _get(agent, path):
    try:
        with urllib.request.urlopen(agent.http.addr + path,
                                    timeout=15) as resp:
            return resp.status, resp.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


def _wait_for_samples(agent, n=5, timeout=15):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        obs = agent.server.runtime_observatory
        if obs.samples >= n and obs.polls >= 1:
            return
        time.sleep(0.05)
    pytest.fail(f"profiler never reached {n} samples")


def test_profile_endpoint_e2e(agent):
    _wait_for_samples(agent)
    status, body = _get(agent, "/v1/agent/profile")
    assert status == 200
    view = json.loads(body)
    prof = view["profiler"]
    assert prof["samples"] >= 5
    assert prof["schedule"]["seed"] == 42
    # The agent's own subsystem threads classified into the taxonomy.
    assert set(prof["roles"]) <= set(ROLES)
    assert "main" in prof["roles"]
    shares = [r["wall_share"] for r in prof["roles"].values()]
    assert abs(sum(shares) - 1.0) < 0.01


def test_profile_collapsed_and_speedscope_exports(agent):
    _wait_for_samples(agent)
    status, body = _get(agent, "/v1/agent/profile?format=collapsed")
    assert status == 200
    lines = body.decode().splitlines()
    assert lines
    for line in lines:
        stack, _, count = line.rpartition(" ")
        assert int(count) >= 1
        assert stack.split(";")[0] in ROLES
    status, body = _get(agent, "/v1/agent/profile?format=speedscope")
    assert status == 200
    doc = json.loads(body)
    assert doc["$schema"].startswith("https://www.speedscope.app/")
    assert doc["profiles"]


def test_runtime_endpoint_e2e(agent):
    _wait_for_samples(agent)
    status, body = _get(agent, "/v1/agent/runtime")
    assert status == 200
    view = json.loads(body)
    # The config-gated watchdog installed at agent construction.
    assert view["locks"]["installed"] is True
    assert view["locks"]["locks_tracked"] > 0
    assert view["locks"]["violations"] == 0
    ledger = view["bytes"]
    assert "events" in ledger["rings"]
    assert ledger["rss"]["current_bytes"] > 0
    assert ledger["tracked_bytes"] > 0
    assert "mirror" in ledger


def test_runtime_prometheus_and_main_scrape(agent):
    _wait_for_samples(agent)
    status, body = _get(agent, "/v1/agent/runtime?format=prometheus")
    assert status == 200
    text = body.decode()
    assert "# TYPE nomad_profile_samples_total counter" in text
    assert "nomad_runtime_rss_bytes" in text
    assert 'nomad_profile_role_share{role="main"}' in text
    assert "nomad_lock_acquisitions_total{lock=" in text
    # Same families ride the main scrape.
    status, body = _get(agent, "/v1/agent/metrics?format=prometheus")
    assert status == 200
    main = body.decode()
    assert "nomad_profile_samples_total" in main
    assert "nomad_lock_wait_ms_total" in main
    # And the metrics JSON body carries both summaries.
    status, body = _get(agent, "/v1/agent/metrics")
    doc = json.loads(body)
    assert doc["runtime"]["samples"] >= 1
    assert doc["locks"]["installed"] is True


def test_sdk_profile_and_runtime_accessors(agent):
    _wait_for_samples(agent)
    api = ApiClient(address=agent.http.addr).agent()
    prof = api.profile()
    assert prof["profiler"]["samples"] >= 1
    runtime = api.runtime()
    assert runtime["locks"]["installed"] is True
    assert runtime["bytes"]["tracked_bytes"] > 0


def test_debug_bundle_carries_profile_and_runtime(agent):
    from nomad_tpu.bundle import BUNDLE_SECTIONS, collect

    assert "profile" in BUNDLE_SECTIONS and "runtime" in BUNDLE_SECTIONS
    _wait_for_samples(agent)
    bundle = collect(agent=agent)
    assert bundle["profile"]["profiler"]["samples"] >= 1
    assert bundle["runtime"]["bytes"]["rss"]["current_bytes"] > 0


def test_runtime_events_flow(agent):
    """Periodic RuntimeSnapshot events land on the stream — on the
    Runtime OBSERVER topic only, so canonical digests exclude them."""
    from nomad_tpu.events import OBSERVER_TOPICS

    assert "Runtime" in OBSERVER_TOPICS
    client = ApiClient(address=agent.http.addr)
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline:
        _idx, events, _trunc = client.events().list(topics=["Runtime"])
        if events:
            assert events[0]["type"] == "RuntimeSnapshot"
            assert "top_role" in events[0]["payload"]
            return
        time.sleep(0.2)
    pytest.fail("no Runtime snapshot event within 15s")


def test_profile_disabled_404(tmp_path):
    config = AgentConfig.dev()
    config.data_dir = str(tmp_path / "agent")
    config.http_port = 0
    config.scheduler_backend = "host"
    config.profile = {"enabled": False}
    a = Agent(config)
    a.start()
    try:
        assert a.server.runtime_observatory._thread is None  # never started
        status, _ = _get(a, "/v1/agent/profile")
        assert status == 404
        status, _ = _get(a, "/v1/agent/runtime")
        assert status == 404
        # The metrics body reports the observatory off, not an error.
        status, body = _get(a, "/v1/agent/metrics")
        assert status == 200
        assert json.loads(body)["runtime"] is None
    finally:
        a.shutdown()
