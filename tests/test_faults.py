"""Deterministic fault injection + graceful degradation.

Unit tier: the registry's seeded decision streams (the reproducibility
contract), the shared backoff/retry policy, the circuit breaker's state
machine, and each named site's injection semantics.

Chaos tier: seeded end-to-end scenarios over real clusters — 20% RPC drop
under load, a one-way leader partition mid-plan, and device death
mid-solve — asserting the exactly-once invariants the reference's failure
machinery exists for: no placement lost or duplicated, every eval reaches
a terminal status (or the _failed reaper), no node overcommitted.
Reference posture: nomad/eval_broker.go nack/delivery-limit redelivery,
nomad/plan_apply.go serialized verification, nomad/leader.go failover.
"""

import os
import time

import pytest

from nomad_tpu import faults, mock, structs, telemetry
from nomad_tpu.backoff import Backoff, CircuitBreaker, retry_undelivered
from nomad_tpu.rpc import (
    ConnPool,
    RPCError,
    RPCServer,
    RPCTimeoutError,
    RPCUndeliveredError,
    RemoteError,
)

CHAOS_SEED = int(os.environ.get("NOMAD_TPU_CHAOS_SEED", "0"))


@pytest.fixture(autouse=True)
def _clean_registry():
    """The registry is process-global (like telemetry): every test starts
    and ends unarmed, and the device breaker is force-closed so a tripped
    state can't leak across tests."""
    faults.get_registry().clear()
    yield
    faults.get_registry().clear()
    from nomad_tpu.scheduler import DEVICE_BREAKER

    DEVICE_BREAKER.reset()


# ---------------------------------------------------------------------------
# Registry: determinism, scoping, lifecycle
# ---------------------------------------------------------------------------


def test_registry_same_seed_same_decisions():
    """The acceptance contract: with a fixed seed the n-th check at a site
    decides identically, run after run — the per-site decision trace is a
    pure function of (seed, site, ordinal)."""
    def trace_of(seed):
        reg = faults.FaultRegistry(seed=seed)
        for site in ("rpc.send", "raft.append", "broker.dequeue"):
            reg.configure(site, mode="drop", probability=0.3)
        return {
            site: [bool(reg.check(site, "t")) for _ in range(50)]
            for site in ("rpc.send", "raft.append", "broker.dequeue")
        }

    t1, t2 = trace_of(1234), trace_of(1234)
    assert t1 == t2
    # Sites draw from independent streams: traces differ across sites.
    assert len({tuple(v) for v in t1.values()}) > 1
    # And a different seed produces a different plan.
    assert trace_of(99) != t1


def test_registry_site_isolation():
    """Adding a rule at one site must not shift another site's decision
    sequence (the site-salted seed contract)."""
    reg1 = faults.FaultRegistry(seed=7)
    reg1.configure("rpc.send", mode="drop", probability=0.5)
    solo = [bool(reg1.check("rpc.send")) for _ in range(30)]

    reg2 = faults.FaultRegistry(seed=7)
    reg2.configure("rpc.send", mode="drop", probability=0.5)
    reg2.configure("fsm.apply", mode="delay", delay=0.001)
    interleaved = []
    for _ in range(30):
        reg2.check("fsm.apply")
        interleaved.append(bool(reg2.check("rpc.send")))
    assert interleaved == solo


def test_rule_count_duration_match():
    reg = faults.FaultRegistry()
    reg.configure("rpc.send", mode="drop", count=2)
    assert [bool(reg.check("rpc.send")) for _ in range(4)] == [
        True, True, False, False,
    ]
    reg.clear()
    reg.configure("rpc.send", mode="drop", duration=0.05)
    assert reg.check("rpc.send") is not None
    time.sleep(0.08)
    assert reg.check("rpc.send") is None
    reg.clear()
    # match scopes to an edge: only matching targets fire, but each check
    # still consumes one draw (ordinal alignment).
    reg.configure("raft.append", mode="drop", match="a->b")
    assert reg.check("raft.append", "a->b") is not None
    assert reg.check("raft.append", "a->c") is None
    assert reg.check("raft.append", "b->a") is None


def test_exhausted_rules_retire_but_keep_forensics():
    """Once every rule spends its count/duration budget the registry
    deactivates (fire() back to one global read, no lock) while
    snapshot() keeps the spent rules' fired counts for the chaos run's
    forensics."""
    reg = faults.FaultRegistry()
    reg.configure("rpc.send", mode="drop", count=2)
    assert reg.active
    assert reg.check("rpc.send") is not None
    assert reg.check("rpc.send") is not None
    assert not reg.active  # budget spent on the firing check itself
    assert reg.check("rpc.send") is None
    snap = reg.snapshot()
    assert snap["sites"]["rpc.send"][0]["fired"] == 2  # forensics kept
    reg.clear("rpc.send")
    assert reg.snapshot()["sites"] == {}


def test_load_validates_atomically():
    reg = faults.FaultRegistry()
    with pytest.raises(ValueError):
        reg.load({"sites": {"rpc.send": {"mode": "drop"},
                            "no.such.site": {"mode": "drop"}}})
    # Nothing armed: the good site must not have been half-applied.
    assert not reg.active
    with pytest.raises(ValueError):
        reg.load({"sites": {"rpc.send": {"mode": "frobnicate"}}})
    reg.load({"seed": 3, "sites": {
        "rpc.send": [{"mode": "drop", "probability": 0.5},
                     {"mode": "delay", "delay": 0.001}],
    }})
    snap = reg.snapshot()
    assert snap["seed"] == 3 and len(snap["sites"]["rpc.send"]) == 2


def test_fire_counts_telemetry():
    faults.get_registry().load(
        {"sites": {"broker.dequeue": {"mode": "error", "count": 1}}}
    )
    assert faults.fire("broker.dequeue") is not None
    assert faults.fire("broker.dequeue") is None
    sink = telemetry.get_global().sink
    if hasattr(sink, "cumulative"):
        counters, _ = sink.cumulative()
        assert any("faults.broker.dequeue.error" in k for k in counters)


# ---------------------------------------------------------------------------
# Backoff + retry policy
# ---------------------------------------------------------------------------


def test_backoff_growth_cap_and_jitter():
    from random import Random

    bo = Backoff(base=0.1, max_delay=0.5, factor=2.0, jitter=0.0)
    assert [bo.next_delay() for _ in range(4)] == [0.1, 0.2, 0.4, 0.5]
    bo.reset()
    assert bo.next_delay() == 0.1

    jittered = Backoff(base=0.1, max_delay=10.0, jitter=0.5,
                       rng=Random(42))
    for n in range(6):
        d = jittered.next_delay()
        full = 0.1 * (2.0 ** n)
        assert 0.5 * full <= d <= full


def test_backoff_deadline():
    bo = Backoff(base=0.01, max_delay=0.02, deadline=0.05)
    t0 = time.monotonic()
    while bo.sleep():
        pass
    assert bo.expired
    assert time.monotonic() - t0 < 1.0


def test_retry_undelivered_policy():
    """ONLY provably-undelivered failures replay (rpc.py:78-88): the
    undelivered path retries to success; timeout and remote errors
    surface immediately."""
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise RPCUndeliveredError("nope")
        return "ok"

    assert retry_undelivered(
        flaky, retries=3, backoff=Backoff(base=0.001, max_delay=0.002)
    ) == "ok"
    assert calls["n"] == 3

    def timed_out():
        calls["n"] += 1
        raise RPCTimeoutError("maybe executed")

    calls["n"] = 0
    with pytest.raises(RPCTimeoutError):
        retry_undelivered(timed_out, retries=3)
    assert calls["n"] == 1  # never retried


# ---------------------------------------------------------------------------
# Circuit breaker
# ---------------------------------------------------------------------------


def test_breaker_trip_halfopen_recover():
    br = CircuitBreaker(threshold=3, cooldown=0.05, name=("t", "breaker"))
    assert br.state == "closed" and br.allow()
    br.record_failure()
    br.record_failure()
    assert br.state == "closed"  # below threshold
    br.record_failure()
    assert br.state == "open" and not br.allow()
    time.sleep(0.06)
    # First caller after cooldown gets the half-open probe; others wait.
    assert br.allow() and br.state == "half_open"
    assert not br.allow()
    br.record_success()
    assert br.state == "closed" and br.allow()


def test_breaker_failed_probe_reopens_with_longer_cooldown():
    br = CircuitBreaker(threshold=1, cooldown=0.05, name=("t", "breaker2"))
    br.record_failure()
    assert br.state == "open"
    time.sleep(0.06)
    assert br.allow()  # half-open probe
    br.record_failure()
    assert br.state == "open"
    # Cooldown doubled: the original 0.05 is no longer enough.
    time.sleep(0.06)
    assert not br.allow()
    time.sleep(0.06)
    assert br.allow()


def test_backoff_and_cooldown_never_overflow():
    """A worker soaking a no-leader period for hours keeps counting
    attempts; float 2.0**1024 raises OverflowError — the exponent caps."""
    bo = Backoff(base=0.001, max_delay=0.01, jitter=0.0)
    bo.attempts = 5000
    assert bo.next_delay() == 0.01
    br = CircuitBreaker(threshold=1, cooldown=0.1, max_cooldown=5.0,
                        name=("t", "breaker_ovf"))
    br._trips = 5000
    assert br._current_cooldown() == 5.0


def test_host_side_bug_does_not_feed_breaker(monkeypatch):
    """Only device-class errors (RuntimeError/OSError + DeviceFault) count
    toward the breaker: a deterministic host-side bug must propagate and
    fail loudly, not silently reroute every eval to the host path."""
    from sched_harness import Harness

    from nomad_tpu import mock as mock_mod
    from nomad_tpu.scheduler import DEVICE_BREAKER
    from nomad_tpu.structs import EVAL_TRIGGER_JOB_REGISTER, Evaluation, \
        generate_uuid
    from nomad_tpu.tpu import solver as solver_mod

    def boom(*a, **k):
        raise TypeError("host-side staging bug")

    monkeypatch.setattr(solver_mod, "solve_many_async", boom)
    DEVICE_BREAKER.reset()
    h = Harness()
    node = mock_mod.node()
    h.state.upsert_node(h.next_index(), node)
    job = mock_mod.job()
    h.state.upsert_job(h.next_index(), job)
    ev = Evaluation(
        id=generate_uuid(), priority=job.priority, type=job.type,
        triggered_by=EVAL_TRIGGER_JOB_REGISTER, job_id=job.id,
    )
    with pytest.raises(TypeError, match="host-side staging bug"):
        h.process(f"tpu-{job.type}", ev)
    assert DEVICE_BREAKER.stats()["consecutive_failures"] == 0
    assert DEVICE_BREAKER.state == "closed"


def test_breaker_success_resets_consecutive_count():
    br = CircuitBreaker(threshold=2, cooldown=60.0, name=("t", "breaker3"))
    br.record_failure()
    br.record_success()
    br.record_failure()
    assert br.state == "closed"  # never two CONSECUTIVE failures


# ---------------------------------------------------------------------------
# Site semantics: rpc.send / rpc.recv
# ---------------------------------------------------------------------------


@pytest.fixture
def echo_server():
    srv = RPCServer()
    hits = []

    def echo(args):
        hits.append(args)
        return {"hi": args.get("name")}

    srv.register("Echo.Hello", echo)
    srv.start()
    pool = ConnPool(timeout=1.0)
    yield srv, pool, hits
    pool.shutdown()
    srv.shutdown()


def test_rpc_send_drop_is_undelivered(echo_server):
    srv, pool, hits = echo_server
    faults.get_registry().configure("rpc.send", mode="drop", count=1)
    with pytest.raises(RPCUndeliveredError):
        pool.call(srv.addr, "Echo.Hello", {"name": "x"})
    assert hits == []  # provably never dispatched
    # Rule exhausted: traffic flows again.
    assert pool.call(srv.addr, "Echo.Hello", {"name": "y"})["hi"] == "y"


def test_rpc_send_error_and_partition_match(echo_server):
    srv, pool, _ = echo_server
    faults.get_registry().configure("rpc.send", mode="error", count=1)
    with pytest.raises(RPCError):
        pool.call(srv.addr, "Echo.Hello", {"name": "x"})
    # A partition matched to a different address never fires here.
    faults.get_registry().clear()
    faults.get_registry().configure(
        "rpc.send", mode="partition", match="203.0.113.9:1"
    )
    assert pool.call(srv.addr, "Echo.Hello", {"name": "z"})["hi"] == "z"


def test_rpc_recv_drop_times_out_after_executing(echo_server):
    """The possibly-executed half of the distinction: the handler RUNS but
    the response is lost — the caller sees RPCTimeoutError, which the
    retry policy must never blindly replay."""
    srv, pool, hits = echo_server
    faults.get_registry().configure("rpc.recv", mode="drop", count=1)
    with pytest.raises(RPCTimeoutError):
        pool.call(srv.addr, "Echo.Hello", {"name": "x"}, timeout=0.3)
    deadline = time.monotonic() + 2.0
    while not hits and time.monotonic() < deadline:
        time.sleep(0.01)
    assert len(hits) == 1  # it DID execute


def test_rpc_recv_error_skips_handler(echo_server):
    srv, pool, hits = echo_server
    faults.get_registry().configure("rpc.recv", mode="error", count=1)
    with pytest.raises(RemoteError, match="injected"):
        pool.call(srv.addr, "Echo.Hello", {"name": "x"})
    assert hits == []


def test_rpc_recv_partition_is_silent_loss(echo_server):
    """Partition at the receiver = the request silently never arrives:
    handler NOT run, no error frame — the caller just times out, like
    every other site's partition semantics (never a fast explicit
    error)."""
    srv, pool, hits = echo_server
    faults.get_registry().configure("rpc.recv", mode="partition", count=1)
    with pytest.raises(RPCTimeoutError):
        pool.call(srv.addr, "Echo.Hello", {"name": "x"}, timeout=0.3)
    time.sleep(0.1)
    assert hits == []  # never dispatched


def test_call_retry_replays_only_undelivered(echo_server):
    srv, pool, hits = echo_server
    faults.get_registry().configure("rpc.send", mode="drop", count=2)
    out = pool.call_retry(srv.addr, "Echo.Hello", {"name": "r"}, retries=3)
    assert out["hi"] == "r" and len(hits) == 1
    faults.get_registry().clear()
    faults.get_registry().configure("rpc.recv", mode="drop", count=1)
    with pytest.raises(RPCTimeoutError):
        pool.call_retry(srv.addr, "Echo.Hello", {"name": "t"}, timeout=0.3)


# ---------------------------------------------------------------------------
# Site semantics: broker / heartbeat / fsm
# ---------------------------------------------------------------------------


def test_broker_dequeue_fault_raises_broker_error():
    from nomad_tpu.server.eval_broker import BrokerError, EvalBroker

    broker = EvalBroker(nack_timeout=5.0)
    broker.set_enabled(True)
    faults.get_registry().configure("broker.dequeue", mode="error", count=1)
    with pytest.raises(BrokerError, match="injected"):
        broker.dequeue(["service"], timeout=0.1)
    assert broker.dequeue(["service"], timeout=0.05) == (None, "")
    broker.set_enabled(False)


def test_heartbeat_tick_drop_skips_renewal():
    from nomad_tpu.server.heartbeat import HeartbeatManager

    class _Cfg:
        min_heartbeat_ttl = 10.0
        max_heartbeats_per_second = 50.0
        seed = 0  # feeds the deterministic TTL-jitter fraction

    class _Srv:
        config = _Cfg()

        class logger:
            warning = staticmethod(lambda *a, **k: None)

    hb = HeartbeatManager(_Srv())
    faults.get_registry().configure("heartbeat.tick", mode="drop", count=1)
    # The INITIAL arm is never droppable: without it no TTL timer exists
    # to expire, which would be the opposite of a missed beat.
    ttl = hb.reset_heartbeat_timer("node-1")
    assert ttl >= 10.0 and hb.num_timers() == 1
    # The renewal IS droppable: the armed timer keeps running toward
    # expiry instead of being re-armed.
    first_timer = hb._timers["node-1"]
    assert hb.reset_heartbeat_timer("node-1") == 0.0
    assert hb._timers["node-1"] is first_timer  # not re-armed
    # Rule exhausted: renewals re-arm again.
    assert hb.reset_heartbeat_timer("node-1") >= 10.0
    assert hb._timers["node-1"] is not first_timer
    hb.clear_all()


def test_fsm_apply_delay_only():
    from nomad_tpu.server.fsm import FSM

    fsm = FSM()
    faults.get_registry().configure(
        "fsm.apply", mode="delay", delay=0.05, count=1
    )
    t0 = time.perf_counter()
    fsm.apply(1, "node_register", {"node": mock.node()})
    assert time.perf_counter() - t0 >= 0.05
    # 'error' at this site is REJECTED at arm time (SITE_MODES): an
    # injected per-replica error would diverge a deterministic FSM, and
    # an armed-but-inert rule would fake its fire counts.
    with pytest.raises(ValueError, match="does not honor"):
        faults.get_registry().configure("fsm.apply", mode="error")
    with pytest.raises(ValueError, match="does not honor"):
        faults.get_registry().load(
            {"sites": {"raft.append": {"mode": "error"}}}
        )


def test_faults_config_block_flows_to_agent_config():
    """agent_config faults{} HCL block -> FileConfig -> AgentConfig spec
    (the shape Agent.start arms the registry with)."""
    from nomad_tpu.agent import AgentConfig
    from nomad_tpu.agent_config import parse_config

    fc = parse_config("""
    faults {
      seed = 7
      sites {
        "rpc.send" = {
          mode = "drop"
          probability = 0.25
        }
      }
    }
    """)
    assert fc.faults.seed == 7
    ac = AgentConfig.from_file_config(fc)
    assert ac.faults == {
        "seed": 7,
        "sites": {"rpc.send": {"mode": "drop", "probability": 0.25}},
    }
    # The spec loads cleanly into a registry (what Agent.start does).
    reg = faults.FaultRegistry()
    reg.load(ac.faults)
    assert reg.snapshot()["sites"]["rpc.send"][0]["probability"] == 0.25
    # Merge: a later file overrides a site wholesale, keeps others.
    fc2 = parse_config("""
    faults {
      sites {
        "rpc.send" = { mode = "delay"
                       delay = 0.01 }
        "fsm.apply" = { mode = "delay"
                        delay = 0.02 }
      }
    }
    """)
    merged = fc.merge(fc2)
    assert merged.faults.seed == 7
    assert merged.faults.sites["rpc.send"]["mode"] == "delay"
    assert "fsm.apply" in merged.faults.sites


# ---------------------------------------------------------------------------
# /v1/agent/faults endpoint + metrics visibility
# ---------------------------------------------------------------------------


def test_agent_faults_endpoint_debug_gated():
    import json
    from urllib.error import HTTPError
    from urllib.request import Request, urlopen

    from nomad_tpu.api.http import HTTPServer
    from nomad_tpu.telemetry import InmemSink

    class FakeAgent:
        server = None
        inmem_sink = InmemSink()

        def __init__(self):
            self.debug = False

        def debug_enabled(self):
            return self.debug

    agent = FakeAgent()
    http = HTTPServer(agent, port=0)
    http.start()
    try:
        base = http.addr
        with pytest.raises(HTTPError) as exc:
            urlopen(f"{base}/v1/agent/faults")
        assert exc.value.code == 404  # gated off

        agent.debug = True
        spec = {"seed": 11, "sites": {
            "rpc.send": {"mode": "drop", "probability": 0.5},
        }}
        req = Request(f"{base}/v1/agent/faults", method="PUT",
                      data=json.dumps(spec).encode(),
                      headers={"Content-Type": "application/json"})
        body = json.loads(urlopen(req).read())
        assert body["seed"] == 11 and "rpc.send" in body["sites"]

        # A bad site 400s and arms nothing new.
        bad = Request(f"{base}/v1/agent/faults", method="PUT",
                      data=b'{"sites": {"bogus.site": {}}}')
        with pytest.raises(HTTPError) as exc:
            urlopen(bad)
        assert exc.value.code == 400

        body = json.loads(urlopen(f"{base}/v1/agent/faults").read())
        assert list(body["sites"]) == ["rpc.send"]

        # PUT is REPLACE, not merge: a second plan disarms unnamed sites
        # (two sequential experiments must not contaminate each other).
        plan_b = Request(f"{base}/v1/agent/faults", method="PUT",
                         data=json.dumps({"sites": {
                             "solver.execute": {"mode": "error"},
                         }}).encode())
        body = json.loads(urlopen(plan_b).read())
        assert list(body["sites"]) == ["solver.execute"]

        clear = Request(f"{base}/v1/agent/faults", method="DELETE")
        body = json.loads(urlopen(clear).read())
        assert body["sites"] == {} and body["active"] is False
    finally:
        http.shutdown()


def test_injected_faults_and_breaker_visible_in_metrics():
    """Acceptance: injected-fault counts and breaker transitions land in
    the /v1/agent/metrics surface (the InmemSink exposition)."""
    import json
    from urllib.request import urlopen

    from nomad_tpu.api.http import HTTPServer
    from nomad_tpu.scheduler import DEVICE_BREAKER
    from nomad_tpu.telemetry import InmemSink, Metrics, prometheus_text

    sink = InmemSink()
    old = telemetry.get_global()
    telemetry.set_global(Metrics(sink, service="nomad"))
    try:
        faults.get_registry().configure(
            "solver.execute", mode="error", count=3
        )
        for _ in range(3):
            assert faults.fire("solver.execute") is not None
        saved = DEVICE_BREAKER.threshold
        DEVICE_BREAKER.threshold = 2
        try:
            DEVICE_BREAKER.record_failure()
            DEVICE_BREAKER.record_failure()
            assert DEVICE_BREAKER.state == "open"
        finally:
            DEVICE_BREAKER.threshold = saved

        class FakeAgent:
            server = None
            inmem_sink = sink
            debug_enabled = staticmethod(lambda: False)

        http = HTTPServer(FakeAgent(), port=0)
        http.start()
        try:
            doc = json.loads(urlopen(f"{http.addr}/v1/agent/metrics").read())
            counters = {}
            gauges = {}
            for ivl in doc["intervals"]:
                counters.update(ivl["counters"])
                gauges.update(ivl["gauges"])
            assert counters["nomad.faults.solver.execute.error"]["sum"] == 3
            assert counters["nomad.solver.breaker.to_open"]["sum"] >= 1
            assert gauges["nomad.solver.breaker.state"] == 2  # open
        finally:
            http.shutdown()
        # And the Prometheus exposition carries the same series.
        text = prometheus_text(sink)
        assert "nomad_faults_solver_execute_error_total" in text
        assert "nomad_solver_breaker_state" in text
    finally:
        DEVICE_BREAKER.reset()
        telemetry.set_global(old)


# ---------------------------------------------------------------------------
# Chaos tier
# ---------------------------------------------------------------------------


def _register_cluster_state(leader, n_nodes, n_jobs):
    from cluster_util import retry_write

    nodes = [mock.node() for _ in range(n_nodes)]
    for node in nodes:
        retry_write(lambda n=node: leader.node_register(n))
    jobs, eval_ids = [], []
    for _ in range(n_jobs):
        job = mock.job()
        ev_id, _ = retry_write(lambda j=job: leader.job_register(j))
        jobs.append(job)
        eval_ids.append(ev_id)
    return nodes, jobs, eval_ids


def _assert_exactly_once(store, nodes, jobs, eval_ids, deadline_s=60.0):
    """Every eval terminal; every job placed exactly count times (live);
    no node overcommitted — the chaos invariants."""
    deadline = time.monotonic() + deadline_s

    def _terminal():
        for ev_id in eval_ids:
            ev = store.eval_by_id(ev_id)
            if ev is None or not ev.terminal_status():
                return False
        return True

    while time.monotonic() < deadline and not _terminal():
        time.sleep(0.1)
    assert _terminal(), [
        (i[:8], getattr(store.eval_by_id(i), "status", None))
        for i in eval_ids
    ]

    def _placed():
        for job in jobs:
            live = structs.filter_terminal_allocs(store.allocs_by_job(job.id))
            if len(live) != job.task_groups[0].count:
                return False
        return True

    while time.monotonic() < deadline and not _placed():
        time.sleep(0.1)
    for job in jobs:
        live = structs.filter_terminal_allocs(store.allocs_by_job(job.id))
        assert len(live) == job.task_groups[0].count, (
            job.id, len(live), job.task_groups[0].count,
        )

    node_by_id = {n.id: n for n in nodes}
    used = {}
    for job in jobs:
        for a in structs.filter_terminal_allocs(store.allocs_by_job(job.id)):
            cpu, mem = used.get(a.node_id, (0, 0))
            used[a.node_id] = (cpu + a.resources.cpu,
                               mem + a.resources.memory_mb)
    for nid, (cpu, mem) in used.items():
        node = node_by_id[nid]
        res, reserved = node.resources, node.reserved
        assert cpu <= res.cpu - (reserved.cpu if reserved else 0), nid
        assert mem <= res.memory_mb - (
            reserved.memory_mb if reserved else 0
        ), nid


def test_chaos_rpc_drop_20pct_under_load():
    """20% of ALL outbound RPC frames dropped (provably-undelivered) while
    a burst of service jobs schedules across a 3-server cluster: raft
    retries, forwarding retries, and broker redelivery must together
    deliver exactly-once placement."""
    from cluster_util import relaxed_cluster_cfg, retry_write
    from nomad_tpu.server import ServerConfig
    from nomad_tpu.server.cluster import form_cluster, wait_for_leader

    # N>=4 concurrent workers per server: exactly-once must hold with
    # the optimistic plan pipeline resolving their contending plans
    # while frames drop / leaders fall.
    servers = form_cluster(3, ServerConfig(
        scheduler_backend="host", scheduler_workers=4,
        min_heartbeat_ttl=300.0,
    ), base_cluster=relaxed_cluster_cfg())
    try:
        leader = wait_for_leader(servers)
        nodes, jobs, eval_ids = _register_cluster_state(leader, 12, 4)

        faults.get_registry().load({"seed": CHAOS_SEED, "sites": {
            "rpc.send": {"mode": "drop", "probability": 0.2,
                         "duration": 20.0},
        }})
        # More load lands WHILE frames are dropping.
        for _ in range(2):
            job = mock.job()
            ev_id, _ = retry_write(lambda j=job: leader.job_register(j),
                                   timeout=30.0)
            jobs.append(job)
            eval_ids.append(ev_id)

        _assert_exactly_once(
            leader.state_store, nodes, jobs, eval_ids, deadline_s=60.0,
        )
        snap = faults.get_registry().snapshot()
        assert snap["sites"]["rpc.send"][0]["fired"] > 0  # it really dropped
    finally:
        faults.get_registry().clear()
        for srv in servers:
            srv.shutdown()


def test_chaos_leader_partition_mid_plan():
    """One-way partition of the leader's OUTBOUND raft traffic while its
    brokered evals are mid-flight: it can no longer commit plans; the
    survivors elect a new leader whose restored broker must finish every
    eval exactly once. Heal, then the cluster serves new work."""
    from cluster_util import relaxed_cluster_cfg, retry_write
    from nomad_tpu.server import ServerConfig
    from nomad_tpu.server.cluster import form_cluster, wait_for_leader

    # N>=4 concurrent workers per server: exactly-once must hold with
    # the optimistic plan pipeline resolving their contending plans
    # while frames drop / leaders fall.
    servers = form_cluster(3, ServerConfig(
        scheduler_backend="host", scheduler_workers=4,
        min_heartbeat_ttl=300.0,
    ), base_cluster=relaxed_cluster_cfg())
    try:
        leader = wait_for_leader(servers)
        nodes, jobs, eval_ids = _register_cluster_state(leader, 12, 4)

        # Partition mid-plan: evals just registered are being scheduled.
        old_id = leader.cluster.node_id
        faults.get_registry().load({"seed": CHAOS_SEED, "sites": {
            "raft.append": {"mode": "partition", "match": f"{old_id}->"},
            "raft.vote": {"mode": "partition", "match": f"{old_id}->"},
        }})

        survivors = [s for s in servers if s is not leader]
        deadline = time.monotonic() + 30.0
        new_leader = None
        while time.monotonic() < deadline:
            live = [s for s in survivors if s.raft.is_leader]
            if live:
                new_leader = live[0]
                break
            time.sleep(0.05)
        assert new_leader is not None, "no survivor took leadership"

        _assert_exactly_once(
            new_leader.state_store, nodes, jobs, eval_ids, deadline_s=60.0,
        )

        # Heal the partition: the deposed leader rejoins as follower and
        # the cluster serves new work end-to-end.
        faults.get_registry().clear()
        job2 = mock.job()
        ev2_id, _ = retry_write(
            lambda: new_leader.job_register(job2), timeout=30.0
        )
        ev2 = new_leader.wait_for_eval(ev2_id, timeout=30.0)
        assert ev2.status == structs.EVAL_STATUS_COMPLETE
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline and leader.raft.is_leader:
            time.sleep(0.05)
        assert not leader.raft.is_leader
    finally:
        faults.get_registry().clear()
        for srv in servers:
            srv.shutdown()


def test_chaos_device_death_mid_solve_trips_breaker():
    """Persistent device death at solver.execute: the first deliveries
    fail and feed the breaker; once it trips, redeliveries route to the
    host-oracle path and the eval completes — no eval lost to a dead
    device. Clearing the fault and waiting out the cooldown, a half-open
    probe closes the breaker on the next eval."""
    from nomad_tpu.scheduler import DEVICE_BREAKER
    from nomad_tpu.server import Server, ServerConfig

    saved = (DEVICE_BREAKER.threshold, DEVICE_BREAKER.cooldown)
    DEVICE_BREAKER.threshold, DEVICE_BREAKER.cooldown = 3, 0.5
    DEVICE_BREAKER.reset()
    srv = Server(ServerConfig(
        scheduler_backend="tpu", num_schedulers=1, eval_batch_size=1,
        eval_delivery_limit=6, prewarm_shapes=False,
    ))
    try:
        srv.start()
        nodes = [mock.node() for _ in range(6)]
        for node in nodes:
            srv.node_register(node)

        faults.get_registry().load({"seed": CHAOS_SEED, "sites": {
            "solver.execute": {"mode": "error"},
        }})
        job = mock.job()
        ev_id, _ = srv.job_register(job)
        ev = srv.wait_for_eval(ev_id, timeout=60.0)
        assert ev.status == structs.EVAL_STATUS_COMPLETE
        live = structs.filter_terminal_allocs(
            srv.state_store.allocs_by_job(job.id)
        )
        assert len(live) == job.task_groups[0].count  # exactly once
        assert DEVICE_BREAKER.state == "open"
        snap = faults.get_registry().snapshot()
        fired = snap["sites"]["solver.execute"][0]["fired"]
        assert fired >= DEVICE_BREAKER.threshold

        # Device "revives": after the cooldown the next eval is the
        # half-open probe; its successful solve closes the breaker.
        faults.get_registry().clear()
        time.sleep(0.6)
        job2 = mock.job()
        ev2_id, _ = srv.job_register(job2)
        ev2 = srv.wait_for_eval(ev2_id, timeout=60.0)
        assert ev2.status == structs.EVAL_STATUS_COMPLETE
        assert DEVICE_BREAKER.state == "closed"
        live2 = structs.filter_terminal_allocs(
            srv.state_store.allocs_by_job(job2.id)
        )
        assert len(live2) == job2.task_groups[0].count
    finally:
        faults.get_registry().clear()
        DEVICE_BREAKER.threshold, DEVICE_BREAKER.cooldown = saved
        DEVICE_BREAKER.reset()
        srv.shutdown()
        from nomad_tpu.ops.coalesce import quiesce_all

        quiesce_all(timeout=15.0)
