"""Black-box test server: fork the real CLI agent and speak HTTP to it.

Reference: /root/reference/testutil/server.go — forks the ``nomad`` binary
found on $PATH with a generated config in dev mode, auto-increments ports
on bind conflicts, and waits on ``/v1/agent/self`` for a leader before
handing the server to the test (server.go:NewTestServer, :105-107 skips
when the binary is absent).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time
import urllib.error
import urllib.request

_next_port = [20646]


def _alloc_port() -> int:
    _next_port[0] += 1
    return _next_port[0]


class ForkedAgent:
    """Forked ``nomad-tpu agent`` with its own HTTP port (dev mode by
    default; pass ``agent_args`` to run a config-file agent instead —
    the caller then owns port selection and must pass ``http_port``)."""

    def __init__(self, timeout: float = 60.0, agent_args=None,
                 http_port=None):
        from nomad_tpu.discover import nomad_command

        if agent_args is not None and http_port is None:
            raise ValueError(
                "http_port is required with agent_args: the config-file "
                "agent binds the config's port, not an allocated one"
            )
        self.port = http_port if http_port is not None else _alloc_port()
        self.addr = f"http://127.0.0.1:{self.port}"
        repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        env = {**os.environ, "PYTHONPATH": repo_root, "JAX_PLATFORMS": "cpu"}
        if agent_args is None:
            agent_args = [
                "-dev",
                "-http-port", str(self.port),
                "-scheduler-backend", "host",
                "-log-level", "WARN",
            ]
        self.proc = subprocess.Popen(
            nomad_command() + ["agent"] + list(agent_args),
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            env=env,
            text=True,
        )
        self._wait_ready(timeout)

    def _wait_ready(self, timeout: float) -> None:
        deadline = time.monotonic() + timeout
        last_err = None
        while time.monotonic() < deadline:
            if self.proc.poll() is not None:
                out = self.proc.stdout.read() if self.proc.stdout else ""
                raise RuntimeError(
                    f"agent exited early ({self.proc.returncode}): {out[-2000:]}"
                )
            try:
                info = self.http_get("/v1/agent/self")
                if info.get("stats", {}).get("server"):
                    return
            except (urllib.error.URLError, OSError, ValueError) as e:
                last_err = e
            time.sleep(0.2)
        self.stop()
        raise TimeoutError(f"agent not ready after {timeout}s: {last_err}")

    def http_get(self, path: str):
        with urllib.request.urlopen(self.addr + path, timeout=5) as resp:
            return json.loads(resp.read().decode())

    def http_put(self, path: str, body) -> dict:
        req = urllib.request.Request(
            self.addr + path,
            data=json.dumps(body).encode(),
            method="PUT",
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=5) as resp:
            return json.loads(resp.read().decode())

    def stop(self) -> None:
        if self.proc.poll() is None:
            self.proc.terminate()
            try:
                self.proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                self.proc.kill()
                self.proc.wait(timeout=5)
