"""Compiled (Mosaic) pallas kernel proof — gated on a real TPU backend.

The interpret-mode differential suite proves kernel semantics on CPU;
this file proves the compiled artifact when run where a TPU exists
(`NOMAD_TPU_PALLAS=compiled`, real lowering + execution). Under the
normal suite the conftest pins the cpu backend, so these skip — the same
environment-gating posture as the reference's docker/rkt driver tests
(/root/reference/client/driver/docker_test.go). On hardware the proof
also runs via tools/bench_watch.py the moment the device relay answers.
"""

import os

import jax
import pytest

requires_tpu = pytest.mark.skipif(
    jax.default_backend() not in ("tpu", "axon"),
    reason="compiled pallas needs a TPU backend (suite pins cpu)",
)


@requires_tpu
def test_compiled_pallas_differential_and_timing(monkeypatch):
    import sys

    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"))
    from pallas_proof import run_proof

    # run_proof setdefaults this env var; pin it via monkeypatch so the
    # mutation is undone after the test.
    monkeypatch.setenv("NOMAD_TPU_PALLAS", "compiled")

    report = run_proof(shapes=((64, 1), (1024, 1), (1024, 4)), seeds=3)
    assert report["ok"], report
    assert report["lowered_shapes"] >= 1
    for row in report["shapes"]:
        assert row.get("mismatched", 0) == 0, row


@requires_tpu
def test_coalescer_proves_compiled_kernel(monkeypatch):
    """End-to-end: the production coalescer dispatches the compiled kernel
    and records the shape as proven (prove-before-trust, coalesce.py)."""
    import numpy as np

    from nomad_tpu.ops import pallas_solve
    from nomad_tpu.ops.coalesce import CoalescingSolver
    from nomad_tpu.ops.binpack import solve_waterfill
    from test_pallas_solve import random_instance

    monkeypatch.setenv("NOMAD_TPU_PALLAS", "compiled")
    saved = (pallas_solve._STATE["failed"], set(pallas_solve._STATE["proven"]))
    pallas_solve.reset_pallas_failed()
    try:
        rng = np.random.default_rng(31)
        args = random_instance(rng, 1024)
        solver = CoalescingSolver()
        fetch = solver.submit(*args[:10], int(args[10]), float(args[11]))
        counts, unplaced = fetch()
        c0, r0 = solve_waterfill(*args, False, False)
        np.testing.assert_array_equal(np.asarray(c0), counts)
        assert int(r0) == unplaced
        assert not pallas_solve._STATE["failed"]
        assert len(pallas_solve._STATE["proven"]) >= 1
    finally:
        pallas_solve._STATE["failed"] = saved[0]
        pallas_solve._STATE["proven"] = saved[1]
