"""Ported iterator tests (/root/reference/scheduler/feasible_test.go,
rank_test.go, select_test.go, context_test.go)."""

import logging

from nomad_tpu import mock, structs
from nomad_tpu.scheduler.context import EvalContext
from nomad_tpu.scheduler.feasible import (
    ConstraintIterator,
    DriverIterator,
    ProposedAllocConstraintIterator,
    StaticIterator,
    check_constraint,
    check_lexical_order,
    new_random_iterator,
    resolve_constraint_target,
)
from nomad_tpu.scheduler.rank import (
    BinPackIterator,
    FeasibleRankIterator,
    JobAntiAffinityIterator,
    RankedNode,
    StaticRankIterator,
)
from nomad_tpu.scheduler.select_iter import LimitIterator, MaxScoreIterator
from nomad_tpu.state import StateStore
from nomad_tpu.structs import (
    Allocation,
    Constraint,
    Node,
    Plan,
    Resources,
    Task,
    generate_uuid,
)

logger = logging.getLogger("test")


def make_context():
    """Equivalent of testContext (context_test.go:12-26)."""
    state = StateStore()
    plan = Plan(node_update={}, node_allocation={})
    ctx = EvalContext(state, plan, logger)
    return state, ctx


def collect_feasible(iterator):
    out = []
    while True:
        nxt = iterator.next()
        if nxt is None:
            return out
        out.append(nxt)


def test_static_iterator_reset():
    """feasible_test.go:11-40"""
    _, ctx = make_context()
    nodes = [mock.node() for _ in range(3)]
    static = StaticIterator(ctx, nodes)

    for i in range(len(nodes) * 3):
        if i % 3 == 0:
            static.reset()
        assert static.next() is not None
    static.reset()
    assert len(collect_feasible(static)) == 3


def test_static_iterator_set_nodes():
    """feasible_test.go:42-57"""
    _, ctx = make_context()
    static = StaticIterator(ctx, [mock.node() for _ in range(3)])
    new_nodes = [mock.node()]
    static.set_nodes(new_nodes)
    assert collect_feasible(static) == new_nodes


def test_random_iterator():
    """feasible_test.go:59-77"""
    _, ctx = make_context()
    nodes = [mock.node() for _ in range(10)]
    rand = new_random_iterator(ctx, nodes[:])
    out = collect_feasible(rand)
    assert len(out) == 10
    assert {n.id for n in out} == {n.id for n in nodes}


def test_driver_iterator():
    """feasible_test.go:79-107"""
    _, ctx = make_context()
    nodes = [mock.node() for _ in range(4)]
    nodes[1].attributes["driver.exec"] = "0"
    nodes[2].attributes["driver.exec"] = "true"
    nodes[3].attributes["driver.exec"] = "False"

    static = StaticIterator(ctx, nodes)
    driver = DriverIterator(ctx, static, {"exec"})
    out = collect_feasible(driver)
    assert [n.id for n in out] == [nodes[0].id, nodes[2].id]


def test_constraint_iterator():
    """feasible_test.go:109-142"""
    _, ctx = make_context()
    nodes = [mock.node() for _ in range(3)]
    nodes[0].attributes["kernel.name"] = "freebsd"
    nodes[1].datacenter = "dc2"

    static = StaticIterator(ctx, nodes)
    constraints = [
        Constraint(l_target="$node.datacenter", r_target="dc1", operand="="),
        Constraint(l_target="$attr.kernel.name", r_target="linux", operand="="),
    ]
    it = ConstraintIterator(ctx, static, constraints)
    out = collect_feasible(it)
    assert [n.id for n in out] == [nodes[2].id]


def test_resolve_constraint_target():
    """feasible_test.go:144-209"""
    node = mock.node()
    assert resolve_constraint_target("$node.id", node) == (node.id, True)
    assert resolve_constraint_target("$node.datacenter", node) == (node.datacenter, True)
    assert resolve_constraint_target("$node.name", node) == (node.name, True)
    assert resolve_constraint_target("$attr.kernel.name", node) == ("linux", True)
    assert resolve_constraint_target("$meta.pci-dss", node) == ("true", True)
    assert resolve_constraint_target("literal", node) == ("literal", True)
    assert resolve_constraint_target("$attr.rand", node)[1] is False
    assert resolve_constraint_target("$meta.rand", node)[1] is False
    assert resolve_constraint_target("$bogus.kernel", node)[1] is False


def test_check_constraint():
    """feasible_test.go:211-271"""
    _, ctx = make_context()
    cases = [
        ("=", "foo", "foo", True),
        ("is", "foo", "foo", True),
        ("==", "foo", "foo", True),
        ("!=", "foo", "foo", False),
        ("!=", "foo", "bar", True),
        ("not", "foo", "bar", True),
        (structs.CONSTRAINT_VERSION, "1.2.3", "~> 1.0", True),
        (structs.CONSTRAINT_REGEX, "foobarbaz", "[\\w]+", True),
        ("<", "foo", "bar", False),
        (structs.CONSTRAINT_DISTINCT_HOSTS, "", "", True),
    ]
    for op, l, r, want in cases:
        assert check_constraint(ctx, op, l, r) is want, (op, l, r)


def test_check_lexical_order():
    """feasible_test.go:273-311"""
    assert check_lexical_order("<", "a", "b")
    assert not check_lexical_order("<", "b", "a")
    assert check_lexical_order("<=", "a", "a")
    assert check_lexical_order(">", "b", "a")
    assert check_lexical_order(">=", "b", "b")
    assert not check_lexical_order(">", "a", "b")


def test_proposed_alloc_constraint_job_distinct_hosts():
    """feasible_test.go:383-419"""
    _, ctx = make_context()
    nodes = [mock.node(), mock.node()]
    static = StaticIterator(ctx, nodes)
    it = ProposedAllocConstraintIterator(ctx, static)

    job = mock.job()
    job.constraints.append(Constraint(operand=structs.CONSTRAINT_DISTINCT_HOSTS))
    it.set_job(job)
    it.set_task_group(job.task_groups[0])

    out = collect_feasible(it)
    assert len(out) == 2


def test_proposed_alloc_constraint_job_distinct_hosts_infeasible():
    """feasible_test.go:421-475"""
    _, ctx = make_context()
    nodes = [mock.node(), mock.node()]
    static = StaticIterator(ctx, nodes)
    it = ProposedAllocConstraintIterator(ctx, static)

    job = mock.job()
    job.constraints.append(Constraint(operand=structs.CONSTRAINT_DISTINCT_HOSTS))
    tg = job.task_groups[0]

    # Place proposed allocs of this job on both nodes
    plan = ctx.plan
    plan.node_allocation[nodes[0].id] = [
        Allocation(id=generate_uuid(), job_id=job.id, task_group=tg.name)
    ]
    plan.node_allocation[nodes[1].id] = [
        Allocation(id=generate_uuid(), job_id=job.id, task_group=tg.name)
    ]

    it.set_job(job)
    it.set_task_group(tg)
    assert collect_feasible(it) == []


def test_proposed_alloc_constraint_tg_distinct_hosts():
    """feasible_test.go:507-566"""
    _, ctx = make_context()
    nodes = [mock.node(), mock.node()]
    static = StaticIterator(ctx, nodes)
    it = ProposedAllocConstraintIterator(ctx, static)

    tg1 = mock.job().task_groups[0]
    tg1.name = "example"
    tg1.constraints = [Constraint(operand=structs.CONSTRAINT_DISTINCT_HOSTS)]
    job = mock.job()
    job.id = "foo"
    job.task_groups = [tg1]

    # tg collision on node 0 only
    plan = ctx.plan
    plan.node_allocation[nodes[0].id] = [
        Allocation(id=generate_uuid(), job_id=job.id, task_group=tg1.name)
    ]

    it.set_job(job)
    it.set_task_group(tg1)
    out = collect_feasible(it)
    assert [n.id for n in out] == [nodes[1].id]


def collect_ranked(iterator):
    out = []
    while True:
        nxt = iterator.next()
        if nxt is None:
            return out
        out.append(nxt)


def test_feasible_rank_iterator():
    """rank_test.go:10-24"""
    _, ctx = make_context()
    nodes = [mock.node() for _ in range(10)]
    static = StaticIterator(ctx, nodes)
    feasible = FeasibleRankIterator(ctx, static)
    assert len(collect_ranked(feasible)) == 10


def test_binpack_no_existing_alloc():
    """rank_test.go:26-96"""
    _, ctx = make_context()
    nodes = [
        RankedNode(Node(  # perfect fit
            resources=Resources(cpu=2048, memory_mb=2048),
            reserved=Resources(cpu=1024, memory_mb=1024),
        )),
        RankedNode(Node(  # overloaded
            resources=Resources(cpu=1024, memory_mb=1024),
            reserved=Resources(cpu=512, memory_mb=512),
        )),
        RankedNode(Node(  # 50% fit
            resources=Resources(cpu=4096, memory_mb=4096),
            reserved=Resources(cpu=1024, memory_mb=1024),
        )),
    ]
    static = StaticRankIterator(ctx, nodes)
    task = Task(name="web", resources=Resources(cpu=1024, memory_mb=1024))
    binp = BinPackIterator(ctx, static, False, 0)
    binp.set_tasks([task])

    out = collect_ranked(binp)
    assert len(out) == 2
    assert out[0] is nodes[0] and out[1] is nodes[2]
    assert out[0].score == 18
    assert 10 < out[1].score < 16


def test_binpack_planned_alloc():
    """rank_test.go:98-167"""
    _, ctx = make_context()
    nodes = [
        RankedNode(Node(id=generate_uuid(), resources=Resources(cpu=2048, memory_mb=2048))),
        RankedNode(Node(id=generate_uuid(), resources=Resources(cpu=2048, memory_mb=2048))),
    ]
    static = StaticRankIterator(ctx, nodes)

    plan = ctx.plan
    plan.node_allocation[nodes[0].node.id] = [
        Allocation(resources=Resources(cpu=2048, memory_mb=2048))
    ]
    plan.node_allocation[nodes[1].node.id] = [
        Allocation(resources=Resources(cpu=1024, memory_mb=1024))
    ]

    task = Task(name="web", resources=Resources(cpu=1024, memory_mb=1024))
    binp = BinPackIterator(ctx, static, False, 0)
    binp.set_tasks([task])

    out = collect_ranked(binp)
    assert len(out) == 1
    assert out[0] is nodes[1]
    assert out[0].score == 18


def test_binpack_existing_alloc():
    """rank_test.go:169-241"""
    state, ctx = make_context()
    nodes = [
        RankedNode(Node(id=generate_uuid(), resources=Resources(cpu=2048, memory_mb=2048))),
        RankedNode(Node(id=generate_uuid(), resources=Resources(cpu=2048, memory_mb=2048))),
    ]
    static = StaticRankIterator(ctx, nodes)

    alloc1 = Allocation(
        id=generate_uuid(), eval_id=generate_uuid(), node_id=nodes[0].node.id,
        job_id=generate_uuid(), resources=Resources(cpu=2048, memory_mb=2048),
        desired_status=structs.ALLOC_DESIRED_STATUS_RUN,
    )
    alloc2 = Allocation(
        id=generate_uuid(), eval_id=generate_uuid(), node_id=nodes[1].node.id,
        job_id=generate_uuid(), resources=Resources(cpu=1024, memory_mb=1024),
        desired_status=structs.ALLOC_DESIRED_STATUS_RUN,
    )
    state.upsert_allocs(1000, [alloc1, alloc2])

    task = Task(name="web", resources=Resources(cpu=1024, memory_mb=1024))
    binp = BinPackIterator(ctx, static, False, 0)
    binp.set_tasks([task])

    out = collect_ranked(binp)
    assert len(out) == 1
    assert out[0] is nodes[1]
    assert out[0].score == 18


def test_binpack_existing_alloc_planned_evict():
    """rank_test.go:243-322"""
    state, ctx = make_context()
    nodes = [
        RankedNode(Node(id=generate_uuid(), resources=Resources(cpu=2048, memory_mb=2048))),
        RankedNode(Node(id=generate_uuid(), resources=Resources(cpu=2048, memory_mb=2048))),
    ]
    static = StaticRankIterator(ctx, nodes)

    alloc1 = Allocation(
        id=generate_uuid(), eval_id=generate_uuid(), node_id=nodes[0].node.id,
        job_id=generate_uuid(), resources=Resources(cpu=2048, memory_mb=2048),
        desired_status=structs.ALLOC_DESIRED_STATUS_RUN,
    )
    alloc2 = Allocation(
        id=generate_uuid(), eval_id=generate_uuid(), node_id=nodes[1].node.id,
        job_id=generate_uuid(), resources=Resources(cpu=1024, memory_mb=1024),
        desired_status=structs.ALLOC_DESIRED_STATUS_RUN,
    )
    state.upsert_allocs(1000, [alloc1, alloc2])

    # Plan evicts alloc1
    ctx.plan.node_update[nodes[0].node.id] = [alloc1]

    task = Task(name="web", resources=Resources(cpu=1024, memory_mb=1024))
    binp = BinPackIterator(ctx, static, False, 0)
    binp.set_tasks([task])

    out = collect_ranked(binp)
    assert len(out) == 2
    assert out[0] is nodes[0] and out[1] is nodes[1]
    assert 10 < out[0].score < 16
    assert out[1].score == 18


def test_job_anti_affinity_planned_alloc():
    """rank_test.go:324-377"""
    _, ctx = make_context()
    nodes = [
        RankedNode(Node(id=generate_uuid())),
        RankedNode(Node(id=generate_uuid())),
    ]
    static = StaticRankIterator(ctx, nodes)

    ctx.plan.node_allocation[nodes[0].node.id] = [
        Allocation(job_id="foo"),
        Allocation(job_id="foo"),
    ]
    ctx.plan.node_allocation[nodes[1].node.id] = [Allocation(job_id="bar")]

    it = JobAntiAffinityIterator(ctx, static, 5.0, "foo")
    out = collect_ranked(it)
    assert len(out) == 2
    assert out[0] is nodes[0]
    assert out[0].score == -10.0
    assert out[1] is nodes[1]
    assert out[1].score == 0.0


def test_limit_iterator():
    """select_test.go:9-51"""
    _, ctx = make_context()
    nodes = [RankedNode(mock.node()) for _ in range(3)]
    static = StaticRankIterator(ctx, nodes)
    limit = LimitIterator(ctx, static, 1)
    out = collect_ranked(limit)
    assert out == [nodes[0]]

    limit.reset()
    limit.set_limit(2)
    out = collect_ranked(limit)
    assert len(out) == 2


def test_max_score_iterator():
    """select_test.go:53-94"""
    _, ctx = make_context()
    nodes = [RankedNode(mock.node()) for _ in range(3)]
    nodes[0].score = 1
    nodes[1].score = 2
    nodes[2].score = 0
    static = StaticRankIterator(ctx, nodes)
    max_it = MaxScoreIterator(ctx, static)
    out = collect_ranked(max_it)
    assert out == [nodes[1]]


def test_eval_context_proposed_allocs():
    """context_test.go:28-107: existing - terminal - evictions + placements"""
    state, ctx = make_context()
    node = mock.node()

    running = Allocation(
        id=generate_uuid(), node_id=node.id, job_id="j1",
        desired_status=structs.ALLOC_DESIRED_STATUS_RUN,
    )
    terminal = Allocation(
        id=generate_uuid(), node_id=node.id, job_id="j1",
        desired_status=structs.ALLOC_DESIRED_STATUS_STOP,
    )
    evicted = Allocation(
        id=generate_uuid(), node_id=node.id, job_id="j2",
        desired_status=structs.ALLOC_DESIRED_STATUS_RUN,
    )
    state.upsert_allocs(1000, [running, terminal, evicted])

    ctx.plan.node_update[node.id] = [evicted]
    placed = Allocation(id=generate_uuid(), node_id=node.id, job_id="j3")
    ctx.plan.node_allocation[node.id] = [placed]

    proposed = ctx.proposed_allocs(node.id)
    ids = {a.id for a in proposed}
    assert ids == {running.id, placed.id}


def test_mirror_constraint_mask_matches_scalar_semantics():
    """The mirror's vectorized constraint mask must agree with the
    per-node ConstraintIterator on every resolution edge: matching and
    non-matching values, a missing attribute (fails any operand), a
    present-but-None meta value (a real value — '!=' accepts it), an
    unknown target form (defers to resolve_constraint_target), and a
    scalar-vs-scalar literal constraint."""
    from nomad_tpu.tpu.mirror import NodeMirror

    _, ctx = make_context()
    nodes = [mock.node() for _ in range(4)]
    nodes[0].meta["rack"] = "r1"
    nodes[1].meta["rack"] = None    # present but null (wire JSON form)
    nodes[2].meta.pop("rack", None)  # absent
    nodes[3].meta["rack"] = "r9"

    cases = [
        [Constraint(l_target="$meta.rack", r_target="r1", operand="=")],
        [Constraint(l_target="$meta.rack", r_target="r1", operand="!=")],
        [Constraint(l_target="$attr.kernel.name", r_target="linux",
                    operand="=")],
        [Constraint(l_target="$bogus.form", r_target="x", operand="!=")],
        [Constraint(l_target="lit", r_target="lit", operand="=")],
        [Constraint(l_target="lit", r_target="other", operand="=")],
    ]
    for constraints in cases:
        mirror = NodeMirror(list(nodes))
        mask = mirror.constraint_mask(ctx, constraints)
        static = StaticIterator(ctx, nodes)
        it = ConstraintIterator(ctx, static, constraints)
        expect = {n.id for n in collect_feasible(it)}
        got = {nodes[i].id for i in range(len(nodes)) if mask[i]}
        assert got == expect, (constraints[0], got, expect)


def test_mirror_version_constraint_over_null_attribute():
    """The factorized mask path evaluates version predicates over ALL
    distinct column values — including present-but-None attributes on
    nodes an earlier constraint already excluded. A None version value
    must be a parse failure (node infeasible), never a crash."""
    from nomad_tpu.tpu.mirror import NodeMirror

    _, ctx = make_context()
    nodes = [mock.node() for _ in range(3)]
    nodes[0].attributes["driver.docker.version"] = "1.10.0"
    nodes[1].attributes["driver.docker.version"] = None
    nodes[2].attributes.pop("driver.docker.version", None)

    constraints = [Constraint(
        l_target="$attr.driver.docker.version", r_target=">= 1.9",
        operand="version",
    )]
    mirror = NodeMirror(list(nodes))
    mask = mirror.constraint_mask(ctx, constraints)
    static = StaticIterator(ctx, nodes)
    it = ConstraintIterator(ctx, static, constraints)
    expect = {n.id for n in collect_feasible(it)}
    got = {nodes[i].id for i in range(len(nodes)) if mask[i]}
    assert got == expect == {nodes[0].id}
