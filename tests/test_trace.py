"""Observability surface: eval-lifecycle trace spans + metrics exposition.

Covers the span/tracer primitives (lifecycle, ring-buffer eviction,
cross-RPC context propagation), the end-to-end trace of a real scheduled
evaluation through a dev agent's HTTP API, and golden checks for the
Prometheus text exposition and Chrome trace-event export formats.
"""

import json
import time
import urllib.error
import urllib.request

import pytest

from nomad_tpu import structs, telemetry, trace
from nomad_tpu.trace import StageTimer, Tracer


# ---------------------------------------------------------------------------
# Span / tracer primitives
# ---------------------------------------------------------------------------


def test_span_lifecycle_and_parent_links():
    tr = Tracer(max_traces=8)
    root = tr.start_span("t1", "eval", root=True,
                         annotations={"job_id": "j1"})
    child = tr.start_span("t1", "worker.invoke_scheduler", parent=root)
    grand = tr.start_span("t1", "solver.staging", parent=child)
    grand.annotate("n_nodes", 10)
    grand.finish()
    child.finish()
    root.finish()

    spans = tr.get_trace("t1")
    by_name = {s["name"]: s for s in spans}
    assert set(by_name) == {"eval", "worker.invoke_scheduler",
                            "solver.staging"}
    assert by_name["eval"]["parent_id"] == ""
    assert by_name["worker.invoke_scheduler"]["parent_id"] == \
        by_name["eval"]["span_id"]
    assert by_name["solver.staging"]["parent_id"] == \
        by_name["worker.invoke_scheduler"]["span_id"]
    assert by_name["solver.staging"]["annotations"]["n_nodes"] == 10
    assert by_name["eval"]["annotations"]["job_id"] == "j1"
    for s in spans:
        assert s["end"] is not None and s["end"] >= s["start"]
        assert s["duration_ms"] is not None

    # finish is idempotent: a racing second finish keeps the first stamp
    end = by_name["eval"]["end"]
    root.finish()
    assert tr.get_trace("t1")[0]["end"] == end

    # root context registered for cross-component parenting
    assert tr.root_ctx("t1") == {"trace_id": "t1",
                                 "span_id": by_name["eval"]["span_id"]}


def test_open_spans_visible_and_summary():
    tr = Tracer()
    root = tr.start_span("t2", "eval", root=True)
    spans = tr.get_trace("t2")
    assert len(spans) == 1 and spans[0]["end"] is None
    root.finish()
    tr.mark_done("t2")
    summaries = tr.traces()
    assert summaries[0]["trace_id"] == "t2"
    assert summaries[0]["done"] is True
    assert summaries[0]["root"] == "eval"
    assert summaries[0]["duration_ms"] is not None


def test_ring_buffer_eviction():
    tr = Tracer(max_traces=4)
    for i in range(10):
        tr.start_span(f"t{i}", "eval", root=True).finish()
    assert tr.get_trace("t0") is None
    assert tr.get_trace("t5") is None
    for i in range(6, 10):
        assert tr.get_trace(f"t{i}") is not None
    assert len(tr.traces()) == 4


def test_per_trace_span_cap():
    tr = Tracer(max_spans=5)
    for i in range(9):
        tr.start_span("t", f"s{i}").finish()
    spans = tr.get_trace("t")
    assert len(spans) == 5
    assert tr.traces()[0]["dropped_spans"] == 4


def test_disabled_tracer_is_inert():
    tr = Tracer(enabled=False)
    span = tr.start_span("t", "eval", root=True)
    assert span is trace.NULL_SPAN
    span.annotate("k", 1).finish()
    assert tr.get_trace("t") is None
    assert tr.traces() == []


def test_cross_rpc_context_propagation():
    """The span context survives the request envelope: a Plan carries
    span_ctx through the wire codec, and a remote tracer adopts the
    leader's root so local spans parent on it."""
    from nomad_tpu.api.codec import from_dict, to_dict
    from nomad_tpu.structs import Plan

    leader = Tracer()
    root = leader.start_span("ev-1", "eval", root=True)
    submit = leader.start_span("ev-1", "worker.submit_plan", parent=root)

    plan = Plan(eval_id="ev-1", span_ctx=submit.ctx())
    wire = json.loads(json.dumps(to_dict(plan)))  # the RPC framing
    back = from_dict(Plan, wire)
    assert back.span_ctx == {"trace_id": "ev-1",
                             "span_id": submit.span_id}

    # Receiving side: parent a plan.apply span on the wire context.
    applier_span = leader.start_span(
        back.span_ctx["trace_id"], "plan.apply", parent=back.span_ctx
    )
    applier_span.finish()
    submit.finish()
    root.finish()
    by_name = {s["name"]: s for s in leader.get_trace("ev-1")}
    assert by_name["plan.apply"]["parent_id"] == submit.span_id

    # Follower posture: adopt_root lets a remote worker parent on the
    # leader's root without ever seeing the Span object.
    follower = Tracer()
    follower.adopt_root("ev-1", root.ctx())
    w = follower.start_span("ev-1", "worker.invoke_scheduler",
                            parent=follower.root_ctx("ev-1"))
    w.finish()
    spans = follower.get_trace("ev-1")
    assert spans[0]["parent_id"] == root.span_id


def test_stage_timer_durations_and_spans():
    tr = Tracer()
    st = StageTimer()
    with st.stage("staging"):
        time.sleep(0.002)
    with st.stage("execute"):
        time.sleep(0.001)
    with st.stage("execute"):
        pass
    d = st.durations_ms()
    assert d["staging"] >= 1.0
    assert set(d) == {"staging", "execute"}

    parent = tr.start_span("t", "worker.invoke_scheduler", root=True)
    st.emit_spans(parent)
    parent.finish()
    names = [s["name"] for s in tr.get_trace("t")]
    assert names.count("solver.execute") == 2
    assert "solver.staging" in names

    # The thread-local install + module-level stage() shorthand
    with trace.use_stages(StageTimer()) as st2:
        with trace.stage("readback"):
            pass
    assert "readback" in st2.durations_ms()
    # no timer installed -> inert
    assert trace.active_stages() is trace.NULL_STAGES
    with trace.stage("whatever"):
        pass


# ---------------------------------------------------------------------------
# Exposition formats (golden)
# ---------------------------------------------------------------------------


def test_prometheus_exposition_golden():
    sink = telemetry.InmemSink(interval=10.0, retain=60.0)
    sink.set_gauge(("nomad", "broker", "total_ready"), 3.0)
    sink.incr_counter(("nomad", "broker", "enqueue"), 1.0)
    sink.incr_counter(("nomad", "broker", "enqueue"), 1.0)
    sink.add_sample(("nomad", "worker", "invoke_scheduler", "tpu-batch"), 12.5)
    sink.add_sample(("nomad", "worker", "invoke_scheduler", "tpu-batch"), 7.5)

    text = telemetry.prometheus_text(sink)
    lines = text.strip().splitlines()
    assert "# TYPE nomad_broker_total_ready gauge" in lines
    assert "nomad_broker_total_ready 3" in lines
    assert "# TYPE nomad_broker_enqueue_total counter" in lines
    assert "nomad_broker_enqueue_total 2" in lines
    name = "nomad_worker_invoke_scheduler_tpu_batch_ms"
    assert f"# TYPE {name} summary" in lines
    assert f"{name}_sum 20" in lines
    assert f"{name}_count 2" in lines
    assert f"{name}_max 12.5" in lines
    # Summary quantiles from the reservoir (both samples retained here).
    assert f'{name}{{quantile="0.5"}} 7.5' in lines
    assert f'{name}{{quantile="0.99"}} 12.5' in lines
    # every exposed series name is valid for the Prometheus data model
    # (labels — {quantile="..."} — are not part of the name)
    for line in lines:
        if line.startswith("#"):
            continue
        metric = line.split(" ")[0].split("{")[0]
        assert metric[0].isalpha() or metric[0] in "_:"
        assert all(c.isalnum() or c in "_:" for c in metric)


def test_prometheus_counters_survive_interval_eviction():
    """Counters are process-lifetime cumulative: the ring evicting old
    intervals must never make an exposed _total decrease (Prometheus
    rate()/increase() treats decreases as counter resets)."""
    sink = telemetry.InmemSink(interval=0.01, retain=0.02)
    sink.incr_counter(("c",), 5.0)
    sink.add_sample(("s",), 3.0)
    time.sleep(0.05)
    # Roll the ring well past the first interval.
    for _ in range(4):
        sink.incr_counter(("c",), 1.0)
        time.sleep(0.015)
    text = telemetry.prometheus_text(sink)
    assert "c_total 9" in text        # 5 + 4x1, incl. evicted intervals
    assert "s_ms_sum 3" in text
    assert "s_ms_count 1" in text


def test_inmem_sink_data_structure():
    sink = telemetry.InmemSink()
    sink.set_gauge(("a", "b"), 1.0)
    sink.incr_counter(("c",), 2.0)
    sink.add_sample(("d",), 5.0)
    data = sink.data()
    assert len(data) == 1
    ivl = data[0]
    assert ivl["gauges"]["a.b"] == 1.0
    assert ivl["counters"]["c"]["sum"] == 2.0
    assert ivl["samples"]["d"] == {
        "count": 1, "sum": 5.0, "min": 5.0, "max": 5.0, "mean": 5.0,
        "stddev": 0.0, "last": 5.0, "p50": 5.0, "p95": 5.0, "p99": 5.0,
    }
    json.dumps(data)  # JSON-able as served


def test_chrome_trace_export_golden():
    tr = Tracer()
    root = tr.start_span("t", "eval", root=True)
    child = tr.start_span("t", "plan.apply", parent=root,
                          annotations={"alloc_index": 7})
    child.finish()
    root.finish()
    doc = tr.chrome_trace("t")
    assert doc["displayTimeUnit"] == "ms"
    events = doc["traceEvents"]
    complete = [e for e in events if e["ph"] == "X"]
    meta = [e for e in events if e["ph"] == "M"]
    assert {e["name"] for e in complete} == {"eval", "plan.apply"}
    for e in complete:
        assert e["pid"] == 1 and isinstance(e["tid"], int)
        assert e["ts"] > 0 and e["dur"] >= 0
    apply_ev = next(e for e in complete if e["name"] == "plan.apply")
    assert apply_ev["args"]["alloc_index"] == 7
    assert apply_ev["args"]["parent_id"]
    assert meta and meta[0]["name"] == "thread_name"
    json.dumps(doc)  # loads into Perfetto as-is
    assert tr.chrome_trace("nope") is None


def test_blocked_eval_wait_spans_all_finish():
    """An eval that transits the blocked queue gets two broker.wait
    segments (blocked->ready restart), BOTH finished — an open leaked
    span would render as a bogus until-now bar in the Chrome export."""
    from nomad_tpu.server.eval_broker import EvalBroker
    from nomad_tpu.structs import Evaluation, generate_uuid

    tracer = trace.configure(max_traces=32, enabled=True)
    b = EvalBroker(5.0, 3)
    b.set_enabled(True)
    job_id = generate_uuid()

    def _ev():
        return Evaluation(id=generate_uuid(), priority=50, type="service",
                          job_id=job_id, status=structs.EVAL_STATUS_PENDING)

    first, second = _ev(), _ev()
    b.enqueue(first)
    b.enqueue(second)  # blocks behind first (per-job serialization)

    ev, tok = b.dequeue(["service"], timeout=1.0)
    assert ev.id == first.id
    b.ack(ev.id, tok)  # unblocks second
    ev2, tok2 = b.dequeue(["service"], timeout=1.0)
    assert ev2.id == second.id
    b.ack(ev2.id, tok2)

    for tid in (first.id, second.id):
        summary = next(t for t in tracer.traces() if t["trace_id"] == tid)
        assert summary["open_spans"] == 0, f"leaked open span on {tid}"
        assert summary["done"] is True
    waits = [s for s in tracer.get_trace(second.id)
             if s["name"] == "broker.wait"]
    assert len(waits) == 2
    assert all(s["end"] is not None for s in waits)
    assert any(s["annotations"].get("blocked") for s in waits)


# ---------------------------------------------------------------------------
# End-to-end: dev agent -> HTTP trace + metrics endpoints
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def agent(tmp_path_factory):
    from nomad_tpu.agent import Agent, AgentConfig

    from nomad_tpu.scheduler import wait_for_device

    # The device path must actually carry the solves (the acceptance
    # criterion names the solver stage spans): block for the probe before
    # any eval dispatches, or the factory would fall back to the host
    # scheduler while the prewarm thread holds the first-caller grace.
    assert wait_for_device(timeout=180.0) is not None

    config = AgentConfig.dev()
    config.data_dir = str(tmp_path_factory.mktemp("trace-agent"))
    config.http_port = 0
    # The TPU factories (on the CPU jax backend) so the solver stage
    # spans ride the device path.
    config.scheduler_backend = "tpu"
    a = Agent(config)
    a.start()
    yield a
    a.shutdown()


def _get(agent, path):
    with urllib.request.urlopen(agent.http.addr + path, timeout=10) as resp:
        body = resp.read()
        return resp.status, resp.headers.get("Content-Type", ""), body


def _get_json(agent, path):
    status, _ctype, body = _get(agent, path)
    assert status == 200
    return json.loads(body.decode())


def test_eval_trace_end_to_end(agent):
    from nomad_tpu import mock
    from nomad_tpu.api import ApiClient

    client = ApiClient(address=agent.http.addr)
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline:
        nodes, _ = client.nodes().list()
        if nodes and nodes[0]["status"] == "ready":
            break
        time.sleep(0.1)
    else:
        pytest.fail("dev node never became ready")

    job = mock.job()
    job.task_groups[0].count = 2
    job.task_groups[0].tasks[0].driver = "mock_driver"
    job.task_groups[0].tasks[0].config = {"run_for": "20", "exit_code": "0"}
    job.task_groups[0].tasks[0].resources.networks = []
    eval_id, _meta = client.jobs().register(job)

    # Eval terminal + the root span finished (ack lands just after the
    # status write).
    deadline = time.monotonic() + 60
    spans = None
    while time.monotonic() < deadline:
        ev, _ = client.evaluations().info(eval_id)
        if ev.status == structs.EVAL_STATUS_COMPLETE:
            doc = _get_json(agent, f"/v1/evaluation/{eval_id}/trace")
            spans = doc["spans"]
            root = next(s for s in spans if s["name"] == "eval")
            if root["end"] is not None:
                break
        time.sleep(0.1)
    else:
        pytest.fail("eval never completed with a finished root span")

    by_name = {}
    for s in spans:
        by_name.setdefault(s["name"], []).append(s)

    # The acceptance span set: broker enqueue->dequeue, scheduler
    # invocation, solver stage breakdown, plan submit/queue/apply, FSM.
    for required in (
        "eval", "broker.wait", "worker.invoke_scheduler",
        "solver.staging", "solver.transfer", "solver.execute",
        "solver.readback", "worker.submit_plan", "plan.queue_wait",
        "plan.evaluate", "plan.apply", "fsm.apply",
    ):
        assert required in by_name, f"missing span {required}: {list(by_name)}"

    ids = {s["span_id"]: s for s in spans}
    root = by_name["eval"][0]
    assert root["annotations"]["job_id"] == job.id
    assert root["annotations"]["outcome"] == "ack"

    eps = 5e-3  # clock-read ordering slack between threads
    for s in spans:
        # Monotonic, nesting-consistent timestamps.
        if s["end"] is not None:
            assert s["end"] >= s["start"]
        parent = ids.get(s["parent_id"])
        if parent is not None:
            assert s["start"] >= parent["start"] - eps
            if parent["end"] is not None and s["end"] is not None:
                assert s["end"] <= parent["end"] + eps
        # Every non-root span links back into the tree.
        if s["name"] != "eval":
            assert s["parent_id"] in ids

    # Solver stages nest under the scheduler invocation.
    inv = by_name["worker.invoke_scheduler"][0]
    for stage in ("solver.staging", "solver.transfer",
                  "solver.execute", "solver.readback"):
        assert any(s["parent_id"] == inv["span_id"]
                   for s in by_name[stage])
    # plan.* under the worker's submit span (the cross-boundary ctx).
    submit = by_name["worker.submit_plan"][0]
    assert by_name["plan.apply"][0]["parent_id"] == submit["span_id"]
    assert by_name["fsm.apply"][0]["annotations"]["msg_type"] in (
        "alloc_update", "eval_update",
    )

    # Chrome export of the same trace.
    doc = _get_json(agent, f"/v1/evaluation/{eval_id}/trace?format=chrome")
    names = {e["name"] for e in doc["traceEvents"] if e["ph"] == "X"}
    assert "eval" in names and "plan.apply" in names

    # Trace listing includes the completed trace.
    listing = _get_json(agent, "/v1/agent/traces")
    entry = next(t for t in listing if t["trace_id"] == eval_id)
    assert entry["done"] is True and entry["spans"] >= 10

    # Unknown eval -> 404
    try:
        _get(agent, "/v1/evaluation/ffffffff/trace")
    except urllib.error.HTTPError as e:
        assert e.code == 404
    else:
        pytest.fail("expected 404 for unknown trace")


def test_agent_metrics_endpoints(agent):
    from nomad_tpu import mock

    # Self-sufficient: drive one eval through the pipeline so the broker
    # counters and fsm.apply samples exist even when this test runs alone
    # (a -k filter or single-test rerun must not depend on the e2e test
    # having populated the module-scoped agent first).
    job = mock.job()
    job.task_groups[0].count = 1
    job.task_groups[0].tasks[0].driver = "mock_driver"
    job.task_groups[0].tasks[0].config = {"run_for": "10", "exit_code": "0"}
    job.task_groups[0].tasks[0].resources.networks = []
    eval_id, _ = agent.server.job_register(job)
    agent.server.wait_for_eval(eval_id, timeout=30)

    doc = _get_json(agent, "/v1/agent/metrics")
    assert "intervals" in doc and doc["intervals"]
    merged_samples = {}
    merged_counters = {}
    for ivl in doc["intervals"]:
        merged_samples.update(ivl["samples"])
        merged_counters.update(ivl["counters"])
    # The new instrumentation feeds the sink: broker counters + fsm
    # per-message-type apply timers ride every job registration.
    assert any(k.endswith("broker.enqueue") for k in merged_counters)
    assert any(".fsm.apply." in k for k in merged_samples)

    # Device-mirror cache stats ride the same endpoint (the delta-roll
    # economy: rolls vs full rebuilds).
    assert "mirror_cache" in doc
    for k in ("hits", "misses", "delta_rolls", "full_rebuilds",
              "rows_restaged"):
        assert k in doc["mirror_cache"], doc["mirror_cache"]

    status, ctype, body = _get(agent, "/v1/agent/metrics?format=prometheus")
    assert status == 200
    assert ctype.startswith("text/plain")
    text = body.decode()
    assert "# TYPE " in text
    assert "broker_enqueue_total" in text
    assert "fsm_apply" in text
    assert "nomad_mirror_cache_delta_rolls_total" in text
    assert "nomad_mirror_cache_full_rebuilds_total" in text
