"""Raft log compaction + InstallSnapshot (paper §7; the reference's
raft.FileSnapshotStore at nomad/server.go:437-453 and FSM snapshot
persist/restore at nomad/fsm.go:299-593)."""

import glob
import os
import pickle
import time

import pytest

from nomad_tpu import mock
from nomad_tpu.raft.node import RaftConfig, RaftNode
from nomad_tpu.rpc import ConnPool, RPCServer
from nomad_tpu.server import ServerConfig
from nomad_tpu.server.cluster import ClusterConfig, form_cluster, wait_for_leader


class KVFSM:
    """Minimal FSM for raw raft-core tests: k/v applies with full-dict
    snapshots (stands in for the real FSM's StateStore serialization)."""

    def __init__(self):
        self.data = {}

    def apply(self, index, msg_type, payload):
        self.data[payload["k"]] = payload["v"]

    def snapshot_bytes(self):
        return pickle.dumps(self.data)

    def restore_bytes(self, data):
        self.data = pickle.loads(data)


def _wait(predicate, timeout=10.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {msg}")


def _make_node(node_id, peers, fsm, data_dir="", threshold=20, trailing=0):
    rpc = RPCServer()
    rpc.start()
    peers[node_id] = rpc.addr
    cfg = RaftConfig(
        node_id=node_id,
        peers=peers,
        data_dir=data_dir,
        snapshot_threshold=threshold,
        trailing_logs=trailing,
        bootstrap_expect=1,
    )
    node = RaftNode(cfg, fsm, rpc, pool=ConnPool(timeout=2.0))
    return node, rpc


def test_leader_compacts_log(tmp_path):
    peers = {}
    fsm = KVFSM()
    node, rpc = _make_node("a", peers, fsm, data_dir=str(tmp_path), threshold=20)
    node.start()
    try:
        _wait(lambda: node.is_leader, msg="leadership")
        for i in range(50):
            node.apply("kv", {"k": f"k{i}", "v": i}).result(5.0)
        _wait(lambda: node.snapshot_index > 0, msg="compaction")
        # Log holds only the tail past the snapshot
        assert len(node.log) < 50
        assert fsm.data["k49"] == 49
        # Snapshot files on disk, retained at most snapshot_retain
        snaps = glob.glob(os.path.join(str(tmp_path), "raft-snap-*.json"))
        assert 1 <= len(snaps) <= node.config.snapshot_retain
    finally:
        node.shutdown()
        rpc.shutdown()


def test_restart_restores_from_snapshot(tmp_path):
    peers = {}
    fsm = KVFSM()
    node, rpc = _make_node("a", peers, fsm, data_dir=str(tmp_path), threshold=10)
    node.start()
    try:
        _wait(lambda: node.is_leader, msg="leadership")
        for i in range(35):
            node.apply("kv", {"k": f"k{i}", "v": i}).result(5.0)
        applied = node.applied_index
        _wait(lambda: node.snapshot_index > 0, msg="compaction")
        snap_index = node.snapshot_index
    finally:
        node.shutdown()
        rpc.shutdown()

    fsm2 = KVFSM()
    node2, rpc2 = _make_node("a", {}, fsm2, data_dir=str(tmp_path), threshold=10)
    try:
        # Snapshot restores synchronously at construction; the log tail
        # applies once the node re-elects itself and commits. Compactions
        # are async, so a newer snapshot than the one first observed may
        # have landed before shutdown.
        assert node2.snapshot_index >= snap_index
        assert node2.applied_index >= node2.snapshot_index
        node2.start()
        _wait(lambda: node2.applied_index >= applied, msg="log replay")
        assert fsm2.data == {f"k{i}": i for i in range(35)}
    finally:
        node2.shutdown()
        rpc2.shutdown()


def test_lagging_follower_catches_up_via_install_snapshot():
    """A follower that was down across a compaction is restored through
    InstallSnapshot, then extends its log normally."""
    peers = {}
    fsm_a, fsm_b, fsm_c = KVFSM(), KVFSM(), KVFSM()
    # C's RPC address exists from the start (it is in the peer set), but
    # its raft handlers don't come up until after the leader has compacted —
    # so C genuinely lags behind the snapshot.
    rpc_c = RPCServer()
    rpc_c.start()
    node_a, rpc_a = _make_node("a", peers, fsm_a, threshold=20)
    node_b, rpc_b = _make_node("b", peers, fsm_b, threshold=20)
    peers["c"] = rpc_c.addr
    node_c = None

    node_a.start()
    node_b.start()
    try:
        # Generous timeout: elections under full-suite CPU contention can
        # take several rounds.
        _wait(lambda: node_a.is_leader or node_b.is_leader, timeout=30.0,
              msg="leadership")
        leader = node_a if node_a.is_leader else node_b
        for i in range(60):
            leader.apply("kv", {"k": f"k{i}", "v": i}).result(5.0)
        _wait(lambda: leader.snapshot_index > 0, msg="compaction")

        # C joins late: everything before the snapshot is gone from the log
        cfg_c = RaftConfig(node_id="c", peers=peers, snapshot_threshold=20,
                           bootstrap_expect=1)
        node_c = RaftNode(cfg_c, fsm_c, rpc_c, pool=ConnPool(timeout=2.0))
        node_c.start()
        _wait(lambda: node_c.applied_index >= leader.applied_index,
              timeout=15.0, msg="follower snapshot catch-up")
        assert fsm_c.data == {f"k{i}": i for i in range(60)}
        assert node_c.snapshot_index >= 20 - 1  # installed, not replayed

        # And it keeps replicating normally afterwards
        leader.apply("kv", {"k": "after", "v": "snap"}).result(5.0)
        _wait(lambda: fsm_c.data.get("after") == "snap", msg="post-snapshot entry")
    finally:
        for n in (node_a, node_b, node_c):
            if n is not None:
                n.shutdown()
        for r in (rpc_a, rpc_b, rpc_c):
            r.shutdown()


def test_trailing_logs_let_lagging_follower_replicate_normally():
    """With trailing_logs, compaction keeps a log tail past the snapshot, so
    a follower behind by less than the tail catches up through ordinary
    AppendEntries — no InstallSnapshot transfer (hashicorp/raft TrailingLogs
    posture)."""
    peers = {}
    fsm_a, fsm_b, fsm_c = KVFSM(), KVFSM(), KVFSM()
    rpc_c = RPCServer()
    rpc_c.start()
    node_a, rpc_a = _make_node("a", peers, fsm_a, threshold=20, trailing=1000)
    node_b, rpc_b = _make_node("b", peers, fsm_b, threshold=20, trailing=1000)
    peers["c"] = rpc_c.addr
    node_c = None

    node_a.start()
    node_b.start()
    try:
        _wait(lambda: node_a.is_leader or node_b.is_leader, timeout=30.0,
              msg="leadership")
        leader = node_a if node_a.is_leader else node_b
        for i in range(60):
            leader.apply("kv", {"k": f"k{i}", "v": i}).result(5.0)
        _wait(lambda: leader.snapshot_index > 0, msg="compaction")
        # The snapshot exists but the tail (here: the whole log) is retained
        assert leader.log_offset < leader.snapshot_index
        assert leader.log_offset + len(leader.log) >= leader.snapshot_index

        # C joins late, behind the snapshot but within the retained tail:
        # it must converge via plain replication, never InstallSnapshot.
        cfg_c = RaftConfig(node_id="c", peers=peers, snapshot_threshold=10_000,
                           bootstrap_expect=1)
        node_c = RaftNode(cfg_c, fsm_c, rpc_c, pool=ConnPool(timeout=2.0))
        node_c.start()
        _wait(lambda: node_c.applied_index >= leader.applied_index,
              timeout=15.0, msg="follower log catch-up")
        assert fsm_c.data == {f"k{i}": i for i in range(60)}
        assert node_c.snapshot_index == 0  # replayed, never installed
    finally:
        for n in (node_a, node_b, node_c):
            if n is not None:
                n.shutdown()
        for r in (rpc_a, rpc_b, rpc_c):
            r.shutdown()


def test_cluster_server_snapshot_restart(tmp_path):
    """Full-stack: a ClusterServer with a tiny snapshot threshold compacts,
    restarts from the snapshot, and serves the same state."""
    cfg = ServerConfig(scheduler_backend="host", num_schedulers=1)
    ccfg = ClusterConfig(raft_data_dir=str(tmp_path / "raft"),
                         snapshot_threshold=10)
    (srv,) = form_cluster(1, cfg, ccfg)
    job = mock.job()
    job.task_groups[0].count = 2
    nodes = [mock.node() for _ in range(8)]
    try:
        wait_for_leader([srv])
        for n in nodes:
            srv.node_register(n)
        eval_id, _ = srv.job_register(job)
        srv.wait_for_eval(eval_id, timeout=15.0)
        # Mark 4 alloc-free nodes down: state diversity for the snapshot
        # without triggering rescheduling races against shutdown.
        used = {a.node_id for a in srv.state_store.allocs_by_job(job.id)}
        empty = [n for n in nodes if n.id not in used][:4]
        assert len(empty) == 4
        for n in empty:
            srv.node_update_status(n.id, "down")
        applied = srv.raft.applied_index
        _wait(lambda: srv.raft.snapshot_index > 0, msg="compaction")
    finally:
        srv.shutdown()

    ccfg2 = ClusterConfig(raft_data_dir=str(tmp_path / "raft"),
                          snapshot_threshold=10)
    (srv2,) = form_cluster(1, cfg, ccfg2)
    try:
        wait_for_leader([srv2])
        _wait(lambda: srv2.raft.applied_index >= applied, msg="replay")
        assert srv2.state_store.job_by_id(job.id) is not None
        assert len(srv2.state_store.allocs_by_job(job.id)) == 2
        down = [n for n in srv2.state_store.nodes() if n.status == "down"]
        assert len(down) == 4
    finally:
        srv2.shutdown()
