"""SCADA-analog uplink tests (reference: command/agent/scada.go — the
provider dials a broker and exposes the agent HTTP API over the tunnel)."""

import json
import time

import pytest

from nomad_tpu.agent import Agent, AgentConfig
from nomad_tpu.scada import UplinkBroker, UplinkProvider


def wait_for(fn, timeout=5.0, interval=0.02):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if fn():
            return True
        time.sleep(interval)
    return False


@pytest.fixture()
def broker():
    b = UplinkBroker(token="sekrit")
    yield b
    b.shutdown()


@pytest.fixture()
def agent(tmp_path, broker):
    config = AgentConfig.dev()
    config.data_dir = str(tmp_path)
    config.http_port = 0
    config.scheduler_backend = "host"
    config.atlas_infrastructure = "acme/prod"
    config.atlas_token = "sekrit"
    config.atlas_endpoint = broker.addr
    a = Agent(config)
    a.start()
    yield a
    a.shutdown()


def test_handshake_registers_session(broker, agent):
    assert wait_for(lambda: "acme/prod" in broker.sessions())
    hs = broker.sessions()["acme/prod"]
    assert hs["service"] == "nomad-tpu"
    assert hs["capabilities"] == {"http": 1}
    assert hs["meta"]["datacenter"] == "dc1"
    assert broker.ping("acme/prod")


def test_http_through_tunnel(broker, agent):
    assert wait_for(lambda: "acme/prod" in broker.sessions())
    resp = broker.http("acme/prod", "GET", "/v1/agent/self")
    assert resp["status"] == 200
    info = json.loads(resp["body"])
    assert info["config"]["server_enabled"] is True

    # Query-meta headers survive the tunnel (the uplink serves the same
    # /v1 surface as the local listener).
    resp = broker.http("acme/prod", "GET", "/v1/nodes")
    assert resp["status"] == 200
    assert "X-Nomad-Index" in resp["headers"]

    resp = broker.http("acme/prod", "GET", "/v1/job/nope")
    assert resp["status"] == 404


def test_provider_reconnects_after_drop(broker, agent):
    assert wait_for(lambda: "acme/prod" in broker.sessions())
    first_sessions = agent.uplink.sessions
    broker.drop("acme/prod")
    assert wait_for(lambda: agent.uplink.sessions > first_sessions, timeout=10)
    assert wait_for(lambda: "acme/prod" in broker.sessions(), timeout=10)
    resp = broker.http("acme/prod", "GET", "/v1/status/leader")
    assert resp["status"] == 200


def test_bad_token_rejected(tmp_path):
    broker = UplinkBroker(token="right")
    provider = UplinkProvider(
        endpoint=broker.addr, infrastructure="x", token="wrong",
        http_addr="127.0.0.1:1",
    )
    provider.start()
    try:
        time.sleep(0.5)
        assert broker.sessions() == {}
        assert provider.sessions == 0
    finally:
        provider.shutdown()
        broker.shutdown()


def test_no_endpoint_means_no_uplink(tmp_path):
    config = AgentConfig.dev()
    config.data_dir = str(tmp_path)
    config.http_port = 0
    config.scheduler_backend = "host"
    config.atlas_infrastructure = "acme/prod"  # but no endpoint
    a = Agent(config)
    a.start()
    try:
        assert a.uplink is None
    finally:
        a.shutdown()
