"""SCADA-analog uplink tests (reference: command/agent/scada.go — the
provider dials a broker and exposes the agent HTTP API over the tunnel)."""

import json
import time

import pytest

from nomad_tpu.agent import Agent, AgentConfig
from nomad_tpu.scada import UplinkBroker, UplinkProvider


def wait_for(fn, timeout=5.0, interval=0.02):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if fn():
            return True
        time.sleep(interval)
    return False


@pytest.fixture()
def broker():
    b = UplinkBroker(token="sekrit")
    yield b
    b.shutdown()


@pytest.fixture()
def agent(tmp_path, broker):
    config = AgentConfig.dev()
    config.data_dir = str(tmp_path)
    config.http_port = 0
    config.scheduler_backend = "host"
    config.atlas_infrastructure = "acme/prod"
    config.atlas_token = "sekrit"
    config.atlas_endpoint = broker.addr
    a = Agent(config)
    a.start()
    yield a
    a.shutdown()


def test_handshake_registers_session(broker, agent):
    assert wait_for(lambda: "acme/prod" in broker.sessions())
    hs = broker.sessions()["acme/prod"]
    assert hs["service"] == "nomad-tpu"
    assert hs["capabilities"] == {"http": 1}
    assert hs["meta"]["datacenter"] == "dc1"
    assert broker.ping("acme/prod")


def test_http_through_tunnel(broker, agent):
    assert wait_for(lambda: "acme/prod" in broker.sessions())
    resp = broker.http("acme/prod", "GET", "/v1/agent/self")
    assert resp["status"] == 200
    info = json.loads(resp["body"])
    assert info["config"]["server_enabled"] is True

    # Query-meta headers survive the tunnel (the uplink serves the same
    # /v1 surface as the local listener).
    resp = broker.http("acme/prod", "GET", "/v1/nodes")
    assert resp["status"] == 200
    assert "X-Nomad-Index" in resp["headers"]

    resp = broker.http("acme/prod", "GET", "/v1/job/nope")
    assert resp["status"] == 404


def test_blocking_query_through_tunnel(broker, agent):
    """A ?index&wait long-poll parks on the provider side past the old
    30s proxy deadline posture and completes when the watch fires."""
    import threading

    assert wait_for(lambda: "acme/prod" in broker.sessions())

    # Settle: the dev agent's own client bumps the nodes table during
    # startup; capture the index only once it has been stable for a bit
    # so the blocking poll genuinely parks.
    def nodes_index():
        r = broker.http("acme/prod", "GET", "/v1/nodes")
        return int(r["headers"]["X-Nomad-Index"])

    index = nodes_index()
    deadline = time.time() + 10
    while time.time() < deadline:
        time.sleep(0.6)
        nxt = nodes_index()
        if nxt == index:
            break
        index = nxt

    out = {}

    def poll():
        try:
            out["resp"] = broker.http(
                "acme/prod", "GET", f"/v1/nodes?index={index}&wait=50s",
                timeout=60,
            )
        except BaseException as e:  # surface the real failure, not KeyError
            out["err"] = e

    t = threading.Thread(target=poll, daemon=True)
    t.start()
    time.sleep(0.3)
    assert "resp" not in out and "err" not in out  # parked on the watch
    from nomad_tpu import mock

    agent.server.node_register(mock.node())  # fires the nodes watch
    t.join(timeout=15)
    assert "err" not in out, out.get("err")
    assert out["resp"]["status"] == 200
    assert int(out["resp"]["headers"]["X-Nomad-Index"]) > index


def test_provider_reconnects_after_drop(broker, agent):
    assert wait_for(lambda: "acme/prod" in broker.sessions())
    first_sessions = agent.uplink.sessions
    broker.drop("acme/prod")
    assert wait_for(lambda: agent.uplink.sessions > first_sessions, timeout=10)
    assert wait_for(lambda: "acme/prod" in broker.sessions(), timeout=10)
    resp = broker.http("acme/prod", "GET", "/v1/status/leader")
    assert resp["status"] == 200


def test_bad_token_rejected(tmp_path):
    broker = UplinkBroker(token="right")
    provider = UplinkProvider(
        endpoint=broker.addr, infrastructure="x", token="wrong",
        http_addr="127.0.0.1:1",
    )
    provider.start()
    try:
        time.sleep(0.5)
        assert broker.sessions() == {}
        assert provider.sessions == 0
    finally:
        provider.shutdown()
        broker.shutdown()


def test_no_endpoint_means_no_uplink(tmp_path):
    config = AgentConfig.dev()
    config.data_dir = str(tmp_path)
    config.http_port = 0
    config.scheduler_backend = "host"
    config.atlas_infrastructure = "acme/prod"  # but no endpoint
    a = Agent(config)
    a.start()
    try:
        assert a.uplink is None
    finally:
        a.shutdown()
