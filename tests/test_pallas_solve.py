"""Differential tests: the pallas water-fill kernel vs the jnp path.

The kernel must be bit-identical to ops/binpack.solve_waterfill (which is
itself differential-fuzzed against the host oracle), so the pallas path
inherits the whole oracle-parity chain. Runs in interpret mode on the CPU
backend; the compiled path is exercised on real TPU by bench.py."""

import numpy as np
import pytest

import jax.numpy as jnp

from nomad_tpu.ops import pallas_solve
from nomad_tpu.ops.binpack import solve_waterfill
from nomad_tpu.ops.coalesce import solve_waterfill_batched
from nomad_tpu.ops.pallas_solve import (
    solve_waterfill_pallas,
    solve_waterfill_pallas_batched,
)


def random_instance(rng, n, d=4):
    total = rng.integers(100, 5000, size=(n, d)).astype(np.int32)
    used = (total * rng.uniform(0, 0.9, size=(n, d))).astype(np.int32)
    sched_cap = total[:, :2].astype(np.float32)
    jc = rng.integers(0, 3, size=n).astype(np.int32)
    tc = rng.integers(0, 2, size=n).astype(np.int32)
    bw_avail = rng.integers(0, 1000, size=n).astype(np.int32)
    bw_used = (bw_avail * rng.uniform(0, 1.0, size=n)).astype(np.int32)
    elig = rng.random(n) < 0.8
    ask = rng.integers(0, 500, size=d).astype(np.int32)
    bw_ask = int(rng.integers(0, 100))
    count = int(rng.integers(0, 3 * n))
    penalty = float(rng.choice([0.0, 5.0, 10.0]))
    return (
        jnp.asarray(total), jnp.asarray(sched_cap), jnp.asarray(used),
        jnp.asarray(jc), jnp.asarray(tc), jnp.asarray(bw_avail),
        jnp.asarray(bw_used), jnp.asarray(elig), jnp.asarray(ask),
        jnp.int32(bw_ask), jnp.int32(count), jnp.float32(penalty),
    )


def assert_match(args, jd, td):
    c0, r0 = solve_waterfill(*args, jd, td)
    c1, r1 = solve_waterfill_pallas(*args, jd, td, interpret=True)
    np.testing.assert_array_equal(np.asarray(c0), np.asarray(c1))
    assert int(r0) == int(r1)


def test_differential_random():
    rng = np.random.default_rng(7)
    for _ in range(4):
        assert_match(random_instance(rng, 64), False, False)


def test_differential_distinct_flags():
    rng = np.random.default_rng(8)
    assert_match(random_instance(rng, 64), True, False)
    assert_match(random_instance(rng, 64), False, True)


def test_edge_cases():
    rng = np.random.default_rng(9)
    args = list(random_instance(rng, 64))
    # count=0: nothing places
    args[10] = jnp.int32(0)
    assert_match(tuple(args), False, False)
    # demand exceeding total capacity: all capacity used, rest unplaced
    args[10] = jnp.int32(10_000_000)
    assert_match(tuple(args), False, False)
    # nothing eligible
    args[7] = jnp.zeros_like(args[7])
    args[10] = jnp.int32(50)
    assert_match(tuple(args), False, False)


def test_tie_break_matches_stable_argsort():
    # Identical nodes -> identical scores: the partial round must pick
    # the lowest node indices, like the jnp path's stable argsort.
    n = 64
    total = jnp.full((n, 4), 1000, dtype=jnp.int32)
    args = (
        total, total[:, :2].astype(jnp.float32),
        jnp.zeros((n, 4), jnp.int32),
        jnp.zeros((n,), jnp.int32), jnp.zeros((n,), jnp.int32),
        jnp.full((n,), 100, jnp.int32), jnp.zeros((n,), jnp.int32),
        jnp.ones((n,), bool), jnp.asarray([10, 10, 0, 0], jnp.int32),
        jnp.int32(0), jnp.int32(7), jnp.float32(0.0),
    )
    c0, r0 = solve_waterfill(*args, False, False)
    c1, r1 = solve_waterfill_pallas(*args, False, False, interpret=True)
    np.testing.assert_array_equal(np.asarray(c0), np.asarray(c1))
    assert int(np.asarray(c1).sum()) == 7
    assert np.asarray(c1)[:7].sum() == 7  # lowest indices won the tie


def test_batched_matches_vmapped():
    rng = np.random.default_rng(11)
    rows = [random_instance(rng, 64) for _ in range(3)]
    # Pad to a uniform batch the way the coalescer stacks entries.
    cols = list(zip(*(r[:10] for r in rows)))
    stacked = [jnp.stack(c) for c in cols]
    counts = jnp.asarray([int(r[10]) for r in rows], dtype=jnp.int32)
    pens = jnp.asarray([float(r[11]) for r in rows], dtype=jnp.float32)
    c0, r0 = solve_waterfill_batched(*stacked, counts, pens, False, False)
    c1, r1 = solve_waterfill_pallas_batched(
        *stacked, counts, pens, False, False, interpret=True
    )
    np.testing.assert_array_equal(np.asarray(c0), np.asarray(c1))
    np.testing.assert_array_equal(np.asarray(r0), np.asarray(r1))


def test_coalescer_uses_pallas_in_interpret_mode(monkeypatch):
    from nomad_tpu.ops.coalesce import CoalescingSolver

    monkeypatch.setenv("NOMAD_TPU_PALLAS", "interpret")
    pallas_solve.reset_pallas_failed()
    assert pallas_solve.pallas_mode() == "interpret"
    rng = np.random.default_rng(12)
    args = random_instance(rng, 64)
    solver = CoalescingSolver()
    fetch = solver.submit(*args[:10], int(args[10]), float(args[11]))
    counts, unplaced = fetch()
    c0, r0 = solve_waterfill(*args, False, False)
    np.testing.assert_array_equal(np.asarray(c0), counts)
    assert int(r0) == unplaced
    # The pallas path must have actually run — a silent fallback to the
    # jnp solver would produce identical results and mask a regression.
    assert not pallas_solve._STATE["failed"]
    assert len(pallas_solve._STATE["proven"]) >= 1


def test_fallback_disables_pallas(monkeypatch):
    monkeypatch.setenv("NOMAD_TPU_PALLAS", "interpret")
    pallas_solve.reset_pallas_failed()
    assert pallas_solve.pallas_mode() == "interpret"
    pallas_solve.mark_pallas_failed()
    assert pallas_solve.pallas_mode() == "off"
    pallas_solve.reset_pallas_failed()


def test_mode_defaults_off_on_cpu(monkeypatch):
    monkeypatch.delenv("NOMAD_TPU_PALLAS", raising=False)
    pallas_solve.reset_pallas_failed()
    assert pallas_solve.pallas_mode() == "off"  # tests pin the cpu backend


# The fuzz corpus: the same randomized instances the waterfill/rounds/
# greedy three-way agreement runs on (test_fuzz_differential.py), so the
# pallas kernel joins the oracle-parity chain at its widest point.
N_PALLAS_FUZZ_SEEDS = int(__import__("os").environ.get(
    "NOMAD_TPU_PALLAS_FUZZ_SEEDS", 16))


@pytest.mark.parametrize("seed", range(N_PALLAS_FUZZ_SEEDS))
def test_fuzz_pallas_vs_waterfill(seed):
    from test_fuzz_differential import _random_solve_inputs

    rng = np.random.default_rng(10_000 + seed)  # same corpus as threeway
    s = _random_solve_inputs(rng)
    sched_cap = s["total"][:, :2].astype(np.float32)
    args = (
        jnp.asarray(s["total"]), jnp.asarray(sched_cap),
        jnp.asarray(s["used"]), jnp.asarray(s["job_count"]),
        jnp.asarray(s["tg_count"]), jnp.asarray(s["bw_avail"]),
        jnp.asarray(s["bw_used"]), jnp.asarray(s["eligible"]),
        jnp.asarray(s["ask"]), jnp.int32(s["bw_ask"]),
        jnp.int32(s["count"]), jnp.float32(s["penalty"]),
    )
    c0, r0 = solve_waterfill(*args, s["jd"], s["td"])
    c1, r1 = solve_waterfill_pallas(*args, s["jd"], s["td"], interpret=True)
    np.testing.assert_array_equal(
        np.asarray(c0), np.asarray(c1),
        err_msg=f"pallas != waterfill (seed {seed})",
    )
    assert int(r0) == int(r1), seed
