"""Optimistic plan pipeline tests (plan_pipeline.py): batch intake order,
queue shutdown hardening (ERR_QUEUE_DISABLED on failover — workers
blocked in submit_plan must unblock promptly), batched commit with
transaction-time conflict attribution, and the scheduler_workers knob."""

import threading
import time

import pytest

from nomad_tpu import mock, structs
from nomad_tpu.server import Server, ServerConfig
from nomad_tpu.server.plan_queue import (
    ERR_QUEUE_DISABLED,
    PlanQueue,
    PlanQueueError,
)
from nomad_tpu.structs import Evaluation, Plan, Resources, generate_uuid


# -- queue intake ------------------------------------------------------------


def test_dequeue_batch_priority_fifo_order():
    """One drain takes up to K plans in priority-FIFO order; leftovers
    stay queued for the next cycle."""
    q = PlanQueue()
    q.set_enabled(True)
    for eval_id, prio in (("lo", 10), ("m1", 50), ("hi", 90), ("m2", 50)):
        q.enqueue(Plan(eval_id=eval_id, priority=prio))
    batch = q.dequeue_batch(3, timeout=0.5)
    assert [p.plan.eval_id for p in batch] == ["hi", "m1", "m2"]
    rest = q.dequeue_batch(3, timeout=0.5)
    assert [p.plan.eval_id for p in rest] == ["lo"]
    q.set_enabled(False)


def test_dequeue_batch_lone_plan_returns_immediately():
    """The batch drain never waits for followers: a lone plan must not
    pay a batching window (the latency-vs-batching tradeoff is resolved
    by draining only what is already queued)."""
    q = PlanQueue()
    q.set_enabled(True)
    q.enqueue(Plan(eval_id="only", priority=50))
    t0 = time.monotonic()
    batch = q.dequeue_batch(8, timeout=5.0)
    elapsed = time.monotonic() - t0
    assert len(batch) == 1 and batch[0].plan.eval_id == "only"
    assert elapsed < 0.5, f"lone plan waited {elapsed:.2f}s for a batch"
    q.set_enabled(False)


# -- shutdown hardening ------------------------------------------------------


def test_flush_fails_pending_with_queue_disabled():
    q = PlanQueue()
    q.set_enabled(True)
    pending = q.enqueue(Plan(eval_id="x", priority=50))
    q.set_enabled(False)
    with pytest.raises(PlanQueueError) as ei:
        pending.wait(timeout=1.0)
    assert ERR_QUEUE_DISABLED in str(ei.value)


def test_worker_blocked_on_submit_unblocks_on_failover():
    """Regression: a worker blocked on submit_plan when leadership flips
    (plan queue disabled) must unblock PROMPTLY with ERR_QUEUE_DISABLED —
    outstanding PendingPlan futures are failed, not leaked until the
    eval's nack timer redelivers somewhere else."""
    srv = Server(ServerConfig(scheduler_backend="host", num_schedulers=0))
    srv.plan_queue.set_enabled(True)
    srv.eval_broker.set_enabled(True)
    # The pipeline is deliberately NOT started: the plan stays pending,
    # like on an applier that is busy (or gone) when leadership flips.
    try:
        errs = []

        def submit():
            try:
                srv.plan_submit(Plan(eval_id=generate_uuid(), priority=50))
            except Exception as e:  # noqa: BLE001 - asserting the type below
                errs.append(e)

        t = threading.Thread(target=submit, daemon=True)
        t.start()
        deadline = time.monotonic() + 5
        while srv.plan_queue.depth() == 0 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert srv.plan_queue.depth() == 1

        t0 = time.monotonic()
        srv.plan_queue.set_enabled(False)  # revokeLeadership path
        t.join(timeout=2.0)
        unblock = time.monotonic() - t0
        assert not t.is_alive(), "worker still blocked after failover"
        assert unblock < 1.0, f"unblock took {unblock:.2f}s"
        assert errs and isinstance(errs[0], PlanQueueError)
        assert ERR_QUEUE_DISABLED in str(errs[0])
    finally:
        srv.shutdown()


# -- batched commit + conflict attribution ----------------------------------


def _seed_eval(srv, job_id):
    ev = Evaluation(
        id=generate_uuid(), priority=50,
        type=structs.JOB_TYPE_SERVICE,
        triggered_by=structs.EVAL_TRIGGER_JOB_REGISTER,
        job_id=job_id, status=structs.EVAL_STATUS_PENDING,
    )
    srv.raft.apply("eval_update", {"evals": [ev]})
    return ev


def _place_plan(eval_id, token, node_id, cpu, snapshot_index):
    alloc = mock.alloc()
    alloc.node_id = node_id
    alloc.eval_id = eval_id
    # cpu/mem-bound contention only: the mock's NIC reservations would
    # fail the port check before capacity ever mattered.
    alloc.resources = Resources(cpu=cpu, memory_mb=64)
    alloc.task_resources = {}
    alloc.desired_status = structs.ALLOC_DESIRED_STATUS_RUN
    plan = Plan(eval_id=eval_id, eval_token=token, priority=50,
                snapshot_index=snapshot_index)
    plan.append_alloc(alloc)
    return plan


def test_pipeline_batch_commits_in_order_and_bounces_conflict():
    """Two plans from the same pre-batch snapshot contending for one
    node's capacity, drained in ONE pipeline batch: the first commits,
    the second fails verification AND is attributed as a transaction-time
    conflict (capacity committed after its snapshot index overlaps its
    footprint) — the Omega bounce, riding the existing RefreshIndex
    path. Exactly one alloc lands on the node."""
    from nomad_tpu.server.plan_pipeline import PIPELINE_TOTALS

    srv = Server(ServerConfig(scheduler_backend="host", num_schedulers=0))
    srv.plan_queue.set_enabled(True)
    srv.eval_broker.set_enabled(True)
    try:
        node = mock.node()
        # 900 cpu headroom after the mock's 100 reserved: fits one 600
        # ask, not two (the mock NIC stays so bandwidth checks pass).
        node.resources.cpu = 1000
        srv.raft.apply("node_register", {"node": node})
        ev_a = _seed_eval(srv, "job-a")
        ev_b = _seed_eval(srv, "job-b")
        dq_a, tok_a, _ = srv.eval_dequeue(["service"], timeout=1.0)
        dq_b, tok_b, _ = srv.eval_dequeue(["service"], timeout=1.0)
        assert {dq_a.id, dq_b.id} == {ev_a.id, ev_b.id}
        tokens = {dq_a.id: tok_a, dq_b.id: tok_b}

        snap_index = srv.raft.applied_index  # both pre-commit snapshots
        plan_a = _place_plan(dq_a.id, tokens[dq_a.id], node.id, 600,
                             snap_index)
        plan_b = _place_plan(dq_b.id, tokens[dq_b.id], node.id, 600,
                             snap_index)
        # Enqueue BOTH before the pipeline starts: one drain, one batch.
        pend_a = srv.plan_queue.enqueue(plan_a)
        pend_b = srv.plan_queue.enqueue(plan_b)
        conflicts0 = PIPELINE_TOTALS.stats()["conflicts"]
        srv.plan_applier.start()

        res_a = pend_a.wait(timeout=5.0)
        res_b = pend_b.wait(timeout=5.0)
        # Commit order is queue order: A whole-committed...
        assert res_a.node_allocation and res_a.refresh_index == 0
        assert not res_a.conflict
        # ...B bounced whole with a refresh token and conflict mark.
        assert not res_b.node_allocation
        assert res_b.refresh_index > 0
        assert res_b.conflict is True
        assert PIPELINE_TOTALS.stats()["conflicts"] == conflicts0 + 1

        allocs = [a for a in srv.state_store.allocs_by_node(node.id)
                  if a.desired_status == structs.ALLOC_DESIRED_STATUS_RUN]
        assert len(allocs) == 1, "double-committed capacity"
    finally:
        srv.shutdown()


def test_stale_refresh_without_overlap_is_not_a_conflict():
    """A plan that fails verification for a reason its own snapshot
    already contained (target node never existed) is a plain stale-data
    refresh, NOT a conflict — attribution requires overlapping capacity
    committed after the plan's snapshot index."""
    srv = Server(ServerConfig(scheduler_backend="host", num_schedulers=0))
    srv.plan_queue.set_enabled(True)
    srv.eval_broker.set_enabled(True)
    try:
        node = mock.node()
        srv.raft.apply("node_register", {"node": node})
        ev = _seed_eval(srv, "job-x")
        dq, tok, _ = srv.eval_dequeue(["service"], timeout=1.0)
        plan = _place_plan(dq.id, tok, "no-such-node", 100,
                           srv.raft.applied_index)
        pend = srv.plan_queue.enqueue(plan)
        srv.plan_applier.start()
        res = pend.wait(timeout=5.0)
        assert res.refresh_index > 0
        assert res.conflict is False
    finally:
        srv.shutdown()


def test_all_bounce_batch_does_not_pin_stale_snapshot():
    """Regression: a batch that commits NOTHING (all plans bounce) leaves
    no applies in flight, and the next batch must re-snapshot fresh —
    out-of-band raft writes (capacity freed, nodes registered) have to be
    visible, or every later plan verifies against the pinned stale
    snapshot and bounces forever."""
    srv = Server(ServerConfig(scheduler_backend="host", num_schedulers=0))
    srv.plan_queue.set_enabled(True)
    srv.eval_broker.set_enabled(True)
    try:
        node = mock.node()
        node.resources.cpu = 1000
        srv.raft.apply("node_register", {"node": node})
        srv.plan_applier.start()

        # Batch 1: bounces whole (2000 cpu never fits the 1000-cpu node)
        # — nothing commits, nothing dispatches.
        ev_a = _seed_eval(srv, "job-bounce")
        dq_a, tok_a, _ = srv.eval_dequeue(["service"], timeout=1.0)
        res_a = srv.plan_queue.enqueue(
            _place_plan(dq_a.id, tok_a, node.id, 2000,
                        srv.raft.applied_index)
        ).wait(timeout=5.0)
        assert res_a.refresh_index > 0 and not res_a.node_allocation

        # Out-of-band raft write AFTER the all-bounce batch: new node.
        node2 = mock.node()
        node2.resources.cpu = 4000
        srv.raft.apply("node_register", {"node": node2})

        # Batch 2 places on node2 — a pinned pre-node2 snapshot would
        # treat it as unknown and bounce this plan indefinitely.
        ev_b = _seed_eval(srv, "job-after")
        dq_b, tok_b, _ = srv.eval_dequeue(["service"], timeout=1.0)
        res_b = srv.plan_queue.enqueue(
            _place_plan(dq_b.id, tok_b, node2.id, 2000,
                        srv.raft.applied_index)
        ).wait(timeout=5.0)
        assert res_b.refresh_index == 0 and res_b.node_allocation, \
            "fresh raft state invisible: stale optimistic snapshot pinned"
    finally:
        srv.shutdown()


def test_batch_commits_carry_distinct_real_indices():
    """Regression: the commit-footprint log must record each of a
    batch's K commits at its OWN raft index (fixed up to the real index
    once the apply resolves), not all K at the same applied_index + 1 —
    identical indices break the reversed scan's early-exit and
    under-attribute conflicts for snapshots taken mid-batch."""
    srv = Server(ServerConfig(scheduler_backend="host", num_schedulers=0))
    srv.plan_queue.set_enabled(True)
    srv.eval_broker.set_enabled(True)
    try:
        nodes = []
        for _ in range(2):
            n = mock.node()
            n.resources.cpu = 4000
            srv.raft.apply("node_register", {"node": n})
            nodes.append(n)
        ev_a = _seed_eval(srv, "job-i1")
        ev_b = _seed_eval(srv, "job-i2")
        dq_a, tok_a, _ = srv.eval_dequeue(["service"], timeout=1.0)
        dq_b, tok_b, _ = srv.eval_dequeue(["service"], timeout=1.0)
        toks = {dq_a.id: tok_a, dq_b.id: tok_b}
        snap_index = srv.raft.applied_index
        # Disjoint nodes: both whole-commit in one batch.
        pend_a = srv.plan_queue.enqueue(
            _place_plan(dq_a.id, toks[dq_a.id], nodes[0].id, 500,
                        snap_index))
        pend_b = srv.plan_queue.enqueue(
            _place_plan(dq_b.id, toks[dq_b.id], nodes[1].id, 500,
                        snap_index))
        srv.plan_applier.start()
        res_a = pend_a.wait(timeout=5.0)
        res_b = pend_b.wait(timeout=5.0)
        assert res_a.node_allocation and res_b.node_allocation
        assert res_a.alloc_index != res_b.alloc_index
        logged = {idx: touched
                  for idx, touched in srv.plan_applier._commit_log}
        assert logged == {
            res_a.alloc_index: {nodes[0].id},
            res_b.alloc_index: {nodes[1].id},
        }
    finally:
        srv.shutdown()


# -- the scheduler_workers knob ----------------------------------------------


def test_scheduler_workers_validation_and_alias():
    with pytest.raises(ValueError):
        ServerConfig(scheduler_workers=-1)
    with pytest.raises(ValueError):
        ServerConfig(scheduler_workers=1000)
    with pytest.raises(ValueError):
        ServerConfig(scheduler_workers="four")
    with pytest.raises(ValueError):
        ServerConfig(plan_batch_size=0)
    # Legacy alias wins when set; both spellings read resolved.
    cfg = ServerConfig(num_schedulers=1)
    assert cfg.scheduler_workers == 1 and cfg.num_schedulers == 1
    cfg = ServerConfig(scheduler_workers=6)
    assert cfg.num_schedulers == 6
    # Default posture: N >= 4 concurrent workers.
    assert ServerConfig().scheduler_workers >= 4


def test_scheduler_workers_agent_config_knob():
    from nomad_tpu.agent_config import parse_config

    cfg = parse_config('server { enabled = true\n scheduler_workers = 8 }')
    assert cfg.server.scheduler_workers == 8
    with pytest.raises(ValueError):
        parse_config('server { scheduler_workers = 500 }')
    # The legacy spelling must not bypass the range check — neither at
    # parse time nor through the agent's post-construction override.
    with pytest.raises(ValueError):
        parse_config('server { num_schedulers = 500 }')
    from nomad_tpu.agent import Agent, AgentConfig

    bad = AgentConfig.dev()
    bad.num_schedulers = 200
    with pytest.raises(ValueError):
        Agent(bad)
    # merge: later file overrides
    base = parse_config('server { scheduler_workers = 2 }')
    over = parse_config('server { scheduler_workers = 8 }')
    assert base.merge(over).server.scheduler_workers == 8


def test_started_server_spawns_configured_workers():
    srv = Server(ServerConfig(scheduler_backend="host",
                              scheduler_workers=5))
    try:
        srv.start()
        assert len(srv.workers) == 5
        assert all(w.is_alive() for w in srv.workers)
    finally:
        srv.shutdown()


# -- runtime validation of the static lock order -----------------------------


def test_lock_watchdog_asserts_static_order_under_pipeline():
    """The nomadlint lock-order pass validated DYNAMICALLY: compute the
    canonical acquisition order from the current tree, install the
    LockWatchdog (every lock built at a known construction site gets
    acquisition tracking), then drive a full multi-worker register →
    eval → plan-pipeline → apply workload. Every nested acquisition any
    thread performs must respect the statically computed order — a
    violation here means the static graph missed a real inversion."""
    from nomad_tpu.telemetry import LockWatchdog
    from tools.nomadlint import lockorder
    from tools.nomadlint.project import Project

    an = lockorder.analyze(Project())
    assert an.order and an.sites() and not an.cycles
    wd = LockWatchdog(order=an.order, sites=an.sites())
    with wd:
        srv = Server(ServerConfig(scheduler_backend="host",
                                  scheduler_workers=4))
        try:
            srv.start()
            for _ in range(10):
                srv.node_register(mock.node())
            eval_ids = [srv.job_register(mock.job())[0] for _ in range(3)]
            for eid in eval_ids:
                ev = srv.wait_for_eval(eid, timeout=20.0)
                assert ev.status == structs.EVAL_STATUS_COMPLETE
        finally:
            srv.shutdown()
    wd.assert_clean()
    observed = wd.observed_edges()
    assert observed, "watchdog tracked no nested acquisitions — the " \
        "construction-site map is stale"
    # The workload exercised edges the static pass predicted (e.g. the
    # FSM's raft lock feeding the broker/state/telemetry locks).
    assert observed & an.closure(), (
        f"no overlap between observed {sorted(observed)[:5]}... and the "
        "static edge closure"
    )
