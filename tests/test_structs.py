"""Data model tests, mirroring the reference's structs tests
(/root/reference/nomad/structs/structs_test.go, funcs_test.go,
network_test.go)."""

import pytest

from nomad_tpu import mock, structs
from nomad_tpu.network import NetworkIndex
from nomad_tpu.structs import (
    Allocation,
    Constraint,
    Job,
    NetworkResource,
    Node,
    Plan,
    PlanResult,
    Resources,
    ValidationError,
    allocs_fit,
    filter_terminal_allocs,
    remove_allocs,
    score_fit,
)
from nomad_tpu.version import check_version_constraint


def test_job_validate():
    j = Job()
    with pytest.raises(ValidationError) as exc:
        j.validate()
    msg = str(exc.value)
    for expected in ("missing job region", "missing job ID", "missing job name",
                     "missing job type", "missing job datacenters",
                     "missing job task groups"):
        assert expected in msg

    j = mock.job()
    j.validate()  # must not raise


def test_resources_superset():
    base = Resources(cpu=1000, memory_mb=512, disk_mb=1000, iops=100)
    ok, _ = base.superset(Resources(cpu=1000, memory_mb=512, disk_mb=1000, iops=100))
    assert ok
    ok, dim = base.superset(Resources(cpu=1001))
    assert not ok and dim == "cpu exhausted"
    ok, dim = base.superset(Resources(memory_mb=513))
    assert not ok and dim == "memory exhausted"
    ok, dim = base.superset(Resources(disk_mb=1001))
    assert not ok and dim == "disk exhausted"
    ok, dim = base.superset(Resources(iops=101))
    assert not ok and dim == "iops exhausted"


def test_resources_add():
    r1 = Resources(
        cpu=2000, memory_mb=2048, disk_mb=10000, iops=100,
        networks=[NetworkResource(cidr="10.0.0.0/8", mbits=100, reserved_ports=[22])],
    )
    r2 = Resources(
        cpu=1000, memory_mb=1024, disk_mb=5000, iops=50,
        networks=[NetworkResource(ip="10.0.0.1", mbits=50, reserved_ports=[80])],
    )
    r1.add(r2)
    assert r1.cpu == 3000
    assert r1.memory_mb == 3072
    assert r1.disk_mb == 15000
    assert r1.iops == 150
    # Same (empty) device -> merged
    assert len(r1.networks) == 1
    assert r1.networks[0].mbits == 150
    assert r1.networks[0].reserved_ports == [22, 80]


def test_allocs_fit():
    node = Node(
        id="n1",
        resources=Resources(
            cpu=2000, memory_mb=2048, disk_mb=10000, iops=100,
            networks=[NetworkResource(device="eth0", cidr="10.0.0.0/8", mbits=100)],
        ),
        reserved=Resources(
            cpu=1000, memory_mb=1024, disk_mb=5000, iops=50,
            networks=[NetworkResource(device="eth0", ip="10.0.0.1",
                                      mbits=50, reserved_ports=[80])],
        ),
    )
    a1 = Allocation(
        id="a1",
        resources=Resources(
            cpu=1000, memory_mb=1024, disk_mb=5000, iops=50,
            networks=[NetworkResource(device="eth0", ip="10.0.0.1",
                                      mbits=50, reserved_ports=[8000])],
        ),
    )
    fit, dim, used = allocs_fit(node, [a1])
    assert fit, dim
    assert used.cpu == 2000
    assert used.memory_mb == 2048

    # Double the alloc: should be exhausted
    fit, dim, used = allocs_fit(node, [a1, a1])
    assert not fit
    assert dim == "cpu exhausted"
    assert used.cpu == 3000


def test_score_fit():
    node = Node(
        resources=Resources(cpu=4096, memory_mb=8192),
        reserved=Resources(cpu=2048, memory_mb=4096),
    )
    # Perfect fit -> 18 (reference: funcs_test.go:184-192)
    assert score_fit(node, Resources(cpu=2048, memory_mb=4096)) == pytest.approx(18.0)
    # Worst fit -> 0 (funcs_test.go:194-202)
    assert score_fit(node, Resources(cpu=0, memory_mb=0)) == pytest.approx(0.0)
    # Mid-case (funcs_test.go:204-212)
    score = score_fit(node, Resources(cpu=1024, memory_mb=2048))
    assert 10.0 < score < 16.0
    assert score == pytest.approx(20.0 - 2 * (10 ** 0.5))


def test_filter_and_remove_allocs():
    a1 = Allocation(id="1", desired_status=structs.ALLOC_DESIRED_STATUS_RUN)
    a2 = Allocation(id="2", desired_status=structs.ALLOC_DESIRED_STATUS_STOP)
    a3 = Allocation(id="3", desired_status=structs.ALLOC_DESIRED_STATUS_EVICT)
    a4 = Allocation(id="4", desired_status=structs.ALLOC_DESIRED_STATUS_FAILED)
    assert filter_terminal_allocs([a1, a2, a3, a4]) == [a1]
    assert remove_allocs([a1, a2, a3], [a2]) == [a1, a3]


def test_plan_helpers():
    plan = Plan(node_update={}, node_allocation={})
    alloc = mock.alloc()
    plan.append_update(alloc, structs.ALLOC_DESIRED_STATUS_STOP, "test")
    assert len(plan.node_update[alloc.node_id]) == 1
    assert plan.node_update[alloc.node_id][0].desired_status == "stop"
    # Original untouched
    assert alloc.desired_status == structs.ALLOC_DESIRED_STATUS_RUN
    plan.pop_update(alloc)
    assert alloc.node_id not in plan.node_update
    assert plan.is_noop()

    plan.append_alloc(alloc)
    assert not plan.is_noop()

    result = PlanResult(node_allocation={alloc.node_id: [alloc]})
    full, expected, actual = result.full_commit(plan)
    assert full and expected == 1 and actual == 1

    result2 = PlanResult()
    full, expected, actual = result2.full_commit(plan)
    assert not full and expected == 1 and actual == 0


def test_network_index():
    node = mock.node()
    idx = NetworkIndex()
    assert not idx.set_node(node)
    assert idx.avail_bandwidth["eth0"] == 1000
    assert idx.used_bandwidth["eth0"] == 1
    assert 22 in idx.used_ports["192.168.0.100"]

    # Assign a network with a dynamic port
    ask = NetworkResource(mbits=50, dynamic_ports=["http"])
    offer, err = idx.assign_network(ask)
    assert offer is not None, err
    assert offer.ip == "192.168.0.100"
    assert len(offer.reserved_ports) == 1
    port = offer.reserved_ports[0]
    assert 20000 <= port < 60000
    assert offer.map_dynamic_ports() == {"http": port}

    # Bandwidth exceeded
    big = NetworkResource(mbits=10000)
    offer, err = idx.assign_network(big)
    assert offer is None
    assert err == "bandwidth exceeded"

    # Reserved port collision
    taken = NetworkResource(mbits=1, reserved_ports=[22])
    offer, err = idx.assign_network(taken)
    assert offer is None
    assert err == "reserved port collision"


def test_network_overcommitted():
    idx = NetworkIndex()
    idx.avail_bandwidth["eth0"] = 100
    idx.used_bandwidth["eth0"] = 50
    assert not idx.overcommitted()
    idx.used_bandwidth["eth0"] = 150
    assert idx.overcommitted()


def test_version_constraints():
    assert check_version_constraint("1.2.3", ">= 1.0")
    assert check_version_constraint("1.2.3", ">= 1.0, < 2.0")
    assert not check_version_constraint("2.1.0", ">= 1.0, < 2.0")
    assert check_version_constraint("1.2.3", "~> 1.2")
    assert not check_version_constraint("1.3.0", "~> 1.2.0")
    assert check_version_constraint("1.2.9", "~> 1.2.0")
    assert check_version_constraint("0.1.0", "= 0.1.0")
    assert not check_version_constraint("0.1.1", "= 0.1.0")
    assert check_version_constraint("0.1.1", "!= 0.1.0")
    # Parse failures -> False
    assert not check_version_constraint("banana", ">= 1.0")
    assert not check_version_constraint("1.0", "banana")


def test_eval_make_plan_and_rolling():
    ev = mock.evaluation()
    job = mock.job()
    plan = ev.make_plan(job)
    assert plan.eval_id == ev.id
    assert plan.priority == ev.priority
    assert plan.all_at_once == job.all_at_once

    rolling = ev.next_rolling_eval(30.0)
    assert rolling.previous_eval == ev.id
    assert rolling.wait == 30.0
    assert rolling.triggered_by == structs.EVAL_TRIGGER_ROLLING_UPDATE
    assert rolling.job_id == ev.job_id
    assert rolling.id != ev.id


def test_should_drain_node():
    assert not structs.should_drain_node(structs.NODE_STATUS_INIT)
    assert not structs.should_drain_node(structs.NODE_STATUS_READY)
    assert structs.should_drain_node(structs.NODE_STATUS_DOWN)
    with pytest.raises(ValueError):
        structs.should_drain_node("bogus")
