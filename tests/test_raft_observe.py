"""Raft & recovery observatory (nomad_tpu/raft_observe.py).

Covers the ISSUE-15 test satellites:

- stage-partition reconciliation: the write-path stages are a PARTITION
  of submit→applied by construction (same contract as lifecycle.py's
  waterfall) — unit-pinned on synthetic anchors and end-to-end against a
  live single-member raft node's own records;
- follower-lag math under a one-way partition (the PR 2 fault sites):
  the partitioned follower's match-index delta grows while the healthy
  follower keeps up, and healing converges the lag back to zero;
- e2e dev-cluster restart: a ClusterServer killed and rebuilt from its
  data dir reports entries_replayed > 0 and reproduces the pre-kill FSM
  state digest exactly;
- config validation, live-agent HTTP/SDK/Prometheus/bundle surfaces,
  and the observer-topic digest exclusion.
"""

import json
import time
import urllib.error
import urllib.request

import pytest

from nomad_tpu import faults, mock, structs
from nomad_tpu.raft.node import RaftConfig, RaftNode
from nomad_tpu.raft_observe import (
    ANCHORS,
    STAGES,
    RaftObserveConfig,
    RaftObservatory,
    fsm_state_digest,
    stage_partition,
)
from nomad_tpu.rpc import ConnPool, RPCServer
from nomad_tpu.server import ServerConfig
from nomad_tpu.server.cluster import (
    ClusterConfig,
    ClusterServer,
    form_cluster,
    wait_for_leader,
)


def _wait(predicate, timeout=15.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {msg}")


# ---------------------------------------------------------------------------
# config validation
# ---------------------------------------------------------------------------


def test_config_defaults_and_parse():
    cfg = RaftObserveConfig.parse(None)
    assert cfg.enabled and cfg.poll_interval == 1.0
    cfg = RaftObserveConfig.parse(
        {"enabled": False, "poll_interval": 0.5, "events_interval": 0})
    assert not cfg.enabled and cfg.events_interval == 0


@pytest.mark.parametrize("spec", [
    {"pol_interval": 1.0},           # typo'd key
    {"poll_interval": 0},            # nonsense cadence
    {"events_interval": -1},         # negative cadence
    "not-a-mapping",
])
def test_config_rejects_bad_specs(spec):
    with pytest.raises(ValueError):
        RaftObserveConfig.parse(spec)


def test_server_config_parses_raft_observe_block():
    cfg = ServerConfig(raft_observe={"poll_interval": 0.25})
    assert cfg.raft_observe_config.poll_interval == 0.25
    with pytest.raises(ValueError):
        ServerConfig(raft_observe={"bogus": 1})


# ---------------------------------------------------------------------------
# stage-partition reconciliation (the lifecycle.py contract)
# ---------------------------------------------------------------------------


def test_stage_partition_full_anchor_chain_reconciles():
    t0 = 100.0
    anchors = {a: t0 + i * 0.010 for i, a in enumerate(ANCHORS)}
    stages = stage_partition(anchors)
    assert set(stages) == set(STAGES)
    total = (anchors["resolved"] - anchors["submit"]) * 1000.0
    assert sum(stages.values()) == pytest.approx(total, abs=1e-9)
    for ms in stages.values():
        assert ms == pytest.approx(10.0, abs=1e-6)


def test_stage_partition_missing_anchors_collapse_to_zero():
    """A single-member cluster never stamps first_ack: the replicate
    stage must be exactly zero wide and the partition must still sum to
    the measured total."""
    anchors = {"submit": 1.0, "persisted": 1.002, "committed": 1.003,
               "fsm_start": 1.004, "fsm_end": 1.009, "resolved": 1.0095}
    stages = stage_partition(anchors)
    assert stages["replicate"] == 0.0
    total = (anchors["resolved"] - anchors["submit"]) * 1000.0
    assert sum(stages.values()) == pytest.approx(total, abs=1e-9)


def test_stage_partition_out_of_order_anchor_clamps():
    """An anchor stamped behind the running cursor (clock races across
    threads) clamps to zero width instead of going negative — the
    partition property survives."""
    anchors = {"submit": 5.0, "persisted": 5.010, "first_ack": 5.002,
               "committed": 5.012, "fsm_start": 5.013, "fsm_end": 5.014,
               "resolved": 5.015}
    stages = stage_partition(anchors)
    assert stages["replicate"] == 0.0
    assert all(ms >= 0 for ms in stages.values())
    total = (anchors["resolved"] - anchors["submit"]) * 1000.0
    assert sum(stages.values()) == pytest.approx(total, abs=1e-9)


class _KVFSM:
    def __init__(self):
        self.data = {}

    def apply(self, index, msg_type, payload):
        self.data[payload["k"]] = payload["v"]

    def snapshot_bytes(self):
        import pickle

        return pickle.dumps(self.data)

    def restore_bytes(self, data):
        import pickle

        self.data = pickle.loads(data)


def _make_node(node_id, peers, fsm, **kw):
    rpc = RPCServer()
    rpc.start()
    peers[node_id] = rpc.addr
    cfg = RaftConfig(node_id=node_id, peers=peers, bootstrap_expect=1,
                     **kw)
    return RaftNode(cfg, fsm, rpc, pool=ConnPool(timeout=2.0)), rpc


def test_write_path_records_reconcile_on_live_node():
    """End-to-end half of the reconciliation satellite: every finalized
    record's stage sums equal its own measured submit→applied, and the
    drained books land per msg_type in the observatory."""
    peers = {}
    node, rpc = _make_node("a", peers, _KVFSM())
    node.start()
    try:
        _wait(lambda: node.is_leader, msg="leadership")
        t0 = time.monotonic()
        for i in range(20):
            node.apply("kv", {"k": f"k{i}", "v": i}).result(5.0)
        wall_ms = (time.monotonic() - t0) * 1000.0
        seq, records = node.write_path_records(0)
        kv = [r for r in records if r["msg_type"] == "kv"]
        assert len(kv) == 20
        total = 0.0
        for rec in kv:
            stages = stage_partition(rec["anchors"])
            rec_total = (rec["anchors"]["resolved"]
                         - rec["anchors"]["submit"]) * 1000.0
            assert sum(stages.values()) == pytest.approx(
                rec_total, abs=1e-9)
            assert rec["bytes"] > 0
            total += rec_total
        # The per-entry totals must stay inside the measured loop wall
        # (they are sub-spans of it).
        assert total <= wall_ms + 1.0
        obs = RaftObservatory(lambda: node)
        obs.refresh()
        snap = obs.snapshot()
        assert snap["write_path"]["kv"]["count"] == 20
        assert snap["write_path"]["kv"]["bytes_per_entry"]["p50"] > 0
        assert snap["raft"]["commit_index"] == snap["raft"]["applied_index"]
        assert snap["log"]["appended_entries"] >= 20
    finally:
        node.shutdown()
        rpc.shutdown()


def test_write_path_ring_overflow_is_counted_not_silent():
    peers = {}
    node, rpc = _make_node("a", peers, _KVFSM())
    node.start()
    try:
        _wait(lambda: node.is_leader, msg="leadership")
        obs = RaftObservatory(lambda: node)
        obs.refresh()  # arms the cursor at the current sequence
        for i in range(1100):  # ring holds 1024
            node.apply("kv", {"k": "k", "v": i}).result(5.0)
        obs.refresh()
        assert obs.records_dropped > 0
        assert (obs.records_ingested + obs.records_dropped
                >= 1100)
    finally:
        node.shutdown()
        rpc.shutdown()


# ---------------------------------------------------------------------------
# follower lag under a one-way partition (PR 2 fault sites)
# ---------------------------------------------------------------------------


def test_follower_lag_under_one_way_partition():
    peers = {}
    fsm_a, fsm_b, fsm_c = _KVFSM(), _KVFSM(), _KVFSM()
    node_a, rpc_a = _make_node("a", peers, fsm_a)
    node_b, rpc_b = _make_node("b", peers, fsm_b)
    node_c, rpc_c = _make_node("c", peers, fsm_c)
    for n in (node_a, node_b, node_c):
        n.start()
    try:
        _wait(lambda: any(n.is_leader for n in (node_a, node_b, node_c)),
              timeout=30.0, msg="leadership")
        nodes = {"a": node_a, "b": node_b, "c": node_c}
        leader = next(n for n in nodes.values() if n.is_leader)
        lagger = "c" if leader.config.node_id != "c" else "b"
        obs = RaftObservatory(lambda: leader)
        # One-way partition of the leader's append stream to the lagger;
        # the lagger's own OUTBOUND votes drop too so its rising term
        # can't depose the leader mid-assertion (the PR 2 chaos tests'
        # one-way-edge posture). The other follower keeps the quorum.
        faults.get_registry().load({"seed": 7, "sites": {
            "raft.append": {
                "mode": "partition",
                "match": f"{leader.config.node_id}->{lagger}",
            },
            "raft.vote": {"mode": "partition", "match": f"{lagger}->"},
        }})
        for i in range(12):
            leader.apply("kv", {"k": f"k{i}", "v": i}).result(5.0)
        leader_applied = leader.applied_index
        obs.refresh()
        snap = obs.snapshot()
        peers_out = snap["replication"]["peers"]
        healthy = next(p for p in peers_out if p != lagger)
        assert peers_out[lagger]["lag_entries"] >= 12
        assert peers_out[healthy]["lag_entries"] == 0
        # The lagger's last ack predates the partition (or never came);
        # the healthy follower acked within the write burst.
        if peers_out[lagger]["last_ack_age_s"] is not None:
            assert (peers_out[lagger]["last_ack_age_s"]
                    > peers_out[healthy]["last_ack_age_s"])
        # Heal: replication resumes and the lagger catches up (leader-
        # agnostic — the lagger's inflated term may force a re-election
        # on first contact, which is raft working as designed).
        faults.get_registry().clear()
        _wait(lambda: nodes[lagger].applied_index >= leader_applied,
              timeout=20.0, msg="lag convergence")
    finally:
        faults.get_registry().clear()
        for n in (node_a, node_b, node_c):
            n.shutdown()
        for r in (rpc_a, rpc_b, rpc_c):
            r.shutdown()


# ---------------------------------------------------------------------------
# e2e dev-cluster restart: replay + state-digest survival
# ---------------------------------------------------------------------------


def test_cluster_restart_recovery_report_and_state_digest(tmp_path):
    """Kill a quiesced single-member ClusterServer, rebuild it from its
    data dir: the recovery report shows entries_replayed > 0 and the
    replayed FSM reproduces the pre-kill state digest exactly."""
    cfg = ServerConfig(scheduler_backend="host", num_schedulers=1)
    ccfg = ClusterConfig(raft_data_dir=str(tmp_path / "raft"))
    (srv,) = form_cluster(1, cfg, ccfg)
    job = mock.job()
    job.task_groups[0].count = 2
    try:
        wait_for_leader([srv])
        for _ in range(6):
            srv.node_register(mock.node())
        eval_id, _ = srv.job_register(job)
        srv.wait_for_eval(eval_id, timeout=15.0)
        applied = srv.raft.applied_index
        digest_before = fsm_state_digest(srv.state_store)
        # A warm start has nothing to recover; the report says so.
        assert srv.raft.recovery["cold_start"] is False
    finally:
        srv.shutdown()

    ccfg2 = ClusterConfig(raft_data_dir=str(tmp_path / "raft"))
    (srv2,) = form_cluster(1, cfg, ccfg2)
    try:
        wait_for_leader([srv2])
        _wait(lambda: srv2.raft.applied_index >= applied, msg="replay")
        obs = srv2.raft_observatory
        obs.refresh()
        recovery = obs.snapshot()["recovery"]
        assert recovery["cold_start"] is True
        assert recovery["entries_replayed"] > 0
        assert recovery["replayed_by_type"].get("node_register", 0) >= 6
        assert recovery["replay_wall_ms"] is not None
        assert recovery["time_to_leader_ms"] is not None
        _wait(lambda: srv2.raft.recovery["time_to_serving_ms"]
              is not None, msg="serving stamp")
        assert fsm_state_digest(srv2.state_store) == digest_before
        assert len(srv2.state_store.allocs_by_job(job.id)) == 2
    finally:
        srv2.shutdown()


# ---------------------------------------------------------------------------
# observer events are digest-excluded
# ---------------------------------------------------------------------------


def test_raft_snapshot_events_are_observer_topic():
    from nomad_tpu.events import OBSERVER_TOPICS, EventBroker
    from nomad_tpu.simcluster.scenario import canonical_events

    assert "Raft" in OBSERVER_TOPICS
    broker = EventBroker(register=False)
    broker.publish("Eval", "EvalUpdated", key="e1",
                   payload={"status": "pending"})
    base = canonical_events(broker.all_events())

    class _FakeRaft:
        applied_index = 3
        commit_index = 3

    obs = RaftObservatory(lambda: _FakeRaft(), events=broker)
    obs.refresh()
    obs.publish_event()
    obs.publish_event()
    assert obs.events_published == 2
    after = canonical_events(broker.all_events())
    assert after["digest"] == base["digest"]
    raft_events = [e for e in broker.all_events() if e.topic == "Raft"]
    assert len(raft_events) == 2
    assert raft_events[0].type == "RaftSnapshot"


# ---------------------------------------------------------------------------
# live-agent surfaces: HTTP + SDK + Prometheus + bundle
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def agent(tmp_path_factory):
    from nomad_tpu.agent import Agent, AgentConfig

    config = AgentConfig.dev()
    config.data_dir = str(tmp_path_factory.mktemp("raft-agent"))
    config.http_port = 0
    config.enable_debug = True
    config.raft_observe = {"poll_interval": 0.2, "events_interval": 0}
    a = Agent(config)
    a.start()
    from nomad_tpu.api import ApiClient

    client = ApiClient(address=a.http.addr)
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        nodes, _ = client.nodes().list()
        if nodes and nodes[0]["status"] == "ready":
            break
        time.sleep(0.1)
    else:
        pytest.fail("dev node never became ready")
    yield a
    a.shutdown()


def _get(agent, path):
    with urllib.request.urlopen(agent.http.addr + path, timeout=10) as r:
        return r.status, r.read()


def test_raft_endpoint_e2e(agent):
    from nomad_tpu.api import ApiClient

    client = ApiClient(address=agent.http.addr)
    job = mock.job()
    job.task_groups[0].count = 1
    job.task_groups[0].tasks[0].driver = "mock_driver"
    job.task_groups[0].tasks[0].config = {"run_for": "20",
                                          "exit_code": "0"}
    job.task_groups[0].tasks[0].resources.networks = []
    eval_id, _ = client.jobs().register(job)
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        ev, _ = client.evaluations().info(eval_id)
        if ev.status == structs.EVAL_STATUS_COMPLETE:
            break
        time.sleep(0.1)
    else:
        pytest.fail("eval never completed")

    status, body = _get(agent, "/v1/agent/raft")
    assert status == 200
    snap = json.loads(body)
    # The dev agent runs the DevMode InProcRaft: attribution degrades
    # honestly — persistence/replication stages zero-wide, fsm_apply
    # carries the cost, the full stage set still partitions. (The
    # RaftNode face is covered by the raw-node tests above and the
    # restart-under-load scenario.)
    assert "job_register" in snap["write_path"]
    books = snap["write_path"]["job_register"]
    assert books["count"] >= 1
    assert set(books["stages_ms"]) == set(STAGES)
    assert books["total_ms"]["max"] > 0
    assert snap["raft"]["applied_index"] >= 1
    assert snap["replication"]["commit_advance"]["entries_per_s"] >= 0

    # Prometheus face of the same endpoint + the main scrape.
    status, body = _get(agent, "/v1/agent/raft?format=prometheus")
    assert status == 200
    text = body.decode()
    assert "# TYPE nomad_raft_write_ms gauge" in text
    assert 'nomad_raft_write_ms{msg_type="job_register",quantile="p95"}' \
        in text
    status, body = _get(agent, "/v1/agent/metrics?format=prometheus")
    assert status == 200
    assert "nomad_raft_write_entries_total" in body.decode()

    # SDK accessor.
    from nomad_tpu.api import ApiClient as _C

    api = _C(address=agent.http.addr).agent()
    sdk = api.raft()
    assert sdk["raft"]["applied_index"] >= snap["raft"]["applied_index"]

    # Debug bundle carries the raft section.
    bundle = api.debug_bundle()
    assert "raft" in bundle
    assert bundle["raft"]["write_path"]

    # Metrics JSON body carries the compact summary.
    metrics = api.metrics()
    assert metrics["raft"]["applied_index"] >= 1


def test_raft_endpoint_disabled_404(tmp_path):
    from nomad_tpu.agent import Agent, AgentConfig

    config = AgentConfig.dev()
    config.data_dir = str(tmp_path / "agent")
    config.http_port = 0
    config.raft_observe = {"enabled": False}
    a = Agent(config)
    a.start()
    try:
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(a.http.addr + "/v1/agent/raft",
                                   timeout=10)
        assert err.value.code == 404
    finally:
        a.shutdown()
