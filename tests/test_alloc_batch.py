"""Columnar AllocBatch placement path: batch construction, plan
verification without expansion, materialization at the state boundary,
wire round-trip, and equivalence with the object flow.

Reference semantics being preserved: a batch is exactly its materialize()
expansion into Allocations (structs.go:1129-1222); plan evaluation per
node matches plan_apply.go:229-277."""

import numpy as np
import pytest

from nomad_tpu import mock, structs
from nomad_tpu.api.codec import from_dict, to_dict
from nomad_tpu.server.plan_apply import evaluate_plan
from nomad_tpu.structs import (
    AllocBatch,
    Evaluation,
    Plan,
    Resources,
    generate_uuid,
)
from tests.sched_harness import Harness

BATCH = 300  # above TPUGenericScheduler.BATCH_PLACE_THRESHOLD


def _big_job(count=BATCH, cpu=100, mem=128):
    job = mock.job()
    job.type = structs.JOB_TYPE_BATCH
    tg = job.task_groups[0]
    tg.count = count
    tg.tasks[0].resources = Resources(cpu=cpu, memory_mb=mem)
    return job


def _eval_for(job):
    return Evaluation(
        id=generate_uuid(),
        priority=job.priority,
        type=job.type,
        triggered_by=structs.EVAL_TRIGGER_JOB_REGISTER,
        job_id=job.id,
    )


def _seed(h, n_nodes=6):
    nodes = [mock.node() for _ in range(n_nodes)]
    for node in nodes:
        h.state.upsert_node(h.next_index(), node)
    return nodes


def test_batch_placement_end_to_end():
    """A fresh big registration goes through the columnar path and lands
    count allocations in state, spread across nodes within capacity."""
    h = Harness()
    nodes = _seed(h)
    job = _big_job()
    h.state.upsert_job(h.next_index(), job)

    h.process("tpu-batch", _eval_for(job))

    assert len(h.plans) == 1
    plan = h.plans[0]
    assert plan.alloc_batches, "big fresh placement should use the batch path"
    assert not plan.node_allocation

    allocs = h.state.allocs_by_job(job.id)
    placed = [a for a in allocs if a.desired_status == "run"]
    # mock nodes: 4000 cpu - 100 reserved; 100cpu/128mb => 39/node by cpu.
    # 6 nodes x 39 = 234 < 300: expect capacity-bound placement + failures.
    assert len(placed) == 234
    per_node = {}
    for a in placed:
        per_node[a.node_id] = per_node.get(a.node_id, 0) + 1
    assert all(c <= 39 for c in per_node.values())
    # Names are the count-expansion form, unique
    names = {a.name for a in placed}
    assert len(names) == len(placed)
    assert all(name.startswith(f"{job.name}.{job.task_groups[0].name}[") for name in names)
    # Ids unique and uuid-shaped
    ids = {a.id for a in placed}
    assert len(ids) == len(placed)
    assert all(len(i) == 36 and i.count("-") == 4 for i in ids)
    # Unplaceable tail recorded as a coalesced failure
    assert plan.failed_allocs
    assert plan.failed_allocs[0].metrics.coalesced_failures == 66 - 1


def test_batch_matches_object_flow_counts():
    """Columnar and object flows place the same number on the same node
    set (same capacity math), for a count that fits entirely."""
    results = {}
    for factory, count in (("tpu-batch", 200), ("tpu-batch", BATCH)):
        h = Harness()
        _seed(h, n_nodes=10)  # 10 x 39 = 390 cap
        job = _big_job(count=count)
        h.state.upsert_job(h.next_index(), job)
        h.process(factory, _eval_for(job))
        placed = [
            a for a in h.state.allocs_by_job(job.id)
            if a.desired_status == "run"
        ]
        results[count] = placed
        assert len(placed) == count
    # 200 goes through the object flow (below threshold), 300 columnar;
    # both saturate nodes within the same cap
    for placed in results.values():
        per_node = {}
        for a in placed:
            per_node[a.node_id] = per_node.get(a.node_id, 0) + 1
        assert all(c <= 39 for c in per_node.values())


def test_evaluate_plan_rejects_stale_batch_nodes():
    """A batch run on a node that no longer fits is dropped (partial
    commit) and refresh_index is set — plan_apply.go:196-216 semantics."""
    h = Harness()
    nodes = _seed(h, n_nodes=2)
    job = _big_job(count=40)

    batch = AllocBatch(
        eval_id="ev1", job=job, tg_name=job.task_groups[0].name,
        resources=Resources(cpu=100, memory_mb=128),
        node_ids=[nodes[0].id, nodes[1].id],
        node_counts=[20, 20],
        name_idx=list(range(40)),
        ids_hex="ab" * 16 * 40,
    )
    plan = Plan(eval_id="ev1", eval_token="t", priority=50)
    plan.append_batch(batch)

    snap = h.state.snapshot()
    result = evaluate_plan(snap, plan)
    assert result.refresh_index == 0
    assert sum(b.n for b in result.alloc_batches) == 40

    # Saturate node 0 with a competing alloc that eats nearly all cpu
    fat = mock.alloc()
    fat.node_id = nodes[0].id
    fat.resources = Resources(cpu=3950, memory_mb=100)
    h.state.upsert_allocs(h.next_index(), [fat])

    snap = h.state.snapshot()
    result = evaluate_plan(snap, plan)
    assert result.refresh_index > 0
    committed = result.alloc_batches
    assert sum(b.n for b in committed) == 20
    assert committed[0].node_ids == [nodes[1].id]
    # Alignment: the surviving run keeps its own ids/names
    allocs = committed[0].materialize()
    assert len(allocs) == 20
    assert all(a.node_id == nodes[1].id for a in allocs)
    assert [int(a.name.split("[")[1].rstrip("]")) for a in allocs] == list(range(20, 40))


def test_batch_wire_roundtrip():
    from nomad_tpu.structs import AllocMetric

    job = _big_job(count=8)
    metrics = AllocMetric()
    metrics.nodes_evaluated = 7
    batch = AllocBatch(
        eval_id="ev", job=job, tg_name="web",
        resources=Resources(cpu=10, memory_mb=20),
        task_resources={"t": Resources(cpu=10, memory_mb=20)},
        metrics=metrics,
        node_ids=["n1", "n2"], node_counts=[3, 5],
        name_idx=np.arange(8), ids_hex="cd" * 16 * 8,
    )
    plan = Plan(eval_id="ev", eval_token="tok", priority=9)
    plan.append_batch(batch)

    wire = to_dict(plan)
    import json

    wire = json.loads(json.dumps(wire))  # must be JSON-able
    back = from_dict(Plan, wire)
    assert len(back.alloc_batches) == 1
    b2 = back.alloc_batches[0]
    assert b2.n == 8
    assert b2.node_ids == ["n1", "n2"]
    assert b2.node_counts == [3, 5]
    assert b2.resources.cpu == 10
    assert b2.metrics is not None and b2.metrics.nodes_evaluated == 7
    a1 = batch.materialize()
    a2 = b2.materialize()
    assert [a.id for a in a1] == [a.id for a in a2]
    assert [a.name for a in a1] == [a.name for a in a2]
    assert [a.node_id for a in a1] == [a.node_id for a in a2]


def test_multi_group_batches_share_capacity():
    """Two big task groups in one job: the second group's solve must see
    the first group's columnar placements (mirror usage from
    plan.alloc_batches), so the total never exceeds node capacity."""
    h = Harness()
    _seed(h, n_nodes=4)  # 4 x 4000 cpu
    import copy

    job = _big_job(count=BATCH)
    tg2 = copy.deepcopy(job.task_groups[0])
    tg2.name = "second"
    tg2.count = BATCH
    job.task_groups.append(tg2)
    h.state.upsert_job(h.next_index(), job)

    h.process("tpu-batch", _eval_for(job))

    placed = [
        a for a in h.state.allocs_by_job(job.id)
        if a.desired_status == "run"
    ]
    # 4 nodes x 39 cap = 156 total across BOTH groups
    assert len(placed) == 156
    per_node = {}
    for a in placed:
        per_node[a.node_id] = per_node.get(a.node_id, 0) + 1
    assert all(c <= 39 for c in per_node.values())


def test_scaleup_uses_batch_path_without_rematerializing():
    """Scale-up of a healthy job recovers missing indices by parsing the
    existing allocs' names and places only those, columnar."""
    h = Harness()
    _seed(h, n_nodes=20)  # 20 x 39 = 780 cap
    job = _big_job(count=BATCH)
    h.state.upsert_job(h.next_index(), job)
    h.process("tpu-batch", _eval_for(job))
    assert len(h.state.allocs_by_job(job.id)) == BATCH

    job.task_groups[0].count = BATCH + 300  # same modify_index: pure scale-up
    h.state.upsert_job(h.next_index(), job)
    h.evals.clear()
    h.process("tpu-batch", _eval_for(job))

    plan = h.plans[-1]
    assert plan.alloc_batches and not plan.node_allocation
    assert sum(b.n for b in plan.alloc_batches) == 300
    placed = [a for a in h.state.allocs_by_job(job.id)
              if a.desired_status == "run"]
    assert len(placed) == BATCH + 300
    assert len({a.name for a in placed}) == BATCH + 300


def test_scaledown_falls_back_to_object_diff():
    """Scale-down needs stops — must take the reference-shaped diff, not
    the batch path."""
    h = Harness()
    _seed(h, n_nodes=10)
    job = _big_job(count=BATCH)
    h.state.upsert_job(h.next_index(), job)
    h.process("tpu-batch", _eval_for(job))

    job.task_groups[0].count = 10
    h.state.upsert_job(h.next_index(), job)
    h.process("tpu-batch", _eval_for(job))

    plan = h.plans[-1]
    stops = sum(len(v) for v in plan.node_update.values())
    assert stops == BATCH - 10
    remaining = [a for a in h.state.allocs_by_job(job.id)
                 if a.desired_status == "run"]
    assert len(remaining) == 10


def test_tainted_node_falls_back_and_migrates():
    """A drained node forces the object diff: its allocs migrate."""
    h = Harness()
    nodes = _seed(h, n_nodes=10)
    job = _big_job(count=BATCH)
    h.state.upsert_job(h.next_index(), job)
    h.process("tpu-batch", _eval_for(job))

    victim = h.state.allocs_by_job(job.id)[0].node_id
    h.state.update_node_drain(h.next_index(), victim, True)
    h.process("tpu-batch", _eval_for(job))

    placed = [a for a in h.state.allocs_by_job(job.id)
              if a.desired_status == "run"]
    assert len(placed) == BATCH
    assert all(a.node_id != victim for a in placed)


def test_inplace_update_batch_path():
    """Re-registering a big job with unchanged tasks re-stamps every alloc
    in place via one AllocUpdateBatch — same ids, same nodes, new job
    version — without touching the per-alloc select path."""
    h = Harness()
    _seed(h, n_nodes=10)
    job = _big_job(count=BATCH)
    h.state.upsert_job(h.next_index(), job)
    h.process("tpu-batch", _eval_for(job))
    before = {a.id: a for a in h.state.allocs_by_job(job.id)}
    assert len(before) == BATCH

    # Re-register a distinct copy (as a wire-crossing registration would
    # be): modify_index bumps, tasks unchanged -> in-place updates
    import copy

    job2 = copy.deepcopy(job)
    h.state.upsert_job(h.next_index(), job2)
    new_index = job2.modify_index
    h.process("tpu-batch", _eval_for(job2))

    plan = h.plans[-1]
    assert plan.update_batches, "expected the columnar in-place path"
    assert sum(b.n for b in plan.update_batches) == BATCH
    assert not plan.node_allocation

    after = {a.id: a for a in h.state.allocs_by_job(job.id)
             if a.desired_status == "run"}
    assert set(after) == set(before)  # same alloc ids
    for aid, alloc in after.items():
        assert alloc.node_id == before[aid].node_id
        assert alloc.job.modify_index == new_index


def test_inplace_update_task_change_falls_back_destructive():
    """Changing a task's driver defeats in-place (tasks_updated true,
    util.go:265-302): the allocs are evicted and replaced, not
    batch-updated."""
    h = Harness()
    _seed(h, n_nodes=10)
    job = _big_job(count=BATCH, cpu=100)
    h.state.upsert_job(h.next_index(), job)
    h.process("tpu-batch", _eval_for(job))

    import copy

    job2 = copy.deepcopy(job)
    job2.task_groups[0].tasks[0].driver = "raw_exec"
    h.state.upsert_job(h.next_index(), job2)
    h.process("tpu-batch", _eval_for(job2))

    plan = h.plans[-1]
    assert not plan.update_batches
    stops = sum(len(v) for v in plan.node_update.values())
    assert stops == BATCH  # destructive: every alloc evicted + replaced


def test_inplace_update_resource_growth_checked_against_headroom():
    """tasks_updated ignores cpu changes (util.go:265-302), so a resource
    grow updates in place — but only within per-node headroom; overflow
    falls back to the per-alloc path and is evicted/replaced."""
    h = Harness()
    _seed(h, n_nodes=10)
    job = _big_job(count=BATCH, cpu=100)  # 30 per node across 10 nodes
    h.state.upsert_job(h.next_index(), job)
    h.process("tpu-batch", _eval_for(job))

    import copy

    job2 = copy.deepcopy(job)
    # 100 -> 120 cpu: 30 allocs/node avg * 120 = 3600 <= 3900: all fit
    job2.task_groups[0].tasks[0].resources = Resources(cpu=120, memory_mb=128)
    h.state.upsert_job(h.next_index(), job2)
    h.process("tpu-batch", _eval_for(job2))

    plan = h.plans[-1]
    assert plan.update_batches
    run = [a for a in h.state.allocs_by_job(job.id)
           if a.desired_status == "run"]
    assert len(run) == BATCH
    assert all(a.resources.cpu == 120 for a in run)


def test_update_batch_wire_resolves_against_state():
    """An update batch arriving over the wire carries only alloc ids; plan
    evaluation resolves them against the snapshot and drops stale ids."""
    import json

    from nomad_tpu.structs import AllocUpdateBatch

    h = Harness()
    _seed(h, n_nodes=4)
    job = _big_job(count=8)
    h.state.upsert_job(h.next_index(), job)
    h.process("tpu-batch", _eval_for(job))
    allocs = h.state.allocs_by_job(job.id)
    assert len(allocs) == 8

    batch = AllocUpdateBatch(
        eval_id="ev9", job=job, tg_name=job.task_groups[0].name,
        resources=Resources(cpu=100, memory_mb=128),
        allocs=allocs,
    )
    plan = Plan(eval_id="ev9", eval_token="t", priority=50)
    plan.append_update_batch(batch)

    wire = json.loads(json.dumps(to_dict(plan)))
    assert wire["update_batches"][0]["alloc_ids"]
    assert "allocs" not in wire["update_batches"][0]
    back = from_dict(Plan, wire)
    # Tamper: one stale id
    back.update_batches[0].alloc_ids.append("not-a-real-alloc")

    result = evaluate_plan(h.state.snapshot(), back)
    committed = result.update_batches
    assert sum(b.n for b in committed) == 8  # stale id dropped
    materialized = [a for b in committed for a in b.materialize()]
    assert {a.id for a in materialized} == {a.id for a in allocs}
    assert all(a.eval_id == "ev9" for a in materialized)


def test_scaledown_with_terminal_low_index_not_masked():
    """A terminal alloc at a low index plus count-1 re-register: the
    out-of-range alloc must stop and the low index be replaced — the
    full-group shortcut must not assume occupancy (diff fidelity,
    util.go:54-131)."""
    h = Harness()
    _seed(h, n_nodes=10)
    job = _big_job(count=BATCH)
    h.state.upsert_job(h.next_index(), job)
    h.process("tpu-batch", _eval_for(job))

    # Kill alloc [0]
    allocs = h.state.allocs_by_job(job.id)
    victim = next(a for a in allocs if a.name.endswith("[0]"))
    dead = victim.copy()
    dead.desired_status = "stop"
    dead.client_status = "dead"
    h.state.upsert_allocs(h.next_index(), [dead])

    import copy

    job2 = copy.deepcopy(job)
    job2.task_groups[0].count = BATCH - 1
    h.state.upsert_job(h.next_index(), job2)
    h.process("tpu-batch", _eval_for(job2))

    run = [a for a in h.state.allocs_by_job(job.id)
           if a.desired_status == "run"]
    names = sorted(int(a.name.split("[")[1].rstrip("]")) for a in run)
    assert len(run) == BATCH - 1
    assert names == list(range(BATCH - 1))  # [0] replaced, [299] stopped


def test_constraint_change_defeats_inplace_batch():
    """Adding a job constraint the current nodes violate must NOT be
    re-stamped in place: the reference re-runs the constraint-masked
    select per alloc (util.go:346-358), failing the node and forcing
    evict-and-place."""
    from nomad_tpu.structs import Constraint

    h = Harness()
    nodes = _seed(h, n_nodes=10)
    # Half the nodes carry a special attribute
    for i, n in enumerate(nodes):
        n.attributes["special"] = "yes" if i < 5 else "no"
        h.state.upsert_node(h.next_index(), n)
    job = _big_job(count=BATCH)
    h.state.upsert_job(h.next_index(), job)
    h.process("tpu-batch", _eval_for(job))

    import copy

    job2 = copy.deepcopy(job)
    job2.constraints = list(job2.constraints) + [
        Constraint(l_target="$attr.special", r_target="yes", operand="=")
    ]
    h.state.upsert_job(h.next_index(), job2)
    h.process("tpu-batch", _eval_for(job2))

    run = [a for a in h.state.allocs_by_job(job.id)
           if a.desired_status == "run"]
    good = {n.id for n in nodes[:5]}
    assert all(a.node_id in good for a in run), "constraint must be re-applied"


def test_lazy_ids_seed_contract():
    """Seed-form id column: ids derive deterministically from the 128-bit
    seed on first read, alloc_id(0) expands only a 16-byte prefix, and
    the seed (not the multi-MB expansion) rides the wire and pickle."""
    from nomad_tpu.structs import AllocBatch, Resources

    batch = AllocBatch(
        eval_id="ev-lazy", tg_name="web", resources=Resources(cpu=100),
        node_ids=["n1", "n2"], node_counts=[3, 2], name_idx=range(5),
        ids_seed=0x0123456789ABCDEF0123456789ABCDEF,
    )
    assert batch.ids_lazy
    first = batch.alloc_id(0)
    assert batch.ids_lazy, "alloc_id(0) must not expand the column"

    # Wire round-trip carries the seed; the receiver derives identically.
    wire = batch.to_wire()
    assert "ids_hex" not in wire and len(wire["ids_seed"]) == 32
    back = AllocBatch.from_wire(wire)
    assert back.ids_lazy

    ids = [batch.alloc_id(i) for i in range(5)]
    assert not batch.ids_lazy  # bulk addressing expanded + cached
    assert ids[0] == first  # prefix property of the SHAKE-256 XOF
    assert [back.alloc_id(i) for i in range(5)] == ids
    assert len(set(ids)) == 5

    # An expanded batch falls back to shipping hex on the wire.
    wire2 = batch.to_wire()
    assert wire2["ids_hex"] == batch.ids_hex


def test_lazy_ids_survive_store_commit_and_snapshot():
    """A seed-form batch stays lazy through commit into the block store
    (the FSM's upsert_alloc_blocks path), pickles as its seed
    (raft-snapshot size posture), and restores to the same ids."""
    import pickle

    from nomad_tpu.state import StateStore
    from nomad_tpu.structs import AllocBatch, Resources

    store = StateStore()
    batch = AllocBatch(
        eval_id="ev-lazy2", tg_name="web", resources=Resources(cpu=10),
        node_ids=[f"n{i}" for i in range(50)], node_counts=[6] * 50,
        name_idx=range(300), ids_seed=0xFEEDFACE,
    )
    store.upsert_alloc_blocks(10, [batch])
    blocks = store.alloc_blocks()
    assert blocks, "batch placement must commit columnar"
    blk = blocks[0]
    assert blk.ids_lazy, "commit must not expand the id column"
    data = pickle.dumps(blk)
    # The pickled form is seed-sized, not expansion-sized.
    assert len(data) < 32 * blk.n
    ids = [blk.alloc_id(i) for i in range(3)]
    blk2 = pickle.loads(data)
    assert blk2.ids_lazy
    assert [blk2.alloc_id(i) for i in range(3)] == ids
    assert blk2.block_id == blk.block_id == ids[0]


def test_src_hint_matches_id_resolution():
    """The solver-mirror row hint (src_rows/src_ids_ref) is a pure
    resolution shortcut: evaluate_plan must commit the identical subset
    with the hint present and with it stripped — including when nodes
    were deregistered or saturated between the solve and the verify, so
    the hint's mirror rows no longer align with the node table."""
    h = Harness()
    nodes = _seed(h, n_nodes=6)
    job = _big_job(count=BATCH)
    h.state.upsert_job(h.next_index(), job)
    h.process("tpu-batch", _eval_for(job))

    plan = h.plans[0]
    assert plan.alloc_batches
    batch = plan.alloc_batches[0]
    assert batch.src_hint is not None, "solver should record mirror rows"

    def strip(p):
        import copy

        p2 = copy.copy(p)
        p2.alloc_batches = []
        for b in p.alloc_batches:
            b2 = copy.copy(b)
            b2.src_ids_ref = None
            b2.src_rows = None
            p2.alloc_batches.append(b2)
        return p2

    def commit_shape(result):
        return [
            (list(b.node_ids), [int(c) for c in b.node_counts])
            for b in result.alloc_batches
        ]

    for mutate in (
        lambda: None,
        # Deregister a placed-on node: its run must drop out of the
        # committable subset identically on both paths.
        lambda: h.state.delete_node(h.next_index(), batch.node_ids[0]),
        # Saturate another placed-on node with a competing alloc.
        lambda: (
            setattr(fat := mock.alloc(), "node_id", batch.node_ids[1]),
            setattr(fat, "resources", Resources(cpu=3950, memory_mb=100)),
            h.state.upsert_allocs(h.next_index(), [fat]),
        ),
    ):
        mutate()
        snap = h.state.snapshot()
        hinted = evaluate_plan(snap, plan)
        plain = evaluate_plan(snap, strip(plan))
        assert commit_shape(hinted) == commit_shape(plain)

    # After both mutations the dropped runs are really gone.
    final = evaluate_plan(h.state.snapshot(), plan)
    surviving = {nid for b in final.alloc_batches for nid in b.node_ids}
    assert batch.node_ids[0] not in surviving
