"""Environment-gated REAL driver tests.

The mock-level driver tests (tests/test_client.py) prove argument
assembly and handle lifecycle; these start actual containers/VMs/JVMs
through the same driver path when the binaries exist, and skip otherwise
— the reference's exact posture (/root/reference/client/driver/
docker_test.go `docker is not connected`, rkt_test.go, java_test.go
checkForJava). A refactor that breaks `docker run` argument assembly
goes red wherever a daemon is available instead of staying green.

raw_exec/exec real-process coverage (spawn roundtrip, chroot+setuid
probe, kill) lives in tests/test_client.py.
"""

import os
import shutil
import subprocess
import time

import pytest

from nomad_tpu import mock, structs
from nomad_tpu.client.config import ClientConfig


def _docker_available() -> bool:
    # The driver's own daemon probe IS the availability gate — the skip
    # condition can't drift from what the driver actually requires.
    from nomad_tpu.client.driver.docker import DockerDriver

    return DockerDriver.fingerprint(ClientConfig(), mock.node())


requires_docker = pytest.mark.skipif(
    not _docker_available(), reason="docker daemon not available"
)
requires_qemu = pytest.mark.skipif(
    shutil.which("qemu-system-x86_64") is None
    or not os.environ.get("NOMAD_TPU_QEMU_IMAGE"),
    reason="qemu binary or NOMAD_TPU_QEMU_IMAGE not available",
)
requires_java = pytest.mark.skipif(
    shutil.which("java") is None or shutil.which("jar") is None
    or shutil.which("javac") is None,
    reason="JDK not available",
)

DOCKER_TEST_IMAGE = os.environ.get("NOMAD_TPU_DOCKER_TEST_IMAGE",
                                   "busybox:latest")


def _ctx(tmp_path, task_name):
    from test_client import _exec_ctx

    return _exec_ctx(tmp_path, [task_name])


@requires_docker
def test_docker_fingerprint_reports_daemon():
    from nomad_tpu.client.driver.docker import DockerDriver

    node = mock.node()
    node.attributes.clear()
    assert DockerDriver.fingerprint(ClientConfig(), node)
    assert node.attributes["driver.docker"] == "1"
    assert node.attributes["driver.docker.version"]


@requires_docker
def test_docker_echo_task_runs_with_alloc_binds(tmp_path):
    """Start a real container through the driver: the task writes into
    /alloc (the shared alloc-dir bind) and its exit code flows back
    through the handle — proving bind wiring, env plumbing, and the
    docker run argument assembly end-to-end
    (docker.go containerBinds + createContainer)."""
    from nomad_tpu.client.driver.docker import DockerDriver

    ctx = _ctx(tmp_path, "pinger")
    task = structs.Task(
        name="pinger", driver="docker",
        config={
            "image": DOCKER_TEST_IMAGE,
            "command": "/bin/sh",
            "args": ["-c", "echo lived-$NOMAD_ALLOC_ID > /alloc/proof; exit 4"],
        },
        resources=structs.Resources(cpu=100, memory_mb=64),
    )
    driver = DockerDriver(ctx)
    handle = driver.start(task)
    try:
        assert handle.wait(timeout=120) == 4
        # docker wait returned -> the container exited; bind writes are
        # visible synchronously.
        proof = os.path.join(ctx.alloc_dir.shared_dir, "proof")
        assert os.path.exists(proof), os.listdir(ctx.alloc_dir.shared_dir)
        with open(proof) as f:
            assert f.read().strip() == f"lived-{ctx.alloc_id}"
    finally:
        handle.kill()


@requires_docker
def test_docker_kill_stops_container(tmp_path):
    from nomad_tpu.client.driver.docker import DockerDriver

    ctx = _ctx(tmp_path, "sleeper")
    task = structs.Task(
        name="sleeper", driver="docker",
        config={"image": DOCKER_TEST_IMAGE, "command": "/bin/sleep",
                "args": ["300"]},
        resources=structs.Resources(cpu=100, memory_mb=64),
    )
    driver = DockerDriver(ctx)
    handle = driver.start(task)
    try:
        deadline = time.time() + 30
        while time.time() < deadline and not handle.is_running():
            time.sleep(0.2)
        assert handle.is_running()
        # Reattach via handle id, like a restarted client (docker.go Open)
        reopened = driver.open(handle.id())
        assert reopened.is_running()
    finally:
        handle.kill()
    assert not handle.is_running()


@requires_qemu
def test_qemu_boots_image(tmp_path):
    """Boot a real VM from NOMAD_TPU_QEMU_IMAGE through the driver path;
    the handle must report running, then die on kill (qemu.go Start)."""
    from nomad_tpu.client.driver.qemu import QemuDriver

    image = os.environ["NOMAD_TPU_QEMU_IMAGE"]
    ctx = _ctx(tmp_path, "vm")
    task_dir = ctx.alloc_dir.task_dirs["vm"]
    local_image = os.path.join(task_dir, "local", os.path.basename(image))
    shutil.copy2(image, local_image)
    task = structs.Task(
        name="vm", driver="qemu",
        config={"image_path": local_image, "accelerator": "tcg"},
        resources=structs.Resources(cpu=500, memory_mb=128),
    )
    driver = QemuDriver(ctx)
    handle = driver.start(task)
    try:
        deadline = time.time() + 30
        while time.time() < deadline and not handle.is_running():
            time.sleep(0.5)
        assert handle.is_running()
    finally:
        handle.kill()
    # The killed child is a zombie until the spawn daemon reaps it —
    # poll instead of asserting instantly.
    deadline = time.time() + 15
    while time.time() < deadline and handle.is_running():
        time.sleep(0.2)
    assert not handle.is_running()


@requires_java
def test_java_runs_compiled_jar(tmp_path):
    """Compile a trivial class, jar it, and run it through the java
    driver — exit code and stdout flow back (java.go Start/run)."""
    from nomad_tpu.client.driver.java import JavaDriver

    src = tmp_path / "Hello.java"
    src.write_text(
        'public class Hello { public static void main(String[] a) {'
        ' System.out.println("jvm-lived"); System.exit(7); } }'
    )
    subprocess.run(["javac", str(src)], check=True, cwd=tmp_path)
    jar = tmp_path / "hello.jar"
    subprocess.run(
        ["jar", "cfe", str(jar), "Hello", "Hello.class"],
        check=True, cwd=tmp_path,
    )

    ctx = _ctx(tmp_path, "jvm")
    task_dir = ctx.alloc_dir.task_dirs["jvm"]
    local_jar = os.path.join(task_dir, "local", "hello.jar")
    shutil.copy2(jar, local_jar)
    task = structs.Task(
        name="jvm", driver="java",
        config={"jar_path": local_jar},
        resources=structs.Resources(cpu=100, memory_mb=128),
    )
    driver = JavaDriver(ctx)
    handle = driver.start(task)
    assert handle.wait(timeout=60) == 7
    stdout = os.path.join(ctx.alloc_dir.log_dir(), "jvm.stdout")
    with open(stdout) as f:
        assert "jvm-lived" in f.read()
