"""Black-box tests: the api SDK against a forked real agent process.

Reference pattern: api/*_test.go run against testutil/server.go's forked
binary; the whole module skips when the agent cannot be forked (the
reference skips when the nomad binary is off $PATH, testutil/server.go:105).
"""

import time

import pytest

from blackbox_util import ForkedAgent


@pytest.fixture(scope="module")
def agent():
    try:
        proc = ForkedAgent()
    except (RuntimeError, TimeoutError, OSError) as e:
        pytest.skip(f"cannot fork black-box agent: {e}")
    yield proc
    proc.stop()


@pytest.fixture()
def client(agent):
    from nomad_tpu.api import ApiClient

    return ApiClient(address=agent.addr)


def _example_job(job_id: str):
    from nomad_tpu import structs
    from nomad_tpu.structs import Job, Resources, RestartPolicy, Task, TaskGroup

    return Job(
        region="global",
        id=job_id,
        name=job_id,
        type=structs.JOB_TYPE_BATCH,
        priority=50,
        datacenters=["dc1"],
        task_groups=[
            TaskGroup(
                name="grp",
                count=1,
                restart_policy=RestartPolicy(attempts=0, interval=60.0, delay=1.0),
                tasks=[
                    Task(
                        name="sleepy",
                        driver="mock_driver",
                        config={"run_for": "0.1"},
                        resources=Resources(cpu=100, memory_mb=64),
                    )
                ],
            )
        ],
    )


def test_agent_self_and_members(agent, client):
    info = client.agent().self_info()
    assert info["config"]["server_enabled"] or info["stats"].get("server")
    members = client.agent().members()
    assert len(members) == 1
    leader = client.status().leader()
    assert leader


def test_register_job_and_monitor_to_running(agent, client):
    job = _example_job("bb-job")
    eval_id, _ = client.jobs().register(job)
    assert eval_id

    deadline = time.monotonic() + 30
    status = None
    while time.monotonic() < deadline:
        ev, _ = client.evaluations().info(eval_id)
        status = ev.status
        if status in ("complete", "failed"):
            break
        time.sleep(0.2)
    assert status == "complete"

    allocs, _ = client.jobs().allocations("bb-job")
    assert len(allocs) == 1
    assert allocs[0]["desired_status"] == "run"

    jobs, _ = client.jobs().list()
    assert any(j["id"] == "bb-job" for j in jobs)


def test_node_listed_and_ready(agent, client):
    nodes, _ = client.nodes().list()
    assert len(nodes) == 1
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline:
        nodes, _ = client.nodes().list()
        if nodes and nodes[0]["status"] == "ready":
            break
        time.sleep(0.2)
    assert nodes[0]["status"] == "ready"


def test_agent_logs_endpoint(agent):
    out = agent.http_get("/v1/agent/logs")
    assert "lines" in out


def test_deregister_job(agent, client):
    job = _example_job("bb-stop")
    client.jobs().register(job)
    eval_id, _ = client.jobs().deregister("bb-stop")
    assert eval_id
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline:
        jobs, _ = client.jobs().list()
        if not any(j["id"] == "bb-stop" for j in jobs):
            break
        time.sleep(0.2)
    jobs, _ = client.jobs().list()
    assert not any(j["id"] == "bb-stop" for j in jobs)
