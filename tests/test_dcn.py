"""Multi-host DCN dryrun (parallel/dcn.py): two OS processes, each with 4
virtual devices, solve ONE globally-sharded water-fill over a (dcn, node)
mesh — the multi-slice posture of SURVEY.md §7 ("DCN via jax.distributed").
"""

import pytest

from nomad_tpu.parallel.dcn import DCNUnsupported, spawn_dcn_workers


def test_two_process_dcn_solve():
    try:
        results, _outs = spawn_dcn_workers(
            n_processes=2, n_nodes=256, count=180
        )
    except DCNUnsupported as e:
        pytest.skip(f"jax.distributed unsupported here: {e}")

    for r in results:
        assert r["ok"] is True
        assert r["n_processes"] == 2
        assert r["n_devices"] == 8  # 2 hosts x 4 virtual devices
        assert r["placed"] == 180 and r["unplaced"] == 0
        # The solve genuinely spread over the global node axis (the top-k
        # partial round is a cross-host collective, not a local pick).
        assert r["nodes_used"] == 180
    # Replicated outputs agree across hosts.
    assert results[0]["placed"] == results[1]["placed"]
