"""Jobspec parser tests, anchored to the reference fixtures
(/root/reference/jobspec/parse_test.go + test-fixtures/*.hcl).

Fixture-backed tests skip cleanly when the reference tree is absent (it
is not part of this repo); the fixture-free tests below still run — the
module must COLLECT either way (a module-level ``open()`` used to
explode collection on hosts without /root/reference)."""

import os

import pytest

from nomad_tpu import structs
from nomad_tpu.jobspec import JobspecError, parse, parse_duration, parse_file

FIXTURES = "/root/reference/jobspec/test-fixtures"

requires_fixtures = pytest.mark.skipif(
    not os.path.isdir(FIXTURES),
    reason=f"reference jobspec fixtures absent ({FIXTURES})",
)


@requires_fixtures
def test_parse_basic():
    """reference: parse_test.go TestParse basic.hcl expectations"""
    job = parse(open(f"{FIXTURES}/basic.hcl").read())
    assert job.id == "binstore-storagelocker"
    assert job.name == "binstore-storagelocker"
    assert job.region == "global"
    assert job.type == "service"
    assert job.priority == 50
    assert job.all_at_once is True
    assert job.datacenters == ["us2", "eu1"]
    assert job.meta == {"foo": "bar"}

    assert len(job.constraints) == 1
    c = job.constraints[0]
    assert c.l_target == "kernel.os"
    assert c.r_target == "windows"
    assert c.operand == "="

    assert job.update.stagger == 60.0
    assert job.update.max_parallel == 2

    # Standalone task becomes its own group, then the explicit group
    assert [tg.name for tg in job.task_groups] == ["outside", "binsl"]
    outside = job.task_groups[0]
    assert outside.count == 1
    assert outside.tasks[0].driver == "java"
    assert outside.tasks[0].config == {"jar": "s3://my-cool-store/foo.jar"}
    assert outside.tasks[0].meta == {"my-cool-key": "foobar"}
    assert outside.restart_policy is not None

    binsl = job.task_groups[1]
    assert binsl.count == 5
    assert binsl.restart_policy.attempts == 5
    assert binsl.restart_policy.interval == 600.0
    assert binsl.restart_policy.delay == 15.0
    assert binsl.meta == {
        "elb_mode": "tcp", "elb_interval": "10", "elb_checks": "3",
    }
    assert len(binsl.constraints) == 1

    assert [t.name for t in binsl.tasks] == ["binstore", "storagelocker"]
    binstore = binsl.tasks[0]
    assert binstore.driver == "docker"
    assert binstore.env == {"HELLO": "world", "LOREM": "ipsum"}
    assert binstore.resources.cpu == 500
    assert binstore.resources.memory_mb == 128
    net = binstore.resources.networks[0]
    assert net.mbits == 100
    assert net.reserved_ports == [1, 2, 3]
    assert net.dynamic_ports == ["http", "https", "admin"]

    storagelocker = binsl.tasks[1]
    assert len(storagelocker.constraints) == 1
    assert storagelocker.constraints[0].l_target == "kernel.arch"


@requires_fixtures
def test_parse_default_job():
    job = parse_file(f"{FIXTURES}/default-job.hcl")
    assert job.id == "foo"
    assert job.name == "foo"
    assert job.priority == 50
    assert job.region == "global"
    assert job.type == "service"


@requires_fixtures
def test_parse_specify_job():
    job = parse_file(f"{FIXTURES}/specify-job.hcl")
    assert job.id == "job1"
    assert job.name == "My Job"


@requires_fixtures
def test_parse_version_constraint():
    job = parse_file(f"{FIXTURES}/version-constraint.hcl")
    c = job.constraints[0]
    assert c.l_target == "$attr.kernel.version"
    assert c.r_target == "~> 3.2"
    assert c.operand == structs.CONSTRAINT_VERSION


@requires_fixtures
def test_parse_regexp_constraint():
    job = parse_file(f"{FIXTURES}/regexp-constraint.hcl")
    c = job.constraints[0]
    assert c.r_target == "[0-9.]+"
    assert c.operand == structs.CONSTRAINT_REGEX


@requires_fixtures
def test_parse_distinct_hosts():
    job = parse_file(
        f"{FIXTURES}/distinctHosts-constraint.hcl"
    )
    assert job.constraints[0].operand == structs.CONSTRAINT_DISTINCT_HOSTS


@requires_fixtures
def test_parse_bad_ports():
    with pytest.raises(JobspecError, match="naming requirements"):
        parse_file(f"{FIXTURES}/bad-ports.hcl")


@requires_fixtures
def test_parse_overlapping_ports():
    with pytest.raises(JobspecError, match="collision"):
        parse_file(f"{FIXTURES}/overlapping-ports.hcl")


@requires_fixtures
def test_parse_multi_network_rejected():
    with pytest.raises(JobspecError, match="only one 'network'"):
        parse_file(f"{FIXTURES}/multi-network.hcl")


@requires_fixtures
def test_parse_multi_resource_rejected():
    with pytest.raises(JobspecError, match="only one 'resource'"):
        parse_file(f"{FIXTURES}/multi-resource.hcl")


def test_parse_errors():
    with pytest.raises(JobspecError, match="'job' stanza not found"):
        parse("")
    with pytest.raises(JobspecError, match="only one 'job'"):
        parse('job "a" {}\njob "b" {}')
    with pytest.raises(JobspecError):
        parse('job "a" { unclosed ')


def test_duration_parsing():
    assert parse_duration("60s") == 60.0
    assert parse_duration("10m") == 600.0
    assert parse_duration("1h30m") == 5400.0
    assert parse_duration("250ms") == 0.25
    assert parse_duration(0) == 0.0
    with pytest.raises(JobspecError):
        parse_duration("10 parsecs")


def test_parsed_job_validates_and_schedules():
    """A parsed spec drives the full scheduler."""
    spec = '''
job "web-app" {
    datacenters = ["dc1"]
    group "web" {
        count = 3
        task "server" {
            driver = "exec"
            config { command = "/bin/sleep" args = "60" }
            resources { cpu = 100 memory = 64 }
        }
    }
}
'''
    job = parse(spec)
    job.validate()

    import sys
    sys.path.insert(0, "tests")
    from sched_harness import Harness, flatten
    from nomad_tpu import mock
    from nomad_tpu.structs import Evaluation, generate_uuid

    h = Harness()
    for _ in range(5):
        h.state.upsert_node(h.next_index(), mock.node())
    h.state.upsert_job(h.next_index(), job)
    ev = Evaluation(
        id=generate_uuid(), priority=job.priority,
        triggered_by=structs.EVAL_TRIGGER_JOB_REGISTER, job_id=job.id,
    )
    h.process("tpu-service", ev)
    assert len(flatten(h.plans[0].node_allocation)) == 3
