"""End-to-end latency attribution tests (PR 8): lifecycle timeline
stitching (nomad_tpu.lifecycle), the SLO layer (nomad_tpu.slo +
telemetry.BurnRateWindow), fixed-bucket histogram exposition, aggregate
trace-loss counters, the event-stream lifecycle-ordering contract the
stitcher rests on (per-key raft-index monotonicity across a real
bounce/refresh cycle), SSE resume-after-truncation, and the HTTP/SDK
surfaces (/v1/agent/slo, /v1/evaluation/<id>/timeline)."""

import json
import threading
import time
import urllib.request

import pytest

from nomad_tpu import events as events_mod
from nomad_tpu import lifecycle, mock, slo, structs, telemetry, trace
from nomad_tpu.server import Server, ServerConfig
from nomad_tpu.structs import Evaluation, Plan, Resources, generate_uuid

# ---------------------------------------------------------------------------
# lifecycle: synthetic-span stitching
# ---------------------------------------------------------------------------


def _span(name, start, end, **annotations):
    return {"trace_id": "ev1", "span_id": name, "parent_id": "",
            "name": name, "start": start, "end": end,
            "annotations": annotations}


def _full_span_set(t0):
    """A complete single-attempt lifecycle: every directly-mapped span
    plus the two derived stages, summing to 90ms of a 100ms e2e."""
    return [
        _span("eval", t0, t0 + 0.099, job_id="j1", type="service",
              triggered_by="job-register"),
        _span("broker.wait", t0, t0 + 0.020),
        _span("worker.wait_for_index", t0 + 0.020, t0 + 0.022),
        _span("worker.invoke_scheduler", t0 + 0.022, t0 + 0.090),
        _span("worker.submit_plan", t0 + 0.060, t0 + 0.090),
        _span("plan.queue_wait", t0 + 0.060, t0 + 0.065),
        _span("plan.evaluate", t0 + 0.065, t0 + 0.075, refresh_index=0),
        _span("plan.apply", t0 + 0.075, t0 + 0.085),
    ]


def test_stage_partition_reconciles_exactly():
    """The stage taxonomy is a PARTITION of submit→placed: directly
    mapped spans + derived (parent-minus-children) stages + the explicit
    unattributed gap sum to the measured end-to-end latency."""
    t0 = 1000.0
    tl = lifecycle.stitch_eval(
        "ev1", _full_span_set(t0),
        {"submitted": t0, "placed": t0 + 0.100, "running": None,
         "job_id": "j1", "triggered_by": "job-register"},
    )
    assert tl.submit_to_placed_ms == pytest.approx(100.0)
    assert tl.stage_ms["broker_wait"] == pytest.approx(20.0)
    assert tl.stage_ms["raft_catchup"] == pytest.approx(2.0)
    # invoke_scheduler(68) minus nested submit_plan(30)
    assert tl.stage_ms["schedule_solve"] == pytest.approx(38.0)
    # submit_plan(30) minus queue_wait+evaluate+apply(25)
    assert tl.stage_ms["submit_overhead"] == pytest.approx(5.0)
    assert tl.stage_ms["plan_queue_wait"] == pytest.approx(5.0)
    assert tl.stage_ms["plan_verify"] == pytest.approx(10.0)
    assert tl.stage_ms["raft_commit"] == pytest.approx(10.0)
    assert tl.stage_ms["unattributed"] == pytest.approx(10.0)
    assert sum(tl.stage_ms.values()) == pytest.approx(100.0)
    assert tl.attempts == 1 and tl.bounces == 0
    # Segments are start-ordered and carry the queue/service kind.
    starts = [s["start_ms"] for s in tl.segments]
    assert starts == sorted(starts)
    kinds = {s["stage"]: s["kind"] for s in tl.segments}
    assert kinds["broker_wait"] == "queue"
    assert kinds["plan_verify"] == "service"

    att = lifecycle.attribution([tl])
    rec = att["reconciliation"]
    assert rec["attributed_fraction"] == pytest.approx(1.0, abs=0.01)
    assert att["submit_to_placed_ms"]["p95_ms"] == pytest.approx(100.0)
    # Waterfall shares over the partition sum to ~1.
    assert sum(w["share"] for w in att["waterfall"]) == pytest.approx(
        1.0, abs=0.01)


def test_bounce_becomes_visible_retry_segments():
    """A conflict/refresh cycle through the optimistic pipeline shows as
    attempts=2 + a bounce count + per-attempt segments — visible retry
    time, not lost time."""
    t0 = 2000.0
    spans = [
        _span("eval", t0, t0 + 0.2, job_id="j2"),
        _span("broker.wait", t0, t0 + 0.01),
        _span("worker.submit_plan", t0 + 0.02, t0 + 0.05),
        _span("plan.evaluate", t0 + 0.03, t0 + 0.04, refresh_index=7),
        _span("broker.wait", t0 + 0.05, t0 + 0.06),
        _span("worker.submit_plan", t0 + 0.07, t0 + 0.10),
        _span("plan.evaluate", t0 + 0.08, t0 + 0.09, refresh_index=0),
        _span("plan.apply", t0 + 0.09, t0 + 0.10),
    ]
    tl = lifecycle.stitch_eval("ev2", spans, {"submitted": t0,
                                              "placed": t0 + 0.11})
    assert tl.attempts == 2
    assert tl.bounces == 1
    attempts = {(s["stage"], s["attempt"]) for s in tl.segments}
    assert ("broker_wait", 2) in attempts
    assert ("plan_verify", 2) in attempts


def test_degraded_no_spans_still_anchors_end_to_end():
    """Tracing off (or trace evicted) is not an error: the end-to-end
    numbers come from event anchors alone and the waterfall is all
    unattributed."""
    tl = lifecycle.stitch_eval(
        "ev3", None,
        {"submitted": 10.0, "placed": 10.05, "running": 10.25},
    )
    assert tl.spans_seen == 0 and tl.attempts == 0
    assert tl.submit_to_placed_ms == pytest.approx(50.0)
    assert tl.submit_to_running_ms == pytest.approx(250.0)
    assert tl.stage_ms["unattributed"] == pytest.approx(50.0)
    assert tl.stage_ms["client_ack"] == pytest.approx(200.0)


def test_worst_k_and_empty_attribution():
    tls = []
    for i, e2e in enumerate((0.03, 0.09, 0.01)):
        tl = lifecycle.stitch_eval(f"e{i}", None,
                                   {"submitted": 0.0, "placed": e2e})
        tls.append(tl)
    worst = lifecycle.worst_k(tls, k=2)
    assert [w["eval_id"] for w in worst] == ["e1", "e0"]

    empty = lifecycle.attribution([])
    assert empty["timelines"] == 0
    assert empty["waterfall"] == []
    assert empty["reconciliation"]["attributed_fraction"] == 0.0


def test_scan_events_anchors_from_broker_events():
    """scan_events pulls submitted/placed/running anchors (and job
    metadata) off the typed stream, accepting Event objects and dicts."""
    broker = events_mod.EventBroker(register=False)
    broker.publish("Eval", "EvalUpdated", key="ev9", raft_index=1,
                   payload={"status": structs.EVAL_STATUS_PENDING,
                            "job_id": "j9", "triggered_by": "t"})
    broker.publish("Plan", "PlanApplied", key="ev9", raft_index=2,
                   payload={"n_allocs": 1})
    broker.publish("Alloc", "AllocClientUpdated", key="a1", raft_index=3,
                   payload={"client_status":
                            structs.ALLOC_CLIENT_STATUS_RUNNING,
                            "eval_id": "ev9", "job_id": "j9"})
    evs = broker.all_events()
    anchors = lifecycle.scan_events(evs)["ev9"]
    assert anchors["submitted"] is not None
    assert anchors["placed"] >= anchors["submitted"]
    assert anchors["running"] >= anchors["placed"]
    assert anchors["job_id"] == "j9"
    # Dict form (debug-bundle / artifact path) resolves identically.
    from_dicts = lifecycle.scan_events([e.to_dict() for e in evs])["ev9"]
    assert from_dicts == anchors


# ---------------------------------------------------------------------------
# the stitcher's core assumption: per-key lifecycle ordering on the
# event stream, across a REAL bounce/refresh cycle
# ---------------------------------------------------------------------------


def _seed_eval(srv, job_id):
    ev = Evaluation(
        id=generate_uuid(), priority=50,
        type=structs.JOB_TYPE_SERVICE,
        triggered_by=structs.EVAL_TRIGGER_JOB_REGISTER,
        job_id=job_id, status=structs.EVAL_STATUS_PENDING,
    )
    srv.raft.apply("eval_update", {"evals": [ev]})
    return ev


def _place_plan(eval_id, token, node_id, cpu, snapshot_index):
    alloc = mock.alloc()
    alloc.node_id = node_id
    alloc.eval_id = eval_id
    alloc.resources = Resources(cpu=cpu, memory_mb=64)
    alloc.task_resources = {}
    alloc.desired_status = structs.ALLOC_DESIRED_STATUS_RUN
    plan = Plan(eval_id=eval_id, eval_token=token, priority=50,
                snapshot_index=snapshot_index)
    plan.append_alloc(alloc)
    return plan


def test_event_ordering_and_timeline_across_bounce_cycle():
    """Per-key event sequences stay gapless and monotonically
    raft-index-ordered through a genuine optimistic bounce (conflict →
    RefreshIndex → re-plan → commit), and the stitched timeline shows
    the bounce as a visible retry instead of losing the eval."""
    srv = Server(ServerConfig(scheduler_backend="host", num_schedulers=0))
    srv.plan_queue.set_enabled(True)
    srv.eval_broker.set_enabled(True)
    try:
        node = mock.node()
        node.resources.cpu = 1000  # fits one 600 ask, not two
        srv.raft.apply("node_register", {"node": node})
        ev_a = _seed_eval(srv, "job-a")
        ev_b = _seed_eval(srv, "job-b")
        dq_a, tok_a, _ = srv.eval_dequeue(["service"], timeout=1.0)
        dq_b, tok_b, _ = srv.eval_dequeue(["service"], timeout=1.0)
        tokens = {dq_a.id: tok_a, dq_b.id: tok_b}

        snap_index = srv.raft.applied_index
        pend_a = srv.plan_queue.enqueue(
            _place_plan(dq_a.id, tokens[dq_a.id], node.id, 600, snap_index))
        pend_b = srv.plan_queue.enqueue(
            _place_plan(dq_b.id, tokens[dq_b.id], node.id, 600, snap_index))
        srv.plan_applier.start()
        res_a = pend_a.wait(timeout=5.0)
        res_b = pend_b.wait(timeout=5.0)
        assert res_a.node_allocation and not res_a.conflict
        assert res_b.conflict is True and res_b.refresh_index > 0

        # The refresh cycle: capacity arrives, the bounced plan re-plans
        # against the refreshed snapshot and commits.
        node2 = mock.node()
        node2.resources.cpu = 1000
        srv.raft.apply("node_register", {"node": node2})
        pend_b2 = srv.plan_queue.enqueue(
            _place_plan(dq_b.id, tokens[dq_b.id], node2.id, 600,
                        srv.raft.applied_index))
        res_b2 = pend_b2.wait(timeout=5.0)
        assert res_b2.node_allocation and res_b2.refresh_index == 0

        evs = srv.fsm.events.all_events()
        # Broker indices: strictly increasing, gapless.
        indices = [e.index for e in evs]
        assert indices == list(range(indices[0], indices[0] + len(evs)))
        # Per-key raft-index sequences: monotonically non-decreasing —
        # the stitcher's anchor-ordering assumption, across the bounce.
        by_key = {}
        for e in evs:
            by_key.setdefault(e.key, []).append(e.raft_index)
        for key, seq in by_key.items():
            assert seq == sorted(seq), f"raft order violated for {key}"
        # Lifecycle order for the bounced eval: pending before its (one)
        # PlanApplied — the bounced attempt committed nothing.
        b_types = [e.type for e in evs if e.key == ev_b.id]
        assert b_types.count("PlanApplied") == 1
        assert (b_types.index("EvalUpdated")
                < b_types.index("PlanApplied"))

        # The stitched timeline survives the bounce: the conflict cycle
        # is a counted retry, and the eval still reads placed.
        timelines = lifecycle.stitch(evs)
        tl = timelines[ev_b.id]
        assert tl.submit_to_placed_ms is not None
        assert tl.bounces >= 1
        assert tl.stage_ms.get("plan_verify", 0.0) > 0.0
    finally:
        srv.shutdown()


def test_real_workload_waterfall_reconciles():
    """Acceptance-shaped: a real host-backend workload's stitched stage
    sums reconcile with measured submit→placed within 10%."""
    srv = Server(ServerConfig(
        scheduler_backend="host", num_schedulers=1,
        min_heartbeat_ttl=300.0, prewarm_shapes=False,
    ))
    srv.start()
    try:
        for _ in range(3):
            srv.node_register(mock.node())
        for _ in range(3):
            ev_id, _ = srv.job_register(mock.job())
            ev = srv.wait_for_eval(ev_id, timeout=15.0)
            assert ev.status == structs.EVAL_STATUS_COMPLETE
        att = lifecycle.attribution(
            lifecycle.stitch(srv.fsm.events.all_events()).values())
        assert att["timelines"] == 3
        rec = att["reconciliation"]
        assert 0.9 <= rec["attributed_fraction"] <= 1.1, rec
        assert att["submit_to_placed_ms"]["n"] == 3
        stages = {w["stage"] for w in att["waterfall"]}
        assert stages == set(lifecycle.STAGES)
    finally:
        srv.shutdown()


# ---------------------------------------------------------------------------
# telemetry: fixed-bucket histogram exposition + BurnRateWindow
# ---------------------------------------------------------------------------


def test_prometheus_histogram_golden_format():
    """Golden exposition: cumulative ``le`` buckets with shared bounds —
    the aggregatable (histogram_quantile) companion to the summary."""
    sink = telemetry.InmemSink(histogram_buckets=[1.0, 10.0, 100.0])
    for v in (0.5, 5.0, 50.0, 500.0):
        sink.add_sample(("plan", "evaluate"), v)
    text = telemetry.prometheus_text(sink)
    golden = (
        "# TYPE plan_evaluate_ms_hist histogram\n"
        'plan_evaluate_ms_hist_bucket{le="1"} 1\n'
        'plan_evaluate_ms_hist_bucket{le="10"} 2\n'
        'plan_evaluate_ms_hist_bucket{le="100"} 3\n'
        'plan_evaluate_ms_hist_bucket{le="+Inf"} 4\n'
        "plan_evaluate_ms_hist_sum 555.5\n"
        "plan_evaluate_ms_hist_count 4"
    )
    assert golden in text
    # Bucket counts are process-lifetime cumulative: a second batch only
    # grows them (rate()/histogram_quantile() need monotonicity).
    sink.add_sample(("plan", "evaluate"), 0.1)
    assert 'plan_evaluate_ms_hist_bucket{le="1"} 2' in (
        telemetry.prometheus_text(sink))


def test_histogram_default_buckets_and_config_override():
    sink = telemetry.InmemSink()
    assert sink.buckets == telemetry.DEFAULT_HISTOGRAM_BUCKETS_MS
    custom = telemetry.InmemSink(histogram_buckets=[50.0, 5.0])
    assert custom.buckets == (5.0, 50.0)  # sorted on ingest


def test_burn_rate_window_math_and_bounds():
    w = telemetry.BurnRateWindow(window_s=60.0, objective=0.95,
                                 max_samples=8)
    for i in range(20):
        w.record(good=(i % 10 != 0), t=float(i))
    stats = w.stats(now=20.0)
    # Bounded at 8 samples, oldest evicted and counted.
    assert stats["total"] == 8 and stats["evicted"] == 12
    # Window pruning is monotonic-time arithmetic.
    late = w.stats(now=100.0)
    assert late["total"] == 0 and late["burn_rate"] == 0.0

    w2 = telemetry.BurnRateWindow(window_s=60.0, objective=0.95)
    for i in range(100):
        w2.record(good=(i % 10 != 0), t=float(i) * 0.1)
    s2 = w2.stats(now=10.0)
    # 10 bad of 100 against a 5% budget: burn rate 2.0, budget gone.
    assert s2["burn_rate"] == pytest.approx(2.0)
    assert s2["budget_remaining_fraction"] == 0.0
    with pytest.raises(ValueError):
        telemetry.BurnRateWindow(objective=1.5)


# ---------------------------------------------------------------------------
# trace: aggregate loss counters
# ---------------------------------------------------------------------------


def test_tracer_aggregate_loss_counters():
    tracer = trace.Tracer(max_traces=2, max_spans=2)
    for i in range(3):
        tracer.start_span(f"t{i}", "eval", root=True).finish()
    assert tracer.stats()["traces_evicted"] == 1
    for _ in range(3):
        tracer.start_span("t2", "fsm.apply").finish()
    stats = tracer.stats()
    # 4 finishes into a 2-span ring (root + 3): 2 dropped.
    assert stats["spans_dropped"] == 2
    assert stats["retained"] == 2
    assert set(stats) == {"enabled", "retained", "max_traces",
                          "max_spans", "spans_dropped", "traces_evicted"}


# ---------------------------------------------------------------------------
# slo: objectives, monitor, artifact evaluation
# ---------------------------------------------------------------------------


def test_objective_parse_spelling_and_validation():
    o = slo.Objective.parse("submit_to_placed_p95_ms", 250)
    assert (o.metric, o.percentile, o.threshold_ms) == (
        "submit_to_placed", 0.95, 250.0)
    with pytest.raises(ValueError):
        slo.Objective.parse("p95_submit_to_placed", 250)  # wrong shape
    with pytest.raises(ValueError):
        slo.Objective.parse("plan_apply_p95_ms", 250)  # unknown metric
    with pytest.raises(ValueError):
        slo.Objective.parse("submit_to_placed_p0_ms", 250)
    with pytest.raises(ValueError):
        slo.Objective.parse("submit_to_placed_p95_ms", 0)
    assert [o.name for o in slo.parse_objectives(None)] == sorted(
        slo.DEFAULT_OBJECTIVES)
    assert slo.parse_objectives({"submit_to_running_p50_ms": 100})[0].name \
        == "submit_to_running_p50_ms"


def _lifecycle_events(broker, eval_id, placed_dt, running_dt=None):
    """Publish one eval's pending→placed(→running) lifecycle with
    controlled inter-event latencies (Event.time is stamped at publish;
    rewrite it to shape the measured interval)."""
    broker.publish("Eval", "EvalUpdated", key=eval_id,
                   payload={"status": structs.EVAL_STATUS_PENDING,
                            "job_id": "j", "triggered_by": "t"})
    broker.publish("Plan", "PlanApplied", key=eval_id, payload={})
    evs = broker.all_events()
    evs[-1].time = evs[-2].time + placed_dt
    if running_dt is not None:
        broker.publish(
            "Alloc", "AllocClientUpdated", key="a-" + eval_id,
            payload={"client_status": structs.ALLOC_CLIENT_STATUS_RUNNING,
                     "eval_id": eval_id, "job_id": "j"})
        broker.all_events()[-1].time = evs[-2].time + (running_dt or 0)


def test_slo_monitor_accounting_and_snapshot():
    broker = events_mod.EventBroker(register=False)
    monitor = slo.SLOMonitor(
        broker, {"submit_to_placed_p95_ms": 250.0,
                 "submit_to_running_p95_ms": 1000.0})
    _lifecycle_events(broker, "ev-fast", placed_dt=0.050, running_dt=0.500)
    _lifecycle_events(broker, "ev-slow", placed_dt=0.400)
    monitor.poll()  # cursor 0 -> latest, no truncation charge

    snap = monitor.snapshot()
    placed = next(o for o in snap["objectives"]
                  if o["name"] == "submit_to_placed_p95_ms")
    # 1 bad of 2 against a 5% budget: breached, burn rate 10.
    assert placed["total"] == 2 and placed["bad"] == 1
    assert placed["met"] is False
    assert placed["burn_rate"] == pytest.approx(10.0)
    running = next(o for o in snap["objectives"]
                   if o["name"] == "submit_to_running_p95_ms")
    assert running["total"] == 1 and running["bad"] == 0
    assert running["met"] is True
    assert snap["samples"]["submit_to_placed"]["count"] == 2
    assert snap["samples"]["submit_to_running"]["count"] == 1
    assert snap["pending_evals"] == 0  # both evals resolved
    assert monitor.summary()["submit_to_placed_p95_ms"]["met"] is False

    # Duplicate PlanApplied (partial-commit follow-ups) must not
    # double-count the eval.
    broker.publish("Plan", "PlanApplied", key="ev-fast", payload={})
    monitor.poll()
    assert monitor.snapshot()["samples"]["submit_to_placed"]["count"] == 2


def test_slo_monitor_counts_ring_truncation():
    class _GappyBroker:
        def events_after(self, cursor):
            return 100, [], True

    monitor = slo.SLOMonitor(_GappyBroker(), {})
    monitor._cursor = 5
    monitor.poll()
    assert monitor.truncated_gaps == 1
    monitor.poll()  # cursor now past the gap: charged once per fall-off
    assert monitor.truncated_gaps == 2


def test_slo_monitor_warmup_reset_windows_past_cold_compile():
    """The PR 8 caveat, closed: a scenario's warmup eval (cold XLA
    compile, seconds) used to burn the live error budget forever.
    reset() at the warmup boundary wipes the books — counted — so the
    steady-state verdict reflects only post-boundary samples."""
    broker = events_mod.EventBroker(register=False)
    monitor = slo.SLOMonitor(
        broker, {"submit_to_placed_p95_ms": 250.0})
    # Warmup: one catastrophic cold-compile sample (4s >> 250ms).
    _lifecycle_events(broker, "ev-warmup", placed_dt=4.0)
    monitor.poll()
    assert monitor.snapshot()["objectives"][0]["met"] is False

    monitor.reset()
    snap = monitor.snapshot()
    assert snap["resets"] == 1
    assert snap["reset_excluded"] == 1
    assert snap["samples"]["submit_to_placed"]["count"] == 0
    assert snap["objectives"][0]["total"] == 0

    # Steady state: fast samples only -> the objective is met, the
    # warmup breach is gone from window AND reservoir.
    for i in range(5):
        _lifecycle_events(broker, f"ev-steady-{i}", placed_dt=0.020)
    monitor.poll()
    snap = monitor.snapshot()
    obj = snap["objectives"][0]
    assert obj["total"] == 5 and obj["bad"] == 0 and obj["met"] is True
    assert snap["samples"]["submit_to_placed"]["count"] == 5
    # A warmup eval whose placement lands only AFTER the boundary must
    # not leak a cross-boundary sample: its pending anchor was wiped.
    broker.publish("Plan", "PlanApplied", key="ev-warmup2", payload={})
    monitor.poll()
    assert monitor.snapshot()["samples"]["submit_to_placed"]["count"] == 5


def test_slo_monitor_samples_express_placed_events():
    """The express lane's in-line latency rides ExpressPlaced payloads
    into the express_placed metric (the async PlanApplied never charges
    it — express evals publish no pending EvalUpdated at all)."""
    broker = events_mod.EventBroker(register=False)
    monitor = slo.SLOMonitor(
        broker, {**slo.DEFAULT_OBJECTIVES, **slo.EXPRESS_OBJECTIVES})
    broker.publish("Express", "ExpressPlaced", key="ev-x",
                   payload={"job_id": "j", "tasks": 1,
                            "placed_ms": 0.42})
    broker.publish("Express", "ExpressPlaced", key="ev-y",
                   payload={"job_id": "j", "tasks": 1,
                            "placed_ms": 3.5})
    monitor.poll()
    snap = monitor.snapshot()
    assert snap["samples"]["express_placed"]["count"] == 2
    obj = next(o for o in snap["objectives"]
               if o["name"] == "express_placed_p50_ms")
    # p50 objective at 1ms: one good, one bad of two -> budget 50%,
    # burn rate 1.0, still met (<= 1.0).
    assert obj["total"] == 2 and obj["bad"] == 1
    assert obj["met"] is True
    # submit_to_placed untouched by express events.
    assert snap["samples"]["submit_to_placed"]["count"] == 0


def test_evaluate_artifact_checks_stricter_cut():
    att = {"submit_to_placed_ms": {"n": 50, "p50_ms": 40.0,
                                   "p95_ms": 180.0, "p99_ms": 900.0}}
    checks = slo.evaluate_artifact(
        att, {"submit_to_placed_p90_ms": 200.0,
              "submit_to_placed_p99_ms": 500.0,
              "submit_to_running_p95_ms": 1000.0})
    by_name = {c["objective"]: c for c in checks}
    # p90 objective, artifact cuts at 50/95/99: checked at the next
    # STRICTER recorded cut (p95).
    p90 = by_name["submit_to_placed_p90_ms"]
    assert p90["checked_percentile"] == 0.95
    assert p90["observed_ms"] == 180.0 and p90["met"] is True
    assert by_name["submit_to_placed_p99_ms"]["met"] is False
    # No running samples in the artifact: reported, not judged.
    assert by_name["submit_to_running_p95_ms"]["met"] is None


# ---------------------------------------------------------------------------
# agent config: telemetry { histogram_buckets, slo {} }
# ---------------------------------------------------------------------------


def test_agent_config_histogram_and_slo_blocks():
    from nomad_tpu.agent_config import _from_mapping

    fc = _from_mapping({"telemetry": {
        "histogram_buckets": [100, 5, 25],
        "slo": {"submit_to_placed_p95_ms": 250},
    }})
    assert fc.telemetry.histogram_buckets == [5.0, 25.0, 100.0]
    assert fc.telemetry.slo == {"submit_to_placed_p95_ms": 250.0}
    with pytest.raises(ValueError):
        _from_mapping({"telemetry": {"histogram_buckets": [0, 5]}})
    with pytest.raises(ValueError):
        _from_mapping({"telemetry": {"histogram_buckets": "wide"}})
    # A typo'd objective fails at config parse, not agent start.
    with pytest.raises(ValueError):
        _from_mapping({"telemetry": {"slo": {"submit_to_plcaed_p95_ms": 1}}})

    # Absent vs explicitly empty: no slo{} block (None) means the default
    # objective set downstream; an empty block is the documented
    # disable switch and must survive parse AND merge.
    assert _from_mapping({}).telemetry.slo is None
    disabled = _from_mapping({"telemetry": {"slo": {}}})
    assert disabled.telemetry.slo == {}

    # Per-objective merge: a later file overrides one threshold without
    # dropping the rest of the set.
    base = _from_mapping({"telemetry": {"slo": {
        "submit_to_placed_p95_ms": 250, "submit_to_running_p95_ms": 1000}}})
    override = _from_mapping({"telemetry": {"slo": {
        "submit_to_placed_p95_ms": 100}}})
    merged = base.merge(override)
    assert merged.telemetry.slo == {"submit_to_placed_p95_ms": 100.0,
                                    "submit_to_running_p95_ms": 1000.0}
    # A later empty block disables; a later absent block changes nothing.
    assert base.merge(disabled).telemetry.slo == {}
    assert base.merge(_from_mapping({})).telemetry.slo \
        == base.telemetry.slo


# ---------------------------------------------------------------------------
# HTTP + SDK surfaces (one dev agent for the module)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def agent(tmp_path_factory):
    from nomad_tpu.agent import Agent, AgentConfig

    config = AgentConfig(
        server_enabled=True, dev_mode=True, node_name="slo-dev",
        enable_debug=True,
        # Small ring so the truncation case is drivable over HTTP.
        event_buffer_size=64,
    )
    config.data_dir = str(tmp_path_factory.mktemp("slo-agent"))
    config.http_port = 0
    config.scheduler_backend = "host"
    a = Agent(config)
    a.start()
    yield a
    a.shutdown()


@pytest.fixture()
def client(agent):
    from nomad_tpu.api.client import ApiClient

    return ApiClient(address=agent.http.addr)


def _place_one(client, agent):
    job = mock.job()
    ev_id, _ = client.jobs().register(job)
    ev = agent.server.wait_for_eval(ev_id, timeout=15.0)
    assert ev.status == structs.EVAL_STATUS_COMPLETE
    return job, ev_id


def test_agent_slo_endpoint_live(client, agent):
    _place_one(client, agent)
    # The monitor is an event-ring consumer on a 0.25s poll cadence:
    # give it a beat to account the placement.
    deadline = time.monotonic() + 5.0
    snap = client.agent().slo()
    while (time.monotonic() < deadline
           and not snap["samples"]["submit_to_placed"]["count"]):
        time.sleep(0.05)
        snap = client.agent().slo()
    names = {o["name"] for o in snap["objectives"]}
    assert names == set(slo.DEFAULT_OBJECTIVES)
    placed = next(o for o in snap["objectives"]
                  if o["metric"] == "submit_to_placed")
    assert placed["observed"]["count"] >= 1
    assert placed["threshold_ms"] == 250.0
    assert "burn_rate" in placed and "budget_remaining_fraction" in placed
    # The monitor publishes through the ordinary sink: gauges ride the
    # metrics surface with zero extra wiring.
    prom = urllib.request.urlopen(
        client.address + "/v1/agent/metrics?format=prometheus",
        timeout=10).read().decode()
    assert "slo_submit_to_placed_p95_ms_burn_rate" in prom
    assert "nomad_trace_spans_dropped_total" in prom


def test_timeline_endpoints_and_sdk(client, agent):
    _, ev_id = _place_one(client, agent)
    tl = client.evaluations().timeline(ev_id)
    assert tl["eval_id"] == ev_id
    assert tl["submit_to_placed_ms"] is not None
    assert tl["spans_seen"] > 0
    assert set(tl["stage_ms"]) <= set(lifecycle.STAGE_KINDS)
    assert tl["segments"], "expected per-stage segments from live spans"

    allocs, _ = client.evaluations().allocations(ev_id)
    assert allocs
    atl = client.allocations().timeline(allocs[0]["id"])
    assert atl["alloc_id"] == allocs[0]["id"]
    assert atl["eval_id"] == ev_id

    from nomad_tpu.api.client import ApiError

    with pytest.raises(ApiError):
        client.evaluations().timeline("no-such-eval")
    with pytest.raises(ApiError):
        client.allocations().timeline("no-such-alloc")


def test_metrics_json_carries_trace_stats(client, agent):
    metrics = client.agent().metrics()
    assert "trace" in metrics
    assert {"spans_dropped", "traces_evicted", "retained"} <= set(
        metrics["trace"])


def test_sse_resume_after_truncation(client, agent):
    """A resume cursor that fell off the bounded ring gets the Truncated
    frame FIRST, then the retained tail — the SSE consumer knows to
    re-list instead of assuming continuity."""
    broker = agent.server.fsm.events
    start_index = broker.get_index()
    for i in range(200):  # blow past the 64-event ring
        broker.publish("Node", "NodeRegistered", key=f"trunc-{i}",
                       payload={})
    req = urllib.request.Request(
        client.address
        + f"/v1/event/stream?format=sse&index={max(start_index, 1)}"
        + "&wait=300ms"
    )
    with urllib.request.urlopen(req, timeout=15.0) as resp:
        body = resp.read().decode()
    frames = [f for f in body.split("\n\n") if f.strip()
              and not f.startswith(":")]
    assert frames, body
    events_seen = []
    for frame in frames:
        lines = dict(line.split(": ", 1) for line in frame.splitlines()
                     if ": " in line)
        events_seen.append(lines["event"])
    assert events_seen[0] == "Truncated"
    assert "NodeRegistered" in events_seen[1:]
    # The resumed tail itself is index-ordered and gapless.
    ids = [int(dict(line.split(": ", 1) for line in f.splitlines()
                    if ": " in line)["id"])
           for f in frames[1:]]
    assert ids == list(range(ids[0], ids[0] + len(ids)))


def test_debug_bundle_slo_and_timeline_sections(client, agent):
    _place_one(client, agent)
    bundle = client.agent().debug_bundle()
    assert bundle["slo"] is not None
    assert {o["name"] for o in bundle["slo"]["objectives"]} == set(
        slo.DEFAULT_OBJECTIVES)
    assert isinstance(bundle["timelines"], list)
    if bundle["timelines"]:
        worst = bundle["timelines"][0]
        assert worst["submit_to_placed_ms"] is not None
        assert "stage_ms" in worst
