"""Native kernel parity + bulk plan verification equivalence.

The numpy fallbacks in nomad_tpu.native are the correctness oracle for the
C++ kernels; _prevaluate_nodes_bulk must agree with the scalar
evaluate_node_plan on every node it chooses to answer for.
"""

import random

import numpy as np
import pytest

from nomad_tpu import mock, native, structs
from nomad_tpu.server.plan_apply import (
    _prevaluate_nodes_bulk,
    evaluate_node_plan,
    evaluate_plan,
)
from nomad_tpu.state import StateStore
from nomad_tpu.structs import (
    Allocation,
    NetworkResource,
    Plan,
    Resources,
    generate_uuid,
)


def test_native_kernels_match_numpy_fallback():
    rng = np.random.default_rng(0)
    idx = rng.integers(0, 50, size=1000).astype(np.int32)
    vals = rng.integers(0, 100, size=(1000, 4)).astype(np.int32)

    got = native.scatter_add(idx, vals, 50)
    want = np.zeros((50, 4), dtype=np.int64)
    np.add.at(want, idx, vals)
    np.testing.assert_array_equal(got, want.astype(np.int32))

    used = rng.integers(0, 100, size=(200, 4)).astype(np.int32)
    total = rng.integers(0, 100, size=(200, 4)).astype(np.int32)
    fit, exhausted = native.fit_check(used, total)
    over = used > total
    np.testing.assert_array_equal(fit, ~over.any(axis=1))
    for i in range(200):
        if fit[i]:
            assert exhausted[i] == -1
        else:
            assert exhausted[i] == over[i].argmax()

    np.testing.assert_array_equal(
        native.bincount(idx, 50), np.bincount(idx, minlength=50)[:50]
    )


def _mk_alloc(node_id, cpu, mem, networks=None):
    res = Resources(cpu=cpu, memory_mb=mem)
    if networks:
        res.networks = networks
    return Allocation(
        id=generate_uuid(),
        node_id=node_id,
        job_id="j",
        task_group="tg",
        resources=res,
        desired_status=structs.ALLOC_DESIRED_STATUS_RUN,
    )


@pytest.mark.parametrize("seed", [0, 1, 2, 3, 4])
def test_bulk_verifier_matches_scalar(seed):
    """Random plans over a mixed cluster: every node the bulk verifier
    answers for must agree with evaluate_node_plan."""
    rng = random.Random(seed)
    state = StateStore()
    nodes = []
    for i in range(20):
        node = mock.node()
        node.id = f"n-{i:02d}"
        if rng.random() < 0.15:
            node.status = structs.NODE_STATUS_DOWN
        if rng.random() < 0.1:
            node.drain = True
        nodes.append(node)
        state.upsert_node(i + 1, node)

    # Seed some existing allocations (some with networks)
    idx = 100
    for node in nodes:
        for _ in range(rng.randrange(0, 3)):
            nets = None
            if rng.random() < 0.2:
                nets = [NetworkResource(device="eth0", ip="10.0.0.1", mbits=10)]
            state.upsert_allocs(
                idx, [_mk_alloc(node.id, rng.choice([200, 800]), 256, nets)]
            )
            idx += 1

    plan = Plan(eval_id=generate_uuid())
    shared = Resources(cpu=300, memory_mb=512)
    for node in nodes:
        n_place = rng.randrange(0, 12)
        for _ in range(n_place):
            alloc = Allocation(
                id=generate_uuid(), node_id=node.id, job_id="j2",
                task_group="tg2", resources=shared,
                desired_status=structs.ALLOC_DESIRED_STATUS_RUN,
            )
            plan.append_alloc(alloc)

    snap = state.snapshot()
    bulk = _prevaluate_nodes_bulk(snap, plan)
    assert bulk, "bulk verifier answered for no nodes"
    for node_id, fit in bulk.items():
        assert fit == evaluate_node_plan(snap, plan, node_id), node_id


def test_evaluate_plan_large_uses_bulk_and_matches():
    """A 500-placement plan through evaluate_plan: result identical to the
    scalar-only path (threshold forced high)."""
    from nomad_tpu.server import plan_apply

    state = StateStore()
    for i in range(10):
        node = mock.node()
        node.id = f"m-{i}"
        state.upsert_node(i + 1, node)

    plan = Plan(eval_id=generate_uuid())
    shared = Resources(cpu=100, memory_mb=128)
    for i in range(500):
        alloc = Allocation(
            id=generate_uuid(), node_id=f"m-{i % 10}", job_id="big",
            task_group="tg", resources=shared,
            desired_status=structs.ALLOC_DESIRED_STATUS_RUN,
        )
        plan.append_alloc(alloc)

    snap = state.snapshot()
    fast = evaluate_plan(snap, plan)

    orig = plan_apply.FAST_VERIFY_THRESHOLD
    plan_apply.FAST_VERIFY_THRESHOLD = 10**9
    try:
        slow = evaluate_plan(state.snapshot(), plan)
    finally:
        plan_apply.FAST_VERIFY_THRESHOLD = orig

    assert set(fast.node_allocation) == set(slow.node_allocation)
    for nid in fast.node_allocation:
        assert len(fast.node_allocation[nid]) == len(slow.node_allocation[nid])
    assert fast.refresh_index == slow.refresh_index


def test_bulk_verify_columnar_against_empty_node_table():
    """A large columnar plan verified against a snapshot whose nodes have
    ALL deregistered must answer fit=False for every node (stale data ->
    refresh), not crash indexing the empty table (the pure-columnar fast
    path's zero-row guard)."""
    from nomad_tpu.structs import AllocBatch

    state = StateStore()
    nodes = []
    for i in range(4):
        node = mock.node()
        node.id = f"gone-{i}"
        state.upsert_node(i + 1, node)
        nodes.append(node)
    job = mock.job()
    state.upsert_job(10, job)

    plan = Plan(eval_id=generate_uuid())
    batch = AllocBatch(
        eval_id=plan.eval_id, job=job, tg_name="web",
        resources=Resources(cpu=10, memory_mb=16),
        task_resources={},
        node_ids=[n.id for n in nodes],
        node_counts=[32, 32, 32, 32],  # past FAST_VERIFY_THRESHOLD
        name_idx=np.arange(128),
        ids_hex="ab" * (16 * 128),
    )
    plan.append_batch(batch)

    # Every node deregisters AFTER the plan was built.
    for i, n in enumerate(nodes):
        state.delete_node(20 + i, n.id)

    result = evaluate_plan(state.snapshot(), plan)
    assert not result.alloc_batches        # nothing committable
    assert result.refresh_index > 0        # stale-data refresh forced
