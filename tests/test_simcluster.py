"""simcluster: scale simulation & load harness tests.

Tier-1 scope: injector determinism, the batched Node.BatchRegister/
BatchHeartbeat RPC tier, the timer-wheel heartbeat manager, and the
steady-1k smoke scenario (the whole register→heartbeat→eval→broker→
worker→solver→plan_apply→raft path at 1k nodes) plus its same-seed
canonical-event replay contract.

Slow scope (`pytest -m slow`): the 10k-node heartbeat churn proof
(VERDICT r5 item 7) — rate_scaled_interval keeps leader-side timer resets
bounded at 10k nodes, a silenced tranche expires through the real TTL
wheel, and the resulting node-down evals coalesce into bounded device
dispatches — and the mixed churn scenario.
"""

import logging
import time

import pytest

from nomad_tpu import structs
from nomad_tpu.server import ServerConfig
from nomad_tpu.server.cluster import ClusterConfig, ClusterServer, wait_for_leader
from nomad_tpu.server.heartbeat import rate_scaled_interval
from nomad_tpu.simcluster import run_scenario
from nomad_tpu.simcluster.scenario import (
    SCENARIOS,
    ScenarioRunner,
    ScenarioSpec,
    canonical_events,
)
from nomad_tpu.simcluster.simnode import SimFleet, sim_node
from nomad_tpu.simcluster.workload import (
    BatchBurstInjector,
    NodeChurnInjector,
    OverdriveInjector,
    SteadyServiceInjector,
    UpdateChurnInjector,
)

log = logging.getLogger("test_simcluster")


# ---------------------------------------------------------------------------
# Injector determinism (the faults.py seeded-stream posture)
# ---------------------------------------------------------------------------


def _schedule(injector):
    return [(round(a.at, 9), a.kind,
             a.payload.get("job_key"), a.payload.get("mutation"))
            for a in injector.actions()]


def test_injectors_are_seed_deterministic():
    for mk in (
        lambda s: SteadyServiceInjector(s, jobs=5, tasks_per_job=50, over=4.0),
        lambda s: BatchBurstInjector(s, bursts=2, jobs_per_burst=3,
                                     tasks_per_job=300),
        lambda s: UpdateChurnInjector(s, base_jobs=3, tasks_per_job=40,
                                      updates=6),
    ):
        assert _schedule(mk(42)) == _schedule(mk(42))
    # Seeds must actually matter where the stream is consumed (arrival
    # jitter / mutation choice).
    a = _schedule(SteadyServiceInjector(1, jobs=5, tasks_per_job=50, over=4.0))
    b = _schedule(SteadyServiceInjector(2, jobs=5, tasks_per_job=50, over=4.0))
    assert a != b
    u1 = _schedule(UpdateChurnInjector(1, base_jobs=5, tasks_per_job=10,
                                       updates=10))
    u2 = _schedule(UpdateChurnInjector(9, base_jobs=5, tasks_per_job=10,
                                       updates=10))
    assert u1 != u2


def test_injector_streams_are_independent():
    """Adding one injector never shifts another's decisions — each is
    salted by its own name (the FaultRule seeding contract)."""
    alone = _schedule(UpdateChurnInjector(7, base_jobs=4, tasks_per_job=10,
                                          updates=8))
    _ = SteadyServiceInjector(7, jobs=9, tasks_per_job=10, over=1.0).actions()
    again = _schedule(UpdateChurnInjector(7, base_jobs=4, tasks_per_job=10,
                                          updates=8))
    assert alone == again


# ---------------------------------------------------------------------------
# Batched registration/heartbeat RPC tier + fleet
# ---------------------------------------------------------------------------


@pytest.fixture
def sim_server():
    srv = ClusterServer(
        ServerConfig(scheduler_backend="host", num_schedulers=1,
                     min_heartbeat_ttl=2.0,
                     max_heartbeats_per_second=2000.0,
                     prewarm_shapes=False),
        ClusterConfig(bootstrap_expect=1),
    )
    srv.start()
    wait_for_leader([srv])
    yield srv
    srv.shutdown()


def test_fleet_batch_register_and_beat(sim_server):
    srv = sim_server
    fleet = SimFleet(srv.rpc_addr, batch_size=50, tick=0.1)
    try:
        nodes = [sim_node(i) for i in range(120)]
        reg = fleet.register(nodes)
        assert reg["n"] == 120 and reg["batches"] == 3
        assert srv.heartbeat.num_timers() == 120
        assert len(srv.state_store.nodes()) == 120
        # One raft entry per tranche, not per node.
        evt = [e for e in srv.fsm.events.all_events()
               if e.type == "NodeBatchRegistered"]
        assert len(evt) == 3
        assert sum(e.payload["count"] for e in evt) == 120

        fleet.start_heartbeats()
        # TTLs are 1-2s (jittered); beats land at 0.8*ttl through
        # Node.BatchHeartbeat and renew the server-side wheel.
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            if srv.heartbeat.stats()["renewals"] >= 120:
                break
            time.sleep(0.05)
        assert srv.heartbeat.stats()["renewals"] >= 120
        assert fleet.beats_sent >= 120
        # Nothing expired while the fleet was beating.
        assert srv.heartbeat.num_timers() == 120
        assert all(n.status == structs.NODE_STATUS_READY
                   for n in srv.state_store.nodes())

        # Silence a tranche: their TTLs run out through the REAL wheel
        # and the server marks them down.
        tranche = [f"sim-{i:05d}" for i in range(10)]
        fleet.fail(tranche)
        deadline = time.monotonic() + 15.0
        while time.monotonic() < deadline:
            down = [nid for nid in tranche
                    if srv.state_store.node_by_id(nid).status
                    == structs.NODE_STATUS_DOWN]
            if len(down) == 10:
                break
            time.sleep(0.1)
        assert len(down) == 10, f"only {len(down)} of tranche went down"
        # The survivors are still being renewed.
        assert all(srv.state_store.node_by_id(f"sim-{i:05d}").status
                   == structs.NODE_STATUS_READY for i in range(20, 30))
    finally:
        fleet.stop()


def test_batch_heartbeat_semantics(sim_server):
    """Node.BatchHeartbeat == N node_heartbeat calls: unknown nodes get
    ttl 0.0, down nodes ride the full status-update path back to ready
    (transition evals fan out), ready nodes get a renewal."""
    srv = sim_server
    fleet = SimFleet(srv.rpc_addr, batch_size=50)
    try:
        nodes = [sim_node(i) for i in range(10)]
        fleet.register(nodes)
        out = fleet._pool().call(
            srv.rpc_addr, "Node.BatchHeartbeat",
            {"node_ids": ["sim-00000", "no-such-node"]},
        )
        ttls = out["heartbeat_ttls"]
        assert ttls["sim-00000"] > 0
        assert ttls["no-such-node"] == 0.0
        # Down -> batch beat -> ready again (the transition path).
        srv.node_update_status("sim-00001", structs.NODE_STATUS_DOWN)
        out = fleet._pool().call(
            srv.rpc_addr, "Node.BatchHeartbeat",
            {"node_ids": ["sim-00001"]},
        )
        assert out["heartbeat_ttls"]["sim-00001"] > 0
        assert (srv.state_store.node_by_id("sim-00001").status
                == structs.NODE_STATUS_READY)
    finally:
        fleet.stop()


def test_heartbeat_wheel_counters(sim_server):
    srv = sim_server
    ttls = srv.heartbeat.reset_many([f"w{i}" for i in range(30)])
    assert len(ttls) == 30 and all(v >= 1.0 for v in ttls.values())
    st = srv.heartbeat.stats()
    assert st["arms"] >= 30 and st["active"] >= 30
    srv.heartbeat.reset_many([f"w{i}" for i in range(10)])
    assert srv.heartbeat.stats()["renewals"] >= 10
    for i in range(30):
        srv.heartbeat.clear_heartbeat_timer(f"w{i}")
    assert srv.heartbeat.num_timers() == 0


# ---------------------------------------------------------------------------
# The smoke scenario: the whole pipeline at 1k nodes (tier-1)
# ---------------------------------------------------------------------------


def test_steady_1k_smoke(tmp_path):
    out = tmp_path / "SIMLOAD_steady-1k_smoke.json"
    art = run_scenario("steady-1k", seed=7, out_path=str(out))
    assert out.exists()
    # 6 jobs x 260 tasks, all placed through broker→worker→solver→
    # plan_apply→raft.
    assert art["placements"]["placed"] == 6 * 260
    assert art["placements"]["evals_injected"] == 6
    assert art["placements"]["plans_applied"] == 6
    assert art["placements"]["placements_per_sec"] > 0
    assert art["placements"]["device_dispatches"] >= 1
    assert art["plan_latency_ms"]["n"] == 6
    assert art["plan_latency_ms"]["p50_ms"] > 0
    assert art["eval_latency_ms"]["n"] == 6
    assert art["heartbeat"]["timers"] == 1000
    assert art["registration"]["n"] == 1000
    assert art["alloc_ack"]["acked"] == 150
    assert art["events"]["truncated"] is False
    assert art["events"]["by_type"]["PlanApplied"] == 6
    assert art["events"]["by_type"]["AllocClientUpdated"] == 150
    # Columnar path: one AllocUpserted per eval, not per placement
    # (client-ack promotions publish AllocClientUpdated, counted above).
    assert art["events"]["by_type"]["AllocUpserted"] == 6
    # The converged renewal load respects the configured cap (production
    # 50/s posture at 1k nodes: TTL >= 20s at full count, beat at
    # 0.8*ttl). The transient scheduled rate right after a rolling
    # bring-up legitimately overshoots (short first grants at small
    # count) and is reported unasserted.
    assert (art["heartbeat"]["equilibrium_renewals_per_sec"]
            <= art["heartbeat"]["rate_cap_per_sec"])
    assert art["heartbeat"]["scheduled_renewals_per_sec"] > 0


def test_steady_100k_nodes_registered():
    """The 100k-node scenario is registered with the intended shape (the
    run itself is a bank-time event — tools/simload.py — not a tier-1
    test: registration alone takes ~30s)."""
    spec = SCENARIOS["steady-100k-nodes"]
    assert spec.n_nodes == 100_000
    assert spec.deterministic is True
    injectors = spec.injectors(42)
    assert len(injectors) == 1
    # Same workload shape as steady-10k: the node axis is the variable.
    actions = injectors[0].actions()
    assert len(actions) == 24
    # TTLs sized so no beat comes due inside the run at 100k.
    assert spec.server_overrides["max_heartbeats_per_second"] == 10.0


def test_steady_smoke_batch_width_and_equiv_sections(tmp_path):
    """The artifact's solver_panel window carries the new batch-width
    and equivalence-class axes (present even when zero — consumers diff
    them across rounds)."""
    out = tmp_path / "SIMLOAD_steady-1k_panel.json"
    art = run_scenario("steady-1k", seed=11, out_path=str(out))
    window = art["solver_panel"]["window"]
    assert "batch_widths" in window
    assert set(window["equiv"]) == {"classes", "members", "copies",
                                    "rows_saved"}
    # The steady smoke's 6 concurrent service evals ride the coalescer:
    # at least one dispatch recorded on the width axis.
    assert sum(
        row["dispatches"] for row in window["batch_widths"].values()
    ) >= 1


def test_overdrive_1k_smoke(tmp_path):
    """The impolite front door at smoke scale: 6 clients blast 8 batch
    jobs each with no self-throttling; admission rate lanes (burst 2,
    glacial refill) admit exactly 2 per client DETERMINISTICALLY, the
    rest reject RATE_LIMITED typed, every queue stays under its cap, and
    admitted work all places."""
    out = tmp_path / "SIMLOAD_overdrive-1k_smoke.json"
    art = run_scenario("overdrive-1k", seed=42, out_path=str(out))
    adm = art["admission"]
    assert adm["injector"]["offered"] == 6 * 8
    assert adm["injector"]["admitted"] == 6 * 2
    assert adm["injector"]["rejected"] == {"RATE_LIMITED": 6 * 6}
    assert adm["caps_respected"] is True
    assert adm["controller"]["rejected"] == 36
    assert adm["controller"]["by_reason"]["RATE_LIMITED"] == 36
    # Admitted work fully places (12 jobs x 20 tasks).
    assert art["placements"]["placed"] == 12 * 20
    assert art["events"]["by_type"]["AdmissionRejected"] == 36
    assert art["events"]["by_type"]["JobRegistered"] == 12
    assert art["events"]["truncated"] is False
    # Peaks bounded by the configured caps (enforced at enqueue).
    assert art["peaks"]["broker_pending"] <= 128
    assert art["peaks"]["plan_queue_depth"] <= 64


def test_express_1k_smoke(tmp_path):
    """The express lane at smoke scale, through real RPC: a service
    background plus a 40-task express stream. Every express submission
    places in-line (ExpressPlaced events = submissions), every entry
    commits asynchronously with nothing left on the ledger, and the
    artifact carries the express quantiles + slo_check rows."""
    out = tmp_path / "SIMLOAD_express-1k_smoke.json"
    art = run_scenario("express-1k", seed=42, out_path=str(out))
    lane = art["express"]["lane"]
    assert lane["enabled"] is True
    # 40 stream submissions (+1 warmup, excluded from the measured
    # window's events but counted in the lane books).
    assert art["express"]["placed_events"] == 40
    assert lane["placed"] == 41
    assert lane["committed"] == 41
    assert lane["reconciled"] == 0
    assert lane["fallbacks"] == {}
    assert lane["backlog"] == 0 and lane["leases"] == 0
    assert lane["ledger"]["granted"] == lane["ledger"]["released"]
    # Express placements landed: 40 express evals, one object alloc each
    # (express allocs commit as object rows; service placements stay
    # columnar), and the service background placed in full.
    assert art["events"]["by_type"]["ExpressPlaced"] == 40
    assert art["placements"]["placed"] == 3 * 60 + 40
    att = art["latency_attribution"]
    assert att["express_placed_ms"]["n"] == 40
    assert att["express_placed_ms"]["p50_ms"] > 0
    by_obj = {c["objective"]: c for c in att["slo_check"]}
    assert "express_placed_p50_ms" in by_obj
    # The live monitor tracked the express metric past the warmup reset
    # (its 0.25s poll may not have drained the very tail of the stream
    # when the artifact snapshots it — presence, not exact count).
    assert art["slo"]["resets"] == 1
    assert 1 <= art["slo"]["samples"]["express_placed"]["count"] <= 40
    assert art["events"]["truncated"] is False


def test_churn_frag_200_smoke(tmp_path):
    """The capacity observatory at smoke scale, contrast arm included:
    6 fill jobs x400 small tasks pack 200 nodes, half deregister (the
    density shred), two chunky probe jobs land after. The artifact must
    bank the stranded/padding trajectories, and the observatory-OFF
    contrast arm must reproduce the main arm's canonical digest — the
    decision-invariance proof."""
    out = tmp_path / "SIMLOAD_churn-frag-200_smoke.json"
    art = run_scenario("churn-frag-200", seed=42, out_path=str(out))
    # 6x400 fill + 2x40 probes placed; 3 deregistered jobs stop 1200.
    assert art["placements"]["placed"] == 6 * 400 + 2 * 40
    assert art["placements"]["stopped"] == 3 * 400
    assert art["events"]["by_type"]["JobDeregistered"] == 3
    assert art["events"]["truncated"] is False

    cap = art["capacity"]
    assert cap["enabled"] is True
    assert len(cap["trajectory"]) >= 3
    final = cap["final"]
    assert final["nodes"]["schedulable"] == 200
    # The shred left remnants: work still occupies nodes, density is a
    # real fraction, and the accountant rode the change logs (rolls
    # dominate — at most the one initial rebuild).
    assert final["nodes"]["occupied"] > 0
    assert 0 < final["binpack_density"]["cpu"] <= 1
    assert final["accountant"]["rebuilds"] <= 1
    assert final["accountant"]["rolls"] >= 1
    shapes = {s["shape"] for s in final["stranded"]}
    assert shapes == {"small", "medium", "large"}
    # Mid-fill the cell strands hard against the large shape; the
    # trajectory must have caught utilization actually moving.
    utils = [s["utilization"]["cpu"] for s in cap["trajectory"]]
    assert max(utils) > min(utils)

    panel = art["solver_panel"]
    assert panel["window"]["solves"] >= 8  # 6 fill + 2 probe solves min
    assert panel["window"]["placed"] >= 6 * 400 + 2 * 40
    assert 0 <= panel["window"]["node_padding_waste"] < 1
    assert panel["window"]["device_ms_per_placement"] > 0
    assert panel["compiles"]["total"] >= 1
    assert len(panel["trajectory"]) >= 3

    # The headline: turning the observatory OFF changes nothing the
    # cluster DID.
    contrast = art["contrast"]
    assert contrast["capacity"] == {"enabled": False}
    assert contrast["digest_matches"] is True
    assert contrast["placements"]["placed"] == 6 * 400 + 2 * 40


def test_restart_800_smoke(tmp_path):
    """Kill-and-recover at smoke scale: 800 nodes, 6 service jobs x120
    tasks, leader killed outright at t=2s and restarted from its
    durable raft state on the same port. Every pre-kill placement must
    survive the replay verbatim (same alloc id, same node), the run
    still places everything, and the artifact banks a populated
    recovery timeline."""
    out = tmp_path / "SIMLOAD_restart-800_smoke.json"
    art = run_scenario("restart-800", seed=42, out_path=str(out))
    assert art["placements"]["placed"] == 6 * 120
    assert art["events"]["truncated"] is False

    raft = art["raft"]
    assert raft["enabled"] is True
    restart = raft["restart"]
    assert restart["placements_survived"] is True
    assert restart["pre_kill_placements"] > 0
    assert restart["surviving_placements"] == restart["pre_kill_placements"]
    assert restart["downtime_s"] > 0
    recovery = raft["recovery"]
    assert recovery["cold_start"] is True
    assert recovery["entries_replayed"] > 0
    assert recovery["replayed_by_type"].get("alloc_update", 0) >= 1
    assert recovery["replay_wall_ms"] is not None
    assert recovery["time_to_leader_ms"] is not None
    assert recovery["time_to_serving_ms"] is not None
    assert recovery["replay_entries_per_s"] > 0
    # Write-path attribution spans both server lives (plan commits land
    # as alloc_update entries; the books carry p50/p95 per msg_type).
    assert raft["write_path"]["alloc_update"]["count"] >= 6
    assert raft["write_path"]["alloc_update"]["total_ms"]["p95"] > 0


def test_restart_smoke_is_seed_deterministic():
    """The kill point is wall-clock and WHICH evals straddle it is
    scheduling noise — but every per-key lifecycle (and therefore the
    canonical digest) must replay under the same seed: placements
    committed pre-kill come back via log replay, in-flight evals
    redeliver from durable state, and the event stream dedups the
    replayed prefix by raft index."""
    a = run_scenario("restart-800", seed=11)
    b = run_scenario("restart-800", seed=11)
    assert a["events"]["digest"] == b["events"]["digest"]
    assert a["events"]["by_type"] == b["events"]["by_type"]


def test_churn_frag_smoke_is_seed_deterministic():
    """Same seed, same canonical digest — deregistration churn and the
    probe wave racing stop plans included."""
    a = run_scenario("churn-frag-200", seed=11, contrast=False)
    b = run_scenario("churn-frag-200", seed=11, contrast=False)
    assert a["events"]["digest"] == b["events"]["digest"]
    assert a["events"]["by_type"] == b["events"]["by_type"]


def test_read_storm_800_smoke(tmp_path):
    """The follower read plane at smoke scale, contrast arm included:
    a 3-member cell at 800 nodes under 6x120 service placements while a
    small impolite read fleet (2 pollers, 2 blocking watchers, 1 SSE
    tail) rides the FOLLOWER front ends — stale lane under the 5s
    bound, every 5th poll linearizable. The artifact must carry all
    three books (serving attribution, watch economy, freshness) on the
    members that actually served, the lanes verdict block, PLUS the
    fleet's client-side view; the leader-only contrast arm must
    reproduce the main arm's canonical digest — the read-path
    decision-invariance proof."""
    out = tmp_path / "SIMLOAD_read-storm-800_smoke.json"
    art = run_scenario("read-storm-800", seed=42, out_path=str(out))
    assert art["placements"]["placed"] == 6 * 120
    assert art["events"]["truncated"] is False

    reads = art["reads"]
    assert reads["enabled"] is True
    # Follower serving: the fleet rode the two follower fronts, so the
    # per-endpoint serving attribution lives in the members' own books
    # (the leader's stay the schema anchor, near-empty by design).
    member_books = list(reads["by_member"].values())
    assert len(member_books) == 2

    def across(path_keys):
        total = 0
        for b in member_books:
            node = b
            for k in path_keys:
                node = (node or {}).get(k, {} if k != path_keys[-1] else 0)
            total += node or 0
        return total

    # Serving attribution keyed on route templates: the pollers rotate
    # the four list endpoints, the watchers long-poll them, the SSE
    # tail rides a follower's own event ring.
    for route in ("/v1/jobs", "/v1/nodes", "/v1/allocations",
                  "/v1/evaluations", "/v1/event/stream"):
        assert across(["endpoints", route, "count"]) > 0, route
        assert across(["endpoints", route, "bytes_total"]) > 0, route
    assert across(["endpoints", "/v1/event/stream", "lanes", "sse"]) >= 1
    # The blocking hold/serve partition: watchers parked on ?index=N,
    # every finished query is a wake or a timeout, and the stage means
    # reconcile with the total by construction — on every member that
    # served any.
    assert any(b.get("blocking") for b in member_books), \
        "no blocking books despite long-poll watchers"
    for b in member_books:
        for route, books in (b.get("blocking") or {}).items():
            assert books["count"] == books["wakes"] + books["timeouts"]
            assert (books["hold_ms"]["mean"] + books["serve_ms"]["mean"]
                    == pytest.approx(books["total_ms"]["mean"], abs=0.02))
    # SSE session books and the freshness stamp both saw traffic.
    assert across(["sse", "started"]) >= 1
    assert across(["sse", "frames"]) > 0
    assert all(b["sse"]["active"] == 0 for b in member_books)
    assert across(["freshness", "responses_stamped"]) > 0
    # The per-role freshness split (read_observe.py): follower-served
    # stale-lane responses land in their own ledger bucket.
    split_roles = set()
    for b in member_books:
        split_roles |= set(b["freshness"].get("by_role") or {})
    assert "follower" in split_roles
    # Watch economy: every member's registry sees the replicated apply
    # stream's notifies; the long-pollers parked on follower registries.
    assert across(["watch", "state", "notifies"]) > 0
    # The client-side fleet view, cross-checkable against the server
    # books: every population actually hit the wire.
    fleet = reads["fleet"]
    assert fleet["pollers"]["readers"] == 2
    assert fleet["watchers"]["readers"] == 2
    assert fleet["sse_tails"]["readers"] == 1
    assert fleet["pollers"]["requests"] > 0
    assert fleet["watchers"]["wakes"] + fleet["watchers"]["timeouts"] > 0
    assert fleet["sse_tails"]["frames"] > 0

    # The lanes verdict block (slo.evaluate_read_lanes consumes this):
    # followers served the fleet, stale ages honored the bound, every
    # response carried its freshness stamps, and no linearizable read
    # returned anything older than its confirmed read index.
    lanes = reads["lanes"]
    assert lanes["enabled"] is True
    assert lanes["members"] == 3
    assert lanes["follower_serve_share"] >= 0.80
    assert lanes["stale_age_ms"]["n"] > 0
    assert lanes["stale_age_ms"]["p95"] <= lanes["stale_bound_ms"]
    assert lanes["linear_reads"] > 0
    assert lanes["linear_violations"] == 0
    assert lanes["stamp_missing"] == 0
    import nomad_tpu.slo as slo_mod
    rows = slo_mod.evaluate_read_lanes(art)
    assert rows and all(r["met"] is not False for r in rows)

    # The contrast arm ran the SAME fleet leader-only with lanes and
    # observatory off: books empty, digest identical (reads never touch
    # decisions, however they are routed).
    contrast = art["contrast"]
    assert contrast["reads"]["enabled"] is False
    assert contrast["reads"]["lanes"]["enabled"] is False
    assert contrast["reads"]["fleet"]["pollers"]["requests"] > 0
    assert contrast["digest_matches"] is True
    assert slo_mod.evaluate_read_lanes(
        {"reads": contrast["reads"]}) == []


def test_read_storm_smoke_is_seed_deterministic():
    """The read fleet is wall-clock-paced and WHICH requests land
    between placements is scheduling noise — but reader traffic rides
    GETs and observer-topic events only, so the canonical digest (and
    the per-key lifecycle multiset) must replay under the same seed
    with the fleet running."""
    a = run_scenario("read-storm-800", seed=11, contrast=False)
    b = run_scenario("read-storm-800", seed=11, contrast=False)
    assert a["events"]["digest"] == b["events"]["digest"]
    assert a["events"]["by_type"] == b["events"]["by_type"]


@pytest.mark.slow
def test_read_storm_scenario():
    """The full 10k-node follower-read-plane proof (the committed
    SIMLOAD_read-storm_* artifacts use tools/simload.py; this keeps it
    executable in-suite): the steady-10k write load on a 3-member cell
    under a 15-reader fleet riding the follower fronts, with the
    leader's plan latency banked as the headline read-relief number."""
    art = run_scenario("read-storm", seed=42)
    assert art["placements"]["placed"] == 24 * 420
    assert art["plan_latency_ms"]["n"] == 24
    reads = art["reads"]
    assert reads["enabled"] is True
    member_books = list(reads["by_member"].values())
    assert len(member_books) == 2
    assert any(b.get("blocking") for b in member_books)
    assert sum(b["sse"]["frames"] for b in member_books) > 0
    assert sum(b["freshness"]["responses_stamped"]
               for b in member_books) > 0
    lanes = reads["lanes"]
    assert lanes["enabled"] is True
    assert lanes["follower_serve_share"] >= 0.80
    assert lanes["stale_age_ms"]["n"] > 0
    assert lanes["stale_age_ms"]["p95"] <= lanes["stale_bound_ms"]
    assert lanes["linear_violations"] == 0
    assert lanes["stamp_missing"] == 0
    fleet = reads["fleet"]
    assert (fleet["pollers"]["readers"] + fleet["watchers"]["readers"]
            + fleet["sse_tails"]["readers"]) == 15
    assert art["contrast"]["reads"]["enabled"] is False
    assert art["contrast"]["digest_matches"] is True


def test_express_smoke_is_seed_deterministic():
    """Express placements ride seeded streams (express.pick /
    express.lease_jitter) and publish ONE deterministic event per
    submission: the canonical digest replays under the same seed even
    with the async committer racing the service background."""
    a = run_scenario("express-1k", seed=11)
    b = run_scenario("express-1k", seed=11)
    assert a["events"]["digest"] == b["events"]["digest"]
    assert a["events"]["by_type"] == b["events"]["by_type"]


def test_overdrive_smoke_is_seed_deterministic():
    """Per-client sequential blasting + per-client token buckets: the
    canonical event digest (admission rejections included, keyed by
    client) replays under the same seed."""
    a = run_scenario("overdrive-1k", seed=11)
    b = run_scenario("overdrive-1k", seed=11)
    assert a["events"]["digest"] == b["events"]["digest"]
    assert a["events"]["by_type"] == b["events"]["by_type"]


def test_overdrive_injector_determinism():
    a = [(x.at, x.kind, x.payload["job_key"], x.payload["client_id"])
         for x in OverdriveInjector(3, clients=4, jobs_per_client=5,
                                    tasks_per_job=10).actions()]
    b = [(x.at, x.kind, x.payload["job_key"], x.payload["client_id"])
         for x in OverdriveInjector(3, clients=4, jobs_per_client=5,
                                    tasks_per_job=10).actions()]
    assert a == b and len(a) == 20
    assert all(x[1] == "register_job" for x in a)


def test_same_seed_reproduces_canonical_event_sequence():
    """The simload replay contract at smoke scale: same seed → same
    canonical event digest (sorted multiset of per-key event-type
    sequences), the reduction the SIMLOAD artifacts bank."""
    spec = ScenarioSpec(
        name="steady-mini", n_nodes=300,
        injectors=lambda seed: [SteadyServiceInjector(
            seed, jobs=3, tasks_per_job=260, over=1.0,
        )],
        quiesce_timeout=60.0, ack_cap=40,
    )
    a = ScenarioRunner(spec, seed=33).run()
    b = ScenarioRunner(spec, seed=33).run()
    assert a["events"]["digest"] == b["events"]["digest"]
    assert a["events"]["by_type"] == b["events"]["by_type"]
    assert a["placements"]["placed"] == b["placements"]["placed"] == 3 * 260


def test_canonical_events_reduction():
    class E:
        def __init__(self, topic, etype, key):
            self.topic, self.type, self.key = topic, etype, key

    seq1 = [E("Eval", "EvalUpdated", "e1"), E("Eval", "EvalUpdated", "e2"),
            E("Plan", "PlanApplied", "e1"), E("Plan", "PlanApplied", "e2")]
    # Same per-key lifecycles, different global interleaving, different
    # uuids: canonically EQUAL.
    seq2 = [E("Eval", "EvalUpdated", "x9"), E("Plan", "PlanApplied", "x9"),
            E("Eval", "EvalUpdated", "x7"), E("Plan", "PlanApplied", "x7")]
    assert canonical_events(seq1)["digest"] == canonical_events(seq2)["digest"]
    # A changed per-key ORDER is a different canonical history.
    seq3 = [E("Plan", "PlanApplied", "e1"), E("Eval", "EvalUpdated", "e1"),
            E("Eval", "EvalUpdated", "e2"), E("Plan", "PlanApplied", "e2")]
    assert canonical_events(seq1)["digest"] != canonical_events(seq3)["digest"]


# ---------------------------------------------------------------------------
# Slow scale proofs (excluded from tier-1 by the `slow` marker)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_heartbeat_churn_10k():
    """VERDICT r5 item 7: the 10k-node control-plane failure-detection
    proof. (1) rate_scaled_interval keeps leader-side timer resets
    bounded: at the production cap (50/s) the granted TTLs schedule
    <= 50 renewals/s — asserted from the grants because the 200s+ TTLs
    cannot be waited out; at this test's compressed cap (2000/s) the
    MEASURED renewal rate over a real beat window also respects the cap.
    (2) A silenced tranche expires through the real TTL wheel and its
    node-down evals coalesce into bounded device dispatches
    (ref nomad/heartbeat.go:52-54)."""
    from nomad_tpu.ops.coalesce import GLOBAL_SOLVER
    from nomad_tpu.simcluster.workload import build_job
    from nomad_tpu.api.codec import to_dict

    # The production-posture half is pure arithmetic on the grant law:
    # 10k nodes at the 50/s cap get 200s base TTLs (+ up to 100% jitter),
    # and a fleet beating at 0.8*ttl schedules sum(1/(0.8*ttl_i)) <= 50/s.
    assert rate_scaled_interval(50.0, 10.0, 10_000) == 200.0
    import random as _random

    rng = _random.Random(42)
    ttls = [200.0 + rng.uniform(0, 200.0) for _ in range(10_000)]
    scheduled = sum(1.0 / (0.8 * t) for t in ttls)
    log.warning("production posture: 10k nodes schedule %.1f renewals/s "
                "(cap 50/s)", scheduled)
    assert scheduled <= 50.0

    srv = ClusterServer(
        ServerConfig(scheduler_backend="tpu", num_schedulers=2,
                     eval_batch_size=4,
                     min_heartbeat_ttl=4.0,
                     max_heartbeats_per_second=2000.0,
                     prewarm_shapes=False),
        ClusterConfig(bootstrap_expect=1),
    )
    fleet = SimFleet(srv.rpc_addr, tick=0.25)
    try:
        srv.start()
        wait_for_leader([srv])
        nodes = [sim_node(i, "dc1" if i % 2 == 0 else "dc2")
                 for i in range(10_000)]
        reg = fleet.register(nodes)
        log.warning("registered 10k nodes in %.2fs (%.0f nodes/s)",
                    reg["seconds"], reg["nodes_per_sec"])
        assert srv.heartbeat.num_timers() == 10_000

        # Measured half: TTLs here are 5-10s (count/rate = 5s base), so a
        # real beat window fits in-test. The first grant cycle is a
        # transient (rolling bring-up granted early tranches short TTLs
        # at small count — the reference's grant law does the same), so
        # let every node renew once at full count, THEN measure: the
        # leader-side renewal rate must sit at the equilibrium, under the
        # configured cap.
        fleet.start_heartbeats()
        time.sleep(12.0)  # one full grant cycle (max granted ttl ~10s)
        hb0 = srv.heartbeat.stats()
        t0 = time.monotonic()
        time.sleep(10.0)
        window = time.monotonic() - t0
        renewals = srv.heartbeat.stats()["renewals"] - hb0["renewals"]
        measured = renewals / window
        scheduled_now = fleet.scheduled_renewals_per_sec()
        log.warning(
            "compressed posture: measured %.1f renewals/s over %.1fs "
            "(scheduled %.1f, cap %.0f, timers %d)",
            measured, window, scheduled_now, 2000.0,
            srv.heartbeat.num_timers(),
        )
        assert measured <= 2000.0
        assert measured > 0, "no renewals landed — the fleet isn't beating"
        assert srv.heartbeat.num_timers() == 10_000  # none expired

        # Place a job so the tranche's expiry has allocs to migrate.
        job = build_job("churn-svc", structs.JOB_TYPE_SERVICE, 300)
        out = fleet._pool().call(
            srv.rpc_addr, "Job.Register", {"job": to_dict(job)},
            timeout=30.0,
        )
        srv.wait_for_eval(out["eval_id"], timeout=180.0)
        snap = srv.state_store.snapshot()
        hosting = sorted({
            a.node_id for a in snap.allocs_by_job(job.id)
            if a.desired_status == structs.ALLOC_DESIRED_STATUS_RUN
        })
        assert hosting, "job placed nowhere"
        tranche = hosting[:100]

        # Count every device-solve invocation (exact AND columnar paths)
        # during the churn window: GLOBAL_SOLVER.dispatches only counts
        # coalesced water-fill dispatches, and small migration re-solves
        # ride the exact path.
        from nomad_tpu.tpu.solver import TPUStack

        solve_calls = {"n": 0}
        orig_sg, orig_sgc = TPUStack.solve_group, TPUStack.solve_group_counts

        def _count(orig):
            def wrapped(self, *a, **k):
                solve_calls["n"] += 1
                return orig(self, *a, **k)
            return wrapped

        TPUStack.solve_group = _count(orig_sg)
        TPUStack.solve_group_counts = _count(orig_sgc)

        dispatches0 = GLOBAL_SOLVER.dispatches
        expirations0 = srv.heartbeat.stats()["expirations"]
        fleet.fail(tranche)
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline:
            snap = srv.state_store.snapshot()
            down = [nid for nid in tranche
                    if snap.node_by_id(nid).status
                    == structs.NODE_STATUS_DOWN]
            if len(down) == len(tranche):
                break
            time.sleep(0.2)
        assert len(down) == len(tranche), (
            f"only {len(down)}/{len(tranche)} expired"
        )
        # Let the node-down evals settle.
        deadline = time.monotonic() + 120.0
        while time.monotonic() < deadline:
            stats = srv.eval_broker.snapshot_stats()
            if (stats.total_ready + stats.total_unacked
                    + stats.total_blocked) == 0:
                pend = [e for e in srv.state_store.evals()
                        if not e.terminal_status()]
                if not pend:
                    break
            time.sleep(0.2)
        TPUStack.solve_group, TPUStack.solve_group_counts = orig_sg, orig_sgc
        dispatches = GLOBAL_SOLVER.dispatches - dispatches0
        expired = srv.heartbeat.stats()["expirations"] - expirations0
        log.warning(
            "expired %d nodes -> %d solve invocations, %d coalesced "
            "water-fill dispatches",
            expired, solve_calls["n"], dispatches,
        )
        assert expired >= len(tranche)
        # Bounded device work: the broker's per-job blocked queue merges
        # node-down evals — while one eval is mid-flight, every further
        # expiry coalesces into the NEXT eval, which re-places all
        # missing allocs in one solve. The solve count is therefore
        # bounded by the expiry spread over the eval-processing rate, and
        # must never amplify past one solve per expired node.
        assert solve_calls["n"] <= len(tranche), (
            f"{solve_calls['n']} solves for {len(tranche)} node expiries"
        )
        assert dispatches <= 24, (
            f"{dispatches} coalesced dispatches for {len(tranche)} expiries"
        )
        # Migrated allocs were re-placed on live nodes.
        snap = srv.state_store.snapshot()
        live = [a for a in snap.allocs_by_job(job.id)
                if a.desired_status == structs.ALLOC_DESIRED_STATUS_RUN]
        assert len(live) == 300, f"{len(live)} live allocs after churn"
        down_set = set(tranche)
        assert all(a.node_id not in down_set for a in live)
    finally:
        fleet.stop()
        srv.shutdown()


@pytest.mark.slow
def test_churn_scenario_runs():
    """The mixed churn scenario end to end: update churn + a 40-node
    failure tranche expiring through real TTLs, with migrations."""
    art = run_scenario("churn", seed=5)
    assert art["heartbeat"]["expirations"] >= 40
    assert art["events"]["by_type"].get("NodeHeartbeatExpired", 0) >= 40
    assert art["placements"]["placed"] > 0
    assert art["events"]["truncated"] is False


@pytest.mark.slow
def test_steady_10k_scenario():
    """The seeded 10k-node artifact scenario (the committed SIMLOAD_*
    runs use tools/simload.py; this keeps it executable in-suite)."""
    art = run_scenario("steady-10k", seed=42)
    assert art["placements"]["placed"] == 24 * 420
    assert art["heartbeat"]["timers"] == 10_000
    assert (art["heartbeat"]["equilibrium_renewals_per_sec"]
            <= art["heartbeat"]["rate_cap_per_sec"])
    assert art["plan_latency_ms"]["n"] == 24
    assert art["events"]["truncated"] is False
    # Same-seed replay pins the BANKED canonical digest: moving the
    # decision-path draws (node shuffle, broker scheduler choice,
    # heartbeat jitter) off the global random module onto seeded
    # per-context streams (nomadlint DET001) must leave the canonical
    # event history byte-identical to the committed r07 artifact.
    import json
    import os

    banked_path = os.path.join(os.path.dirname(__file__), "..",
                               "SIMLOAD_steady-10k_s42_r07.json")
    with open(banked_path) as f:
        banked = json.load(f)
    assert art["events"]["digest"] == banked["events"]["digest"]
