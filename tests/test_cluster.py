"""Multi-server cluster tests: raft election/replication, RPC forwarding,
leader failover (reference: nomad/server_test.go multi-server joins,
nomad/leader_test.go failover re-enabling broker/plan queue)."""

import time

import pytest

from nomad_tpu import mock, structs
from nomad_tpu.raft import NotLeaderError
from nomad_tpu.rpc import ConnPool, RPCError, RPCServer, RemoteError
from nomad_tpu.server import ServerConfig
from nomad_tpu.server.cluster import ClusterServer, form_cluster, wait_for_leader

from cluster_util import relaxed_cluster_cfg, retry_write


# ---------------------------------------------------------------------------
# RPC layer
# ---------------------------------------------------------------------------


def test_rpc_roundtrip():
    srv = RPCServer()
    srv.register("Echo.Hello", lambda args: {"hi": args["name"]})

    def boom(args):
        raise ValueError("kaboom")

    srv.register("Echo.Boom", boom)
    srv.start()
    try:
        pool = ConnPool()
        out = pool.call(srv.addr, "Echo.Hello", {"name": "world"})
        assert out == {"hi": "world"}
        with pytest.raises(RemoteError, match="kaboom"):
            pool.call(srv.addr, "Echo.Boom", {})
        with pytest.raises(RemoteError, match="unknown method"):
            pool.call(srv.addr, "No.Such", {})
        # Connection reuse: 50 sequential calls on one pooled conn
        for i in range(50):
            assert pool.call(srv.addr, "Echo.Hello", {"name": str(i)})["hi"] == str(i)
        pool.shutdown()
    finally:
        srv.shutdown()


def test_rpc_connection_refused():
    pool = ConnPool(timeout=0.5)
    with pytest.raises(RPCError):
        pool.call("127.0.0.1:1", "X.Y", {})


# ---------------------------------------------------------------------------
# Cluster: election + replication + forwarding
# ---------------------------------------------------------------------------


@pytest.fixture
def cluster3():
    # Start from a quiesced heap: earlier suite tests leave megabytes of
    # garbage whose collection mid-election is one of the stall sources
    # behind the round-4 test_leader_failover flake.
    import gc
    gc.collect()
    servers = form_cluster(3, ServerConfig(
        scheduler_backend="host", num_schedulers=1,
        min_heartbeat_ttl=30.0,
    ), base_cluster=relaxed_cluster_cfg())
    yield servers
    for srv in servers:
        srv.shutdown()


def test_single_server_cluster_elects_itself():
    (srv,) = form_cluster(1, ServerConfig(scheduler_backend="host"))
    try:
        leader = wait_for_leader([srv])
        assert leader is srv
        # End-to-end on the raft path
        srv.node_register(mock.node())
        job = mock.job()
        job.task_groups[0].count = 2
        eval_id, _ = srv.job_register(job)
        ev = srv.wait_for_eval(eval_id, timeout=15.0)
        assert ev.status == structs.EVAL_STATUS_COMPLETE
        assert len(srv.state_store.allocs_by_job(job.id)) == 2
    finally:
        srv.shutdown()


def test_three_server_election_and_replication(cluster3):
    # Early cluster life can re-elect; converge on a stable leader view:
    # one leader, both followers agreeing on its address.
    deadline = time.monotonic() + 20.0
    leader = None
    followers = []
    while time.monotonic() < deadline:
        leaders = [s for s in cluster3 if s.raft.is_leader]
        if len(leaders) == 1:
            leader = leaders[0]
            followers = [s for s in cluster3 if s is not leader]
            if all(
                not f.raft.is_leader
                and f.raft.leader_addr == leader.rpc_addr
                for f in followers
            ):
                break
        time.sleep(0.05)
    else:
        raise AssertionError("cluster never converged on one leader")
    assert len(followers) == 2

    # Write through the leader; replicated state visible on all servers.
    # Writes retry across leader churn (the client posture, wait.go:13-29).
    node = mock.node()
    retry_write(lambda: leader.node_register(node))
    job = mock.job()
    job.task_groups[0].count = 3
    eval_id, _ = retry_write(lambda: leader.job_register(job))
    ev = leader.wait_for_eval(eval_id, timeout=15.0)
    assert ev.status == structs.EVAL_STATUS_COMPLETE

    deadline = time.monotonic() + 5
    while time.monotonic() < deadline:
        if all(
            len(f.state_store.allocs_by_job(job.id)) == 3 for f in followers
        ):
            break
        time.sleep(0.05)
    for f in followers:
        assert f.state_store.job_by_id(job.id) is not None
        assert len(f.state_store.allocs_by_job(job.id)) == 3
        assert f.state_store.node_by_id(node.id) is not None


def test_follower_forwards_writes(cluster3):
    leader = wait_for_leader(cluster3)
    follower = next(s for s in cluster3 if s is not leader)

    node = mock.node()
    reply = retry_write(lambda: follower.node_register(node))
    assert reply["index"] > 0

    job = mock.job()
    job.task_groups[0].count = 2
    eval_id, _ = retry_write(lambda: follower.job_register(job))

    # The eval completes cluster-wide; read from the follower's replica
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline:
        ev = follower.state_store.eval_by_id(eval_id)
        if ev is not None and ev.terminal_status():
            break
        time.sleep(0.05)
    assert ev is not None and ev.status == structs.EVAL_STATUS_COMPLETE
    assert len(follower.state_store.allocs_by_job(job.id)) == 2

    # Deregister via the follower too
    eval_id2, _ = retry_write(lambda: follower.job_deregister(job.id))
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline:
        ev2 = follower.state_store.eval_by_id(eval_id2)
        if ev2 is not None and ev2.terminal_status():
            break
        time.sleep(0.05)
    live = structs.filter_terminal_allocs(
        follower.state_store.allocs_by_job(job.id)
    )
    assert live == []


def test_leader_failover(cluster3):
    """Kill the leader: a new one is elected, broker restored, and pending
    work continues (leader_test.go failover)."""
    leader = wait_for_leader(cluster3)

    # Seed state. Writes RETRY across leader churn: early-cluster
    # re-elections under suite load can depose the first leader between
    # wait_for_leader and the write — the bare node_register here was the
    # residual 1-in-3 full-suite flake (round-5 verdict weak #1;
    # NotLeaderError out of raft.apply). Followers forward writes, so
    # retrying against the same server converges once any leader exists.
    node = mock.node()
    retry_write(lambda: leader.node_register(node))
    job = mock.job()
    job.task_groups[0].count = 1
    eval_id, _ = retry_write(lambda: leader.job_register(job))
    leader.wait_for_eval(eval_id, timeout=15.0)

    # Kill the CURRENT leader (leadership may have moved since the first
    # wait: killing a deposed ex-leader would measure nothing).
    leader = wait_for_leader(cluster3)
    survivors = [s for s in cluster3 if s is not leader]
    leader.shutdown()

    # Post-kill elections on a suite-loaded box have been observed to need
    # well past 10s (round-4 flake); the wait is generous because an
    # eventually-elected leader is the pass condition, not election speed.
    new_leader = wait_for_leader(survivors, timeout=30.0)
    assert new_leader is not leader
    # Replicated state survived
    assert new_leader.state_store.job_by_id(job.id) is not None
    assert len(new_leader.state_store.allocs_by_job(job.id)) == 1

    # The new leader schedules new work
    job2 = mock.job()
    job2.task_groups[0].count = 1
    eval_id2, _ = new_leader.job_register(job2)
    ev2 = new_leader.wait_for_eval(eval_id2, timeout=15.0)
    assert ev2.status == structs.EVAL_STATUS_COMPLETE


def test_no_leader_rejects_writes():
    (srv,) = form_cluster(1, ServerConfig(scheduler_backend="host"))
    try:
        wait_for_leader([srv])
        # Force follower state with a higher observed term and no leader
        with srv.raft._lock:
            srv.raft._become_follower(srv.raft.current_term + 1, None)
            srv.raft.leader_id = None
            # Park the election so no self-election fires mid-assert
            srv.raft._election_deadline = time.monotonic() + 60
        with pytest.raises(NotLeaderError):
            srv.job_register(mock.job())
    finally:
        srv.shutdown()


def test_raft_log_persistence(tmp_path):
    """A restarted single-server cluster replays its log into the FSM."""
    from nomad_tpu.server.cluster import ClusterConfig

    cfg = ServerConfig(scheduler_backend="host", num_schedulers=1)
    cluster_cfg = ClusterConfig(raft_data_dir=str(tmp_path / "raft"))
    (srv,) = form_cluster(1, cfg, cluster_cfg)
    node = mock.node()
    job = mock.job()
    try:
        wait_for_leader([srv])
        srv.node_register(node)
        job.task_groups[0].count = 1
        eval_id, _ = srv.job_register(job)
        srv.wait_for_eval(eval_id, timeout=15.0)
        applied = srv.raft.applied_index
    finally:
        srv.shutdown()

    # Restart with the same data dir (new ports are fine: single node)
    cluster_cfg2 = ClusterConfig(raft_data_dir=str(tmp_path / "raft"))
    (srv2,) = form_cluster(1, cfg, cluster_cfg2)
    try:
        wait_for_leader([srv2])
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            if srv2.raft.applied_index >= applied:
                break
            time.sleep(0.05)
        assert srv2.state_store.node_by_id(node.id) is not None
        assert srv2.state_store.job_by_id(job.id) is not None
        assert len(srv2.state_store.allocs_by_job(job.id)) == 1
    finally:
        srv2.shutdown()


# ---------------------------------------------------------------------------
# Chaos: leader death under coalesced load
# ---------------------------------------------------------------------------


def test_leader_death_mid_coalesced_burst():
    """Kill the leader while a burst of coalesced evals is mid-flight —
    solves running, plans queued, broker evals outstanding. The highest-
    risk interleaving of the batched-solve design: in-flight work dies
    with the leader's broker/plan queue, but every eval is raft-committed
    at registration, so the new leader's restored broker must finish all
    of them exactly once — full placement per job, no node overcommitted,
    and the survivor pipeline healthy for new work.

    Seeded via NOMAD_TPU_CHAOS_SEED (kill-delay replayable). Reference
    posture: nomad/leader_test.go (failover re-enables broker/plan queue)
    + nomad/plan_apply.go:39-117 (plan apply is the serialization point).
    """
    import os

    import numpy as np

    seed = int(os.environ.get("NOMAD_TPU_CHAOS_SEED", "0"))
    rng = np.random.default_rng(seed)

    servers = form_cluster(3, ServerConfig(
        scheduler_backend="tpu", num_schedulers=2, eval_batch_size=4,
        # Mock nodes never heartbeat; the TTL must outlive the whole
        # recovery window or expiry marks every node down mid-assert and
        # the test measures TTL behavior instead of failover semantics.
        min_heartbeat_ttl=300.0,
    ), base_cluster=relaxed_cluster_cfg())
    try:
        leader = wait_for_leader(servers)
        nodes = [mock.node() for _ in range(20)]
        for node in nodes:
            retry_write(lambda n=node: leader.node_register(n))

        # Burst: 8 service jobs x 10 allocs, registered back-to-back so
        # the broker coalesces them across both schedulers.
        jobs = []
        eval_ids = []
        for _ in range(8):
            job = mock.job()
            ev_id, _ = retry_write(lambda j=job: leader.job_register(j))
            jobs.append(job)
            eval_ids.append(ev_id)

        # Kill the leader at a seeded point inside the burst's flight
        # window — solves dispatched, plans queued, evals unacked.
        time.sleep(float(rng.uniform(0.05, 0.6)))
        leader.shutdown()

        survivors = [s for s in servers if s is not leader]
        # Generous: under GIL contention (2 servers' workers + solves in
        # one process) election churn can stretch well past the relaxed
        # 0.4-0.8s timeouts.
        new_leader = wait_for_leader(survivors, timeout=30.0)

        # Every eval reaches a terminal status on the new leader. An eval
        # that died unacked with the old broker is re-enqueued from
        # replicated state (restore_eval_broker); blocked children count
        # as progress, so wait on JOB completion below, not eval count.
        deadline = time.monotonic() + 60.0
        def _all_terminal():
            for ev_id in eval_ids:
                ev = new_leader.state_store.eval_by_id(ev_id)
                if ev is None or not ev.terminal_status():
                    return False
            return True
        while time.monotonic() < deadline and not _all_terminal():
            time.sleep(0.1)
        assert _all_terminal(), [
            (i, getattr(new_leader.state_store.eval_by_id(i), "status", None))
            for i in eval_ids
        ]

        # Exactly-once placement: every job fully placed, never over-placed
        # (a replayed plan would show up as > count live allocs).
        deadline = time.monotonic() + 60.0
        def _fully_placed():
            for job in jobs:
                live = structs.filter_terminal_allocs(
                    new_leader.state_store.allocs_by_job(job.id))
                if len(live) != job.task_groups[0].count:
                    return False
            return True
        while time.monotonic() < deadline and not _fully_placed():
            time.sleep(0.1)
        state = [
            {
                "job": job.id,
                "live": len(structs.filter_terminal_allocs(
                    new_leader.state_store.allocs_by_job(job.id))),
                "want": job.task_groups[0].count,
                "evals": [
                    (e.id[:8], e.status, e.triggered_by,
                     e.status_description)
                    for e in new_leader.state_store.evals_by_job(job.id)
                ],
                "allocs": [
                    (a.id[:8], a.eval_id[:8], a.node_id[:8],
                     a.desired_status, a.client_status, a.create_index)
                    for a in new_leader.state_store.allocs_by_job(job.id)
                ],
            }
            for job in jobs
        ]
        bad = [r for r in state if r["live"] != r["want"]]
        if bad:
            import json as _json
            with open("/tmp/chaos_dump.json", "w") as f:
                _json.dump(state, f, indent=1)
            raise AssertionError(
                f"exactly-once violated (full dump /tmp/chaos_dump.json): "
                f"{_json.dumps(bad)[:3000]}"
            )

        # No node overcommitted: sum of live asks fits its resources.
        node_by_id = {n.id: n for n in nodes}
        used = {}
        for job in jobs:
            for a in structs.filter_terminal_allocs(
                    new_leader.state_store.allocs_by_job(job.id)):
                cpu, mem = used.get(a.node_id, (0, 0))
                res = a.resources
                used[a.node_id] = (cpu + res.cpu, mem + res.memory_mb)
        for nid, (cpu, mem) in used.items():
            node = node_by_id[nid]
            res = node.resources
            reserved = node.reserved
            cap_cpu = res.cpu - (reserved.cpu if reserved else 0)
            cap_mem = res.memory_mb - (reserved.memory_mb if reserved else 0)
            assert cpu <= cap_cpu, (nid, cpu, cap_cpu)
            assert mem <= cap_mem, (nid, mem, cap_mem)

        # Survivor pipeline serves NEW work end-to-end.
        job2 = mock.job()
        job2.task_groups[0].count = 2
        ev2_id, _ = retry_write(lambda: new_leader.job_register(job2))
        ev2 = new_leader.wait_for_eval(ev2_id, timeout=30.0)
        assert ev2.status == structs.EVAL_STATUS_COMPLETE
    finally:
        for srv in servers:
            srv.shutdown()
        # Interpreter teardown while a daemon thread (coalescer dispatch,
        # a dead server's shape prewarm) sits inside an XLA call aborts
        # the process (std::terminate) — drain before returning.
        from nomad_tpu.ops.coalesce import quiesce_all

        quiesce_all(timeout=15.0)
