"""Admission control & backpressure (nomad_tpu/server/admission.py):
typed rejection round trips, token-bucket rate lanes, SLO-coupled
shedding, bounded broker/plan queues with readmission, and the end-to-end
HTTP/SDK retry contract."""

import threading
import time

import pytest

from nomad_tpu import mock, structs
from nomad_tpu.events import EventBroker
from nomad_tpu.server.admission import (
    LANE_BATCH,
    LANE_SERVICE,
    AdmissionConfig,
    AdmissionController,
    lane_for,
)
from nomad_tpu.server.eval_broker import (
    BrokerFullError,
    EvalBroker,
)
from nomad_tpu.server.plan_queue import (
    ERR_QUEUE_FULL,
    PlanQueue,
    PlanQueueError,
)
from nomad_tpu.structs import (
    REJECT_QUEUE_FULL,
    REJECT_RATE_LIMITED,
    REJECT_SHED,
    Plan,
    RejectError,
    parse_reject,
)


def _job():
    """A registerable job on the in-process mock driver: the sandbox has
    no exec spawn, and a real-driver task would sit in its restart-backoff
    loop until agent shutdown (a ~200s teardown stall, not a test
    signal)."""
    job = mock.job()
    job.task_groups[0].tasks[0].driver = "mock_driver"
    return job


# -- typed rejection wire contract ------------------------------------------


def test_reject_error_roundtrip():
    e = RejectError(REJECT_RATE_LIMITED, "client c1 batch lane rate limited",
                    retry_after=12.5)
    back = parse_reject(str(e))
    assert back is not None
    assert back.reason == REJECT_RATE_LIMITED
    assert back.retry_after == 12.5
    # Survives the RPC error envelope ("RejectError: <str>") and nested
    # forwarding prefixes.
    wrapped = f"RemoteError: RejectError: {e}"
    back2 = parse_reject(wrapped)
    assert back2.reason == REJECT_RATE_LIMITED
    assert back2.retry_after == 12.5
    assert parse_reject("some ordinary error") is None


def test_lane_mapping():
    assert lane_for(structs.JOB_TYPE_BATCH) == LANE_BATCH
    assert lane_for(structs.JOB_TYPE_SERVICE) == LANE_SERVICE
    assert lane_for(structs.JOB_TYPE_SYSTEM) == LANE_SERVICE


# -- controller: rate lanes --------------------------------------------------


def test_permissive_default_admits_everything():
    events = EventBroker(register=False)
    ctl = AdmissionController(AdmissionConfig(), events=events)
    for _ in range(100):
        ctl.admit("c1", LANE_BATCH)
    assert ctl.admitted == 100
    assert ctl.rejected == 0
    # No events, no lane table: the permissive fast path touches nothing.
    assert events.get_index() == 0
    assert ctl.snapshot()["rate_lanes"] == {}


def test_rate_lane_burst_then_typed_rejection():
    ctl = AdmissionController(
        AdmissionConfig(client_rate=0.01, client_burst=3))
    for _ in range(3):
        ctl.admit("c1", LANE_BATCH)
    with pytest.raises(RejectError) as exc:
        ctl.admit("c1", LANE_BATCH)
    assert exc.value.reason == REJECT_RATE_LIMITED
    # Hint = time until a whole token accrues at 0.01/s: ~100s.
    assert 50.0 < exc.value.retry_after <= 100.0
    assert ctl.by_reason[REJECT_RATE_LIMITED] == 1
    assert ctl.by_lane[LANE_BATCH] == {"admit": 3, "reject": 1}


def test_rate_lane_refills():
    ctl = AdmissionController(
        AdmissionConfig(client_rate=50.0, client_burst=1))
    ctl.admit("c1", LANE_SERVICE)
    with pytest.raises(RejectError):
        ctl.admit("c1", LANE_SERVICE)
    time.sleep(0.05)  # > 1/50s: one token accrued
    ctl.admit("c1", LANE_SERVICE)


def test_per_client_lanes_are_independent():
    ctl = AdmissionController(
        AdmissionConfig(client_rate=0.01, client_burst=1))
    ctl.admit("c1", LANE_BATCH)
    with pytest.raises(RejectError):
        ctl.admit("c1", LANE_BATCH)
    # A different client — and the SAME client's other lane — still flow.
    ctl.admit("c2", LANE_BATCH)
    ctl.admit("c1", LANE_SERVICE)


def test_client_table_bounded_with_eviction():
    ctl = AdmissionController(
        AdmissionConfig(client_rate=0.01, client_burst=1, max_clients=2))
    for c in ("a", "b", "c", "d"):
        ctl.admit(c, LANE_BATCH)
    assert len(ctl.snapshot()["rate_lanes"]) <= 2
    assert ctl.evicted_clients >= 2


# -- controller: queue cap + shed -------------------------------------------


def test_queue_full_rejection():
    depth = {"n": 0}
    ctl = AdmissionController(
        AdmissionConfig(), queue_depth=lambda: depth["n"], queue_cap=10)
    ctl.admit("c1", LANE_SERVICE)
    depth["n"] = 10
    with pytest.raises(RejectError) as exc:
        ctl.admit("c1", LANE_SERVICE)
    assert exc.value.reason == REJECT_QUEUE_FULL
    assert exc.value.retry_after > 0


def test_shed_batch_first_service_keeps_flowing():
    burn = {"rate": 0.0}
    ctl = AdmissionController(
        AdmissionConfig(shed_start_burn=1.0, shed_full_burn=2.0),
        burn_rate=lambda: burn["rate"],
    )
    # Budget healthy: both lanes flow.
    ctl.admit("c1", LANE_BATCH)
    ctl.admit("c1", LANE_SERVICE)
    # Budget burning past the full mark: batch fully sheds (frac=1.0 —
    # every draw < 1), service keeps flowing regardless.
    burn["rate"] = 5.0
    for _ in range(5):
        with pytest.raises(RejectError) as exc:
            ctl.admit("c1", LANE_BATCH)
        assert exc.value.reason == REJECT_SHED
        ctl.admit("c1", LANE_SERVICE)
    assert ctl.by_lane[LANE_SERVICE]["reject"] == 0


def test_shed_draws_are_seed_deterministic():
    """Mid-ramp shedding draws from a name-salted seeded stream: two
    controllers with the same seed shed the identical subsequence of an
    identical decision sequence (replay-determinism)."""

    def decisions(seed):
        ctl = AdmissionController(
            AdmissionConfig(shed_start_burn=1.0, shed_full_burn=3.0),
            seed=seed, burn_rate=lambda: 2.0,  # frac = 0.5
        )
        out = []
        for _ in range(40):
            try:
                ctl.admit("c1", LANE_BATCH)
                out.append("admit")
            except RejectError:
                out.append("shed")
        return out

    a, b = decisions(7), decisions(7)
    assert a == b
    assert "shed" in a and "admit" in a  # mid-ramp: genuinely mixed
    assert decisions(8) != a  # a different seed decorrelates


def test_rejections_publish_admission_events():
    events = EventBroker(register=False)
    ctl = AdmissionController(
        AdmissionConfig(client_rate=0.01, client_burst=1), events=events)
    ctl.admit("c1", LANE_BATCH)
    with pytest.raises(RejectError):
        ctl.admit("c1", LANE_BATCH)
    _, evs, _ = events.events_after(0)
    assert len(evs) == 1
    e = evs[0]
    assert (e.topic, e.type, e.key) == ("Admission", "AdmissionRejected", "c1")
    assert e.payload["reason"] == REJECT_RATE_LIMITED
    assert e.payload["lane"] == LANE_BATCH
    assert e.payload["retry_after"] > 0


def test_admission_config_parse_validation():
    with pytest.raises(ValueError, match="unknown admission"):
        AdmissionConfig.parse({"clientrate": 5})
    with pytest.raises(ValueError, match="client_rate"):
        AdmissionConfig.parse({"client_rate": -1})
    with pytest.raises(ValueError, match="shed_full_burn"):
        AdmissionConfig.parse({"shed_start_burn": 2.0,
                               "shed_full_burn": 1.0})
    cfg = AdmissionConfig.parse({"client_rate": 5, "client_burst": 0})
    assert cfg.burst == 5.0  # unset burst defaults to one second of rate


def test_agent_config_admission_block_validated_at_parse():
    from nomad_tpu.agent_config import parse_config

    cfg = parse_config("""
server {
  eval_pending_cap = 4096
  plan_queue_cap = 512
  max_blocking_watchers = 50000
  admission {
    client_rate = 10
    client_burst = 50
  }
}
""")
    assert cfg.server.eval_pending_cap == 4096
    assert cfg.server.plan_queue_cap == 512
    assert cfg.server.max_blocking_watchers == 50000
    assert cfg.server.admission["client_rate"] == 10

    with pytest.raises(ValueError):
        parse_config("server { admission { bogus_knob = 1 } }")
    with pytest.raises(ValueError):
        parse_config("server { eval_pending_cap = -5 }")


def test_agent_config_admission_merge_key_by_key():
    from nomad_tpu.agent_config import parse_config

    base = parse_config(
        "server { admission { client_rate = 10  client_burst = 50 } }")
    override = parse_config("server { admission { client_rate = 20 } }")
    merged = base.merge(override)
    assert merged.server.admission == {"client_rate": 20, "client_burst": 50}


# -- bounded broker + plan queue --------------------------------------------


def _pending_eval(i=0, job_id=None):
    ev = mock.evaluation()
    ev.id = structs.generate_uuid()
    ev.job_id = job_id or f"job-{i}"
    ev.status = structs.EVAL_STATUS_PENDING
    return ev


def test_broker_pending_cap_typed_nack_and_spill():
    broker = EvalBroker(pending_cap=2)
    broker.set_enabled(True)
    broker.enqueue(_pending_eval(0))
    broker.enqueue(_pending_eval(1))
    assert broker.pending_total() == 2
    with pytest.raises(BrokerFullError):
        broker.enqueue(_pending_eval(2))
    # The FSM path spills instead of raising (a committed entry cannot
    # fail) and reports the count.
    assert broker.enqueue_many([_pending_eval(3), _pending_eval(4)]) == 2
    # reclaim handshake: False while full, True once capacity frees.
    assert not broker.reclaim_spilled()
    ev, token = broker.dequeue([ev_type(broker)], timeout=1.0)
    broker.ack(ev.id, token)
    assert broker.reclaim_spilled()
    # One True per spill episode.
    assert not broker.reclaim_spilled()


def ev_type(broker):
    return mock.evaluation().type


def test_broker_cap_ignores_tracked_requeues():
    """Re-enqueueing an already-tracked eval (redelivery bookkeeping)
    never counts against the cap."""
    broker = EvalBroker(pending_cap=1)
    broker.set_enabled(True)
    ev = _pending_eval(0)
    broker.enqueue(ev)
    broker.enqueue(ev, wait_index=50)  # no BrokerFullError
    assert broker.wait_index(ev.id) == 50


def test_plan_queue_depth_cap():
    q = PlanQueue(max_depth=1)
    q.set_enabled(True)
    q.enqueue(Plan(eval_id="e1"))
    with pytest.raises(PlanQueueError, match=ERR_QUEUE_FULL):
        q.enqueue(Plan(eval_id="e2"))
    # Draining frees capacity.
    assert q.dequeue(timeout=0.1) is not None
    q.enqueue(Plan(eval_id="e3"))


def test_server_readmits_spilled_evals():
    """Spilled evals stay durable in state and the readmission loop
    re-enqueues them as capacity frees — bounded queue, no lost work."""
    from nomad_tpu.server import Server, ServerConfig

    srv = Server(ServerConfig(
        scheduler_workers=0, eval_pending_cap=1,
        scheduler_backend="host", slo_objectives={},
    ))
    srv.start()
    try:
        evals = [_pending_eval(i) for i in range(3)]
        srv.eval_upsert(evals)  # one admitted, two spilled (counted)
        assert srv.eval_broker.pending_total() == 1
        # Drain + ack one; the readmission loop (0.5s poll) must refill.
        ev, token = srv.eval_broker.dequeue([evals[0].type], timeout=2.0)
        srv.eval_broker.ack(ev.id, token)
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            if srv.eval_broker.pending_total() >= 1:
                break
            time.sleep(0.05)
        assert srv.eval_broker.pending_total() >= 1, \
            "readmission loop never refilled the bounded broker"
    finally:
        srv.shutdown()


# -- server + HTTP + SDK integration ----------------------------------------


@pytest.fixture(scope="module")
def throttled_agent(tmp_path_factory):
    from nomad_tpu.agent import Agent, AgentConfig

    config = AgentConfig.dev()
    config.data_dir = str(tmp_path_factory.mktemp("agent-admission"))
    config.http_port = 0
    config.scheduler_backend = "host"
    # One admission per client per ~forever: the second register rejects.
    config.admission = {"client_rate": 0.001, "client_burst": 1}
    a = Agent(config)
    a.start()
    yield a
    a.shutdown()


def test_http_rejection_is_429_with_retry_after(throttled_agent):
    import json as json_mod
    import urllib.error
    import urllib.request

    from nomad_tpu.api.codec import to_dict

    addr = throttled_agent.http.addr

    def register(job, client):
        req = urllib.request.Request(
            f"{addr}/v1/jobs",
            data=json_mod.dumps({"job": to_dict(job)}).encode(),
            method="PUT", headers={"Content-Type": "application/json",
                                   "X-Nomad-Client": client},
        )
        with urllib.request.urlopen(req, timeout=10) as resp:
            return json_mod.loads(resp.read())

    register(_job(), "raw-1")
    with pytest.raises(urllib.error.HTTPError) as exc:
        register(_job(), "raw-1")
    assert exc.value.code == 429
    assert int(exc.value.headers["Retry-After"]) >= 1
    body = json_mod.loads(exc.value.read())
    assert body["reason"] == REJECT_RATE_LIMITED
    assert body["retry_after"] > 0


def test_sdk_surfaces_typed_rejection(throttled_agent):
    from nomad_tpu.api import ApiClient

    client = ApiClient(address=throttled_agent.http.addr,
                       client_id="sdk-1", reject_retries=0)
    client.jobs().register(_job())
    with pytest.raises(RejectError) as exc:
        client.jobs().register(_job())
    assert exc.value.reason == REJECT_RATE_LIMITED
    assert exc.value.retry_after > 0


def test_sdk_retries_rate_limited_honoring_hint(throttled_agent):
    """A fresh client lane with burst 1 and a fast refill is NOT
    available here (rate is glacial), so exercise the retry loop against
    a synthetic 429: patch urlopen to reject once with a small hint,
    then succeed — the SDK must sleep >= the hint and NOT surface the
    typed error."""
    import urllib.request

    from nomad_tpu.api import ApiClient

    client = ApiClient(address=throttled_agent.http.addr,
                       client_id="sdk-retry", reject_retries=2)
    real_urlopen = urllib.request.urlopen
    state = {"calls": 0}

    def flaky(req, timeout=None):
        state["calls"] += 1
        if state["calls"] == 1:
            import io
            import urllib.error

            raise urllib.error.HTTPError(
                req.full_url, 429, "Too Many Requests",
                {"Retry-After": "1"},
                io.BytesIO(
                    b'{"reason": "RATE_LIMITED", "retry_after": 0.05,'
                    b' "error": "x"}'),
            )
        return real_urlopen(req, timeout=timeout)

    t0 = time.monotonic()
    try:
        urllib.request.urlopen = flaky
        client.jobs().register(_job())
    finally:
        urllib.request.urlopen = real_urlopen
    assert state["calls"] == 2
    assert time.monotonic() - t0 >= 0.05  # honored the hint


def test_rpc_call_retry_honors_rate_limit_hint():
    """backoff.retry_undelivered: a typed RATE_LIMITED RemoteError
    retries after max(hint, backoff); other reasons surface typed at
    once (never a hot loop, never a bare RemoteError)."""
    from nomad_tpu.backoff import retry_undelivered
    from nomad_tpu.rpc import RemoteError

    calls = {"n": 0}
    rejection = RejectError(REJECT_RATE_LIMITED, "lane empty",
                            retry_after=0.05)

    def flaky():
        calls["n"] += 1
        if calls["n"] == 1:
            raise RemoteError(f"RejectError: {rejection}")
        return "ok"

    t0 = time.monotonic()
    assert retry_undelivered(flaky) == "ok"
    assert calls["n"] == 2
    assert time.monotonic() - t0 >= 0.05

    def always_full():
        raise RemoteError(
            f"RejectError: {RejectError(REJECT_QUEUE_FULL, 'full', 1.0)}")

    with pytest.raises(RejectError) as exc:
        retry_undelivered(always_full)
    assert exc.value.reason == REJECT_QUEUE_FULL

    def rate_limited_forever():
        raise RemoteError(
            f"RejectError: "
            f"{RejectError(REJECT_RATE_LIMITED, 'nope', 0.01)}")

    with pytest.raises(RejectError) as exc:
        retry_undelivered(rate_limited_forever, rate_limit_retries=2)
    assert exc.value.reason == REJECT_RATE_LIMITED


def test_admission_endpoint_and_bundle_section(throttled_agent):
    from nomad_tpu.api import ApiClient
    from nomad_tpu.bundle import BUNDLE_SECTIONS, collect

    client = ApiClient(address=throttled_agent.http.addr,
                       client_id="obs-1", reject_retries=0)
    client.jobs().register(_job())
    with pytest.raises(RejectError):
        client.jobs().register(_job())

    out = client.agent().admission()
    assert out["rejected"] >= 1
    assert out["by_reason"].get(REJECT_RATE_LIMITED, 0) >= 1
    assert any(r["client_id"] == "obs-1"
               for r in out["recent_rejections"])
    assert "eval_pending" in out["queues"]
    assert "watchers" in out["queues"]["watchers"] or True

    # /v1/agent/metrics carries the admission totals.
    metrics = client.agent().metrics()
    assert metrics["admission"]["rejected"] >= 1

    # The flight recorder inherits the section.
    assert "admission" in BUNDLE_SECTIONS
    bundle = collect(agent=throttled_agent, last_events=16)
    assert bundle["admission"]["rejected"] >= 1


def test_server_stats_carry_admission():
    from nomad_tpu.server import Server, ServerConfig

    srv = Server(ServerConfig(scheduler_workers=0,
                              scheduler_backend="host",
                              slo_objectives={}))
    assert srv.stats()["admission"]["admitted"] == 0
