"""Auxiliary subsystems: telemetry sinks, log plumbing, agent config files.

Reference patterns: go-metrics inmem tests, command/agent/config_test.go
merge tests, command/agent/log_writer_test.go ring semantics.
"""

import json
import logging
import time

import pytest

from nomad_tpu import telemetry
from nomad_tpu.agent_config import (
    FileConfig,
    default_config,
    dev_config,
    load_config_path,
    parse_config,
)
from nomad_tpu.logbuf import GatedHandler, LogWriter, setup_agent_logging


# -- telemetry --------------------------------------------------------------


def test_inmem_sink_aggregates():
    sink = telemetry.InmemSink(interval=10.0)
    sink.set_gauge(("nomad", "broker", "depth"), 3)
    sink.incr_counter(("nomad", "rpc", "query"), 1)
    sink.incr_counter(("nomad", "rpc", "query"), 1)
    sink.add_sample(("nomad", "worker", "invoke"), 12.0)
    sink.add_sample(("nomad", "worker", "invoke"), 8.0)

    cur = sink.intervals[-1]
    assert cur.gauges["nomad.broker.depth"] == 3
    assert cur.counters["nomad.rpc.query"].count == 2
    agg = cur.samples["nomad.worker.invoke"]
    assert agg.count == 2
    assert agg.min == 8.0 and agg.max == 12.0
    assert agg.mean == 10.0

    text = sink.dump()
    assert "nomad.broker.depth" in text
    assert "[G]" in text and "[C]" in text and "[S]" in text


def test_metrics_front_prefix_and_measure_since():
    sink = telemetry.InmemSink()
    m = telemetry.Metrics(sink, service="nomad", enable_hostname=False)
    start = time.perf_counter()
    m.measure_since(("plan", "evaluate"), start)
    m.incr_counter(("rpc", "query"))
    cur = sink.intervals[-1]
    assert "nomad.plan.evaluate" in cur.samples
    assert cur.samples["nomad.plan.evaluate"].max < 1000.0
    assert "nomad.rpc.query" in cur.counters


def test_fanout_and_build_sink():
    a, b = telemetry.InmemSink(), telemetry.InmemSink()
    fan = telemetry.FanoutSink([a, b])
    fan.set_gauge(("x",), 1.0)
    assert a.intervals[-1].gauges["x"] == 1.0
    assert b.intervals[-1].gauges["x"] == 1.0

    inmem, sink = telemetry.build_sink()
    assert sink is inmem
    inmem2, sink2 = telemetry.build_sink(statsd_addr="127.0.0.1:9")
    assert isinstance(sink2, telemetry.FanoutSink)
    # fire-and-forget: must not raise even with nothing listening
    sink2.incr_counter(("nomad", "test"), 1.0)


def test_global_metrics_registry():
    sink = telemetry.InmemSink()
    telemetry.set_global(telemetry.Metrics(sink, enable_hostname=False))
    telemetry.incr_counter(("global", "hit"))
    assert "nomad.global.hit" in sink.intervals[-1].counters


# -- log plumbing -----------------------------------------------------------


def _record(msg: str, level=logging.INFO) -> logging.LogRecord:
    return logging.LogRecord("nomad_tpu.test", level, __file__, 1, msg, (), None)


def test_log_writer_ring_and_stream():
    w = LogWriter(buf_size=4)
    for i in range(6):
        w.emit(_record(f"line-{i}"))
    tail = w.tail()
    assert len(tail) == 4
    assert tail[0].endswith("line-2") and tail[-1].endswith("line-5")

    got = []
    w.register_sink(got.append)
    assert len(got) == 4  # backlog replayed first
    w.emit(_record("live"))
    assert got[-1].endswith("live")
    w.deregister_sink(got.append)
    w.emit(_record("after"))
    assert not got[-1].endswith("after")


def test_gated_handler_buffers_until_flush():
    lines = []

    class Sink(logging.Handler):
        def emit(self, record):
            lines.append(record.getMessage())

    g = GatedHandler(Sink())
    g.emit(_record("early"))
    assert lines == []
    g.flush_through()
    assert lines == ["early"]
    g.emit(_record("late"))
    assert lines == ["early", "late"]


def test_setup_agent_logging_idempotent():
    logger = logging.getLogger("nomad_tpu")
    before = len(logger.handlers)
    w1 = setup_agent_logging("INFO")
    w2 = setup_agent_logging("DEBUG")
    after = len(
        [h for h in logger.handlers if isinstance(h, LogWriter)]
    )
    assert after == 1
    logger.removeHandler(w2)
    del w1
    assert len(logger.handlers) <= before + 1


# -- agent config files -----------------------------------------------------


HCL_CONFIG = '''
region = "eu1"
datacenter = "dc2"
data_dir = "/var/nomad"
log_level = "DEBUG"
enable_syslog = true

ports {
    http = 5646
}

server {
    enabled = true
    bootstrap_expect = 3
    num_schedulers = 4
}

client {
    enabled = true
    servers = ["10.0.0.1:4647"]
    meta {
        rack = "r1"
    }
    options {
        "driver.exec.enable" = "1"
    }
}

telemetry {
    statsd_address = "127.0.0.1:8125"
    disable_hostname = true
}

atlas {
    infrastructure = "acme/prod"
}
'''


def test_parse_hcl_agent_config():
    cfg = parse_config(HCL_CONFIG)
    assert cfg.region == "eu1"
    assert cfg.datacenter == "dc2"
    assert cfg.log_level == "DEBUG"
    assert cfg.enable_syslog is True
    assert cfg.ports.http == 5646
    assert cfg.ports.rpc == 4647  # untouched default
    assert cfg.server.enabled and cfg.server.bootstrap_expect == 3
    assert cfg.server.num_schedulers == 4
    assert cfg.client.enabled
    assert cfg.client.servers == ["10.0.0.1:4647"]
    assert cfg.client.meta == {"rack": "r1"}
    assert cfg.client.options == {"driver.exec.enable": "1"}
    assert cfg.telemetry.statsd_address == "127.0.0.1:8125"
    assert cfg.telemetry.disable_hostname is True
    assert cfg.atlas.infrastructure == "acme/prod"


def test_parse_json_agent_config():
    cfg = parse_config(json.dumps({
        "region": "ap1",
        "ports": {"http": 7000},
        "server": {"enabled": True},
    }))
    assert cfg.region == "ap1"
    assert cfg.ports.http == 7000
    assert cfg.server.enabled


def test_unknown_key_rejected():
    with pytest.raises(ValueError, match="unknown agent config key"):
        parse_config('bogus_key = true')


def test_merge_semantics():
    base = default_config()
    override = parse_config(HCL_CONFIG)
    merged = base.merge(override)
    assert merged.region == "eu1"
    assert merged.bind_addr == "127.0.0.1"  # kept from base
    assert merged.ports.http == 5646

    # second-level override: later file wins field-by-field, maps merge
    second = parse_config('''
client {
    meta {
        rack = "r2"
        zone = "z1"
    }
}
log_level = "WARN"
''')
    final = merged.merge(second)
    assert final.log_level == "WARN"
    assert final.region == "eu1"
    assert final.client.meta == {"rack": "r2", "zone": "z1"}
    assert final.client.servers == ["10.0.0.1:4647"]


def test_load_config_dir(tmp_path):
    (tmp_path / "a.hcl").write_text('region = "r-a"\nlog_level = "DEBUG"')
    (tmp_path / "b.json").write_text('{"region": "r-b"}')
    (tmp_path / "ignored.txt").write_text("not config")
    cfg = load_config_path(str(tmp_path))
    # sorted order: a.hcl then b.json -> b wins region, a's log level kept
    assert cfg.region == "r-b"
    assert cfg.log_level == "DEBUG"


def test_dev_config_and_agent_conversion():
    from nomad_tpu.agent import AgentConfig

    fc = dev_config()
    ac = AgentConfig.from_file_config(fc)
    assert ac.server_enabled and ac.client_enabled
    assert ac.client_options.get("driver.raw_exec.enable") == "1"
    assert ac.http_port == 4646

    fc2 = fc.merge(parse_config(HCL_CONFIG))
    ac2 = AgentConfig.from_file_config(fc2)
    assert ac2.num_schedulers == 4
    assert ac2.statsd_addr == "127.0.0.1:8125"
    assert ac2.enable_syslog


def test_solver_mesh_config_parse_and_merge():
    """server { solver_mesh { } }: parse-time validated (unknown keys,
    ranges, power-of-two), key-by-key merge like admission/express, and
    wired through ServerConfig.__post_init__."""
    from nomad_tpu.parallel.mesh import SolverMeshConfig
    from nomad_tpu.server import ServerConfig

    cfg = parse_config('''
server {
    enabled = true
    solver_mesh {
        node_shards = 4
        eval_parallel = 2
    }
}
''')
    assert cfg.server.solver_mesh == {"node_shards": 4, "eval_parallel": 2}

    # Key-by-key merge: a later file overrides one knob, keeps the rest.
    merged = cfg.merge(parse_config(
        'server { solver_mesh { node_shards = 8 } }'
    ))
    assert merged.server.solver_mesh == {"node_shards": 8,
                                         "eval_parallel": 2}

    for bad in ('server { solver_mesh { node_shards = 3 } }',
                'server { solver_mesh { node_shards = -1 } }',
                'server { solver_mesh { bogus = 1 } }',
                'server { solver_mesh { eval_parallel = 0 } }'):
        with pytest.raises(ValueError):
            parse_config(bad)

    sc = ServerConfig(solver_mesh={"node_shards": 2})
    assert sc.solver_mesh_config.enabled
    assert sc.solver_mesh_config.node_shards == 2
    assert sc.solver_mesh_config.eval_parallel == 1
    assert not ServerConfig().solver_mesh_config.enabled
    with pytest.raises(ValueError):
        ServerConfig(solver_mesh={"node_shards": 6})

    parsed = SolverMeshConfig.parse({"node_shards": 4, "eval_parallel": 2})
    assert parsed.as_dict() == {"node_shards": 4, "eval_parallel": 2}


def test_cli_parses_new_commands():
    from nomad_tpu.cli import make_parser

    parser = make_parser()
    args = parser.parse_args(
        ["agent", "-dev", "-config", "/tmp/x.hcl", "-config", "/tmp/d"]
    )
    assert args.config == ["/tmp/x.hcl", "/tmp/d"]
    for argv in (
        ["server-join", "127.0.0.1:4648"],
        ["server-force-leave", "node1"],
        ["client-config", "-servers"],
        ["spawn-daemon", "{}"],
    ):
        args = parser.parse_args(argv)
        assert callable(args.func)
