"""Cold-compile resilience: a first solve slower than the nack timeout
must not be redelivered (worker nack-touch), and the leader pre-warms the
shape buckets so it rarely happens at all (tpu/solver.py warm_shapes).

Reference machinery: OutstandingReset + Nack timers,
/root/reference/nomad/eval_broker.go:319-412.
"""

import threading
import time

import pytest

from nomad_tpu import mock, structs
from nomad_tpu.scheduler import BUILTIN_SCHEDULERS, register
from nomad_tpu.server import Server, ServerConfig
from nomad_tpu.structs import Evaluation, generate_uuid


def _wait_complete(srv, eval_id, timeout=20.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        got = srv.state_store.eval_by_id(eval_id)
        if got is not None and got.status != structs.EVAL_STATUS_PENDING:
            return got
        time.sleep(0.02)
    raise TimeoutError("eval still pending")


def test_slow_first_solve_not_redelivered():
    """Scheduler invocation takes 3x the nack timeout; the touch loop must
    keep the broker from redelivering, so the scheduler runs exactly once
    and the eval completes."""
    invocations = []
    orig = BUILTIN_SCHEDULERS["service"]

    def slow_factory(state, planner, logger):
        inner = orig(state, planner, logger)

        class Slow:
            def process(self, ev):
                invocations.append(ev.id)
                time.sleep(1.6)  # > 3x nack timeout below
                return inner.process(ev)

        return Slow()

    register("service", slow_factory)
    srv = Server(ServerConfig(
        scheduler_backend="host", num_schedulers=1, eval_batch_size=1,
        eval_nack_timeout=0.5, prewarm_shapes=False,
    ))
    try:
        node = mock.node()
        srv.raft.apply("node_register", {"node": node})
        job = mock.job()
        job.task_groups[0].count = 1
        srv.raft.apply("job_register", {"job": job})
        srv.start()
        ev = Evaluation(
            id=generate_uuid(), priority=job.priority, type=job.type,
            triggered_by=structs.EVAL_TRIGGER_JOB_REGISTER, job_id=job.id,
            status=structs.EVAL_STATUS_PENDING,
        )
        srv.raft.apply("eval_update", {"evals": [ev]})
        got = _wait_complete(srv, ev.id)
        assert got.status == structs.EVAL_STATUS_COMPLETE
        # Exactly one delivery: the nack timer never fired mid-solve.
        assert invocations == [ev.id]
        stats = srv.eval_broker.snapshot_stats()
        assert stats.total_unacked == 0 and stats.total_ready == 0
    finally:
        register("service", orig)
        srv.shutdown()


def test_slow_solve_without_touch_redelivers():
    """Control for the test above: with touching disabled the same slow
    solve IS redelivered — proving the touch loop is load-bearing."""
    invocations = []
    orig = BUILTIN_SCHEDULERS["service"]

    def slow_factory(state, planner, logger):
        inner = orig(state, planner, logger)

        class Slow:
            def process(self, ev):
                invocations.append(ev.id)
                time.sleep(1.6)
                return inner.process(ev)

        return Slow()

    register("service", slow_factory)
    srv = Server(ServerConfig(
        scheduler_backend="host", num_schedulers=1, eval_batch_size=1,
        eval_nack_timeout=0.5, prewarm_shapes=False,
    ))
    srv.eval_touch = lambda eval_id, token: None  # disable the touch loop
    try:
        node = mock.node()
        srv.raft.apply("node_register", {"node": node})
        job = mock.job()
        job.task_groups[0].count = 1
        srv.raft.apply("job_register", {"job": job})
        srv.start()
        ev = Evaluation(
            id=generate_uuid(), priority=job.priority, type=job.type,
            triggered_by=structs.EVAL_TRIGGER_JOB_REGISTER, job_id=job.id,
            status=structs.EVAL_STATUS_PENDING,
        )
        srv.raft.apply("eval_update", {"evals": [ev]})
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline and len(invocations) < 2:
            time.sleep(0.05)
        assert len(invocations) >= 2  # nack timer fired -> redelivery
    finally:
        register("service", orig)
        srv.shutdown()


def test_warm_shapes_compiles_cluster_buckets():
    from nomad_tpu.state import StateStore
    from nomad_tpu.tpu import solver as tpu_solver
    from nomad_tpu.tpu.mirror import GLOBAL_MIRROR_CACHE
    from nomad_tpu.ops.binpack import bucket

    store = StateStore()
    # 12 nodes in dc1 + 3 in dc2: union bucket 16, dc1 bucket 16 (dedup),
    # dc2 bucket 8 -> two distinct node buckets.
    for i in range(15):
        n = mock.node()
        n.id = f"warm-{i}"
        n.datacenter = "dc1" if i < 12 else "dc2"
        store.upsert_node(i + 1, n)
    snap = store.snapshot()
    counts = (1, 129)
    dispatches = tpu_solver.warm_shapes(snap, counts=counts)
    # Per node bucket: one dispatch per count, plus the coalesced
    # eval-axis batch buckets (1, 2, 4, 8 — ops/coalesce.warm_batch_shapes),
    # plus the stacked exact-scan widths (2, 4, 8) per exact count bucket
    # (ops/coalesce.warm_exact_batch_shapes — the cross-eval batching's
    # third shape axis).
    exact_buckets = len({bucket(c) for c in counts if c <= 128})
    assert dispatches == 2 * (len(counts) + 4 + 3 * exact_buckets)

    # The warmed mirror is the one a real eval adopts (cache hit).
    hits0 = GLOBAL_MIRROR_CACHE.hits
    _nodes, mirror = GLOBAL_MIRROR_CACHE.get(snap, ["dc1", "dc2"])
    assert GLOBAL_MIRROR_CACHE.hits == hits0 + 1
    assert mirror.padded == bucket(15)


def test_warm_shapes_empty_store_noop():
    from nomad_tpu.state import StateStore
    from nomad_tpu.tpu import solver as tpu_solver

    assert tpu_solver.warm_shapes(StateStore().snapshot()) == 0
