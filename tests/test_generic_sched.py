"""Ported service/batch scheduler tests
(/root/reference/scheduler/generic_sched_test.go).

Parametrized over the host factory and (once registered) the TPU factory so
both solvers are held to the same oracle.
"""

import pytest

from nomad_tpu import mock, structs
from nomad_tpu.structs import Evaluation, UpdateStrategy, generate_uuid

from sched_harness import Harness, RejectPlan, flatten

SERVICE_FACTORIES = ["service", "tpu-service"]


@pytest.mark.parametrize("factory", SERVICE_FACTORIES)
def test_job_register(factory):
    """reference: generic_sched_test.go:12-64"""
    h = Harness()
    for _ in range(10):
        h.state.upsert_node(h.next_index(), mock.node())

    job = mock.job()
    h.state.upsert_job(h.next_index(), job)

    ev = Evaluation(
        id=generate_uuid(),
        priority=job.priority,
        triggered_by=structs.EVAL_TRIGGER_JOB_REGISTER,
        job_id=job.id,
    )
    h.process(factory, ev)

    assert len(h.plans) == 1
    plan = h.plans[0]
    planned = flatten(plan.node_allocation)
    assert len(planned) == 10, plan

    out = h.state.allocs_by_job(job.id)
    assert len(out) == 10
    h.assert_eval_status(structs.EVAL_STATUS_COMPLETE)


@pytest.mark.parametrize("factory", SERVICE_FACTORIES)
def test_job_register_alloc_fail(factory):
    """reference: generic_sched_test.go:66-114"""
    h = Harness()
    # no nodes
    job = mock.job()
    h.state.upsert_job(h.next_index(), job)

    ev = Evaluation(
        id=generate_uuid(),
        priority=job.priority,
        triggered_by=structs.EVAL_TRIGGER_JOB_REGISTER,
        job_id=job.id,
    )
    h.process(factory, ev)

    assert len(h.plans) == 1
    plan = h.plans[0]
    assert len(plan.failed_allocs) == 1

    out = h.state.allocs_by_job(job.id)
    assert len(out) == 1
    assert out[0].metrics.coalesced_failures == 9
    h.assert_eval_status(structs.EVAL_STATUS_COMPLETE)


@pytest.mark.parametrize("factory", SERVICE_FACTORIES)
def test_job_modify(factory):
    """reference: generic_sched_test.go:116-212"""
    h = Harness()
    nodes = [mock.node() for _ in range(10)]
    for n in nodes:
        h.state.upsert_node(h.next_index(), n)

    job = mock.job()
    h.state.upsert_job(h.next_index(), job)

    allocs = []
    for i in range(10):
        alloc = mock.alloc()
        alloc.job = job
        alloc.job_id = job.id
        alloc.node_id = nodes[i].id
        alloc.name = f"my-job.web[{i}]"
        allocs.append(alloc)
    h.state.upsert_allocs(h.next_index(), allocs)

    # Terminal allocs should be ignored
    terminal = []
    for i in range(5):
        alloc = mock.alloc()
        alloc.job = job
        alloc.job_id = job.id
        alloc.node_id = nodes[i].id
        alloc.name = f"my-job.web[{i}]"
        alloc.desired_status = structs.ALLOC_DESIRED_STATUS_FAILED
        terminal.append(alloc)
    h.state.upsert_allocs(h.next_index(), terminal)

    # Update so it cannot be done in place
    job2 = mock.job()
    job2.id = job.id
    job2.task_groups[0].tasks[0].config["command"] = "/bin/other"
    h.state.upsert_job(h.next_index(), job2)

    ev = Evaluation(
        id=generate_uuid(),
        priority=50,
        triggered_by=structs.EVAL_TRIGGER_JOB_REGISTER,
        job_id=job.id,
    )
    h.process(factory, ev)

    assert len(h.plans) == 1
    plan = h.plans[0]
    update = flatten(plan.node_update)
    assert len(update) == len(allocs), plan
    planned = flatten(plan.node_allocation)
    assert len(planned) == 10

    out = structs.filter_terminal_allocs(h.state.allocs_by_job(job.id))
    assert len(out) == 10
    h.assert_eval_status(structs.EVAL_STATUS_COMPLETE)


@pytest.mark.parametrize("factory", SERVICE_FACTORIES)
def test_job_modify_rolling(factory):
    """reference: generic_sched_test.go:214-313"""
    h = Harness()
    nodes = [mock.node() for _ in range(10)]
    for n in nodes:
        h.state.upsert_node(h.next_index(), n)

    job = mock.job()
    h.state.upsert_job(h.next_index(), job)

    allocs = []
    for i in range(10):
        alloc = mock.alloc()
        alloc.job = job
        alloc.job_id = job.id
        alloc.node_id = nodes[i].id
        alloc.name = f"my-job.web[{i}]"
        allocs.append(alloc)
    h.state.upsert_allocs(h.next_index(), allocs)

    job2 = mock.job()
    job2.id = job.id
    job2.update = UpdateStrategy(stagger=30.0, max_parallel=5)
    job2.task_groups[0].tasks[0].config["command"] = "/bin/other"
    h.state.upsert_job(h.next_index(), job2)

    ev = Evaluation(
        id=generate_uuid(),
        priority=50,
        triggered_by=structs.EVAL_TRIGGER_JOB_REGISTER,
        job_id=job.id,
    )
    h.process(factory, ev)

    assert len(h.plans) == 1
    plan = h.plans[0]
    update = flatten(plan.node_update)
    assert len(update) == job2.update.max_parallel
    planned = flatten(plan.node_allocation)
    assert len(planned) == job2.update.max_parallel

    h.assert_eval_status(structs.EVAL_STATUS_COMPLETE)

    # Follow-up rolling eval chain
    ev_update = h.evals[0]
    assert ev_update.next_eval
    assert len(h.create_evals) > 0
    create = h.create_evals[0]
    assert ev_update.next_eval == create.id
    assert create.previous_eval == ev_update.id
    assert create.triggered_by == structs.EVAL_TRIGGER_ROLLING_UPDATE


@pytest.mark.parametrize("factory", SERVICE_FACTORIES)
def test_job_modify_in_place(factory):
    """reference: generic_sched_test.go:315-407"""
    h = Harness()
    nodes = [mock.node() for _ in range(10)]
    for n in nodes:
        h.state.upsert_node(h.next_index(), n)

    job = mock.job()
    h.state.upsert_job(h.next_index(), job)

    allocs = []
    for i in range(10):
        alloc = mock.alloc()
        alloc.job = job
        alloc.job_id = job.id
        alloc.node_id = nodes[i].id
        alloc.name = f"my-job.web[{i}]"
        allocs.append(alloc)
    h.state.upsert_allocs(h.next_index(), allocs)

    job2 = mock.job()
    job2.id = job.id
    h.state.upsert_job(h.next_index(), job2)

    ev = Evaluation(
        id=generate_uuid(),
        priority=50,
        triggered_by=structs.EVAL_TRIGGER_JOB_REGISTER,
        job_id=job.id,
    )
    h.process(factory, ev)

    assert len(h.plans) == 1
    plan = h.plans[0]
    assert flatten(plan.node_update) == []
    planned = flatten(plan.node_allocation)
    assert len(planned) == 10
    for p in planned:
        assert p.job is h.state.job_by_id(job.id) or p.job.modify_index == job2.modify_index

    out = h.state.allocs_by_job(job.id)
    assert len(out) == 10
    h.assert_eval_status(structs.EVAL_STATUS_COMPLETE)

    # Networks must not change on in-place update
    for alloc in out:
        for resources in alloc.task_resources.values():
            assert resources.networks[0].reserved_ports[0] == 5000, alloc


@pytest.mark.parametrize("factory", SERVICE_FACTORIES)
def test_job_deregister(factory):
    """reference: generic_sched_test.go:409-460"""
    h = Harness()
    job = mock.job()
    allocs = []
    for _ in range(10):
        alloc = mock.alloc()
        alloc.job = job
        alloc.job_id = job.id
        allocs.append(alloc)
    h.state.upsert_allocs(h.next_index(), allocs)

    ev = Evaluation(
        id=generate_uuid(),
        priority=50,
        triggered_by=structs.EVAL_TRIGGER_JOB_DEREGISTER,
        job_id=job.id,
    )
    h.process(factory, ev)

    assert len(h.plans) == 1
    plan = h.plans[0]
    assert len(plan.node_update.get("foo", [])) == len(allocs)

    out = structs.filter_terminal_allocs(h.state.allocs_by_job(job.id))
    assert out == []
    h.assert_eval_status(structs.EVAL_STATUS_COMPLETE)


@pytest.mark.parametrize("factory", SERVICE_FACTORIES)
def test_node_drain(factory):
    """reference: generic_sched_test.go:462-537"""
    h = Harness()
    drain_node = mock.node()
    drain_node.drain = True
    h.state.upsert_node(h.next_index(), drain_node)

    for _ in range(10):
        h.state.upsert_node(h.next_index(), mock.node())

    job = mock.job()
    h.state.upsert_job(h.next_index(), job)

    allocs = []
    for i in range(10):
        alloc = mock.alloc()
        alloc.job = job
        alloc.job_id = job.id
        alloc.node_id = drain_node.id
        alloc.name = f"my-job.web[{i}]"
        allocs.append(alloc)
    h.state.upsert_allocs(h.next_index(), allocs)

    ev = Evaluation(
        id=generate_uuid(),
        priority=50,
        triggered_by=structs.EVAL_TRIGGER_NODE_UPDATE,
        job_id=job.id,
        node_id=drain_node.id,
    )
    h.process(factory, ev)

    assert len(h.plans) == 1
    plan = h.plans[0]
    assert len(plan.node_update[drain_node.id]) == len(allocs)
    planned = flatten(plan.node_allocation)
    assert len(planned) == 10

    out = structs.filter_terminal_allocs(h.state.allocs_by_job(job.id))
    assert len(out) == 10
    h.assert_eval_status(structs.EVAL_STATUS_COMPLETE)


@pytest.mark.parametrize("factory", SERVICE_FACTORIES)
def test_retry_limit(factory):
    """reference: generic_sched_test.go:539-583"""
    h = Harness()
    h.planner = RejectPlan(h)

    for _ in range(10):
        h.state.upsert_node(h.next_index(), mock.node())

    job = mock.job()
    h.state.upsert_job(h.next_index(), job)

    ev = Evaluation(
        id=generate_uuid(),
        priority=job.priority,
        triggered_by=structs.EVAL_TRIGGER_JOB_REGISTER,
        job_id=job.id,
    )
    h.process(factory, ev)

    assert len(h.plans) > 0
    out = h.state.allocs_by_job(job.id)
    assert out == []
    h.assert_eval_status(structs.EVAL_STATUS_FAILED)


@pytest.mark.parametrize("factory", SERVICE_FACTORIES)
def test_bad_trigger(factory):
    """Unknown trigger reason fails the eval (generic_sched.go:90-98)."""
    h = Harness()
    job = mock.job()
    h.state.upsert_job(h.next_index(), job)
    ev = Evaluation(
        id=generate_uuid(),
        priority=50,
        triggered_by="bogus-trigger",
        job_id=job.id,
    )
    h.process(factory, ev)
    h.assert_eval_status(structs.EVAL_STATUS_FAILED)
