"""CLI tests (reference: command/*_test.go with cli.MockUi)."""

import time

import pytest

from nomad_tpu.agent import Agent, AgentConfig
from nomad_tpu.cli import main


@pytest.fixture(scope="module")
def agent(tmp_path_factory):
    config = AgentConfig.dev()
    config.data_dir = str(tmp_path_factory.mktemp("cli-agent"))
    config.http_port = 0
    config.scheduler_backend = "host"
    a = Agent(config)
    a.start()
    # wait for the dev node
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline:
        node = a.client.node if a.client else None
        if node and a.server.state_store.node_by_id(node.id) and \
           a.server.state_store.node_by_id(node.id).status == "ready":
            break
        time.sleep(0.1)
    yield a
    a.shutdown()


def _run(agent, *argv):
    return main(["--address", agent.http.addr, *argv])


def test_version(capsys, agent):
    assert _run(agent, "version") == 0
    assert "nomad-tpu v" in capsys.readouterr().out


def test_init_validate(tmp_path, monkeypatch, capsys, agent):
    monkeypatch.chdir(tmp_path)
    assert _run(agent, "init") == 0
    assert _run(agent, "validate", "example.hcl") == 0
    out = capsys.readouterr().out
    assert "Job validation successful" in out
    # Second init fails (file exists)
    assert _run(agent, "init") == 1


def test_run_status_stop(tmp_path, capsys, agent):
    jobfile = tmp_path / "job.hcl"
    jobfile.write_text('''
job "cli-test" {
    datacenters = ["dc1"]
    type = "service"
    group "g" {
        count = 1
        task "t" {
            driver = "mock_driver"
            config { run_for = "60" }
            resources { cpu = 50 memory = 32 }
        }
    }
}
''')
    assert _run(agent, "run", str(jobfile)) == 0
    out = capsys.readouterr().out
    assert "Monitoring evaluation" in out
    assert 'Allocation' in out
    assert '"pending" -> "complete"' in out

    assert _run(agent, "status") == 0
    assert "cli-test" in capsys.readouterr().out

    assert _run(agent, "status", "cli-test") == 0
    out = capsys.readouterr().out
    assert "ID          = cli-test" in out
    assert "==> Allocations" in out

    assert _run(agent, "node-status") == 0
    out = capsys.readouterr().out
    assert "ready" in out

    # node-status detail + alloc-status
    node_id = agent.client.node.id
    assert _run(agent, "node-status", node_id) == 0
    out = capsys.readouterr().out
    assert f"ID         = {node_id}" in out

    allocs = agent.server.state_store.allocs_by_job("cli-test")
    assert _run(agent, "alloc-status", allocs[0].id) == 0
    out = capsys.readouterr().out
    assert "Placement Metrics" in out

    assert _run(agent, "stop", "cli-test") == 0
    out = capsys.readouterr().out
    assert '"pending" -> "complete"' in out


def test_run_placement_failure_reported(tmp_path, capsys, agent):
    jobfile = tmp_path / "fail.hcl"
    jobfile.write_text('''
job "impossible" {
    datacenters = ["dc1"]
    group "g" {
        count = 1
        task "t" {
            driver = "mock_driver"
            config { run_for = "1" }
            resources { cpu = 99999999 memory = 99999999 }
        }
    }
}
''')
    assert _run(agent, "run", str(jobfile)) == 0  # eval completes with failed alloc
    out = capsys.readouterr().out
    assert "Scheduling error" in out
    _run(agent, "stop", "-detach", "impossible")
    capsys.readouterr()


def test_validate_bad_job(tmp_path, capsys, agent):
    bad = tmp_path / "bad.hcl"
    bad.write_text('job "x" { }')  # no datacenters/task groups
    assert _run(agent, "validate", str(bad)) == 1
    assert "Error validating job" in capsys.readouterr().out


def test_server_members_and_agent_info(capsys, agent):
    assert _run(agent, "server-members") == 0
    assert "alive" in capsys.readouterr().out
    assert _run(agent, "agent-info") == 0
    assert "server_enabled" in capsys.readouterr().out


def test_node_drain_cli(capsys, agent):
    node_id = agent.client.node.id
    assert _run(agent, "node-drain", node_id) == 1  # missing flag
    capsys.readouterr()
    assert _run(agent, "node-drain", "-enable", node_id) == 0
    assert agent.server.state_store.node_by_id(node_id).drain
    assert _run(agent, "node-drain", "-disable", node_id) == 0
    assert not agent.server.state_store.node_by_id(node_id).drain
