"""Test configuration.

Tests run on a virtual 8-device CPU mesh so multi-chip sharding logic is
exercised without TPU hardware (the driver separately dry-run-compiles the
multi-chip path; bench.py runs on the real chip). These env vars must be set
before jax is imported anywhere.
"""

import os
import sys

# NOMAD_TPU_TEST_TPU=1 opts OUT of the cpu pin so the hardware-gated tests
# (tests/test_pallas_compiled.py) can actually claim the real device —
# only set it where a TPU backend is known-alive; a dead relay will wedge
# backend init.
_TPU_RUN = os.environ.get("NOMAD_TPU_TEST_TPU") == "1"

if not _TPU_RUN:
    os.environ["JAX_PLATFORMS"] = "cpu"
    # Child processes (device probes, forked servers) must claim the cpu
    # backend too — the image's sitecustomize pins the axon platform
    # regardless of JAX_PLATFORMS, so the probe child honors this explicit
    # re-pin knob.
    os.environ["NOMAD_TPU_PROBE_FORCE_CPU"] = "1"
    # Hermetic relay target: probe children scan a known-closed port (1,
    # tcpmux) instead of whatever live relay happens to be listening on
    # loopback. Without this, a relay window opening mid-suite flips the
    # reachable-relay leash extension (device_probe.CLAIM_TIMEOUT) on and
    # changes kill-timing the wedge tests assert on. Tests that need a
    # reachable relay open their own listener and monkeypatch this.
    os.environ["PALLAS_AXON_POOL_IPS"] = "127.0.0.1:1"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The image's sitecustomize registers the axon TPU backend and pins
# jax_platforms regardless of the env var; override it back to cpu before
# the backend initializes.
import jax  # noqa: E402

if not _TPU_RUN:
    jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import pytest  # noqa: E402


def pytest_configure(config):
    # Tier-1 runs with `-m "not slow"` (ROADMAP.md); heavy scale scenarios
    # (10k-node simcluster runs) carry this marker so they only run when
    # asked for explicitly: `pytest -m slow tests/test_simcluster.py`.
    config.addinivalue_line(
        "markers", "slow: heavy scale tests excluded from tier-1"
    )


@pytest.fixture(autouse=True, scope="session")
def _drain_device_threads():
    """Interpreter teardown while a daemon thread (coalescer dispatcher,
    a shut-down server's shape prewarm) sits inside an XLA call aborts the
    process with std::terminate AFTER all tests passed — drain device work
    before pytest exits."""
    yield
    from nomad_tpu.ops.coalesce import quiesce_all

    quiesce_all(timeout=20.0)
