"""Ported scheduler util tests (/root/reference/scheduler/util_test.go)."""

import logging

import pytest

from nomad_tpu import mock, structs
from nomad_tpu.scheduler import SetStatusError
from nomad_tpu.scheduler.context import EvalContext
from nomad_tpu.scheduler.stack import GenericStack
from nomad_tpu.scheduler.util import (
    AllocTuple,
    DiffResult,
    diff_allocs,
    diff_system_allocs,
    evict_and_place,
    inplace_update,
    materialize_task_groups,
    ready_nodes_in_dcs,
    retry_max,
    set_status,
    tainted_nodes,
    task_group_constraints,
    tasks_updated,
)
from nomad_tpu.state import StateStore
from nomad_tpu.structs import (
    Allocation,
    Evaluation,
    Plan,
    Resources,
    generate_uuid,
)

logger = logging.getLogger("test")


def test_materialize_task_groups():
    """util_test.go:15-32"""
    job = mock.job()
    index = materialize_task_groups(job)
    assert len(index) == 10
    for i in range(10):
        name = f"my-job.web[{i}]"
        assert name in index
        assert index[name] is job.task_groups[0]
    assert materialize_task_groups(None) == {}


def test_diff_allocs():
    """util_test.go:34-111"""
    job = mock.job()
    required = materialize_task_groups(job)

    # Previous job version for update detection
    old_job = mock.job()
    old_job.id = job.id
    old_job.modify_index = job.modify_index - 1

    tainted = {"dead": True, "zip": False}

    allocs = [
        # Update (stale job)
        Allocation(id=generate_uuid(), node_id="zip", name="my-job.web[0]", job=old_job),
        # Ignore (current job)
        Allocation(id=generate_uuid(), node_id="zip", name="my-job.web[1]", job=job),
        # Stop (not required)
        Allocation(id=generate_uuid(), node_id="zip", name="my-job.web[12]", job=job),
        # Migrate (tainted node)
        Allocation(id=generate_uuid(), node_id="dead", name="my-job.web[2]", job=old_job),
    ]

    diff = diff_allocs(job, tainted, required, allocs)
    assert len(diff.update) == 1 and diff.update[0].alloc is allocs[0]
    assert len(diff.ignore) == 1 and diff.ignore[0].alloc is allocs[1]
    assert len(diff.stop) == 1 and diff.stop[0].alloc is allocs[2]
    assert len(diff.migrate) == 1 and diff.migrate[0].alloc is allocs[3]
    assert len(diff.place) == 7


def test_diff_system_allocs():
    """util_test.go:113-185"""
    job = mock.system_job()

    old_job = mock.system_job()
    old_job.id = job.id
    old_job.modify_index = job.modify_index - 1

    nodes = [structs.Node(id="foo"), structs.Node(id="bar"), structs.Node(id="baz")]
    tainted = {"dead": True, "baz": False}

    allocs = [
        # Update (stale)
        Allocation(id=generate_uuid(), node_id="foo", name="my-job.web[0]", job=old_job),
        # Ignore (current)
        Allocation(id=generate_uuid(), node_id="bar", name="my-job.web[0]", job=job),
        # Stop (tainted node -> system stops, not migrates)
        Allocation(id=generate_uuid(), node_id="dead", name="my-job.web[0]", job=old_job),
    ]

    diff = diff_system_allocs(job, nodes, tainted, allocs)
    assert len(diff.update) == 1 and diff.update[0].alloc is allocs[0]
    assert len(diff.ignore) == 1 and diff.ignore[0].alloc is allocs[1]
    assert len(diff.stop) == 1 and diff.stop[0].alloc is allocs[2]
    assert diff.migrate == []
    # Place on baz (no alloc there yet)
    assert len(diff.place) == 1
    assert diff.place[0].alloc.node_id == "baz"


def test_ready_nodes_in_dcs():
    """util_test.go:187-218"""
    state = StateStore()
    node1 = mock.node()
    node2 = mock.node()
    node2.datacenter = "dc2"
    node3 = mock.node()
    node3.datacenter = "dc2"
    node3.status = structs.NODE_STATUS_DOWN
    node4 = mock.node()
    node4.drain = True

    for i, n in enumerate([node1, node2, node3, node4]):
        state.upsert_node(1000 + i, n)

    nodes = ready_nodes_in_dcs(state, ["dc1", "dc2"])
    ids = {n.id for n in nodes}
    assert ids == {node1.id, node2.id}


def test_retry_max():
    """util_test.go:220-246"""
    calls = [0]

    def bad():
        calls[0] += 1
        return False

    with pytest.raises(SetStatusError) as exc:
        retry_max(3, bad)
    assert calls[0] == 3
    assert exc.value.eval_status == structs.EVAL_STATUS_FAILED

    calls[0] = 0

    def good():
        calls[0] += 1
        return True

    retry_max(3, good)
    assert calls[0] == 1


def test_tainted_nodes():
    """util_test.go:248-288"""
    state = StateStore()
    node1 = mock.node()
    node2 = mock.node()
    node2.drain = True
    node3 = mock.node()
    node3.status = structs.NODE_STATUS_DOWN
    for i, n in enumerate([node1, node2, node3]):
        state.upsert_node(1000 + i, n)

    allocs = [
        Allocation(id=generate_uuid(), node_id=node1.id),
        Allocation(id=generate_uuid(), node_id=node2.id),
        Allocation(id=generate_uuid(), node_id=node3.id),
        Allocation(id=generate_uuid(), node_id="missing"),
    ]
    tainted = tainted_nodes(state, allocs)
    assert len(tainted) == 4
    assert not tainted[node1.id]
    assert tainted[node2.id]
    assert tainted[node3.id]
    assert tainted["missing"]


def test_tasks_updated():
    """util_test.go:313-356"""
    j1 = mock.job()
    j2 = mock.job()
    assert not tasks_updated(j1.task_groups[0], j2.task_groups[0])

    j2b = mock.job()
    j2b.task_groups[0].tasks[0].config["command"] = "/bin/other"
    assert tasks_updated(j1.task_groups[0], j2b.task_groups[0])

    j3 = mock.job()
    j3.task_groups[0].tasks[0].driver = "foobar"
    assert tasks_updated(j1.task_groups[0], j3.task_groups[0])

    j4 = mock.job()
    j4.task_groups[0].tasks.append(mock.job().task_groups[0].tasks[0].__class__(name="extra", driver="exec"))
    assert tasks_updated(j1.task_groups[0], j4.task_groups[0])

    j5 = mock.job()
    j5.task_groups[0].tasks[0].env["NEW"] = "1"
    assert tasks_updated(j1.task_groups[0], j5.task_groups[0])

    j6 = mock.job()
    j6.task_groups[0].tasks[0].resources.networks[0].dynamic_ports = ["http", "https"]
    assert tasks_updated(j1.task_groups[0], j6.task_groups[0])


def _evict_ctx():
    state = StateStore()
    plan = Plan(node_update={}, node_allocation={})
    return EvalContext(state, plan, logger)


def _tuples(n):
    return [
        AllocTuple(
            name=f"a[{i}]",
            task_group=None,
            alloc=Allocation(id=generate_uuid(), node_id=f"n{i}"),
        )
        for i in range(n)
    ]


def test_evict_and_place_limit_less_than_allocs():
    """util_test.go:358-380"""
    ctx = _evict_ctx()
    allocs = _tuples(4)
    diff = DiffResult()
    limit = [2]
    assert evict_and_place(ctx, diff, allocs, "", limit)
    assert limit[0] == 0
    assert len(diff.place) == 2
    assert len(ctx.plan.node_update) == 2


def test_evict_and_place_limit_equal_to_allocs():
    """util_test.go:382-404"""
    ctx = _evict_ctx()
    allocs = _tuples(2)
    diff = DiffResult()
    limit = [2]
    assert not evict_and_place(ctx, diff, allocs, "", limit)
    assert limit[0] == 0
    assert len(diff.place) == 2


def test_evict_and_place_limit_greater_than_allocs():
    """util_test.go:578-600"""
    ctx = _evict_ctx()
    allocs = _tuples(2)
    diff = DiffResult()
    limit = [4]
    assert not evict_and_place(ctx, diff, allocs, "", limit)
    assert limit[0] == 2
    assert len(diff.place) == 2


class _RecordingPlanner:
    def __init__(self):
        self.evals = []

    def update_eval(self, ev):
        self.evals.append(ev)


def test_set_status():
    """util_test.go:406-439"""
    planner = _RecordingPlanner()
    ev = mock.evaluation()
    set_status(logger, planner, ev, None, structs.EVAL_STATUS_COMPLETE, "")
    assert len(planner.evals) == 1
    assert planner.evals[0].status == structs.EVAL_STATUS_COMPLETE
    assert planner.evals[0] is not ev  # must be a copy

    planner2 = _RecordingPlanner()
    next_eval = mock.evaluation()
    set_status(logger, planner2, ev, next_eval, structs.EVAL_STATUS_FAILED, "oops")
    out = planner2.evals[0]
    assert out.status == structs.EVAL_STATUS_FAILED
    assert out.status_description == "oops"
    assert out.next_eval == next_eval.id


def _inplace_fixture(change=None):
    state = StateStore()
    node = mock.node()
    state.upsert_node(900, node)

    job = mock.job()
    alloc = mock.alloc()
    alloc.job = job
    alloc.job_id = job.id
    alloc.node_id = node.id
    alloc.name = "my-job.web[0]"
    state.upsert_allocs(1000, [alloc])

    job2 = mock.job()
    job2.id = job.id
    if change:
        change(job2)

    ev = Evaluation(id=generate_uuid(), priority=50, job_id=job.id)
    plan = ev.make_plan(job2)
    ctx = EvalContext(state, plan, logger)
    stack = GenericStack(False, ctx)
    stack.set_job(job2)
    updates = [AllocTuple(name=alloc.name, task_group=job2.task_groups[0], alloc=alloc)]
    return ctx, ev, job2, stack, updates


def test_inplace_update_changed_task_group():
    """util_test.go:441-485: destructive change cannot be in-place."""
    ctx, ev, job2, stack, updates = _inplace_fixture(
        change=lambda j: j.task_groups[0].tasks[0].config.update(command="/bin/other")
    )
    remaining = inplace_update(ctx, ev, job2, stack, updates)
    assert len(remaining) == 1
    assert ctx.plan.node_allocation == {}


def test_inplace_update_no_match():
    """util_test.go:487-530: resources exceed the node -> no in-place."""

    def grow(j):
        j.task_groups[0].tasks[0].resources = Resources(cpu=1 << 20, memory_mb=1 << 20)

    ctx, ev, job2, stack, updates = _inplace_fixture(change=grow)
    remaining = inplace_update(ctx, ev, job2, stack, updates)
    assert len(remaining) == 1
    assert ctx.plan.node_allocation == {}


def test_inplace_update_success():
    """util_test.go:532-576"""
    ctx, ev, job2, stack, updates = _inplace_fixture()
    remaining = inplace_update(ctx, ev, job2, stack, updates)
    assert remaining == []
    # The plan has the updated alloc, evictions popped
    assert len(ctx.plan.node_allocation) == 1
    assert ctx.plan.node_update == {}
    placed = list(ctx.plan.node_allocation.values())[0][0]
    assert placed.eval_id == ev.id
    assert placed.job is job2


def test_task_group_constraints():
    """util_test.go:602-650"""
    job = mock.job()
    tg = job.task_groups[0]
    tup = task_group_constraints(tg)
    assert tup.drivers == {"exec"}
    assert tup.size.cpu == 500
    assert tup.size.memory_mb == 256
    assert len(tup.constraints) == len(tg.constraints) + sum(
        len(t.constraints) for t in tg.tasks
    )
