"""Coalescing solve engine: concurrent evals stack into one vmapped
dispatch (the device half of the broker's coalescing dequeue,
SURVEY.md §7 'Batched evals'; concurrency semantics mirror the
reference's optimistic worker parallelism, nomad/worker.go:45-125)."""

import threading

import jax.numpy as jnp
import numpy as np
import pytest

from nomad_tpu.ops.binpack import solve_waterfill
from nomad_tpu.ops.coalesce import CoalescingSolver

N = 64


def _inputs(ask_cpu, count):
    total = np.zeros((N, 4), dtype=np.int32)
    total[:, 0] = 4000
    total[:, 1] = 8192
    total[:, 2] = 100 * 1024
    total[:, 3] = 150
    return dict(
        total=jnp.asarray(total),
        sched_cap=jnp.asarray(total[:, :2].astype(np.float32)),
        used0=jnp.zeros((N, 4), dtype=jnp.int32),
        job_count0=jnp.zeros((N,), dtype=jnp.int32),
        tg_count0=jnp.zeros((N,), dtype=jnp.int32),
        bw_avail=jnp.full((N,), 1000, dtype=jnp.int32),
        bw_used0=jnp.zeros((N,), dtype=jnp.int32),
        eligible=jnp.ones((N,), dtype=bool),
        ask=jnp.array([ask_cpu, 128, 0, 0], dtype=jnp.int32),
        bw_ask=jnp.int32(0),
        count=count,
        penalty=10.0,
    )


def _direct(inp):
    counts, remaining = solve_waterfill(
        inp["total"], inp["sched_cap"], inp["used0"], inp["job_count0"],
        inp["tg_count0"], inp["bw_avail"], inp["bw_used0"], inp["eligible"],
        inp["ask"], inp["bw_ask"], jnp.int32(inp["count"]),
        jnp.float32(inp["penalty"]), False, False,
    )
    return np.asarray(counts), int(remaining)


def _submit(engine, inp):
    return engine.submit(
        inp["total"], inp["sched_cap"], inp["used0"], inp["job_count0"],
        inp["tg_count0"], inp["bw_avail"], inp["bw_used0"], inp["eligible"],
        inp["ask"], inp["bw_ask"], inp["count"], inp["penalty"],
    )


def test_single_submission_matches_direct():
    engine = CoalescingSolver()
    inp = _inputs(100, 500)
    counts, unplaced = _submit(engine, inp)()
    d_counts, d_unplaced = _direct(inp)
    assert unplaced == d_unplaced
    np.testing.assert_array_equal(counts, d_counts)


def test_concurrent_submissions_coalesce_and_match():
    """K threads submitting while the dispatcher is busy coalesce into
    vmapped dispatches; every result matches its individual solve."""
    engine = CoalescingSolver()
    specs = [(50 + 10 * i, 200 + 37 * i) for i in range(12)]
    inputs = [_inputs(c, n) for c, n in specs]
    results = [None] * len(inputs)
    errors = []

    def worker(i):
        try:
            results[i] = _submit(engine, inputs[i])()
        except Exception as e:  # pragma: no cover
            errors.append(e)

    threads = [
        threading.Thread(target=worker, args=(i,))
        for i in range(len(inputs))
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert not errors
    for i, inp in enumerate(inputs):
        counts, unplaced = results[i]
        d_counts, d_unplaced = _direct(inp)
        assert unplaced == d_unplaced, i
        np.testing.assert_array_equal(counts, d_counts, err_msg=f"eval {i}")
    # With 12 concurrent submissions at least some must have coalesced
    assert engine.dispatches >= 1
    assert engine.dispatches + engine.coalesced >= len(inputs)


def test_mixed_shapes_group_separately():
    """Different padded node counts can't share a program: they dispatch
    as separate groups but all complete correctly."""
    engine = CoalescingSolver()
    inp_a = _inputs(100, 100)

    total_b = np.zeros((128, 4), dtype=np.int32)
    total_b[:, 0] = 2000
    total_b[:, 1] = 4096
    inp_b = dict(
        total=jnp.asarray(total_b),
        sched_cap=jnp.asarray(total_b[:, :2].astype(np.float32)),
        used0=jnp.zeros((128, 4), dtype=jnp.int32),
        job_count0=jnp.zeros((128,), dtype=jnp.int32),
        tg_count0=jnp.zeros((128,), dtype=jnp.int32),
        bw_avail=jnp.full((128,), 1000, dtype=jnp.int32),
        bw_used0=jnp.zeros((128,), dtype=jnp.int32),
        eligible=jnp.ones((128,), dtype=bool),
        ask=jnp.array([100, 64, 0, 0], dtype=jnp.int32),
        bw_ask=jnp.int32(0),
        count=50,
        penalty=5.0,
    )

    fetches = [_submit(engine, inp_a), _submit(engine, inp_b)]
    (ca, ua), (cb, ub) = fetches[0](), fetches[1]()
    da, dua = _direct(inp_a)
    np.testing.assert_array_equal(ca, da)
    assert ua == dua
    assert cb.shape == (128,)
    assert int(cb.sum()) + ub == 50


def _entries(inputs):
    from nomad_tpu.ops.coalesce import _Entry

    return [
        _Entry((
            inp["total"], inp["sched_cap"], inp["used0"], inp["job_count0"],
            inp["tg_count0"], inp["bw_avail"], inp["bw_used0"],
            inp["eligible"], inp["ask"], inp["bw_ask"], inp["count"],
            inp["penalty"], False, False,
        ))
        for inp in inputs
    ]


def test_batch_failure_falls_open_to_individual_solves(monkeypatch):
    """A batch-level dispatch error retries each entry individually; the
    fallback results carry the leading batch axis so fetch() returns the
    full [N] counts vector, matching the direct solve."""
    from nomad_tpu.ops import coalesce

    engine = CoalescingSolver()
    inputs = [_inputs(100, 100), _inputs(120, 200)]
    entries = _entries(inputs)

    def boom(*args, **kwargs):
        raise RuntimeError("batched program failed")

    monkeypatch.setattr(coalesce, "solve_waterfill_batched", boom)
    engine._dispatch(entries)
    for entry, inp in zip(entries, inputs):
        counts, unplaced = entry.result()
        d_counts, d_unplaced = _direct(inp)
        assert counts.shape == d_counts.shape
        np.testing.assert_array_equal(counts, d_counts)
        assert unplaced == d_unplaced


def test_total_failure_raises_instead_of_hanging(monkeypatch):
    """If the per-entry retry also fails, waiters get the exception through
    the real submit() fetch path — not a hang or an AttributeError on a
    never-set group."""
    from nomad_tpu.ops import coalesce

    engine = CoalescingSolver()

    def boom(*args, **kwargs):
        raise ValueError("device is gone")

    monkeypatch.setattr(coalesce, "solve_waterfill_batched", boom)
    monkeypatch.setattr(coalesce, "solve_waterfill", boom)
    fetches = [
        _submit(engine, _inputs(100, 100)), _submit(engine, _inputs(120, 200))
    ]
    for fetch in fetches:
        with pytest.raises(RuntimeError, match="coalesced solve failed") as ei:
            fetch()
        assert isinstance(ei.value.__cause__, ValueError)


def test_post_proof_fault_disables_pallas(monkeypatch):
    """Once a shape is proven, dispatches skip the synchronous prove, so an
    async device fault surfaces at the result fetch. A pallas-provenance
    group must route that fault through the fallback (disabling the kernel
    for the process); a jnp-provenance group must not."""
    from nomad_tpu.ops import coalesce, pallas_solve

    pallas_solve.reset_pallas_failed()

    def boom(_):
        raise RuntimeError("async mosaic fault")

    monkeypatch.setattr(coalesce.jax, "device_get", boom)

    g = coalesce._Group("counts", "remaining", from_pallas=True)
    with pytest.raises(RuntimeError):
        g.fetch(0)
    assert pallas_solve._STATE["failed"], (
        "post-proof pallas fault did not disable the kernel"
    )

    pallas_solve.reset_pallas_failed()
    g2 = coalesce._Group("counts", "remaining", from_pallas=False)
    with pytest.raises(RuntimeError):
        g2.fetch(0)
    assert not pallas_solve._STATE["failed"], (
        "jnp-path fault wrongly disabled the pallas kernel"
    )
