"""Coalescing solve engine: concurrent evals stack into one vmapped
dispatch (the device half of the broker's coalescing dequeue,
SURVEY.md §7 'Batched evals'; concurrency semantics mirror the
reference's optimistic worker parallelism, nomad/worker.go:45-125)."""

import threading

import jax.numpy as jnp
import numpy as np
import pytest

from nomad_tpu.ops.binpack import solve_waterfill
from nomad_tpu.ops.coalesce import CoalescingSolver

N = 64


def _inputs(ask_cpu, count):
    total = np.zeros((N, 4), dtype=np.int32)
    total[:, 0] = 4000
    total[:, 1] = 8192
    total[:, 2] = 100 * 1024
    total[:, 3] = 150
    return dict(
        total=jnp.asarray(total),
        sched_cap=jnp.asarray(total[:, :2].astype(np.float32)),
        used0=jnp.zeros((N, 4), dtype=jnp.int32),
        job_count0=jnp.zeros((N,), dtype=jnp.int32),
        tg_count0=jnp.zeros((N,), dtype=jnp.int32),
        bw_avail=jnp.full((N,), 1000, dtype=jnp.int32),
        bw_used0=jnp.zeros((N,), dtype=jnp.int32),
        eligible=jnp.ones((N,), dtype=bool),
        ask=jnp.array([ask_cpu, 128, 0, 0], dtype=jnp.int32),
        bw_ask=jnp.int32(0),
        count=count,
        penalty=10.0,
    )


def _direct(inp):
    counts, remaining = solve_waterfill(
        inp["total"], inp["sched_cap"], inp["used0"], inp["job_count0"],
        inp["tg_count0"], inp["bw_avail"], inp["bw_used0"], inp["eligible"],
        inp["ask"], inp["bw_ask"], jnp.int32(inp["count"]),
        jnp.float32(inp["penalty"]), False, False,
    )
    return np.asarray(counts), int(remaining)


def _submit(engine, inp):
    return engine.submit(
        inp["total"], inp["sched_cap"], inp["used0"], inp["job_count0"],
        inp["tg_count0"], inp["bw_avail"], inp["bw_used0"], inp["eligible"],
        inp["ask"], inp["bw_ask"], inp["count"], inp["penalty"],
    )


def test_single_submission_matches_direct():
    engine = CoalescingSolver()
    inp = _inputs(100, 500)
    counts, unplaced = _submit(engine, inp)()
    d_counts, d_unplaced = _direct(inp)
    assert unplaced == d_unplaced
    np.testing.assert_array_equal(counts, d_counts)


def test_concurrent_submissions_coalesce_and_match():
    """K threads submitting while the dispatcher is busy coalesce into
    vmapped dispatches; every result matches its individual solve."""
    engine = CoalescingSolver()
    specs = [(50 + 10 * i, 200 + 37 * i) for i in range(12)]
    inputs = [_inputs(c, n) for c, n in specs]
    results = [None] * len(inputs)
    errors = []

    def worker(i):
        try:
            results[i] = _submit(engine, inputs[i])()
        except Exception as e:  # pragma: no cover
            errors.append(e)

    threads = [
        threading.Thread(target=worker, args=(i,))
        for i in range(len(inputs))
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert not errors
    for i, inp in enumerate(inputs):
        counts, unplaced = results[i]
        d_counts, d_unplaced = _direct(inp)
        assert unplaced == d_unplaced, i
        np.testing.assert_array_equal(counts, d_counts, err_msg=f"eval {i}")
    # With 12 concurrent submissions at least some must have coalesced
    assert engine.dispatches >= 1
    assert engine.dispatches + engine.coalesced >= len(inputs)


def test_mixed_shapes_group_separately():
    """Different padded node counts can't share a program: they dispatch
    as separate groups but all complete correctly."""
    engine = CoalescingSolver()
    inp_a = _inputs(100, 100)

    total_b = np.zeros((128, 4), dtype=np.int32)
    total_b[:, 0] = 2000
    total_b[:, 1] = 4096
    inp_b = dict(
        total=jnp.asarray(total_b),
        sched_cap=jnp.asarray(total_b[:, :2].astype(np.float32)),
        used0=jnp.zeros((128, 4), dtype=jnp.int32),
        job_count0=jnp.zeros((128,), dtype=jnp.int32),
        tg_count0=jnp.zeros((128,), dtype=jnp.int32),
        bw_avail=jnp.full((128,), 1000, dtype=jnp.int32),
        bw_used0=jnp.zeros((128,), dtype=jnp.int32),
        eligible=jnp.ones((128,), dtype=bool),
        ask=jnp.array([100, 64, 0, 0], dtype=jnp.int32),
        bw_ask=jnp.int32(0),
        count=50,
        penalty=5.0,
    )

    fetches = [_submit(engine, inp_a), _submit(engine, inp_b)]
    (ca, ua), (cb, ub) = fetches[0](), fetches[1]()
    da, dua = _direct(inp_a)
    np.testing.assert_array_equal(ca, da)
    assert ua == dua
    assert cb.shape == (128,)
    assert int(cb.sum()) + ub == 50


def _entries(inputs):
    from nomad_tpu.ops.coalesce import _Entry

    return [
        _Entry((
            inp["total"], inp["sched_cap"], inp["used0"], inp["job_count0"],
            inp["tg_count0"], inp["bw_avail"], inp["bw_used0"],
            inp["eligible"], inp["ask"], inp["bw_ask"], inp["count"],
            inp["penalty"], False, False,
        ))
        for inp in inputs
    ]


def test_batch_failure_falls_open_to_individual_solves(monkeypatch):
    """A batch-level dispatch error retries each entry individually; the
    fallback results carry the leading batch axis so fetch() returns the
    full [N] counts vector, matching the direct solve."""
    from nomad_tpu.ops import coalesce

    engine = CoalescingSolver()
    inputs = [_inputs(100, 100), _inputs(120, 200)]
    entries = _entries(inputs)

    def boom(*args, **kwargs):
        raise RuntimeError("batched program failed")

    monkeypatch.setattr(coalesce, "solve_waterfill_batched", boom)
    engine._dispatch(entries)
    for entry, inp in zip(entries, inputs):
        counts, unplaced = entry.result()
        d_counts, d_unplaced = _direct(inp)
        assert counts.shape == d_counts.shape
        np.testing.assert_array_equal(counts, d_counts)
        assert unplaced == d_unplaced


def test_total_failure_raises_instead_of_hanging(monkeypatch):
    """If the per-entry retry also fails, waiters get the exception through
    the real submit() fetch path — not a hang or an AttributeError on a
    never-set group."""
    from nomad_tpu.ops import coalesce

    engine = CoalescingSolver()

    def boom(*args, **kwargs):
        raise ValueError("device is gone")

    monkeypatch.setattr(coalesce, "solve_waterfill_batched", boom)
    monkeypatch.setattr(coalesce, "solve_waterfill", boom)
    fetches = [
        _submit(engine, _inputs(100, 100)), _submit(engine, _inputs(120, 200))
    ]
    for fetch in fetches:
        with pytest.raises(RuntimeError, match="coalesced solve failed") as ei:
            fetch()
        assert isinstance(ei.value.__cause__, ValueError)


def test_post_proof_fault_disables_pallas(monkeypatch):
    """Once a shape is proven, dispatches skip the synchronous prove, so an
    async device fault surfaces at the result fetch. A pallas-provenance
    group must route that fault through the fallback (disabling the kernel
    for the process); a jnp-provenance group must not."""
    from nomad_tpu.ops import coalesce, pallas_solve

    pallas_solve.reset_pallas_failed()

    def boom(_):
        raise RuntimeError("async mosaic fault")

    monkeypatch.setattr(coalesce.jax, "device_get", boom)

    g = coalesce._Group("counts", "remaining", from_pallas=True)
    with pytest.raises(RuntimeError):
        g.fetch(0)
    assert pallas_solve._STATE["failed"], (
        "post-proof pallas fault did not disable the kernel"
    )

    pallas_solve.reset_pallas_failed()
    g2 = coalesce._Group("counts", "remaining", from_pallas=False)
    with pytest.raises(RuntimeError):
        g2.fetch(0)
    assert not pallas_solve._STATE["failed"], (
        "jnp-path fault wrongly disabled the pallas kernel"
    )


def test_hint_burst_holds_dispatch_for_full_burst():
    """An announced burst stacks into ONE dispatch even when the submits
    arrive staggered (the batch-worker posture: K eval threads' host prep
    lands their solves a few ms apart)."""
    import time

    engine = CoalescingSolver()
    # Warm the dispatcher thread + compile both shapes outside the burst.
    _submit(engine, _inputs(50, 100))()
    engine.hint_burst(4, window_s=2.0, gap_s=1.0)
    d0 = engine.dispatches
    inputs = [_inputs(50 + 10 * i, 100 + 17 * i) for i in range(4)]
    fetches = []
    for i, inp in enumerate(inputs):
        # Each submit plays one announced eval thread (burst_begin re-arms
        # the thread-local membership between sequential submits).
        engine.burst_begin()
        fetches.append(_submit(engine, inp))
        time.sleep(0.01)  # staggered, but within the inter-arrival gap
    results = [f() for f in fetches]
    assert engine.dispatches == d0 + 1, "burst must land as one dispatch"
    for inp, (counts, unplaced) in zip(inputs, results):
        d_counts, d_unplaced = _direct(inp)
        assert unplaced == d_unplaced
        np.testing.assert_array_equal(counts, d_counts)


def test_hint_burst_expires_without_full_burst():
    """An expectation that never fills (announced evals that submit no
    solve) costs at most the window: the partial burst dispatches at the
    deadline and later lone submits don't inherit any wait."""
    import time

    engine = CoalescingSolver()
    _submit(engine, _inputs(50, 100))()
    engine.hint_burst(8, window_s=0.1)
    t0 = time.monotonic()
    counts, unplaced = _submit(engine, _inputs(60, 120))()
    waited = time.monotonic() - t0
    # At most the hard window plus solve time + margin — the documented
    # cost ceiling of an expectation that never fills.
    assert waited < 0.5
    d_counts, d_unplaced = _direct(_inputs(60, 120))
    assert unplaced == d_unplaced
    np.testing.assert_array_equal(counts, d_counts)
    # Residual expectation cleared: a lone submit returns promptly.
    t0 = time.monotonic()
    _submit(engine, _inputs(70, 130))()
    assert time.monotonic() - t0 < 0.09


def test_hint_burst_dead_residue_does_not_stack():
    """A burst whose evals never submit ANY solve leaves its expectation
    behind (the dispatcher is parked on an empty queue and can't clear
    it); the next hint must replace the dead residue, not stack on it."""
    import time

    engine = CoalescingSolver()
    engine.hint_burst(8, window_s=0.01)
    time.sleep(0.03)  # deadline passes with zero submits
    engine.hint_burst(2, window_s=1.0, gap_s=1.0)
    with engine._lock:
        assert engine._burst_outstanding == 2
    d0 = engine.dispatches
    engine.burst_begin()
    f1 = _submit(engine, _inputs(50, 100))
    engine.burst_begin()
    f2 = _submit(engine, _inputs(60, 110))
    f1(), f2()
    assert engine.dispatches == d0 + 1


def test_burst_done_releases_hold_without_submits():
    """Announced evals that finish WITHOUT ever reaching the coalescer
    (exact-path small counts, scale-downs) resolve their slots via
    burst_done: the hold releases the moment the last one reports, not
    at the give-up gap or window."""
    import time

    engine = CoalescingSolver()
    _submit(engine, _inputs(50, 100))()
    # Gap and window far beyond the assertion bound: only precise
    # accounting can release the hold this fast.
    engine.hint_burst(3, window_s=30.0, gap_s=30.0)
    d0 = engine.dispatches
    engine.burst_begin()
    fetch = _submit(engine, _inputs(60, 120))  # member 1: real solve
    for _ in range(2):  # members 2, 3: no solve, completion resolves
        engine.burst_begin()
        engine.burst_done()
    t0 = time.monotonic()
    counts, unplaced = fetch()
    assert time.monotonic() - t0 < 5.0
    assert engine.dispatches == d0 + 1
    d_counts, d_unplaced = _direct(_inputs(60, 120))
    assert unplaced == d_unplaced
    np.testing.assert_array_equal(counts, d_counts)


def test_dispatcher_survives_unexpected_batch_error(monkeypatch):
    """A failure OUTSIDE the per-chunk fail-open (a bug in grouping, an
    allocation failure) must fail that batch's waiters and leave the
    dispatcher loop alive for subsequent submits — a dead dispatcher
    parks every future eval forever."""
    engine = CoalescingSolver()

    orig = engine._dispatch
    calls = {"n": 0}

    def boom_once(batch):
        calls["n"] += 1
        if calls["n"] == 1:
            raise MemoryError("unexpected batch-level failure")
        return orig(batch)

    monkeypatch.setattr(engine, "_dispatch", boom_once)
    with pytest.raises(RuntimeError) as ei:
        _submit(engine, _inputs(100, 200))()
    assert isinstance(ei.value.__cause__, MemoryError)
    # Loop survived: the next submit dispatches normally.
    counts, unplaced = _submit(engine, _inputs(110, 210))()
    d_counts, d_unplaced = _direct(_inputs(110, 210))
    np.testing.assert_array_equal(counts, d_counts)
    assert unplaced == d_unplaced


def _submit_exact(engine, inp):
    return engine.submit_exact(
        inp["total"], inp["sched_cap"], inp["used0"], inp["job_count0"],
        inp["tg_count0"], inp["bw_avail"], inp["bw_used0"], inp["eligible"],
        inp["ask"], inp["bw_ask"], inp["count"], inp["penalty"],
    )


def _direct_exact(inp):
    from nomad_tpu.ops.binpack import bucket, solve_greedy

    k = bucket(inp["count"])
    active = jnp.arange(k) < inp["count"]
    idxs, oks, _ = solve_greedy(
        inp["total"], inp["sched_cap"], inp["used0"], inp["job_count0"],
        inp["tg_count0"], inp["bw_avail"], inp["bw_used0"], inp["eligible"],
        inp["ask"], inp["bw_ask"], active, jnp.float32(inp["penalty"]),
        k, False, False,
    )
    return (np.asarray(idxs)[: inp["count"]],
            np.asarray(oks)[: inp["count"]])


def test_exact_submissions_stack_into_one_dispatch():
    """Announced-burst exact solves of one (node, count-bucket) shape
    stack into ONE solve_greedy_batched dispatch, each row bit-equal to
    its lone dispatch; the solver panel's batch-width axis records the
    stacked width."""
    from nomad_tpu.tpu.solver import SOLVER_PANEL

    engine = CoalescingSolver()
    # Warm the dispatcher + both shapes outside the burst.
    _submit_exact(engine, _inputs(50, 40))()
    engine.hint_burst(4, window_s=2.0, gap_s=1.0)
    d0 = engine.dispatches
    with SOLVER_PANEL._lock:
        w0 = dict(
            (w, list(v)) for w, v in SOLVER_PANEL._batch_widths.items()
        )
    # Counts 33..48 share the 64 bucket; asks differ per entry. One
    # SHARED set of node tensors across the burst (the production shape:
    # burst members solve against one mirror) — stacking is keyed on
    # mirror identity.
    base = _inputs(60, 33)
    inputs = []
    for i in range(4):
        inp = dict(base)
        inp["ask"] = jnp.array([60 + 10 * i, 128, 0, 0], dtype=jnp.int32)
        inp["count"] = 33 + 5 * i
        inputs.append(inp)
    fetches = []
    for inp in inputs:
        engine.burst_begin()
        fetches.append(_submit_exact(engine, inp))
    results = [f() for f in fetches]
    assert engine.dispatches == d0 + 1, "burst must land as one dispatch"
    for inp, (idxs, oks) in zip(inputs, results):
        d_idxs, d_oks = _direct_exact(inp)
        np.testing.assert_array_equal(idxs, d_idxs)
        np.testing.assert_array_equal(oks, d_oks)
    with SOLVER_PANEL._lock:
        row = SOLVER_PANEL._batch_widths.get(4)
        prev = w0.get(4, [0, 0, 0.0])
    assert row is not None and row[0] >= prev[0] + 1, (
        "width-4 dispatch not recorded on the panel's batch-width axis"
    )


def test_exact_and_waterfill_entries_never_share_a_dispatch():
    """Mixed-kind pending entries group by program family: a wf entry
    and an exact entry in one drain dispatch separately, both correct."""
    from nomad_tpu.ops.coalesce import _Entry

    engine = CoalescingSolver()
    wf_inp = _inputs(100, 300)
    ex_inp = _inputs(80, 50)
    entries = _entries([wf_inp])
    from nomad_tpu.ops.binpack import bucket

    entries.append(_Entry((
        ex_inp["total"], ex_inp["sched_cap"], ex_inp["used0"],
        ex_inp["job_count0"], ex_inp["tg_count0"], ex_inp["bw_avail"],
        ex_inp["bw_used0"], ex_inp["eligible"], ex_inp["ask"],
        ex_inp["bw_ask"], ex_inp["count"], ex_inp["penalty"],
        False, False,
    ), kind="exact", k=bucket(ex_inp["count"])))
    d0 = engine.dispatches
    engine._dispatch(entries)
    assert engine.dispatches == d0 + 2
    counts, unplaced = entries[0].result()
    d_counts, d_unplaced = _direct(wf_inp)
    np.testing.assert_array_equal(counts, d_counts)
    assert unplaced == d_unplaced
    idxs, oks = entries[1].result()
    d_idxs, d_oks = _direct_exact(ex_inp)
    np.testing.assert_array_equal(np.asarray(idxs)[: ex_inp["count"]],
                                  d_idxs)
    np.testing.assert_array_equal(np.asarray(oks)[: ex_inp["count"]],
                                  d_oks)


def test_warm_exact_batch_shapes_compiles():
    from nomad_tpu.ops.coalesce import warm_exact_batch_shapes

    # 2 count buckets x 3 widths at one node bucket.
    assert warm_exact_batch_shapes(64, counts=(8, 16)) == 6


def test_burst_generation_scopes_accounting():
    """A straggler from an earlier (given-up or over-announced) burst
    must not decrement a successor burst's expectation — member
    accounting is scoped by the generation token hint_burst returns."""
    import time

    engine = CoalescingSolver()
    tok_a = engine.hint_burst(2, window_s=0.01)
    time.sleep(0.03)  # burst A's window passes unresolved
    tok_b = engine.hint_burst(2, window_s=5.0, gap_s=5.0)
    assert tok_b != tok_a
    # Straggler member of burst A reports done AFTER B was announced:
    engine.burst_begin(tok_a)
    engine.burst_done()
    with engine._lock:
        assert engine._burst_outstanding == 2, (
            "stale-generation burst_done must not release B's hold"
        )
    # B's own members resolve it normally.
    for _ in range(2):
        engine.burst_begin(tok_b)
        engine.burst_done()
    with engine._lock:
        assert engine._burst_outstanding == 0
