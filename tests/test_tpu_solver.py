"""TPU solver-specific tests: regressions and host/TPU differential checks."""

import random

import pytest

from nomad_tpu import mock, structs
from nomad_tpu.structs import (
    Allocation,
    Constraint,
    Evaluation,
    Resources,
    generate_uuid,
)

from sched_harness import Harness, flatten


def _eval_for(job):
    return Evaluation(
        id=generate_uuid(),
        priority=job.priority,
        triggered_by=structs.EVAL_TRIGGER_JOB_REGISTER,
        job_id=job.id,
    )


def test_job_level_distinct_hosts_spans_task_groups():
    """Job-level distinct_hosts must reject same-job allocs from *other* task
    groups (feasible.go:237-242). Regression for the dense solve collapsing
    both scopes into the tg check."""
    h = Harness()
    for _ in range(4):
        h.state.upsert_node(h.next_index(), mock.node())

    job = mock.job()
    job.constraints.append(Constraint(operand=structs.CONSTRAINT_DISTINCT_HOSTS))
    # Two task groups, count 2 each -> 4 placements, all on distinct hosts
    import copy

    tg2 = copy.deepcopy(job.task_groups[0])
    tg2.name = "api"
    job.task_groups[0].count = 2
    tg2.count = 2
    job.task_groups.append(tg2)
    h.state.upsert_job(h.next_index(), job)

    h.process("tpu-service", _eval_for(job))

    planned = flatten(h.plans[0].node_allocation)
    assert len(planned) == 4
    nodes_used = [a.node_id for a in planned]
    assert len(set(nodes_used)) == 4, f"job distinct_hosts violated: {nodes_used}"


def test_tpu_system_no_overcommit_same_node():
    """The batched system solve must not overcommit a node when several
    placements of one group are pinned to it."""
    import logging

    from nomad_tpu.scheduler.context import EvalContext
    from nomad_tpu.scheduler.util import AllocTuple
    from nomad_tpu.state import StateStore
    from nomad_tpu.tpu.solver import TPUSystemScheduler

    state = StateStore()
    node = mock.node()
    node.resources = Resources(cpu=1100, memory_mb=1024, disk_mb=50000, iops=100)
    node.reserved = None
    state.upsert_node(1, node)

    job = mock.system_job()
    job.task_groups[0].tasks[0].resources = Resources(cpu=500, memory_mb=256)
    state.upsert_job(2, job)

    class _Sink:
        def submit_plan(self, plan):
            raise AssertionError("not used")

        def update_eval(self, ev):
            pass

        def create_eval(self, ev):
            pass

    sched = TPUSystemScheduler(state.snapshot(), _Sink(), logging.getLogger("t"))
    sched.eval = _eval_for(job)
    sched.job = job
    sched.nodes = [node]
    sched.plan = sched.eval.make_plan(job)
    sched.ctx = EvalContext(sched.state, sched.plan, sched.logger)
    sched.stack = sched.make_stack(sched.ctx)
    sched.stack.set_job(job)

    # Three copies pinned to the same node; only 2x500 cpu fits in 1100.
    tg = job.task_groups[0]
    place = [
        AllocTuple(name=f"my-job.web[{i}]", task_group=tg,
                   alloc=Allocation(node_id=node.id))
        for i in range(3)
    ]
    sched.compute_placements(place)

    placed = flatten(sched.plan.node_allocation)
    assert len(placed) == 2, f"overcommitted: {len(placed)} placed"
    total_cpu = sum(a.resources.cpu for a in placed)
    assert total_cpu <= 1100


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_differential_host_vs_tpu(seed):
    """Fuzz: same cluster + job through both solvers must place the same
    number of allocs with valid packing (node identity may differ: the host
    samples ~log2(n) candidates, the TPU solves globally)."""
    rng = random.Random(seed)
    results = {}
    node_specs = [
        (rng.choice([1000, 2000, 4000]), rng.choice([1024, 4096, 8192]))
        for _ in range(12)
    ]
    count = rng.randint(5, 25)
    cpu_ask = rng.choice([100, 300, 500])
    mem_ask = rng.choice([64, 256, 512])

    for factory in ("service", "tpu-service"):
        h = Harness()
        nodes = []
        for cpu, mem in node_specs:
            node = mock.node()
            node.resources = Resources(
                cpu=cpu, memory_mb=mem, disk_mb=100 * 1024, iops=150,
                networks=node.resources.networks,
            )
            node.reserved = None
            nodes.append(node)
            h.state.upsert_node(h.next_index(), node)

        job = mock.job()
        job.task_groups[0].count = count
        job.task_groups[0].tasks[0].resources = Resources(
            cpu=cpu_ask, memory_mb=mem_ask
        )
        h.state.upsert_job(h.next_index(), job)
        h.process(factory, _eval_for(job))

        planned = flatten(h.plans[0].node_allocation)
        # Validate packing: per-node sums within capacity
        per_node = {}
        for a in planned:
            per_node[a.node_id] = per_node.get(a.node_id, 0) + a.resources.cpu
        caps = {n.id: n.resources.cpu for n in nodes}
        for node_id, used in per_node.items():
            assert used <= caps[node_id], f"{factory} overcommitted {node_id}"
        results[factory] = len(planned)

    # The TPU global solve must place at least as many as the sampled host.
    assert results["tpu-service"] >= results["service"], results


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_waterfill_matches_round_solver(seed):
    """solve_waterfill must reproduce solve_rounds_fused's per-node counts
    exactly on random heterogeneous instances (it is the closed form of the
    same semantics: L full rounds + one scored partial round)."""
    import jax.numpy as jnp
    import numpy as np

    from nomad_tpu.ops.binpack import solve_rounds_fused, solve_waterfill

    rng = random.Random(seed)
    n = 64
    total = np.zeros((n, 4), dtype=np.int32)
    for i in range(n):
        total[i] = [
            rng.choice([500, 1000, 2000, 4000]),
            rng.choice([512, 1024, 4096]),
            50_000,
            100,
        ]
    used0 = np.zeros((n, 4), dtype=np.int32)
    for i in range(n):
        if rng.random() < 0.3:
            used0[i, 0] = rng.randrange(0, total[i, 0])
            used0[i, 1] = rng.randrange(0, total[i, 1])
    job_count0 = np.array([rng.choice([0, 0, 0, 1, 2]) for _ in range(n)], np.int32)
    eligible = np.array([rng.random() < 0.9 for _ in range(n)])
    count = rng.choice([5, 40, 300, 2000])
    args = dict(
        total=jnp.asarray(total),
        sched_cap=jnp.asarray(total[:, :2].astype(np.float32)),
        used0=jnp.asarray(used0),
        job_count0=jnp.asarray(job_count0),
        tg_count0=jnp.asarray(job_count0),
        bw_avail=jnp.full((n,), 1000, jnp.int32),
        bw_used0=jnp.zeros((n,), jnp.int32),
        eligible=jnp.asarray(eligible),
        ask=jnp.asarray(np.array([100, 128, 0, 0], np.int32)),
        bw_ask=jnp.int32(0),
        count=jnp.int32(count),
        penalty=jnp.float32(5.0),
    )
    for job_distinct in (False, True):
        c1, r1 = solve_rounds_fused(
            *args.values(), job_distinct=job_distinct, tg_distinct=False
        )
        c2, r2 = solve_waterfill(
            *args.values(), job_distinct=job_distinct, tg_distinct=False
        )
        np.testing.assert_array_equal(np.asarray(c1), np.asarray(c2))
        assert int(r1) == int(r2)
