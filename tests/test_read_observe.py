"""Read-path observatory tests (nomad_tpu/read_observe.py): config
parse validation, the recorder's books, the blocking hold/serve stage
partition over a live agent, SSE session books surviving ring
truncation, the watch-registry wake economy, the uniform freshness
stamp on EVERY read route (structural route-table walk), and the
/v1/agent/reads + SDK surfaces."""

import json
import re
import threading
import time
import urllib.error
import urllib.request

import pytest

from nomad_tpu import mock
from nomad_tpu.agent import Agent, AgentConfig
from nomad_tpu.api import ApiClient, QueryOptions
from nomad_tpu.read_observe import (
    ReadObserveConfig,
    ReadObservatory,
    ReadRecorder,
)
from nomad_tpu.state.store import _Watch, item_table


@pytest.fixture(scope="module")
def agent(tmp_path_factory):
    config = AgentConfig.dev()
    config.data_dir = str(tmp_path_factory.mktemp("agent"))
    config.http_port = 0  # auto-assign
    config.scheduler_backend = "host"
    # Tiny event ring so an SSE resume cursor can actually fall off it
    # (the truncation-books test); every other test is ring-agnostic.
    config.event_buffer_size = 8
    a = Agent(config)
    a.start()
    yield a
    a.shutdown()


@pytest.fixture()
def client(agent):
    return ApiClient(address=agent.http.addr)


def _get(agent, path):
    """GET returning (status, headers, body-bytes) for ANY status —
    error responses carry headers too, and that is the point."""
    try:
        with urllib.request.urlopen(agent.http.addr + path,
                                    timeout=15) as resp:
            return resp.status, resp.headers, resp.read()
    except urllib.error.HTTPError as e:
        return e.code, e.headers, e.read()


def _register_job(agent, run_for="60"):
    job = mock.job()
    job.task_groups[0].tasks[0].driver = "mock_driver"
    job.task_groups[0].tasks[0].config = {"run_for": run_for,
                                          "exit_code": "0"}
    job.task_groups[0].tasks[0].resources.networks = []
    agent.server.job_register(job)
    return job


# -- config parse -------------------------------------------------------------


def test_config_parse_defaults_and_coercion():
    cfg = ReadObserveConfig.parse(None)
    assert cfg.enabled is True
    assert cfg.poll_interval == 1.0
    assert cfg.events_interval == 10.0

    cfg = ReadObserveConfig.parse(
        {"enabled": 1, "poll_interval": "0.5", "events_interval": 0}
    )
    assert cfg.enabled is True
    assert cfg.poll_interval == 0.5
    assert cfg.events_interval == 0.0


def test_config_parse_rejects_nonsense():
    with pytest.raises(ValueError, match="unknown reads config key"):
        ReadObserveConfig.parse({"pol_interval": 1.0})
    with pytest.raises(ValueError, match="must be a mapping"):
        ReadObserveConfig.parse("fast")
    with pytest.raises(ValueError, match="poll_interval must be > 0"):
        ReadObserveConfig.parse({"poll_interval": 0})
    with pytest.raises(ValueError, match="events_interval must be >= 0"):
        ReadObserveConfig.parse({"events_interval": -1})


def test_file_config_validates_reads_block(tmp_path):
    """Typos in server { reads { } } fail config LOAD, not first use."""
    from nomad_tpu.agent_config import load_config_file

    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps(
        {"server": {"enabled": True, "reads": {"pol_interval": 1}}}
    ))
    with pytest.raises(ValueError, match="unknown reads config key"):
        load_config_file(str(bad))

    good = tmp_path / "good.json"
    good.write_text(json.dumps(
        {"server": {"enabled": True,
                    "reads": {"poll_interval": 0.25, "enabled": True}}}
    ))
    cfg = load_config_file(str(good))
    assert cfg.server.reads == {"poll_interval": 0.25, "enabled": True}


# -- recorder books (unit) ----------------------------------------------------


def test_recorder_route_and_lane_books():
    rec = ReadRecorder()
    rec.record_request("/v1/jobs", "plain", 200, 0.010, 512)
    rec.record_request("/v1/jobs", "blocking", 200, 0.050, 256)
    rec.record_request("/v1/jobs", "plain", 404, 0.001, 9)
    snap = rec.snapshot()
    books = snap["endpoints"]["/v1/jobs"]
    assert books["count"] == 3
    assert books["errors"] == 1
    assert books["bytes_total"] == 512 + 256 + 9
    assert books["lanes"]["plain"] == 2
    assert books["lanes"]["blocking"] == 1
    assert books["lanes"]["sse"] == 0
    assert books["latency_ms"]["max"] == 50.0


def test_recorder_hold_serve_partition_reconciles():
    """serve = total − hold at record time, so the stage sums reconcile
    with the total by construction — including the clamped degenerate
    where a hold outlasts the measured total."""
    rec = ReadRecorder()
    rec.record_blocking("/v1/jobs", hold_s=0.8, total_s=1.0, woke=True)
    rec.record_blocking("/v1/jobs", hold_s=2.0, total_s=2.0, woke=False)
    rec.record_blocking("/v1/jobs", hold_s=0.5, total_s=0.4, woke=True)
    books = rec._blocking["/v1/jobs"]
    assert books.count == 3
    assert books.wakes == 2 and books.timeouts == 1
    assert books.hold.sum + books.serve.sum == pytest.approx(
        books.total.sum)
    assert min(books.serve.min, 0.0) == 0.0  # clamped, never negative
    snap = rec.snapshot()["blocking"]["/v1/jobs"]
    assert snap["wakes"] == 2 and snap["timeouts"] == 1
    assert snap["hold_ms"]["mean"] + snap["serve_ms"]["mean"] == (
        pytest.approx(snap["total_ms"]["mean"], abs=0.01))


def test_recorder_sse_books_count_truncation():
    """The Truncated frame is COUNTED, never absorbed into the ordinary
    frame books — a lagging tail that lost events must show as loss."""
    rec = ReadRecorder()
    rec.sse_session_start()
    rec.sse_delivered(5, lag_entries=2)
    rec.sse_truncated()
    rec.sse_delivered(3, lag_entries=0)
    rec.sse_heartbeat()
    rec.sse_session_end()
    sse = rec.snapshot()["sse"]
    assert sse["started"] == 1 and sse["active"] == 0
    assert sse["frames"] == 8
    assert sse["truncations"] == 1
    assert sse["heartbeats"] == 1
    assert sse["lag_entries"]["max"] == 2.0


# -- watch-registry wake economy (unit) ---------------------------------------


def test_watch_economy_counters():
    w = _Watch()
    t1 = w.register([item_table("jobs")])
    t2 = w.register([item_table("jobs")])
    t3 = w.register([item_table("nodes")])

    stats = w.stats()
    assert stats["watchers"] == 3
    assert sum(stats["bucket_watchers"]) == 3
    jobs_bucket = _Watch._bucket(item_table("jobs"))
    occupancy_before = stats["bucket_watchers"][jobs_bucket]
    assert occupancy_before >= 2  # both jobs watchers share the bucket

    # One publish touching jobs wakes every watcher parked on that
    # bucket — fan-out accounting, not per-ticket delivery.
    w.notify([item_table("jobs")])
    stats = w.stats()
    assert stats["notifies"] == 1
    assert stats["wakes_delivered"] == occupancy_before
    assert w.wait(t1, timeout=1.0) is True
    assert w.wait(t2, timeout=1.0) is True

    # Spurious wakes are caller-bumped plain counters (the registry
    # itself cannot know an index re-probe came up empty).
    w.spurious_wakes += 1
    assert w.stats()["spurious_wakes"] == 1

    for t in (t1, t2, t3):
        w.unregister(t)
    stats = w.stats()
    assert stats["watchers"] == 0
    assert sum(stats["bucket_watchers"]) == 0
    assert {"buckets", "multi_waiters", "peak_watchers",
            "rejected"} <= set(stats)


def test_observatory_watch_view_derivations():
    """buckets_occupied / bucket_max_watchers / wakes_per_notify derive
    from the plain counters; absent keys degrade to zeros."""
    view = ReadObservatory._watch_view({
        "watchers": 4, "notifies": 2, "wakes_delivered": 6,
        "bucket_watchers": [0, 3, 0, 1],
    })
    assert view["buckets_occupied"] == 2
    assert view["bucket_max_watchers"] == 3
    assert view["wakes_per_notify"] == 3.0
    empty = ReadObservatory._watch_view({})
    assert empty["wakes_per_notify"] == 0.0
    assert empty["buckets_occupied"] == 0


# -- live-agent: blocking partition, SSE, freshness ---------------------------


def test_blocking_hold_serve_partition_live(client, agent):
    """A woken blocking query and a timed-out one both land in the
    /v1/jobs blocking books, partitioned into hold (parked on the
    watch) vs serve (building the response) — and the outcome lanes
    plus stage means reconcile."""
    _, meta = client.jobs().list()
    # index=0 is the non-blocking list convention; on a virgin jobs
    # table park one index ahead so both lanes actually block.
    start_index = max(meta.last_index, 1)

    # Timeout lane: nothing writes during a short wait.
    client.jobs().list(QueryOptions(wait_index=start_index,
                                    wait_time="300ms"))

    # Wake lane: a jobs-table write lands mid-park.
    def blocked():
        client.jobs().list(QueryOptions(wait_index=start_index,
                                        wait_time="10s"))

    t = threading.Thread(target=blocked)
    t.start()
    time.sleep(0.3)
    _register_job(agent)
    t.join(timeout=10)
    assert not t.is_alive()

    reads = client.agent().reads()
    books = reads["blocking"]["/v1/jobs"]
    assert books["wakes"] >= 1
    assert books["timeouts"] >= 1
    assert books["count"] == books["wakes"] + books["timeouts"]
    # Means reconcile (same ingest count across the three series).
    assert books["hold_ms"]["mean"] + books["serve_ms"]["mean"] == (
        pytest.approx(books["total_ms"]["mean"], abs=0.02))
    # The timed-out query parked ~300ms; hold dominates serve.
    assert books["hold_ms"]["max"] >= 250.0
    assert books["total_ms"]["max"] >= books["hold_ms"]["max"]
    # Lane attribution rode the same requests.
    route = reads["endpoints"]["/v1/jobs"]
    assert route["lanes"]["blocking"] >= 2


def test_sse_session_books_survive_ring_truncation(client, agent):
    """With an 8-slot event ring, a resume cursor of 1 is off the ring
    once the cluster has published more than 8 events: the stream leads
    with a Truncated frame and the session books count it — alongside
    delivered frames, heartbeats, and the session open/close."""
    # Ensure the ring has wrapped: every register writes multiple events.
    for _ in range(4):
        _register_job(agent, run_for="0.1")
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        if agent.server.fsm.events.horizon() > 1:
            break
        time.sleep(0.05)
    else:
        pytest.fail("event ring never wrapped")

    before = client.agent().reads()["sse"]
    status, headers, body = _get(
        agent, "/v1/event/stream?format=sse&index=1&wait=0.5s")
    assert status == 200
    assert headers["Content-Type"].startswith("text/event-stream")
    # Freshness stamped on the stream preamble too.
    assert headers["X-Nomad-Applied-Index"] is not None
    text = body.decode()
    assert "event: Truncated" in text
    assert "data:" in text

    after = client.agent().reads()["sse"]
    assert after["truncations"] >= before["truncations"] + 1
    assert after["started"] >= before["started"] + 1
    assert after["frames"] > before["frames"]
    assert after["active"] == 0  # session closed out of the books
    # And the stream rode the sse lane in route attribution.
    route = client.agent().reads()["endpoints"]["/v1/event/stream"]
    assert route["lanes"]["sse"] >= 1
    assert route["bytes_total"] > 0


def test_freshness_headers_on_every_read_route(agent):
    """Structural: walk the live route TABLE — every route, including
    parameterized ones hit with junk ids (404s) and write-only routes
    answering GET with 405, carries the freshness stamp. A new route
    cannot dodge this test by not being listed anywhere."""
    assert len(agent.http.routes) >= 30
    walked = 0
    for pattern, template, _handler in agent.http.routes:
        path = re.sub(r"\(\?P<[^>]+>[^)]+\)", "x",
                      pattern.pattern).lstrip("^").rstrip("$")
        status, headers, _body = _get(agent, path)
        for header in ("X-Nomad-Applied-Index", "X-Nomad-Staleness",
                       "X-Nomad-KnownLeader"):
            assert headers[header] is not None, (
                f"{template} ({status}) missing {header}")
        assert int(headers["X-Nomad-Staleness"]) >= 0
        assert headers["X-Nomad-KnownLeader"] in ("true", "false")
        walked += 1
    assert walked == len(agent.http.routes)


def test_freshness_recorded_into_staleness_books(client):
    before = client.agent().reads()["freshness"]
    client.jobs().list()
    client.nodes().list()
    after = client.agent().reads()["freshness"]
    assert after["responses_stamped"] >= before["responses_stamped"] + 2
    assert after["applied_index"] >= 1
    assert after["commit_index"] >= after["applied_index"]
    assert "staleness_entries" in after
    assert after["staleness_entries"]["max"] >= 0.0


# -- surfaces -----------------------------------------------------------------


def test_agent_reads_endpoint_and_sdk(client, agent):
    client.jobs().list()  # ensure at least one plain read is booked
    reads = client.agent().reads()
    assert {"endpoints", "blocking", "sse", "freshness", "watch",
            "observer"} <= set(reads)
    jobs = reads["endpoints"]["/v1/jobs"]
    assert jobs["count"] >= 1
    assert {"p50", "p95", "p99"} <= set(jobs["latency_ms"])
    # Watch economy view present for both registries.
    for registry in ("state", "events"):
        view = reads["watch"][registry]
        assert "wakes_per_notify" in view
        assert "spurious_wakes" in view
        assert "buckets_occupied" in view

    status, headers, body = _get(agent,
                                 "/v1/agent/reads?format=prometheus")
    assert status == 200
    text = body.decode()
    assert "nomad_read_requests_total" in text
    assert "nomad_read_latency_ms" in text
    assert 'route="/v1/jobs"' in text


def test_main_scrape_and_metrics_json_carry_reads(agent, client):
    status, _headers, body = _get(agent,
                                  "/v1/agent/metrics?format=prometheus")
    assert status == 200
    assert "nomad_read_requests_total" in body.decode()

    metrics, _ = client.query("/v1/agent/metrics")
    summary = metrics["reads"]
    assert summary["requests"] >= 1
    assert "read_p95_ms_worst" in summary
    assert "staleness_p99_entries" in summary


def test_reads_disabled_404_but_headers_stay(tmp_path_factory):
    """reads { enabled = false } kills the books and the endpoint, but
    the freshness headers are a protocol feature and survive."""
    cfg = AgentConfig.dev()
    cfg.data_dir = str(tmp_path_factory.mktemp("reads-off"))
    cfg.http_port = 0
    cfg.scheduler_backend = "host"
    cfg.reads = {"enabled": False}
    a = Agent(cfg)
    a.start()
    try:
        status, headers, _body = _get(a, "/v1/agent/reads")
        assert status == 404
        assert headers["X-Nomad-Applied-Index"] is not None
        assert headers["X-Nomad-Staleness"] is not None
        # Plain reads still answer; nothing is recorded.
        status, headers, _body = _get(a, "/v1/jobs")
        assert status == 200
        assert headers["X-Nomad-Applied-Index"] is not None
        rec = a.server.read_observatory.recorder
        assert rec.snapshot()["endpoints"] == {}
    finally:
        a.shutdown()
