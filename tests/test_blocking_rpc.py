"""Generic RPC-tier blocking queries (server/blocking.py).

The reference's blockingRPC (/root/reference/nomad/rpc.go:270-335) is one
shared mechanism; here Node.GetAllocs, Eval.GetEval, and Job.GetJob all
ride it. Includes the snapshot-rebind race: a blocking query parked on a
store that a raft snapshot install replaces must wake and re-check against
the live store, not sleep out its timeout on the orphan.
"""

import threading
import time

import pytest

from nomad_tpu import mock, structs
from nomad_tpu.server import ServerConfig
from nomad_tpu.server.blocking import blocking_query
from nomad_tpu.server.cluster import ClusterConfig, ClusterServer, wait_for_leader
from nomad_tpu.structs import Evaluation, generate_uuid


@pytest.fixture
def srv():
    s = ClusterServer(
        ServerConfig(scheduler_backend="host", num_schedulers=1),
        ClusterConfig(node_id="blk-1"),
    )
    s.start()
    wait_for_leader([s])
    yield s
    s.shutdown()


def _call_async(fn, args):
    out = {}

    def run():
        out["result"] = fn(args)

    t = threading.Thread(target=run, daemon=True)
    t.start()
    return t, out


def test_node_get_allocs_blocks_until_write(srv):
    node = mock.node()
    srv.node_register(node)
    index0 = srv.state_store.get_index("allocs")

    t, out = _call_async(
        srv._rpc_node_get_allocs,
        {"node_id": node.id, "min_index": index0, "timeout": 8.0},
    )
    time.sleep(0.3)
    assert t.is_alive()  # parked, not polling out

    alloc = mock.alloc()
    alloc.node_id = node.id
    srv.raft.apply("alloc_update", {"allocs": [alloc]}).result()
    t.join(5.0)
    assert not t.is_alive()
    assert out["result"]["index"] > index0
    assert [a["id"] for a in out["result"]["allocs"]] == [alloc.id]


def test_eval_get_blocks_until_status_change(srv):
    ev = Evaluation(
        id=generate_uuid(), priority=50, type=structs.JOB_TYPE_SERVICE,
        triggered_by=structs.EVAL_TRIGGER_JOB_REGISTER, job_id="j1",
        status=structs.EVAL_STATUS_FAILED,
    )
    srv.raft.apply("eval_update", {"evals": [ev]}).result()
    first = srv._rpc_eval_get({"eval_id": ev.id, "min_index": 0})
    assert first["eval"]["status"] == structs.EVAL_STATUS_FAILED
    index0 = first["index"]

    t, out = _call_async(
        srv._rpc_eval_get,
        {"eval_id": ev.id, "min_index": index0, "timeout": 8.0},
    )
    time.sleep(0.3)
    assert t.is_alive()

    ev2 = ev.copy()
    ev2.status = structs.EVAL_STATUS_COMPLETE
    srv.raft.apply("eval_update", {"evals": [ev2]}).result()
    t.join(5.0)
    assert not t.is_alive()
    assert out["result"]["eval"]["status"] == structs.EVAL_STATUS_COMPLETE
    assert out["result"]["index"] > index0


def test_job_get_blocks_until_update(srv):
    job = mock.job()
    srv.job_register(job)
    first = srv._rpc_job_get({"job_id": job.id, "min_index": 0})
    index0 = first["index"]
    assert first["job"]["id"] == job.id

    t, out = _call_async(
        srv._rpc_job_get,
        {"job_id": job.id, "min_index": index0, "timeout": 8.0},
    )
    time.sleep(0.3)
    assert t.is_alive()

    import copy

    job2 = copy.deepcopy(job)
    job2.priority = 70
    srv.job_register(job2)
    t.join(5.0)
    assert not t.is_alive()
    assert out["result"]["job"]["priority"] == 70


def test_blocking_query_timeout_returns_last_read(srv):
    node = mock.node()
    srv.node_register(node)
    index0 = srv.state_store.get_index("allocs")
    t0 = time.monotonic()
    out = srv._rpc_node_get_allocs(
        {"node_id": node.id, "min_index": index0, "timeout": 0.4}
    )
    assert 0.3 <= time.monotonic() - t0 < 5.0
    assert out["allocs"] is None
    assert out["index"] == index0


def test_snapshot_rebind_race_wakes_parked_query(srv):
    """Park a blocking query, then install an FSM snapshot (rebinds
    fsm.state to a fresh store). The query must wake via the old store's
    notify_all and resolve against the NEW store's index."""
    node = mock.node()
    srv.node_register(node)

    # Build snapshot state that already contains an alloc for the node —
    # the new store's allocs index exceeds min_index, so after the rebind
    # the parked query resolves immediately with the new content.
    alloc = mock.alloc()
    alloc.node_id = node.id
    donor = ClusterServer(
        ServerConfig(scheduler_backend="host", num_schedulers=1),
        ClusterConfig(node_id="blk-donor"),
    )
    try:
        donor.start()
        wait_for_leader([donor])
        donor.node_register(node.copy())
        base = srv.state_store.get_index("allocs")
        # Push the donor's alloc index past the parked query's min_index.
        for i in range(int(base) + 1):
            donor.raft.apply(
                "alloc_update", {"allocs": [alloc.copy()]}
            ).result()
        data = donor.fsm.snapshot_bytes()

        min_index = srv.state_store.get_index("allocs")
        t, out = _call_async(
            srv._rpc_node_get_allocs,
            {"node_id": node.id, "min_index": min_index, "timeout": 8.0},
        )
        time.sleep(0.3)
        assert t.is_alive()

        srv.fsm.restore_bytes(data)  # rebind: old store orphaned
        t.join(5.0)
        assert not t.is_alive(), "query slept through the store rebind"
        assert out["result"]["allocs"] is not None
        assert [a["id"] for a in out["result"]["allocs"]] == [alloc.id]
    finally:
        donor.shutdown()


def test_blocking_query_helper_semantics():
    """Unit-level: a fresh index returns immediately, and the full query
    runs exactly once — the index probe, not the query, drives the wait
    decision (a query may materialize a large result)."""
    from nomad_tpu.state import StateStore

    store = StateStore()
    store.upsert_node(3, mock.node())
    runs = []
    index, result = blocking_query(
        get_store=lambda: store,
        items=lambda s: [("table", "nodes")],
        run=lambda s: runs.append(1) or (s.get_index("nodes"), "payload"),
        index_of=lambda s: s.get_index("nodes"),
        min_index=0,
        timeout=5.0,
    )
    assert (index, result) == (3, "payload")
    assert runs == [1]  # the expensive query ran exactly once
