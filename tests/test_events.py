"""Cluster event stream + operator debug bundle.

Unit tier: the broker's ordering contract (strictly increasing gapless
indices, even under concurrent FSM applies), bounded-buffer eviction with
the truncation marker, topic filtering, and the blocking-consumption path
(long-poll wake + timeout) over HTTP.

Chaos tier: a PR-2 seeded scenario (one-way leader partition mid-plan)
asserting the event log records exactly ONE PlanApplied per committed
plan, and a determinism check — two runs with the same fault seed produce
identical event-type sequences.

Bundle tier: /v1/agent/debug/bundle schema, secret redaction, and the
debug gate. Reference posture: nomad/stream/event_broker.go (Nomad 1.0
/v1/event/stream) + `nomad operator debug`.
"""

import json
import threading
import time
import urllib.request

import pytest

from nomad_tpu import events, faults, mock, structs
from nomad_tpu.events import EventBroker, TopicFilter
from nomad_tpu.server.fsm import FSM, InProcRaft


@pytest.fixture(autouse=True)
def _clean_registry():
    faults.get_registry().clear()
    yield
    faults.get_registry().clear()


# ---------------------------------------------------------------------------
# Broker: ordering, eviction, filtering
# ---------------------------------------------------------------------------


def test_index_monotonic_under_concurrent_fsm_applies():
    """Many threads racing raft applies: the per-FSM event log still has
    strictly increasing indices with no gaps or duplicates, and every
    event carries the raft index of the entry that produced it."""
    fsm = FSM()
    raft = InProcRaft(fsm)
    n_threads, n_each = 8, 40

    def pump():
        for _ in range(n_each):
            raft.apply("node_register", {"node": mock.node()})

    threads = [threading.Thread(target=pump) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()

    evs = fsm.events.all_events()
    assert len(evs) == n_threads * n_each
    assert [e.index for e in evs] == list(range(1, len(evs) + 1))
    # raft indices are monotonic too (publish happens under the apply
    # lock) and each event names the entry that produced it.
    raft_indices = [e.raft_index for e in evs]
    assert raft_indices == sorted(raft_indices)
    assert all(e.type == "NodeRegistered" for e in evs)


def test_bounded_eviction_and_truncation_marker():
    broker = EventBroker(capacity=16, register=False)
    for i in range(50):
        broker.publish("Node", "NodeRegistered", key=f"n{i}")
    assert broker.get_index() == 50
    assert broker.horizon() == 35  # 50 - 16 + 1

    # Resume from 0: events before the horizon were evicted — truncated.
    idx, evs, truncated = broker.events_after(0)
    assert truncated
    assert idx == 50
    assert [e.index for e in evs] == list(range(35, 51))

    # Resume exactly at the horizon boundary: nothing was missed.
    idx, evs, truncated = broker.events_after(34)
    assert not truncated
    assert [e.index for e in evs] == list(range(35, 51))

    # Fully caught up: empty page, still not truncated.
    idx, evs, truncated = broker.events_after(50)
    assert not truncated and evs == []


def test_topic_filtering():
    broker = EventBroker(register=False)
    broker.publish("Node", "NodeRegistered", key="node-7")
    broker.publish("Node", "NodeRegistered", key="node-8")
    broker.publish("Eval", "EvalUpdated", key="ev-1")
    broker.publish("Job", "JobRegistered", key="job-1")

    _, evs, _ = broker.events_after(0, TopicFilter(["Eval"]))
    assert [e.type for e in evs] == ["EvalUpdated"]

    _, evs, _ = broker.events_after(0, TopicFilter(["Node:node-7"]))
    assert [(e.type, e.key) for e in evs] == [("NodeRegistered", "node-7")]

    _, evs, _ = broker.events_after(0, TopicFilter(["Eval", "Node:node-7"]))
    assert len(evs) == 2

    # '*' and no selection both match everything.
    _, evs, _ = broker.events_after(0, TopicFilter(["*"]))
    assert len(evs) == 4
    assert TopicFilter([]).matches(evs[0])

    # Bare topic subsumes a keyed selection of the same topic.
    tf = TopicFilter(["Node:node-7", "Node"])
    _, evs, _ = broker.events_after(0, tf)
    assert [e.key for e in evs] == ["node-7", "node-8"]

    # Filtered waiters park on per-topic items only.
    assert events.item_topic("Eval") in TopicFilter(["Eval"]).watch_items()
    assert TopicFilter([]).watch_items() == [events.ITEM_ANY]


def test_broadcast_reaches_live_brokers():
    """Process-scoped emitters (faults, breaker) fan out to every live
    broker; a garbage-collected broker drops out of the registry."""
    b1 = EventBroker()
    b2 = EventBroker()
    events.broadcast("Fault", "FaultInjected", key="rpc.send",
                     payload={"mode": "drop"})
    for b in (b1, b2):
        _, evs, _ = b.events_after(0, TopicFilter(["Fault"]))
        assert [e.type for e in evs] == ["FaultInjected"]
        assert evs[0].payload["mode"] == "drop"


def test_fault_fire_and_breaker_transitions_publish_events():
    broker = EventBroker()
    faults.get_registry().configure("solver.execute", mode="error", count=1)
    try:
        faults.fire("solver.execute", target="probe")
    finally:
        faults.get_registry().clear()
    _, evs, _ = broker.events_after(0, TopicFilter(["Fault"]))
    assert [(e.type, e.key) for e in evs] == [("FaultInjected",
                                               "solver.execute")]
    assert evs[0].payload == {"mode": "error", "target": "probe"}

    from nomad_tpu.backoff import CircuitBreaker

    cb = CircuitBreaker(threshold=1, cooldown=60.0, name=("t", "breaker"))
    cb.record_failure()  # closed -> open
    _, evs, _ = broker.events_after(0, TopicFilter(["Breaker"]))
    assert [(e.type, e.key) for e in evs] == [("BreakerStateChanged",
                                               "t.breaker")]
    assert evs[0].payload["to"] == "open"


# ---------------------------------------------------------------------------
# HTTP tier: long-poll, SSE, client SDK, debug bundle
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def agent(tmp_path_factory):
    from nomad_tpu.agent import Agent, AgentConfig

    config = AgentConfig(
        server_enabled=True, dev_mode=True, node_name="events-dev",
        enable_debug=True,
    )
    config.data_dir = str(tmp_path_factory.mktemp("events-agent"))
    config.http_port = 0
    config.scheduler_backend = "host"
    a = Agent(config)
    a.start()
    yield a
    a.shutdown()


@pytest.fixture()
def client(agent):
    from nomad_tpu.api.client import ApiClient

    return ApiClient(address=agent.http.addr)


def test_event_stream_end_to_end(client, agent):
    """A job registration produces the canonical lifecycle sequence, in
    index order, resumable mid-stream."""
    job = mock.job()
    ev_id, _ = client.jobs().register(job)
    ev = agent.server.wait_for_eval(ev_id, timeout=15.0)
    assert ev.status == structs.EVAL_STATUS_COMPLETE

    idx, evs, truncated = client.events().list()
    assert not truncated
    indices = [e["index"] for e in evs]
    assert indices == sorted(indices) and len(set(indices)) == len(indices)
    types = [e["type"] for e in evs if e["key"] in (job.id, ev_id)
             or e["payload"].get("job_id") == job.id]
    assert types[0] == "JobRegistered"
    assert "PlanApplied" in types
    assert types.count("PlanApplied") == 1
    # Terminal eval update comes after the plan applied.
    assert types.index("PlanApplied") < len(types) - 1

    # Resume: nothing new past the cursor.
    idx2, evs2, _ = client.events().list(index=idx, wait="200ms")
    assert evs2 == [] and idx2 == idx

    # Topic + key filter straight off the query string.
    _, only_job, _ = client.events().list(topics=[f"Job:{job.id}"])
    assert [e["type"] for e in only_job] == ["JobRegistered"]


def test_event_stream_long_poll_wake_and_timeout(client, agent):
    idx, _, _ = client.events().list()

    # Timeout: no new event arrives — the poll returns empty at ~wait.
    t0 = time.monotonic()
    idx2, evs, _ = client.events().list(index=idx, wait="300ms")
    assert evs == [] and idx2 == idx
    assert 0.2 <= time.monotonic() - t0 < 5.0

    # Wake: a registration lands mid-poll and the poll returns early.
    def register_later():
        time.sleep(0.3)
        client.jobs().register(mock.job())

    t = threading.Thread(target=register_later)
    t.start()
    t0 = time.monotonic()
    _, evs, _ = client.events().list(index=idx, wait="10s")
    waited = time.monotonic() - t0
    t.join()
    assert evs, "long-poll returned empty despite a publish"
    assert waited < 8.0


def test_event_stream_filtered_long_poll_ignores_other_topics(client, agent):
    """A topic-filtered long-poll must NOT return early on unrelated
    publishes — probing the global index would turn a filtered tail on a
    busy cluster into one empty page per event batch."""
    idx, _, _ = client.events().list()

    def unrelated_later():
        time.sleep(0.2)
        client.jobs().register(mock.job())  # Job/Eval/... events, no Fault

    t = threading.Thread(target=unrelated_later)
    t.start()
    t0 = time.monotonic()
    _, evs, _ = client.events().list(index=idx, topics=["Fault"],
                                     wait="700ms")
    waited = time.monotonic() - t0
    t.join()
    assert evs == []
    assert waited >= 0.5, f"filtered poll woke early ({waited:.2f}s)"


def test_event_stream_sse_framing(client, agent):
    client.jobs().register(mock.job())
    req = urllib.request.Request(
        client.address + "/v1/event/stream?format=sse&wait=500ms"
    )
    with urllib.request.urlopen(req, timeout=15.0) as resp:
        assert resp.headers["Content-Type"].startswith("text/event-stream")
        body = resp.read().decode()
    frames = [f for f in body.split("\n\n") if f.strip()
              and not f.startswith(":")]
    assert frames, body
    for frame in frames:
        lines = dict(
            line.split(": ", 1) for line in frame.splitlines()
            if ": " in line
        )
        payload = json.loads(lines["data"])
        assert lines["event"] == payload["type"]
        assert int(lines["id"]) == payload["index"]


def test_events_stream_iterator_resumes(client, agent):
    """The SDK iterator pages through ?index= resume without gaps or
    repeats."""
    job = mock.job()
    client.jobs().register(job)
    time.sleep(0.2)
    seen = []
    for event in client.events().stream(poll_wait="200ms"):
        seen.append(event)
        if any(e["type"] == "JobRegistered" and e["key"] == job.id
               for e in seen):
            break
    indices = [e["index"] for e in seen]
    assert indices == sorted(indices) and len(set(indices)) == len(indices)


def test_events_stream_iterator_truncation_marker():
    """A resume cursor that fell off the ring yields the synthetic
    Truncated marker first."""
    from nomad_tpu.api.client import Events

    class _FakeClient:
        def query(self, path, q=None, params=None):
            return {"index": 60, "truncated": True,
                    "events": [{"index": 60, "type": "EvalUpdated",
                                "topic": "Eval", "key": "e",
                                "payload": {}}]}, None

    out = []
    for event in Events(_FakeClient()).stream(index=3):
        out.append(event)
        if len(out) == 2:
            break
    assert out[0]["topic"] == "Truncated"
    assert out[1]["type"] == "EvalUpdated"


def test_debug_bundle_schema_and_redaction(client, agent):
    from nomad_tpu.bundle import BUNDLE_FORMAT, BUNDLE_SECTIONS

    # Make sure there is something in every section.
    client.jobs().register(mock.job())
    time.sleep(0.2)
    agent.config.atlas_token = "hunter2"
    try:
        bundle = client.agent().debug_bundle()
    finally:
        agent.config.atlas_token = ""
    for section in BUNDLE_SECTIONS:
        assert section in bundle, f"bundle missing {section!r}"
    assert bundle["format"] == BUNDLE_FORMAT
    assert bundle["config"]["atlas_token"] == "<redacted>"
    assert bundle["config"]["node_name"] == "events-dev"
    assert bundle["events"], "bundle carries no events"
    assert any("http" in name or "MainThread" in name
               for name in bundle["threads"]), bundle["threads"].keys()
    assert bundle["breaker"]["state"] in ("closed", "half_open", "open")
    assert "delta_rolls" in bundle["mirror"], bundle["mirror"]
    assert "full_rebuilds" in bundle["mirror"]
    assert "sites" in bundle["faults"]
    assert "intervals" in bundle["metrics"]
    assert "cumulative" in bundle["metrics"]
    json.dumps(bundle)  # the artifact is a single JSON document


def test_debug_bundle_is_debug_gated(tmp_path):
    from nomad_tpu.agent import Agent, AgentConfig
    from nomad_tpu.api.client import ApiClient, ApiError

    config = AgentConfig(server_enabled=True, dev_mode=True)
    config.data_dir = str(tmp_path)
    config.http_port = 0
    config.scheduler_backend = "host"
    a = Agent(config)
    a.start()
    try:
        api = ApiClient(address=a.http.addr)
        with pytest.raises(ApiError) as err:
            api.agent().debug_bundle()
        assert err.value.code == 404
        # Piggyback on the untouched agent: ?index=0 against an EMPTY
        # broker returns immediately (no event has ever been published,
        # so the index probe alone would park the poll).
        t0 = time.monotonic()
        idx, evs, truncated = api.events().list()
        assert evs == [] and idx == 0 and not truncated
        assert time.monotonic() - t0 < 5.0
        # SSE with no ?wait= (tail-forever mode) must not 400: the first
        # retained bytes arrive once an event lands.
        a.server.node_register(mock.node())
        req = urllib.request.Request(a.http.addr + "/v1/event/stream",
                                     headers={"Accept": "text/event-stream"})
        with urllib.request.urlopen(req, timeout=10.0) as resp:
            assert resp.status == 200
            first = resp.read(24).decode()
        assert first.startswith("event: NodeRegistered")
    finally:
        a.shutdown()


def test_process_local_bundle():
    """The no-agent capture path tier1.py uses on a red run."""
    from nomad_tpu.bundle import BUNDLE_SECTIONS, collect

    broker = EventBroker()
    broker.publish("Node", "NodeRegistered", key="n1")
    bundle = collect(agent=None, last_events=10)
    for section in BUNDLE_SECTIONS:
        assert section in bundle
    assert bundle["config"] is None  # no agent, no config
    assert any(e["type"] == "NodeRegistered" for e in bundle["events"])
    assert bundle["threads"]
    json.dumps(bundle, default=str)


# ---------------------------------------------------------------------------
# Determinism + chaos tier
# ---------------------------------------------------------------------------


def _run_seeded_workload(seed: int):
    """One dev server, a seeded fault plan, a serial workload; returns the
    event-type sequence of the server's log."""
    from nomad_tpu.server import Server, ServerConfig

    srv = Server(ServerConfig(
        scheduler_backend="host", num_schedulers=1,
        min_heartbeat_ttl=300.0, prewarm_shapes=False,
    ))
    srv.start()
    try:
        faults.get_registry().load({"seed": seed, "sites": {
            # fsm.apply fires once per applied entry on the applying
            # thread: its decisions (and the FaultInjected events they
            # publish) land at deterministic positions in the log.
            "fsm.apply": {"mode": "delay", "delay": 0.001,
                          "probability": 0.5},
        }})
        for _ in range(3):
            srv.node_register(mock.node())
        for _ in range(3):
            ev_id, _ = srv.job_register(mock.job())
            ev = srv.wait_for_eval(ev_id, timeout=15.0)
            assert ev.status == structs.EVAL_STATUS_COMPLETE
        return [e.type for e in srv.fsm.events.all_events()]
    finally:
        faults.get_registry().clear()
        srv.shutdown()


def test_same_seed_identical_event_type_sequences():
    """Acceptance: two runs with the same fault seed produce identical
    event-type sequences — the chaos replay contract."""
    first = _run_seeded_workload(seed=42)
    second = _run_seeded_workload(seed=42)
    assert first == second
    assert "FaultInjected" in first  # the plan really fired
    assert first.count("PlanApplied") == 3  # one per job


def test_chaos_leader_partition_one_plan_applied_per_placement():
    """PR-2 chaos scenario: one-way partition of the leader's outbound
    raft traffic mid-plan. After failover the surviving leader's event
    log must record exactly one PlanApplied per committed plan, with
    strictly increasing gapless broker indices."""
    from cluster_util import relaxed_cluster_cfg, retry_write
    from nomad_tpu.server import ServerConfig
    from nomad_tpu.server.cluster import form_cluster, wait_for_leader

    servers = form_cluster(3, ServerConfig(
        scheduler_backend="host", num_schedulers=1,
        min_heartbeat_ttl=300.0,
    ), base_cluster=relaxed_cluster_cfg())
    try:
        leader = wait_for_leader(servers)
        nodes = [mock.node() for _ in range(12)]
        for node in nodes:
            retry_write(lambda n=node: leader.node_register(n))
        jobs, eval_ids = [], []
        for _ in range(4):
            job = mock.job()
            ev_id, _ = retry_write(lambda j=job: leader.job_register(j))
            jobs.append(job)
            eval_ids.append(ev_id)

        old_id = leader.cluster.node_id
        faults.get_registry().load({"seed": 7, "sites": {
            "raft.append": {"mode": "partition", "match": f"{old_id}->"},
            "raft.vote": {"mode": "partition", "match": f"{old_id}->"},
        }})

        survivors = [s for s in servers if s is not leader]
        deadline = time.monotonic() + 30.0
        new_leader = None
        while time.monotonic() < deadline:
            live = [s for s in survivors if s.raft.is_leader]
            if live:
                new_leader = live[0]
                break
            time.sleep(0.05)
        assert new_leader is not None, "no survivor took leadership"

        store = new_leader.state_store
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline:
            evs = [store.eval_by_id(i) for i in eval_ids]
            if all(e is not None and e.terminal_status() for e in evs):
                break
            time.sleep(0.1)
        placed_evals = set()
        for job in jobs:
            live = structs.filter_terminal_allocs(store.allocs_by_job(job.id))
            assert len(live) == job.task_groups[0].count
            placed_evals.update(a.eval_id for a in live)

        log = new_leader.fsm.events.all_events()
        indices = [e.index for e in log]
        assert indices == list(range(indices[0], indices[0] + len(indices)))

        plan_evals = [e.key for e in log if e.type == "PlanApplied"]
        # Exactly once: no eval's plan committed twice despite the
        # partition, redelivery, and failover — and every placement's
        # eval shows exactly one committed plan.
        assert len(plan_evals) == len(set(plan_evals)), plan_evals
        assert placed_evals <= set(plan_evals)
        # Failover is visible in the log too.
        assert any(e.type == "LeaderAcquired" for e in log)
    finally:
        faults.get_registry().clear()
        for srv in servers:
            srv.shutdown()
