"""Server control-plane tests: broker, plan queue/apply, and the end-to-end
single-process pipeline (reference: nomad/eval_broker_test.go,
plan_apply_test.go, worker_test.go, job_endpoint_test.go,
node_endpoint_test.go)."""

import threading
import time

import pytest

from nomad_tpu import mock, structs
from nomad_tpu.server import Server, ServerConfig
from nomad_tpu.server.eval_broker import FAILED_QUEUE, BrokerError, EvalBroker
from nomad_tpu.server.plan_apply import evaluate_plan
from nomad_tpu.server.plan_queue import PlanQueue, PlanQueueError
from nomad_tpu.state import StateStore
from nomad_tpu.structs import Evaluation, Plan, Resources, generate_uuid


def _eval(priority=50, job_id=None, eval_type="service"):
    return Evaluation(
        id=generate_uuid(),
        priority=priority,
        type=eval_type,
        job_id=job_id or generate_uuid(),
        status=structs.EVAL_STATUS_PENDING,
    )


# ---------------------------------------------------------------------------
# Eval broker (reference: eval_broker_test.go, 755 LoC)
# ---------------------------------------------------------------------------


def test_broker_enqueue_dequeue_ack():
    b = EvalBroker(5.0, 3)
    b.set_enabled(True)
    ev = _eval()
    b.enqueue(ev)
    assert b.snapshot_stats().total_ready == 1

    out, token = b.dequeue(["service"], timeout=1.0)
    assert out is ev
    assert token
    stats = b.snapshot_stats()
    assert stats.total_ready == 0
    assert stats.total_unacked == 1

    # Outstanding tracks the token
    tok, ok = b.outstanding(ev.id)
    assert ok and tok == token

    b.ack(ev.id, token)
    stats = b.snapshot_stats()
    assert stats.total_unacked == 0
    assert b.outstanding(ev.id) == ("", False)


def test_broker_priority_order():
    b = EvalBroker(5.0, 3)
    b.set_enabled(True)
    low = _eval(priority=10)
    high = _eval(priority=90)
    mid = _eval(priority=50)
    for ev in (low, high, mid):
        b.enqueue(ev)

    out1, t1 = b.dequeue(["service"], timeout=1.0)
    out2, t2 = b.dequeue(["service"], timeout=1.0)
    out3, t3 = b.dequeue(["service"], timeout=1.0)
    assert [out1.id, out2.id, out3.id] == [high.id, mid.id, low.id]


def test_broker_job_serialization():
    """One outstanding eval per job; later ones block until Ack."""
    b = EvalBroker(5.0, 3)
    b.set_enabled(True)
    job_id = generate_uuid()
    ev1 = _eval(job_id=job_id)
    ev2 = _eval(job_id=job_id)
    b.enqueue(ev1)
    b.enqueue(ev2)

    stats = b.snapshot_stats()
    assert stats.total_ready == 1
    assert stats.total_blocked == 1

    out, token = b.dequeue(["service"], timeout=1.0)
    assert out is ev1
    # No more ready work while ev1 is outstanding
    assert b.dequeue(["service"], timeout=0.05) == (None, "")

    b.ack(ev1.id, token)
    out2, token2 = b.dequeue(["service"], timeout=1.0)
    assert out2 is ev2
    b.ack(ev2.id, token2)


def test_broker_nack_redelivers_then_fails():
    b = EvalBroker(5.0, delivery_limit=2)
    b.set_enabled(True)
    ev = _eval()
    b.enqueue(ev)

    # First delivery + nack -> redelivered
    out, token = b.dequeue(["service"], timeout=1.0)
    b.nack(ev.id, token)
    out, token = b.dequeue(["service"], timeout=1.0)
    assert out is ev
    # Second nack hits the delivery limit -> _failed queue
    b.nack(ev.id, token)
    assert b.dequeue(["service"], timeout=0.05) == (None, "")
    out, token = b.dequeue([FAILED_QUEUE], timeout=1.0)
    assert out is ev


def test_broker_nack_timeout_redelivers():
    b = EvalBroker(nack_timeout=0.1, delivery_limit=5)
    b.set_enabled(True)
    ev = _eval()
    b.enqueue(ev)
    out, token = b.dequeue(["service"], timeout=1.0)
    # Don't ack; wait for the nack timer
    out2, token2 = b.dequeue(["service"], timeout=2.0)
    assert out2 is ev
    assert token2 != token
    # The old token no longer acks
    with pytest.raises(BrokerError):
        b.ack(ev.id, token)
    b.ack(ev.id, token2)


def test_broker_wait_eval():
    b = EvalBroker(5.0, 3)
    b.set_enabled(True)
    ev = _eval()
    ev.wait = 0.1
    b.enqueue(ev)
    assert b.snapshot_stats().total_waiting == 1
    assert b.dequeue(["service"], timeout=0.01) == (None, "")
    out, _ = b.dequeue(["service"], timeout=2.0)
    assert out is ev


def test_broker_dedup_enqueue():
    b = EvalBroker(5.0, 3)
    b.set_enabled(True)
    ev = _eval()
    b.enqueue(ev)
    b.enqueue(ev)
    assert b.snapshot_stats().total_ready == 1


def test_broker_disabled():
    b = EvalBroker(5.0, 3)
    ev = _eval()
    b.enqueue(ev)  # no-op while disabled
    with pytest.raises(BrokerError):
        b.dequeue(["service"], timeout=0.05)


def test_broker_dequeue_batch():
    b = EvalBroker(5.0, 3)
    b.set_enabled(True)
    evs = [_eval() for _ in range(5)]
    for ev in evs:
        b.enqueue(ev)
    batch = b.dequeue_batch(["service"], max_batch=3, timeout=1.0)
    assert len(batch) == 3
    ids = {ev.id for ev, _ in batch}
    assert len(ids) == 3
    for ev, token in batch:
        b.ack(ev.id, token)


def test_broker_outstanding_reset_token_mismatch():
    b = EvalBroker(5.0, 3)
    b.set_enabled(True)
    ev = _eval()
    b.enqueue(ev)
    _, token = b.dequeue(["service"], timeout=1.0)
    b.outstanding_reset(ev.id, token)  # ok
    with pytest.raises(BrokerError):
        b.outstanding_reset(ev.id, "bogus-token")
    with pytest.raises(BrokerError):
        b.outstanding_reset("missing", token)


# ---------------------------------------------------------------------------
# Plan queue
# ---------------------------------------------------------------------------


def test_plan_queue_priority_and_future():
    q = PlanQueue()
    q.set_enabled(True)
    low = Plan(priority=10)
    high = Plan(priority=90)
    p1 = q.enqueue(low)
    p2 = q.enqueue(high)

    out = q.dequeue(timeout=0.1)
    assert out.plan is high
    out2 = q.dequeue(timeout=0.1)
    assert out2.plan is low

    from nomad_tpu.structs import PlanResult

    result = PlanResult()
    out.respond(result, None)
    assert p2.wait(0.1) is result


def test_plan_queue_disabled():
    q = PlanQueue()
    with pytest.raises(PlanQueueError):
        q.enqueue(Plan())


# ---------------------------------------------------------------------------
# Plan evaluation (reference: plan_apply_test.go)
# ---------------------------------------------------------------------------


def test_evaluate_plan_partial_commit():
    state = StateStore()
    node = mock.node()
    state.upsert_node(1000, node)

    # Fits
    alloc_ok = mock.alloc()
    alloc_ok.node_id = node.id
    # Does not fit (oversized)
    alloc_bad = mock.alloc()
    alloc_bad.node_id = "missing-node"

    plan = Plan(
        node_allocation={node.id: [alloc_ok], "missing-node": [alloc_bad]},
        node_update={},
    )
    snap = state.snapshot()
    result = evaluate_plan(snap, plan)
    assert node.id in result.node_allocation
    assert "missing-node" not in result.node_allocation
    assert result.refresh_index > 0
    full, expected, actual = result.full_commit(plan)
    assert not full and expected == 2 and actual == 1


def test_evaluate_plan_all_at_once_rejects_all():
    state = StateStore()
    node = mock.node()
    state.upsert_node(1000, node)
    alloc_bad = mock.alloc()
    alloc_bad.node_id = "missing-node"
    plan = Plan(
        all_at_once=True,
        node_allocation={"missing-node": [alloc_bad]},
        node_update={},
    )
    result = evaluate_plan(state.snapshot(), plan)
    assert result.node_allocation == {}


def test_evaluate_plan_evict_only_always_fits():
    state = StateStore()
    alloc = mock.alloc()
    plan = Plan(node_update={"any-node": [alloc]}, node_allocation={})
    result = evaluate_plan(state.snapshot(), plan)
    assert result.node_update == {"any-node": [alloc]}
    assert result.refresh_index == 0


def test_evaluate_plan_overcommit_rejected():
    state = StateStore()
    node = mock.node()
    node.resources = Resources(cpu=1000, memory_mb=1000, disk_mb=10000, iops=100)
    node.reserved = None
    state.upsert_node(1000, node)

    big = mock.alloc()
    big.node_id = node.id
    big.resources = Resources(cpu=900, memory_mb=900)
    state.upsert_allocs(1001, [big])

    alloc = mock.alloc()
    alloc.node_id = node.id
    alloc.resources = Resources(cpu=500, memory_mb=256)
    plan = Plan(node_allocation={node.id: [alloc]}, node_update={})
    result = evaluate_plan(state.snapshot(), plan)
    assert result.node_allocation == {}
    assert result.refresh_index > 0


# ---------------------------------------------------------------------------
# End-to-end single-process pipeline
# ---------------------------------------------------------------------------


@pytest.fixture(params=["host", "tpu"])
def server(request):
    srv = Server(ServerConfig(scheduler_backend=request.param, num_schedulers=2))
    srv.start()
    yield srv
    srv.shutdown()


def test_end_to_end_job_register(server):
    """register job -> eval -> broker -> worker -> solver -> plan apply ->
    allocs in state (call stack SURVEY.md §3.1)."""
    for _ in range(10):
        server.node_register(mock.node())

    job = mock.job()
    eval_id, _ = server.job_register(job)

    ev = server.wait_for_eval(eval_id, timeout=15.0)
    assert ev.status == structs.EVAL_STATUS_COMPLETE

    allocs = server.state_store.allocs_by_job(job.id)
    assert len(allocs) == 10
    assert all(a.desired_status == structs.ALLOC_DESIRED_STATUS_RUN for a in allocs)
    # All on distinct ready nodes
    assert len({a.node_id for a in allocs}) == 10


def test_end_to_end_deregister(server):
    for _ in range(3):
        server.node_register(mock.node())
    job = mock.job()
    job.task_groups[0].count = 3
    eval_id, _ = server.job_register(job)
    server.wait_for_eval(eval_id, timeout=15.0)

    eval_id2, _ = server.job_deregister(job.id)
    server.wait_for_eval(eval_id2, timeout=15.0)

    allocs = structs.filter_terminal_allocs(server.state_store.allocs_by_job(job.id))
    assert allocs == []


def test_end_to_end_node_down_reschedules(server):
    reply1 = server.node_register(mock.node())
    node2 = mock.node()
    server.node_register(node2)

    job = mock.job()
    job.task_groups[0].count = 2
    eval_id, _ = server.job_register(job)
    server.wait_for_eval(eval_id, timeout=15.0)
    allocs = server.state_store.allocs_by_job(job.id)
    assert len(allocs) == 2

    # Mark node2 down: its alloc migrates to node1 (or fails if full)
    reply = server.node_update_status(node2.id, structs.NODE_STATUS_DOWN)
    assert reply["eval_ids"]
    for ev_id in reply["eval_ids"]:
        server.wait_for_eval(ev_id, timeout=15.0)

    live = structs.filter_terminal_allocs(server.state_store.allocs_by_job(job.id))
    assert all(a.node_id != node2.id for a in live)


def test_heartbeat_ttl_marks_node_down():
    cfg = ServerConfig(min_heartbeat_ttl=0.1, max_heartbeats_per_second=1000.0)
    srv = Server(cfg)
    srv.start()
    try:
        node = mock.node()
        reply = server_reply = srv.node_register(node)
        assert reply["heartbeat_ttl"] > 0
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            out = srv.state_store.node_by_id(node.id)
            if out.status == structs.NODE_STATUS_DOWN:
                break
            time.sleep(0.05)
        assert srv.state_store.node_by_id(node.id).status == structs.NODE_STATUS_DOWN
    finally:
        srv.shutdown()


def test_fsm_snapshot_restore_roundtrip():
    srv = Server(ServerConfig(scheduler_backend="host"))
    srv.start()
    try:
        for _ in range(3):
            srv.node_register(mock.node())
        job = mock.job()
        job.task_groups[0].count = 3
        eval_id, _ = srv.job_register(job)
        srv.wait_for_eval(eval_id, timeout=15.0)

        data = srv.fsm.snapshot_bytes()

        from nomad_tpu.server.fsm import FSM

        fsm2 = FSM()
        fsm2.restore_bytes(data)
        assert len(fsm2.state.nodes()) == 3
        assert fsm2.state.job_by_id(job.id) is not None
        assert len(fsm2.state.allocs_by_job(job.id)) == 3
        assert fsm2.state.get_index("allocs") == srv.state_store.get_index("allocs")
    finally:
        srv.shutdown()


def test_failed_eval_reaped_and_job_unwedged():
    """An eval that exhausts its delivery limit lands in _failed, is marked
    failed by the reaper (leader.go:202-238), and does not wedge later evals
    for the same job."""
    cfg = ServerConfig(eval_delivery_limit=1, eval_nack_timeout=60.0)
    cfg.enabled_schedulers = cfg.enabled_schedulers + ["explode"]
    srv = Server(cfg)
    srv.start()
    try:
        job_id = generate_uuid()
        bad = Evaluation(
            id=generate_uuid(), priority=50, type="explode",
            triggered_by=structs.EVAL_TRIGGER_JOB_REGISTER,
            job_id=job_id, status=structs.EVAL_STATUS_PENDING,
        )
        srv.raft.apply("eval_update", {"evals": [bad]}).result()

        ev = srv.wait_for_eval(bad.id, timeout=10.0)
        assert ev.status == structs.EVAL_STATUS_FAILED
        assert "delivery limit" in ev.status_description

        # The job must not be wedged: a good eval for the same job completes
        srv.node_register(mock.node())
        job = mock.job()
        job.id = job_id
        job.task_groups[0].count = 1
        eval_id, _ = srv.job_register(job)
        good = srv.wait_for_eval(eval_id, timeout=10.0)
        assert good.status == structs.EVAL_STATUS_COMPLETE
    finally:
        srv.shutdown()


def test_broker_nack_deferred_while_plan_inflight():
    """A nack (explicit or timer) must not redeliver an eval whose
    token-verified plan is mid-commit in the applier: the second worker's
    snapshot would race the commit and double-place. plan_done lifts the
    deferral and bumps the eval's wait_index past the commit."""
    from nomad_tpu.server.eval_broker import EvalBroker

    b = EvalBroker(nack_timeout=60.0)
    b.set_enabled(True)
    ev = mock.evaluation()
    b.enqueue(ev, wait_index=7)
    out, token = b.dequeue(["service"], timeout=1.0)
    assert out.id == ev.id

    # Applier verifies + marks atomically
    b.outstanding_reset_and_mark(ev.id, token)

    # Worker gives up mid-commit: nack is DEFERRED, not redelivered
    b.nack(ev.id, token)
    assert b.dequeue(["service"], timeout=0.1) == (None, "")
    _tok, outstanding = b.outstanding(ev.id)
    assert outstanding  # still held by the original delivery

    # Commit lands: wait_index bumped, deferral lifted on the re-check
    b.plan_done(ev.id, commit_index=42)
    assert b.wait_index(ev.id) == 42
    deadline = time.time() + 5
    redelivered = (None, "")
    while time.time() < deadline and redelivered[0] is None:
        redelivered = b.dequeue(["service"], timeout=0.2)
    assert redelivered[0] is not None and redelivered[0].id == ev.id
    # The redelivery's wait_index still carries the commit bump
    assert b.wait_index(ev.id) == 42


def test_broker_enqueue_many_wakes_batch_dequeuer_to_full_burst():
    """enqueue_many lands a whole burst under one lock hold: a parked
    dequeue_batch caller must see every eval of the burst in ONE batch,
    never a fragment (per-eval enqueue notifies racing the dequeuer can
    split an 8-eval burst into several small coalesced dispatches)."""
    import threading

    b = EvalBroker(5.0, 3)
    b.set_enabled(True)
    got = []
    ready = threading.Event()

    def park():
        ready.set()
        got.append(b.dequeue_batch(["service"], max_batch=8, timeout=5.0))

    t = threading.Thread(target=park)
    t.start()
    ready.wait(2.0)
    import time as _t
    _t.sleep(0.05)  # let the dequeuer actually park on the condition
    evs = [_eval() for _ in range(8)]
    b.enqueue_many(evs, wait_index=7)
    t.join(5.0)
    assert not t.is_alive()
    assert len(got) == 1 and len(got[0]) == 8
    assert {ev.id for ev, _ in got[0]} == {ev.id for ev in evs}
    # wait_index recorded for every member of the burst
    assert all(b.wait_index(ev.id) == 7 for ev in evs)
    for ev, token in got[0]:
        b.ack(ev.id, token)
