"""Subprocess-isolated device acquisition (scheduler/device_probe.py).

The round-2 failure mode this design exists for: jax backend init is
process-global, so an in-process retry of a wedged jax.devices() can never
succeed. The child probe must be killable, report how far acquisition got,
and be replaced by a fresh child on retry.
"""

import socket

from nomad_tpu.scheduler import device_probe


def test_probe_child_succeeds_and_reports_stages():
    # Children inherit JAX_PLATFORMS=cpu from the test env: the claim
    # completes quickly on the host backend.
    r = device_probe.probe_once(timeout=120)
    assert r.ok and not r.killed and r.rc == 0
    stages = [s["stage"] for s in r.stages]
    assert stages[:2] == ["env", "relay"]
    assert "claim" in stages and "smoke" in stages and stages[-1] == "ready"
    assert r.backend == "cpu"
    assert r.stage("smoke")["ok"] is True
    summary = r.summary()
    assert summary["ok"] is True and summary["last_stage"] == "ready"
    assert "relay_reachable" in summary


def test_wedged_child_is_killed_and_stage_recorded():
    r = device_probe.probe_once(
        timeout=3, env={"NOMAD_TPU_PROBE_TEST_WEDGE": "relay:60"}
    )
    assert not r.ok and r.killed
    # The forensic trail shows acquisition stopped after the relay stage —
    # i.e. before the jax import/claim, distinguishable from a claim hang.
    assert r.last_stage == "relay"
    assert "stage 'relay'" in r.error


def test_reachable_relay_extends_child_leash(monkeypatch):
    # An answering relay means a pending claim is plausibly queued behind
    # another tenant, not wedged — the child gets CLAIM_TIMEOUT, not the
    # base leash, so a slow-but-live grant isn't killed (and the kill
    # can't orphan a server-side grant that would block the next child).
    srv = socket.socket()
    try:
        srv.bind(("127.0.0.1", 0))
        srv.listen(1)
        port = srv.getsockname()[1]
        monkeypatch.setenv("PALLAS_AXON_POOL_IPS", f"127.0.0.1:{port}")
        monkeypatch.setenv("NOMAD_TPU_PROBE_TEST_WEDGE", "relay:10")
        # Base leash 5s gives child startup (spawn + env + relay scan)
        # headroom on a loaded machine; the wedge still outlives it.
        r = device_probe.probe_once(timeout=5, claim_timeout=60)
        # Wedge (10s) outlives the base leash (5s); the reachable relay
        # extends the deadline and the child runs to ready on the cpu pin.
        assert r.ok and not r.killed and r.last_stage == "ready"
        assert r.elapsed_s > 5
    finally:
        srv.close()


def test_unreachable_relay_keeps_short_leash(monkeypatch):
    # The extension is gated on reachability: against a closed port the
    # same wedge dies at the base leash — a dead relay is never worth a
    # CLAIM_TIMEOUT wait.
    monkeypatch.setenv("PALLAS_AXON_POOL_IPS", "127.0.0.1:1")
    monkeypatch.setenv("NOMAD_TPU_PROBE_TEST_WEDGE", "relay:30")
    r = device_probe.probe_once(timeout=2, claim_timeout=60)
    # On a loaded machine the child may not even reach the relay scan
    # before the leash fires; any pre-claim stage proves the point —
    # the kill came at the short leash, not the extended one.
    assert not r.ok and r.killed
    assert r.last_stage in ("spawn", "env", "relay")
    assert r.elapsed_s < 15


def test_acquire_replaces_killed_children(monkeypatch):
    monkeypatch.setenv("NOMAD_TPU_PROBE_TEST_WEDGE", "env:60")
    attempts = []
    r = device_probe.acquire(
        total_timeout=8, child_timeout=2,
        on_attempt=lambda i, rep: attempts.append(rep.killed),
    )
    assert not r.ok
    # Killed children are replaced immediately by fresh ones — the retry
    # that in-process probing structurally could not do.
    assert len(attempts) >= 2 and all(attempts)


def test_relay_reachability_diagnostic(monkeypatch):
    srv = socket.socket()
    try:
        srv.bind(("127.0.0.1", 0))
        srv.listen(1)
        port = srv.getsockname()[1]
        monkeypatch.setenv("PALLAS_AXON_POOL_IPS", f"127.0.0.1:{port}")
        r = device_probe.probe_once(timeout=120)
        relay = r.stage("relay")
        assert relay["reachable"] is True
        assert relay["targets"][0]["open_ports"] == [port]
    finally:
        srv.close()


def test_relay_unreachable_diagnostic(monkeypatch):
    # Port 1 (tcpmux) is closed: the diagnostic must say so — this is the
    # "relay down" half of the relay-down vs claim-pending distinction.
    monkeypatch.setenv("PALLAS_AXON_POOL_IPS", "127.0.0.1:1")
    r = device_probe.probe_once(timeout=120)
    assert r.stage("relay")["reachable"] is False


def test_status_carries_child_diagnostics():
    from nomad_tpu.scheduler import device_probe_status, wait_for_device

    solver = wait_for_device(timeout=120)
    assert solver is not None  # cpu backend in tests
    status = device_probe_status()
    assert status["status"] == "ready"
    assert status["backend"] == "cpu"
    assert status["attempts"] >= 1
    child = status["child"]
    assert child["ok"] is True and child["last_stage"] == "ready"
