"""State store tests, mirroring the reference's coverage
(/root/reference/nomad/state/state_store_test.go: CRUD, indexes, snapshots,
watch-fire assertions, restore)."""

import threading

from nomad_tpu import mock, structs
from nomad_tpu.state import StateStore
from nomad_tpu.state.store import (
    item_alloc_node,
    item_node,
    item_table,
)


def test_node_crud():
    store = StateStore()
    node = mock.node()
    store.upsert_node(1000, node)

    out = store.node_by_id(node.id)
    assert out is node
    assert out.create_index == 1000
    assert out.modify_index == 1000
    assert store.get_index("nodes") == 1000

    store.update_node_status(1001, node.id, structs.NODE_STATUS_DOWN)
    out = store.node_by_id(node.id)
    assert out.status == structs.NODE_STATUS_DOWN
    assert out.create_index == 1000
    assert out.modify_index == 1001

    store.update_node_drain(1002, node.id, True)
    assert store.node_by_id(node.id).drain

    store.delete_node(1003, node.id)
    assert store.node_by_id(node.id) is None
    assert store.get_index("nodes") == 1003


def test_job_crud():
    store = StateStore()
    job = mock.job()
    store.upsert_job(1000, job)
    assert store.job_by_id(job.id) is job
    assert job.create_index == 1000

    # Re-upsert preserves create index
    job2 = mock.job()
    job2.id = job.id
    store.upsert_job(1001, job2)
    assert store.job_by_id(job.id).create_index == 1000
    assert store.job_by_id(job.id).modify_index == 1001

    sysjob = mock.system_job()
    store.upsert_job(1002, sysjob)
    assert [j.id for j in store.jobs_by_scheduler("system")] == [sysjob.id]
    assert len(store.jobs()) == 2

    store.delete_job(1003, job.id)
    assert store.job_by_id(job.id) is None


def test_eval_and_alloc_indexes():
    store = StateStore()
    ev = mock.evaluation()
    store.upsert_evals(1000, [ev])
    assert store.eval_by_id(ev.id) is ev
    assert [e.id for e in store.evals_by_job(ev.job_id)] == [ev.id]

    alloc = mock.alloc()
    alloc.eval_id = ev.id
    store.upsert_allocs(1001, [alloc])
    assert store.alloc_by_id(alloc.id) is alloc
    assert [a.id for a in store.allocs_by_job(alloc.job_id)] == [alloc.id]
    assert [a.id for a in store.allocs_by_node(alloc.node_id)] == [alloc.id]
    assert [a.id for a in store.allocs_by_eval(ev.id)] == [alloc.id]

    # GC both
    store.delete_eval(1002, [ev.id], [alloc.id])
    assert store.eval_by_id(ev.id) is None
    assert store.alloc_by_id(alloc.id) is None
    assert store.allocs_by_job(alloc.job_id) == []


def test_update_alloc_from_client():
    store = StateStore()
    alloc = mock.alloc()
    store.upsert_allocs(1000, [alloc])

    update = alloc.copy()
    update.client_status = structs.ALLOC_CLIENT_STATUS_RUNNING
    # Client must not be able to change desired status
    update.desired_status = structs.ALLOC_DESIRED_STATUS_EVICT
    store.update_alloc_from_client(1001, update)

    out = store.alloc_by_id(alloc.id)
    assert out.client_status == structs.ALLOC_CLIENT_STATUS_RUNNING
    assert out.desired_status == structs.ALLOC_DESIRED_STATUS_RUN
    assert out.modify_index == 1001


def test_snapshot_isolation():
    store = StateStore()
    node = mock.node()
    store.upsert_node(1000, node)

    snap = store.snapshot()
    assert snap.node_by_id(node.id) is not None

    node2 = mock.node()
    store.upsert_node(1001, node2)
    # Snapshot does not see the new node
    assert snap.node_by_id(node2.id) is None
    assert len(snap.nodes()) == 1
    assert len(store.nodes()) == 2
    assert snap.get_index("nodes") == 1000

    # Optimistic writes on the snapshot do not leak to the store
    alloc = mock.alloc()
    snap.upsert_allocs(1002, [alloc])
    assert snap.alloc_by_id(alloc.id) is not None
    assert store.alloc_by_id(alloc.id) is None


def test_watch_fires():
    store = StateStore()
    node = mock.node()

    # Coalesced watch (store._Watch): register samples bucket
    # generations; a notify on the item moves them and wait() returns
    # True (here without blocking — the write already landed).
    ticket = store.watch.register([item_table("nodes")])
    store.upsert_node(1000, node)
    assert store.watch.wait(ticket, timeout=1.0)
    store.watch.unregister(ticket)

    # Per-item watch
    ticket2 = store.watch.register([item_node(node.id)])
    store.update_node_status(1001, node.id, structs.NODE_STATUS_DOWN)
    assert store.watch.wait(ticket2, timeout=1.0)
    store.watch.unregister(ticket2)

    # alloc_node watch fires for allocs placed on that node
    alloc = mock.alloc()
    ticket3 = store.watch.register([item_alloc_node(alloc.node_id)])
    store.upsert_allocs(1002, [alloc])
    assert store.watch.wait(ticket3, timeout=1.0)
    store.watch.unregister(ticket3)

    # A fresh registration AFTER a write does not see stale wakeups from
    # it (generation sampled at register time), unless a bucket-sharing
    # write lands — so probe an item whose table stays untouched.
    ticket4 = store.watch.register([item_table("jobs")])
    assert not store.watch.wait(ticket4, timeout=0.05)
    store.watch.unregister(ticket4)
    # unregister drops the watcher count (stop_watch analog).
    assert store.watch.stats()["watchers"] == 0


def test_restore():
    store = StateStore()
    restore = store.restore()
    node = mock.node()
    node.modify_index = 50
    job = mock.job()
    job.modify_index = 60
    ev = mock.evaluation()
    ev.modify_index = 70
    alloc = mock.alloc()
    alloc.modify_index = 80
    restore.node_restore(node)
    restore.job_restore(job)
    restore.eval_restore(ev)
    restore.alloc_restore(alloc)
    restore.index_restore("nodes", 50)
    restore.commit()

    assert store.node_by_id(node.id) is node
    assert store.job_by_id(job.id) is job
    assert store.eval_by_id(ev.id) is ev
    assert store.alloc_by_id(alloc.id) is alloc
    assert [a.id for a in store.allocs_by_node(alloc.node_id)] == [alloc.id]
    assert store.get_index("nodes") == 50
