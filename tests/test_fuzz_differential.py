"""Randomized differential fuzz: the TPU dense solve vs the host oracle.

The highest-value test for a solver with interchangeable kernels whose
equivalence is otherwise argued in comments (ops/binpack.py): hundreds of
random clusters/jobs/existing-alloc states, asserting

1. kernel agreement — ``solve_rounds_fused`` (direct round simulation) and
   ``solve_waterfill`` (closed form) produce identical per-node counts, and
   ``solve_greedy`` places the same total;
2. scheduler agreement — the ``tpu-*`` factories place exactly as many
   allocations as the host oracle (the ported iterator chain, the
   reference's correctness contract: /root/reference/scheduler/
   generic_sched_test.go, rank_test.go, feasible_test.go);
3. plan soundness — every committed placement lands on an eligible node
   and no node exceeds capacity (structs.allocs_fit, funcs.go:44-87).

Seed count tunable via NOMAD_TPU_FUZZ_SEEDS (default keeps the suite
fast; failures print the seed for replay).
"""

import os

import jax.numpy as jnp
import numpy as np
import pytest

from nomad_tpu import mock, structs
from nomad_tpu.network import NetworkIndex
from nomad_tpu.structs import (
    Constraint,
    Evaluation,
    Job,
    NetworkResource,
    Node,
    Resources,
    RestartPolicy,
    Task,
    TaskGroup,
    generate_uuid,
)

from sched_harness import Harness

N_KERNEL_SEEDS = int(os.environ.get("NOMAD_TPU_FUZZ_SEEDS", 60))
N_SCHED_SEEDS = int(os.environ.get("NOMAD_TPU_FUZZ_SEEDS", 60))


# ---------------------------------------------------------------------------
# 1. Kernel-level agreement


def _random_solve_inputs(rng):
    n = int(rng.choice([8, 16, 32, 64, 128]))
    total = np.zeros((n, 4), dtype=np.int32)
    total[:, 0] = rng.integers(200, 8000, n)      # cpu: some nodes tiny
    total[:, 1] = rng.integers(128, 16384, n)     # mem
    total[:, 2] = rng.integers(1024, 200_000, n)  # disk
    total[:, 3] = rng.integers(10, 300, n)        # iops
    used = np.zeros((n, 4), dtype=np.int32)
    if rng.random() < 0.5:  # existing utilization, possibly near-full
        frac = rng.random((n, 1)) * rng.choice([0.5, 0.95])
        used = (total * frac).astype(np.int32)
    job_count = rng.integers(0, 3, n).astype(np.int32) * (rng.random() < 0.4)
    tg_count = np.minimum(job_count, rng.integers(0, 2, n)).astype(np.int32)
    bw_avail = rng.integers(100, 2000, n).astype(np.int32)
    bw_used = (bw_avail * rng.random(n) * 0.8).astype(np.int32) * (
        rng.random() < 0.5
    )
    eligible = rng.random(n) > rng.choice([0.0, 0.3, 0.9])
    ask = np.array([
        int(rng.integers(1, 1500)), int(rng.integers(1, 2048)),
        int(rng.integers(0, 2000)), int(rng.integers(0, 50)),
    ], dtype=np.int32)
    bw_ask = int(rng.integers(0, 200)) if rng.random() < 0.5 else 0
    count = int(rng.integers(1, 800))
    penalty = float(rng.choice([5.0, 10.0]))
    jd = bool(rng.random() < 0.15)
    td = bool(rng.random() < 0.15 and not jd)
    return dict(
        total=total, used=used, job_count=job_count, tg_count=tg_count,
        bw_avail=bw_avail, bw_used=bw_used, eligible=eligible, ask=ask,
        bw_ask=bw_ask, count=count, penalty=penalty, jd=jd, td=td,
    )


@pytest.mark.parametrize("seed", range(N_KERNEL_SEEDS))
def test_kernel_threeway_agreement(seed):
    """waterfill == rounds_fused exactly; greedy places the same total and
    respects the same per-node capacity."""
    from nomad_tpu.ops.binpack import (
        bucket,
        solve_greedy,
        solve_rounds_fused,
        solve_waterfill,
    )

    rng = np.random.default_rng(10_000 + seed)
    s = _random_solve_inputs(rng)
    sched_cap = s["total"][:, :2].astype(np.float32)
    args = (
        jnp.asarray(s["total"]), jnp.asarray(sched_cap),
        jnp.asarray(s["used"]), jnp.asarray(s["job_count"]),
        jnp.asarray(s["tg_count"]), jnp.asarray(s["bw_avail"]),
        jnp.asarray(s["bw_used"]), jnp.asarray(s["eligible"]),
        jnp.asarray(s["ask"]), jnp.int32(s["bw_ask"]),
    )
    wf_counts, wf_left = solve_waterfill(
        *args, jnp.int32(s["count"]), jnp.float32(s["penalty"]),
        s["jd"], s["td"],
    )
    rf_counts, rf_left = solve_rounds_fused(
        *args, jnp.int32(s["count"]), jnp.float32(s["penalty"]),
        s["jd"], s["td"],
    )
    wf_counts = np.asarray(wf_counts)
    np.testing.assert_array_equal(
        wf_counts, np.asarray(rf_counts),
        err_msg=f"waterfill != rounds_fused (seed {seed})",
    )
    assert int(wf_left) == int(rf_left), seed

    # Greedy scan (capped k for runtime): same placement total over the
    # same prefix.
    k_cap = min(s["count"], 64)
    k = bucket(k_cap)
    active = jnp.arange(k) < k_cap
    _idxs, oks, _ = solve_greedy(
        *args, active, jnp.float32(s["penalty"]), k, s["jd"], s["td"],
    )
    greedy_placed = int(np.asarray(oks).sum())
    # Both must saturate: greedy places min(k_cap, capacity); water-fill's
    # total is min(count, capacity) with k_cap <= count.
    capacity_reached = int(wf_counts.sum())
    assert greedy_placed == min(k_cap, capacity_reached), (
        seed, greedy_placed, capacity_reached,
    )

    # Soundness: counts never exceed per-ask capacity on any node.
    avail = s["total"] - s["used"]
    for i in range(len(wf_counts)):
        c = int(wf_counts[i])
        if c == 0:
            continue
        assert s["eligible"][i], (seed, i)
        assert np.all(s["ask"] * c <= avail[i]), (seed, i)
        if s["bw_ask"] > 0:
            assert s["bw_used"][i] + c * s["bw_ask"] <= s["bw_avail"][i]
        if s["jd"]:
            assert c <= 1 and s["job_count"][i] == 0
        if s["td"]:
            assert c <= 1 and s["tg_count"][i] == 0


# ---------------------------------------------------------------------------
# 2. Scheduler-level differential: tpu-* vs host oracle


def _random_cluster(rng, n):
    nodes = []
    for i in range(n):
        res = Resources(
            cpu=int(rng.integers(500, 8000)),
            memory_mb=int(rng.integers(512, 16384)),
            disk_mb=int(rng.integers(10_000, 200_000)),
            iops=int(rng.integers(50, 300)),
            networks=[NetworkResource(
                device="eth0", cidr="192.168.0.0/16", ip=f"192.168.{i%250}.1",
                mbits=int(rng.integers(100, 1001)),
            )],
        )
        node = Node(
            id=f"{seeded_hex(rng)}",
            datacenter="dc1" if rng.random() < 0.7 else "dc2",
            name=f"node-{i}",
            attributes={
                "kernel.name": "linux" if rng.random() < 0.8 else "darwin",
                "arch": "amd64",
                "driver.exec": "1",
                "driver.docker": "1" if rng.random() < 0.6 else "0",
            },
            resources=res,
            status=structs.NODE_STATUS_READY,
        )
        nodes.append(node)
    return nodes


def seeded_hex(rng):
    return "".join(rng.choice(list("0123456789abcdef"), 32))


def _random_job(rng):
    jtype = str(rng.choice([structs.JOB_TYPE_SERVICE, structs.JOB_TYPE_BATCH]))
    constraints = []
    if rng.random() < 0.5:
        constraints.append(Constraint(
            l_target="$attr.kernel.name", r_target="linux", operand="=",
        ))
    if rng.random() < 0.2:
        constraints.append(Constraint(operand="distinct_hosts"))
    task_res = Resources(
        cpu=int(rng.integers(20, 1200)),
        memory_mb=int(rng.integers(16, 2048)),
    )
    if rng.random() < 0.4:
        task_res.networks = [
            NetworkResource(mbits=int(rng.integers(1, 120)))
        ]
    count = int(rng.choice([1, 3, 17, 60, 140, 300]))
    job = Job(
        region="global",
        id=generate_uuid(),
        name="fuzz",
        type=jtype,
        priority=50,
        datacenters=["dc1"] if rng.random() < 0.5 else ["dc1", "dc2"],
        constraints=constraints,
        task_groups=[TaskGroup(
            name="tg",
            count=count,
            restart_policy=RestartPolicy(
                attempts=1, interval=600.0, delay=5.0
            ),
            tasks=[Task(name="t", driver="exec", resources=task_res)],
        )],
    )
    return job


def _run_eval(factory, nodes, job, trigger=structs.EVAL_TRIGGER_JOB_REGISTER,
              harness=None):
    h = harness or Harness()
    if harness is None:
        for node in nodes:
            h.state.upsert_node(h.next_index(), node)
        h.state.upsert_job(h.next_index(), job)
    ev = Evaluation(
        id=generate_uuid(), priority=job.priority, type=job.type,
        triggered_by=trigger, job_id=job.id,
    )
    h.process(factory, ev)
    return h


def _placed_and_failed(h):
    placed = 0
    for plan in h.plans:
        placed += sum(len(v) for v in plan.node_allocation.values())
        placed += sum(b.n for b in plan.alloc_batches)
    failed = sum(
        (a.metrics.coalesced_failures + 1 if a.metrics else 1)
        for plan in h.plans for a in plan.failed_allocs
    )
    return placed, failed


def _check_capacity(h, nodes):
    """No committed plan may overcommit any node (funcs.go:44-87)."""
    by_id = {n.id: n for n in nodes}
    for node in nodes:
        allocs = [
            a for a in h.state.allocs_by_node(node.id)
            if a.desired_status == structs.ALLOC_DESIRED_STATUS_RUN
        ]
        if not allocs:
            continue
        idx = NetworkIndex()
        idx.set_node(node)
        fit, dim, _used = structs.allocs_fit(node, allocs, idx)
        assert fit, (node.id, dim, len(allocs))
    # And every placement names a real node
    for plan in h.plans:
        for nid in plan.node_allocation:
            assert nid in by_id
        for b in plan.alloc_batches:
            for nid in b.node_ids:
                assert nid in by_id


@pytest.mark.parametrize("seed", range(N_SCHED_SEEDS))
def test_scheduler_differential_fresh_registration(seed):
    """Fresh job registration on a random cluster: the dense solve places
    exactly as many as the host oracle, soundly."""
    master = np.random.default_rng(20_000 + seed)
    n = int(master.integers(1, 60)) if seed % 10 else int(
        master.integers(100, 201)
    )
    results = {}
    for factory_kind in ("host", "tpu"):
        rng = np.random.default_rng(20_000 + seed)  # identical stream
        _ = rng.integers(1, 60) if seed % 10 else rng.integers(100, 201)
        nodes = _random_cluster(rng, n)
        job = _random_job(rng)
        factory = job.type if factory_kind == "host" else f"tpu-{job.type}"
        h = _run_eval(factory, nodes, job)
        placed, failed = _placed_and_failed(h)
        _check_capacity(h, nodes)
        results[factory_kind] = (placed, failed, job.task_groups[0].count)

    (hp, hf, count) = results["host"]
    (tp, tf, _) = results["tpu"]
    assert hp + hf == count
    assert tp + tf == count
    assert tp == hp, (
        f"seed {seed}: tpu placed {tp}, host placed {hp} (count {count})"
    )


@pytest.mark.parametrize("seed", range(0, N_SCHED_SEEDS, 3))
def test_scheduler_differential_rolling_update(seed):
    """Phase 2: mutate the job (resources bump -> destructive update) and
    re-evaluate against existing allocs; the dense solve matches the host
    oracle's placement count through the diff/evict path."""
    results = {}
    for factory_kind in ("host", "tpu"):
        rng = np.random.default_rng(30_000 + seed)
        n = int(rng.integers(2, 40))
        nodes = _random_cluster(rng, n)
        job = _random_job(rng)
        job.task_groups[0].count = min(job.task_groups[0].count, 60)
        factory = job.type if factory_kind == "host" else f"tpu-{job.type}"
        h = _run_eval(factory, nodes, job)

        # Mutate: resource bump forces destructive updates; count change
        # exercises place/stop.
        job2 = job  # same object graph is fine: store holds it by id
        if rng.random() < 0.5:
            job2.task_groups[0].tasks[0].resources.cpu += 17
        else:
            job2.task_groups[0].count = max(
                1, job2.task_groups[0].count + int(rng.integers(-20, 21))
            )
        h.state.upsert_job(h.next_index(), job2)
        ev = Evaluation(
            id=generate_uuid(), priority=job2.priority, type=job2.type,
            triggered_by=structs.EVAL_TRIGGER_JOB_REGISTER, job_id=job2.id,
        )
        h.process(factory, ev)
        _check_capacity(h, nodes)
        final = [
            a for a in h.state.allocs_by_job(job2.id)
            if a.desired_status == structs.ALLOC_DESIRED_STATUS_RUN
        ]
        results[factory_kind] = len(final)

    assert results["tpu"] == results["host"], f"seed {seed}: {results}"


# ---------------------------------------------------------------------------
# 2b. Placement QUALITY: dense global argmax vs power-of-two-choices
# ---------------------------------------------------------------------------


N_QUALITY_SEEDS = int(os.environ.get("NOMAD_TPU_QUALITY_SEEDS", 110))


def _aggregate_fitness(h, nodes):
    """Aggregate BestFit-v3 quality of a committed placement: each RUN
    alloc scores its node's FINAL utilization (structs.score_fit, the
    same kernel the device solve maximizes — funcs.go:89-124), weighted
    by the allocs packed there. Higher = tighter packing."""
    from nomad_tpu.structs import score_fit

    total = 0.0
    for node in nodes:
        live = [
            a for a in h.state.allocs_by_node(node.id)
            if a.desired_status == structs.ALLOC_DESIRED_STATUS_RUN
        ]
        if not live:
            continue
        util = Resources(
            cpu=sum(a.resources.cpu for a in live),
            memory_mb=sum(a.resources.memory_mb for a in live),
        )
        total += len(live) * score_fit(node, util)
    return total


def test_scheduler_quality_tpu_at_least_host():
    """The tpu/solver.py design claim, asserted instead of argued: the
    host GenericStack ranks only a random ~log2(n) subset of feasible
    nodes (power-of-two-choices, stack.go:94-121) while the dense solve
    scores every node, "so placement quality is >= host". Aggregated
    across >= 100 seeded random clusters on identical state, the TPU
    factories' aggregate score_fit must be at least the host oracle's
    (both greedy, so any single seed can wobble either way — the
    aggregate is the claim; gross per-seed regressions are also caught).
    """
    totals = {"host": 0.0, "tpu": 0.0}
    per_seed = []
    for seed in range(N_QUALITY_SEEDS):
        scores = {}
        for factory_kind in ("host", "tpu"):
            rng = np.random.default_rng(80_000 + seed)  # identical stream
            n = int(rng.integers(4, 40))
            nodes = _random_cluster(rng, n)
            job = _random_job(rng)
            # Network-free: port assignment is a host post-pass on BOTH
            # paths and only adds runtime, not quality signal.
            job.task_groups[0].tasks[0].resources.networks = []
            job.task_groups[0].count = min(job.task_groups[0].count, 80)
            factory = job.type if factory_kind == "host" else f"tpu-{job.type}"
            h = _run_eval(factory, nodes, job)
            placed, _ = _placed_and_failed(h)
            scores[factory_kind] = (_aggregate_fitness(h, nodes), placed)
        # Quality is only comparable on equal placement counts (count
        # parity is its own differential above).
        assert scores["tpu"][1] == scores["host"][1], (seed, scores)
        totals["host"] += scores["host"][0]
        totals["tpu"] += scores["tpu"][0]
        per_seed.append((seed, scores["tpu"][0], scores["host"][0]))

    assert totals["tpu"] >= totals["host"] * (1.0 - 1e-9), (
        f"aggregate quality regression: tpu {totals['tpu']:.1f} < "
        f"host {totals['host']:.1f} over {N_QUALITY_SEEDS} seeds; worst "
        f"seeds: {sorted(per_seed, key=lambda s: s[1] - s[2])[:5]}"
    )
    # No catastrophic single-seed loss hiding inside a winning aggregate:
    # flag any seed where tpu scores under half the host packing.
    bad = [s for s in per_seed if s[1] < 0.5 * s[2] - 1e-9]
    assert not bad, f"gross per-seed quality loss: {bad[:5]}"


# ---------------------------------------------------------------------------
# 3. System-scheduler differential: tpu-system vs host oracle


def _random_system_job(rng):
    constraints = []
    if rng.random() < 0.6:
        constraints.append(Constraint(
            l_target="$attr.kernel.name", r_target="linux", operand="=",
        ))
    if rng.random() < 0.3:
        constraints.append(Constraint(
            l_target="$attr.driver.docker", r_target="1", operand="=",
        ))
    task_res = Resources(
        cpu=int(rng.integers(20, 1500)),
        memory_mb=int(rng.integers(16, 4096)),
    )
    if rng.random() < 0.3:
        task_res.networks = [NetworkResource(mbits=int(rng.integers(1, 200)))]
    return Job(
        region="global",
        id=generate_uuid(),
        name="fuzz-sys",
        type=structs.JOB_TYPE_SYSTEM,
        priority=50,
        datacenters=["dc1"] if rng.random() < 0.5 else ["dc1", "dc2"],
        constraints=constraints,
        task_groups=[TaskGroup(
            name="sys",
            count=1,
            restart_policy=RestartPolicy(attempts=1, interval=600.0, delay=5.0),
            tasks=[Task(name="t", driver="exec", resources=task_res)],
        )],
    )


@pytest.mark.parametrize("seed", range(0, N_SCHED_SEEDS, 2))
def test_scheduler_differential_system(seed):
    """System (one-alloc-per-node) jobs: tpu-system must place on exactly
    the same number of nodes as the host oracle, never more than one per
    node (reference oracle: scheduler/system_sched_test.go)."""
    results = {}
    for factory_kind in ("host", "tpu"):
        rng = np.random.default_rng(40_000 + seed)
        n = int(rng.integers(1, 80))
        nodes = _random_cluster(rng, n)
        job = _random_system_job(rng)
        factory = "system" if factory_kind == "host" else "tpu-system"
        h = _run_eval(factory, nodes, job)
        placed, _failed = _placed_and_failed(h)
        _check_capacity(h, nodes)
        # One-per-node invariant.
        per_node = {}
        for node in nodes:
            live = [
                a for a in h.state.allocs_by_node(node.id)
                if a.desired_status == structs.ALLOC_DESIRED_STATUS_RUN
            ]
            assert len(live) <= 1, (seed, node.id, len(live))
            per_node[node.id] = len(live)
        results[factory_kind] = (placed, sum(per_node.values()))

    assert results["tpu"] == results["host"], f"seed {seed}: {results}"


# ---------------------------------------------------------------------------
# 4. Port-bearing groups at scale (the small-path routing's parity contract)


def _random_port_job(rng, count):
    """Network asks with reserved AND dynamic ports — the inherently
    sequential assignment the device path routes host-side
    (network.go:136-194); parity must survive count > BATCH threshold."""
    net = NetworkResource(mbits=int(rng.integers(1, 80)))
    if rng.random() < 0.6:
        net.reserved_ports = [int(rng.integers(20000, 20004))]
    if rng.random() < 0.6:
        net.dynamic_ports = ["http"]
    if not net.reserved_ports and not net.dynamic_ports:
        net.reserved_ports = [20001]
    task_res = Resources(
        cpu=int(rng.integers(20, 400)),
        memory_mb=int(rng.integers(16, 512)),
        networks=[net],
    )
    return Job(
        region="global",
        id=generate_uuid(),
        name="fuzz-ports",
        type=str(rng.choice([structs.JOB_TYPE_SERVICE, structs.JOB_TYPE_BATCH])),
        priority=50,
        datacenters=["dc1", "dc2"],
        task_groups=[TaskGroup(
            name="web",
            count=count,
            restart_policy=RestartPolicy(attempts=1, interval=600.0, delay=5.0),
            tasks=[Task(name="t", driver="exec", resources=task_res)],
        )],
    )


@pytest.mark.parametrize("seed", range(0, N_SCHED_SEEDS, 4))
def test_scheduler_differential_ports_at_scale(seed):
    """count > 128 (the batched-path threshold) with reserved/dynamic port
    asks: the device factories route these through the sequential network
    offer, and the placement count must still match the host oracle —
    with no port collisions in committed state (allocs_fit port check)."""
    results = {}
    for factory_kind in ("host", "tpu"):
        rng = np.random.default_rng(50_000 + seed)
        n = int(rng.integers(40, 140))
        count = int(rng.integers(129, 300))
        nodes = _random_cluster(rng, n)
        job = _random_port_job(rng, count)
        factory = job.type if factory_kind == "host" else f"tpu-{job.type}"
        h = _run_eval(factory, nodes, job)
        placed, failed = _placed_and_failed(h)
        _check_capacity(h, nodes)  # includes NetworkIndex port collisions
        assert placed + failed == count, (seed, placed, failed, count)
        # Offered networks must never reuse a (ip, reserved port) pair on a
        # node — the same port on DIFFERENT IPs of the CIDR is legal
        # (AssignNetwork yields per-IP, network.go:136-194).
        for node in nodes:
            seen = set()
            for a in h.state.allocs_by_node(node.id):
                if a.desired_status != structs.ALLOC_DESIRED_STATUS_RUN:
                    continue
                for tr in a.task_resources.values():
                    for net in tr.networks:
                        for port in net.reserved_ports:
                            key = (net.ip, port)
                            assert key not in seen, (seed, node.id, key)
                            seen.add(key)
        results[factory_kind] = placed

    assert results["tpu"] == results["host"], f"seed {seed}: {results}"


# ---------------------------------------------------------------------------
# 5. Concurrent coalesced evals racing plan-apply (optimistic concurrency)


@pytest.mark.parametrize("seed", range(4))
def test_concurrent_coalesced_race_no_overcommit(seed):
    """Several jobs whose combined ask EXCEEDS cluster capacity are solved
    concurrently (broker batch -> coalesced dispatch) against the same
    snapshot; plan-apply's serialized verification must reject the
    overflow: post-commit, no node is overcommitted and total placements
    never exceed capacity (nomad/plan_apply.go:167-277 posture)."""
    import time as _time

    from nomad_tpu.server import Server, ServerConfig

    rng = np.random.default_rng(60_000 + seed)
    n_nodes = 8
    per_node_cap = 4  # 4 tasks of 1000cpu on a 4000cpu node
    capacity = n_nodes * per_node_cap
    n_jobs = 4
    # Each job alone fits; together they ask for 2x capacity.
    per_job = capacity * 2 // n_jobs

    srv = Server(ServerConfig(
        scheduler_backend="tpu", num_schedulers=2, eval_batch_size=n_jobs,
        periodic_dispatch=False, prewarm_shapes=False,
    ))
    try:
        nodes = []
        for i in range(n_nodes):
            node = Node(
                id=f"race-{seed}-{i}",
                datacenter="dc1",
                name=f"n{i}",
                attributes={"kernel.name": "linux", "driver.exec": "1"},
                resources=Resources(
                    cpu=4000, memory_mb=16384, disk_mb=100_000, iops=1000,
                ),
                status=structs.NODE_STATUS_READY,
            )
            srv.raft.apply("node_register", {"node": node})
            nodes.append(node)
        evals = []
        for j in range(n_jobs):
            job = Job(
                region="global", id=generate_uuid(), name=f"race-{j}",
                type=structs.JOB_TYPE_BATCH, priority=50,
                datacenters=["dc1"],
                task_groups=[TaskGroup(
                    name="work", count=per_job,
                    restart_policy=RestartPolicy(
                        attempts=0, interval=600.0, delay=1.0,
                    ),
                    tasks=[Task(name="t", driver="exec",
                                resources=Resources(cpu=1000, memory_mb=64))],
                )],
            )
            srv.raft.apply("job_register", {"job": job})
            evals.append(Evaluation(
                id=generate_uuid(), priority=50, type=job.type,
                triggered_by=structs.EVAL_TRIGGER_JOB_REGISTER,
                job_id=job.id, status=structs.EVAL_STATUS_PENDING,
            ))
        srv.start()
        # One batch: all evals land at once and race through plan-apply.
        srv.raft.apply("eval_update", {"evals": evals})
        deadline = _time.monotonic() + 90
        while _time.monotonic() < deadline:
            done = [srv.state_store.eval_by_id(e.id) for e in evals]
            if all(d is not None
                   and d.status != structs.EVAL_STATUS_PENDING for d in done):
                break
            _time.sleep(0.02)
        else:
            raise AssertionError("evals did not finish")

        total_live = 0
        for node in nodes:
            live = [
                a for a in srv.state_store.allocs_by_node(node.id)
                if a.desired_status == structs.ALLOC_DESIRED_STATUS_RUN
            ]
            total_live += len(live)
            fit, dim, _ = structs.allocs_fit(node, live)
            assert fit, (seed, node.id, dim, len(live))
            assert len(live) <= per_node_cap
        assert total_live <= capacity
        # The winners actually landed: the race must not starve everyone.
        assert total_live > 0
    finally:
        srv.shutdown()


# ---------------------------------------------------------------------------
# 2c. Anti-affinity penalty accounting parity (VERDICT r5 item 5b)
# ---------------------------------------------------------------------------


def _collision_penalty(h, nodes, job):
    """Total anti-affinity penalty the committed placement incurred:
    placing the k-th alloc of a job on a node already holding j of them
    costs j*p (rank.go:240-302), so a node ending with k allocs paid
    p * k*(k-1)/2."""
    from nomad_tpu.scheduler.stack import (
        BATCH_JOB_ANTI_AFFINITY_PENALTY,
        SERVICE_JOB_ANTI_AFFINITY_PENALTY,
    )

    p = (BATCH_JOB_ANTI_AFFINITY_PENALTY
         if job.type == structs.JOB_TYPE_BATCH
         else SERVICE_JOB_ANTI_AFFINITY_PENALTY)
    total = 0.0
    for node in nodes:
        k = sum(
            1 for a in h.state.allocs_by_node(node.id)
            if a.desired_status == structs.ALLOC_DESIRED_STATUS_RUN
            and a.job_id == job.id
        )
        total += p * k * (k - 1) / 2.0
    return total


def _roomy_nodes(n):
    """Identical, roomy nodes: collisions are capacity-feasible, so the
    only force spreading placements is the anti-affinity penalty — a path
    that ignored it would BestFit-stack onto few nodes."""
    from nomad_tpu.structs import Node, Resources

    return [
        Node(
            id=f"aff-{i:03d}", datacenter="dc1", name=f"n{i}",
            attributes={"kernel.name": "linux", "driver.exec": "1"},
            resources=Resources(cpu=16000, memory_mb=32768,
                                disk_mb=500_000, iops=10_000),
            status=structs.NODE_STATUS_READY,
        )
        for i in range(n)
    ]


@pytest.mark.parametrize("seed", range(0, N_SCHED_SEEDS, 2))
def test_scheduler_differential_anti_affinity_penalty(seed):
    """Forced co-placement (count a multiple of the node count, ample
    capacity): the device solve's in-kernel penalty term must account
    collisions like the host's JobAntiAffinityIterator. Asserted on the
    committed state: equal placement counts, TPU total collision penalty
    <= host's (the dense solve scores every node; the host samples
    ~log2(n)), and — on identical nodes, where even spread is the unique
    penalty-optimal shape — a perfectly balanced per-node distribution."""
    results = {}
    for factory_kind in ("host", "tpu"):
        rng = np.random.default_rng(90_000 + seed)
        n = int(rng.integers(3, 12))
        per_node = int(rng.integers(2, 5))
        count = n * per_node
        jtype = str(rng.choice(
            [structs.JOB_TYPE_SERVICE, structs.JOB_TYPE_BATCH]
        ))
        nodes = _roomy_nodes(n)
        job = Job(
            region="global", id=generate_uuid(), name="fuzz-aff",
            type=jtype, priority=50, datacenters=["dc1"],
            task_groups=[TaskGroup(
                name="tg", count=count,
                restart_policy=RestartPolicy(
                    attempts=1, interval=600.0, delay=5.0,
                ),
                tasks=[Task(name="t", driver="exec",
                            resources=Resources(cpu=100, memory_mb=64))],
            )],
        )
        factory = job.type if factory_kind == "host" else f"tpu-{job.type}"
        h = _run_eval(factory, nodes, job)
        placed, failed = _placed_and_failed(h)
        assert placed == count and failed == 0, (seed, factory_kind, placed)
        _check_capacity(h, nodes)
        per_node_counts = sorted(
            sum(1 for a in h.state.allocs_by_node(node.id)
                if a.desired_status == structs.ALLOC_DESIRED_STATUS_RUN)
            for node in nodes
        )
        results[factory_kind] = (
            _collision_penalty(h, nodes, job), per_node_counts,
        )

    host_pen, host_dist = results["host"]
    tpu_pen, tpu_dist = results["tpu"]
    # Identical nodes: even spread is penalty-optimal and both greedy
    # paths must find it — any stacking means the penalty was dropped.
    assert tpu_dist[0] == tpu_dist[-1] == per_node, (seed, tpu_dist)
    assert host_dist[0] == host_dist[-1] == per_node, (seed, host_dist)
    assert tpu_pen <= host_pen + 1e-9, (seed, tpu_pen, host_pen)


# ---------------------------------------------------------------------------
# 2d. Rolling-update / in-place identity parity (VERDICT r5 item 5c)
# ---------------------------------------------------------------------------


def _run_ids(h, job):
    return sorted(
        a.id for a in h.state.allocs_by_job(job.id)
        if a.desired_status == structs.ALLOC_DESIRED_STATUS_RUN
    )


def _identity_phases(factory_kind, seed, count):
    """Place -> resource-only bump (in-place) -> env change (destructive);
    returns the three RUN-alloc id sets plus the job modify indexes the
    final allocs carry."""
    import copy

    rng = np.random.default_rng(95_000 + seed)
    n = max(4, count // 2)
    nodes = _roomy_nodes(n)
    job = Job(
        region="global", id=generate_uuid(), name="fuzz-ident",
        type=structs.JOB_TYPE_SERVICE, priority=50, datacenters=["dc1"],
        task_groups=[TaskGroup(
            name="web", count=count,
            restart_policy=RestartPolicy(
                attempts=1, interval=600.0, delay=5.0,
            ),
            tasks=[Task(name="t", driver="exec",
                        resources=Resources(
                            cpu=int(rng.integers(50, 200)),
                            memory_mb=64,
                        ))],
        )],
    )
    factory = job.type if factory_kind == "host" else f"tpu-{job.type}"
    h = _run_eval(factory, nodes, job)
    ids0 = _run_ids(h, job)
    assert len(ids0) == count, (seed, factory_kind, len(ids0))

    # Phase 2: cpu+1 — tasks_updated() false, every node has headroom:
    # the in-place path MUST keep every alloc id (util.go:316-398; the
    # block path commits a field swap, state/blocks.py with_update).
    # Deep copy: existing allocs embed the job object, and mutating it in
    # place would make the diff see no modify_index change at all.
    job2 = copy.deepcopy(job)
    job2.task_groups[0].tasks[0].resources.cpu += 1
    h.state.upsert_job(h.next_index(), job2)
    ev = Evaluation(
        id=generate_uuid(), priority=job2.priority, type=job2.type,
        triggered_by=structs.EVAL_TRIGGER_JOB_REGISTER, job_id=job2.id,
    )
    h.process(factory, ev)
    ids1 = _run_ids(h, job2)
    inplace_mod = {
        a.job.modify_index
        for a in h.state.allocs_by_job(job2.id)
        if a.desired_status == structs.ALLOC_DESIRED_STATUS_RUN
    }

    # Phase 3: env change — destructive; every alloc must be REPLACED.
    job3 = copy.deepcopy(job2)
    job3.task_groups[0].tasks[0].env = {"V": "2"}
    h.state.upsert_job(h.next_index(), job3)
    ev = Evaluation(
        id=generate_uuid(), priority=job3.priority, type=job3.type,
        triggered_by=structs.EVAL_TRIGGER_JOB_REGISTER, job_id=job3.id,
    )
    h.process(factory, ev)
    ids2 = _run_ids(h, job3)
    _check_capacity(h, nodes)
    return ids0, ids1, ids2, inplace_mod, job2.modify_index


@pytest.mark.parametrize("seed", range(0, N_SCHED_SEEDS, 3))
def test_scheduler_differential_inplace_identity(seed):
    """Resource-only bump with guaranteed headroom: BOTH factories must
    update the same allocs in place (identical id sets before/after, job
    version advanced) — and an env change must replace every id. The
    object-diff path (count < 256)."""
    for factory_kind in ("host", "tpu"):
        ids0, ids1, ids2, mods, job2_idx = _identity_phases(
            factory_kind, seed, count=int(
                np.random.default_rng(95_000 + seed).integers(5, 40)
            ),
        )
        assert ids1 == ids0, (seed, factory_kind, "in-place changed ids")
        assert mods == {job2_idx}, (seed, factory_kind, mods)
        assert len(ids2) == len(ids0), (seed, factory_kind)
        assert not set(ids2) & set(ids0), (
            seed, factory_kind, "destructive update kept old ids"
        )


def test_scheduler_inplace_identity_block_native():
    """Same contract at columnar scale (count >= 256): the TPU path's
    block-native in-place machinery (whole-block field swap, no member
    materialization) must preserve the seed-derived id column exactly,
    and the host oracle agrees on every phase's cardinality."""
    out = {}
    for factory_kind in ("host", "tpu"):
        ids0, ids1, ids2, mods, job2_idx = _identity_phases(
            factory_kind, seed=1, count=300,
        )
        assert ids1 == ids0, (factory_kind, "in-place changed ids")
        assert mods == {job2_idx}, (factory_kind, mods)
        assert not set(ids2) & set(ids0), (factory_kind,)
        out[factory_kind] = (len(ids0), len(ids1), len(ids2))
    assert out["tpu"] == out["host"] == (300, 300, 300)


@pytest.mark.parametrize(
    "seed", range(int(os.environ.get("NOMAD_TPU_BURST_SEEDS", "6")))
)
def test_burst_mix_matches_serial(seed):
    """Differential for the announced-burst machinery (enqueue_many +
    hint_burst + generation-scoped accounting): a random mix of jobs —
    columnar-scale counts, exact-path small counts, and system jobs —
    lands once as ONE broker burst and once serially. Both modes must
    complete every eval, place every asked task (total ask fits), and
    leave every node within capacity; burst members that never reach the
    coalescer (exact path) must resolve the hold, not stall it."""
    import time as _time

    from nomad_tpu.server import Server, ServerConfig

    rng = np.random.default_rng(70_000 + seed)
    n_nodes = 16
    asks = []
    for _ in range(int(rng.integers(3, 7))):
        kind = rng.choice(["columnar", "exact", "system", "equiv"])
        if kind == "columnar":
            count = int(rng.integers(129, 400))
        elif kind == "exact":
            count = int(rng.integers(1, 129))
        elif kind == "equiv":
            # 2-3 identical columnar task groups in ONE job: the
            # equivalence-class collapse rides the burst too.
            count = int(rng.integers(2, 4)) * 256
        else:
            count = None  # one per node
        asks.append((kind, count))
    # Small per-task ask so the whole mix always fits: worst case
    # 6 jobs * max(399, 768) tasks * 10cpu <= 16 nodes * 4000 cpu.
    expected = sum(
        (n_nodes if kind == "system" else count) for kind, count in asks
    )

    def run_mode(batch_size):
        srv = Server(ServerConfig(
            scheduler_backend="tpu", num_schedulers=2,
            eval_batch_size=batch_size, periodic_dispatch=False,
            prewarm_shapes=False,
        ))
        try:
            nodes = []
            for i in range(n_nodes):
                node = Node(
                    id=f"bm-{seed}-{i}", datacenter="dc1", name=f"n{i}",
                    attributes={"kernel.name": "linux", "driver.exec": "1"},
                    resources=Resources(cpu=4000, memory_mb=16384,
                                        disk_mb=100_000, iops=1000),
                    status=structs.NODE_STATUS_READY,
                )
                srv.raft.apply("node_register", {"node": node})
                nodes.append(node)
            jobs, evals = [], []
            for j, (kind, count) in enumerate(asks):
                if kind == "equiv":
                    tgs = [
                        TaskGroup(
                            name=f"work{m}", count=256,
                            restart_policy=RestartPolicy(
                                attempts=0, interval=600.0, delay=1.0,
                            ),
                            tasks=[Task(
                                name="t", driver="exec",
                                resources=Resources(cpu=10,
                                                    memory_mb=16))],
                        )
                        for m in range(count // 256)
                    ]
                else:
                    tgs = [TaskGroup(
                        name="work", count=1 if kind == "system" else count,
                        restart_policy=RestartPolicy(
                            attempts=0, interval=600.0, delay=1.0,
                        ),
                        tasks=[Task(
                            name="t", driver="exec",
                            resources=Resources(cpu=10, memory_mb=16))],
                    )]
                job = Job(
                    region="global", id=generate_uuid(),
                    name=f"bm-{j}-{kind}",
                    type=(structs.JOB_TYPE_SYSTEM if kind == "system"
                          else structs.JOB_TYPE_BATCH),
                    priority=50, datacenters=["dc1"], task_groups=tgs,
                )
                srv.raft.apply("job_register", {"job": job})
                jobs.append(job)
                evals.append(Evaluation(
                    id=generate_uuid(), priority=50, type=job.type,
                    triggered_by=structs.EVAL_TRIGGER_JOB_REGISTER,
                    job_id=job.id, status=structs.EVAL_STATUS_PENDING,
                ))
            srv.start()
            if batch_size > 1:
                srv.raft.apply("eval_update", {"evals": evals})
            else:
                for ev in evals:
                    srv.raft.apply("eval_update", {"evals": [ev]})
            deadline = _time.monotonic() + 120
            while _time.monotonic() < deadline:
                done = [srv.state_store.eval_by_id(e.id) for e in evals]
                if all(d is not None and d.status not in
                       (structs.EVAL_STATUS_PENDING,) for d in done):
                    break
                _time.sleep(0.02)
            else:
                raise AssertionError((seed, batch_size, "evals stuck"))
            statuses = {srv.state_store.eval_by_id(e.id).status
                        for e in evals}
            assert statuses == {structs.EVAL_STATUS_COMPLETE}, (
                seed, batch_size, statuses)
            placed = {}
            for job in jobs:
                placed[job.name] = sum(
                    1 for a in srv.state_store.allocs_by_job(job.id)
                    if a.desired_status == structs.ALLOC_DESIRED_STATUS_RUN
                )
            for node in nodes:
                live = [
                    a for a in srv.state_store.allocs_by_node(node.id)
                    if a.desired_status == structs.ALLOC_DESIRED_STATUS_RUN
                ]
                fit, dim, _ = structs.allocs_fit(node, live)
                assert fit, (seed, batch_size, node.id, dim)
            return placed
        finally:
            srv.shutdown()

    burst = run_mode(len(asks))
    serial = run_mode(1)
    assert burst == serial, (seed, burst, serial)
    assert sum(burst.values()) == expected, (seed, burst, expected)


# ---------------------------------------------------------------------------
# 2f. Cross-eval batched exact solve: stacked dispatch ≡ individual solves
# ---------------------------------------------------------------------------


def _exact_cluster(rng, n):
    """Shared node tensors for a stacked exact dispatch — one mirror's
    (total, sched_cap, bw_avail), the identity the coalescer groups on."""
    total = np.zeros((n, 4), dtype=np.int32)
    total[:, 0] = rng.integers(200, 8000, n)
    total[:, 1] = rng.integers(128, 16384, n)
    total[:, 2] = rng.integers(1024, 200_000, n)
    total[:, 3] = rng.integers(10, 300, n)
    return (
        jnp.asarray(total), jnp.asarray(total[:, :2].astype(np.float32)),
        jnp.asarray(rng.integers(100, 2000, n).astype(np.int32)),
        total,
    )


def _exact_entry_args(rng, n, cluster):
    """One random exact-solve input set over the shared cluster: the
    per-eval tensors (usage, eligibility, ask) vary, the node tensors
    are the mirror's (shared objects, like burst members of one state
    generation)."""
    total_dev, sched_cap_dev, bw_avail_dev, total = cluster
    used = (total * (rng.random((n, 1)) * 0.6)).astype(np.int32)
    ask = np.array([
        int(rng.integers(1, 1500)), int(rng.integers(1, 2048)),
        int(rng.integers(0, 2000)), int(rng.integers(0, 50)),
    ], dtype=np.int32)
    count = int(rng.integers(1, 129))
    return (
        total_dev, sched_cap_dev,
        jnp.asarray(used), jnp.zeros((n,), jnp.int32),
        jnp.zeros((n,), jnp.int32),
        bw_avail_dev,
        jnp.zeros((n,), jnp.int32),
        jnp.asarray(rng.random(n) > 0.2),
        jnp.asarray(ask), jnp.int32(int(rng.integers(0, 100))),
        count, float(rng.choice([5.0, 10.0])), False, False,
    )


@pytest.mark.parametrize("seed", range(0, N_KERNEL_SEEDS, 4))
def test_stacked_exact_dispatch_matches_individual(seed):
    """The cross-eval batched exact scan (solve_greedy_batched through
    the coalescer's stacked dispatch) must return BIT-IDENTICAL
    (idxs, oks) to each entry's lone solve_greedy dispatch — the
    decision-identity contract of ISSUE 14's batching. Heterogeneous
    counts within one count bucket, heterogeneous asks/usage, padded
    eval rows; mesh=1 (the default single-device fallback path)."""
    from nomad_tpu.ops.binpack import bucket, solve_greedy
    from nomad_tpu.ops.coalesce import CoalescingSolver, _Entry

    rng = np.random.default_rng(130_000 + seed)
    n = int(rng.choice([32, 64]))
    cluster = _exact_cluster(rng, n)
    k_target = None
    entries = []
    raw = []
    # 2-7 entries of ONE count bucket (the dispatcher's grouping key),
    # counts heterogeneous inside it.
    width = int(rng.integers(2, 8))
    while len(entries) < width:
        args = _exact_entry_args(rng, n, cluster)
        k = bucket(args[10])
        if k_target is None:
            k_target = k
        elif k != k_target:
            continue
        raw.append(args)
        entries.append(_Entry(args, kind="exact", k=k))
    engine = CoalescingSolver()
    d0 = engine.dispatches
    engine._dispatch(list(entries))
    assert engine.dispatches == d0 + 1, "one stacked dispatch expected"
    for e, args in zip(entries, raw):
        count = args[10]
        idxs, oks = e.result()
        active = jnp.arange(e.k) < count
        ref_idxs, ref_oks, _ = solve_greedy(
            *args[:10], active, jnp.float32(args[11]), e.k,
            args[12], args[13],
        )
        np.testing.assert_array_equal(
            np.asarray(idxs), np.asarray(ref_idxs),
            err_msg=f"seed {seed} idxs diverge",
        )
        np.testing.assert_array_equal(
            np.asarray(oks), np.asarray(ref_oks),
            err_msg=f"seed {seed} oks diverge",
        )


@pytest.mark.parametrize("seed", range(0, N_SCHED_SEEDS, 6))
def test_equiv_class_collapse_matches_combined(seed):
    """Equivalence classes (Borg): a job of M identical columnar task
    groups must (a) dispatch ONE counts-solve, (b) produce the same
    per-node placement distribution as the single combined-count group
    solved alone, (c) place every copy within capacity, and (d) leave
    the per-member batches carrying the right name-index shares."""
    from nomad_tpu.tpu.solver import SOLVER_PANEL

    rng = np.random.default_rng(140_000 + seed)
    n_nodes = int(rng.integers(8, 24))
    members = int(rng.integers(2, 5))
    count = int(rng.integers(256, 400))
    cpu = int(rng.integers(4, 10))

    def mk_nodes():
        nodes = []
        for i in range(n_nodes):
            node = Node(
                id=f"eq-{seed}-{i}", datacenter="dc1", name=f"n{i}",
                attributes={"kernel.name": "linux", "driver.exec": "1"},
                resources=Resources(cpu=14000, memory_mb=28000,
                                    disk_mb=100_000, iops=1000),
                status=structs.NODE_STATUS_READY,
            )
            nodes.append(node)
        return nodes

    def run(tg_counts):
        h = Harness()
        for node in mk_nodes():
            h.state.upsert_node(h.next_index(), node)
        tgs = [
            TaskGroup(
                name=f"g{j}", count=c,
                restart_policy=RestartPolicy(attempts=0, interval=600.0,
                                             delay=1.0),
                tasks=[Task(name="t", driver="exec",
                            resources=Resources(cpu=cpu, memory_mb=16))],
            )
            for j, c in enumerate(tg_counts)
        ]
        job = Job(
            region="global", id=generate_uuid(), name=f"eqf-{seed}",
            type=structs.JOB_TYPE_BATCH, priority=50,
            datacenters=["dc1"], task_groups=tgs,
        )
        h.state.upsert_job(h.next_index(), job)
        ev = Evaluation(
            id=generate_uuid(), priority=50, type=job.type,
            triggered_by=structs.EVAL_TRIGGER_JOB_REGISTER, job_id=job.id,
        )
        h.process("tpu-batch", ev)
        assert len(h.plans) == 1
        per_node: dict = {}
        per_tg: dict = {}
        for b in h.plans[0].alloc_batches:
            per_tg[b.tg_name] = per_tg.get(b.tg_name, 0) + b.n
            for nid, cnt in zip(b.node_ids, b.node_counts):
                per_node[nid] = per_node.get(nid, 0) + int(cnt)
        return h, per_node, per_tg

    e0 = SOLVER_PANEL.equiv_classes
    s0 = SOLVER_PANEL.solves
    _h, per_node, per_tg = run([count] * members)
    assert SOLVER_PANEL.equiv_classes == e0 + 1, "class did not collapse"
    assert SOLVER_PANEL.solves == s0 + 1, "expected exactly one solve"
    total = members * count
    assert sum(per_tg.values()) == total, (seed, per_tg)
    assert all(per_tg[f"g{j}"] == count for j in range(members)), per_tg
    # The combined-count reference: one group of members*count copies.
    _h2, per_node_ref, _ = run([total])
    assert per_node == per_node_ref, (
        seed, "class expansion changed the placement distribution",
    )


def test_equiv_class_interleaved_groups_do_not_collapse():
    """Only CONSECUTIVE equivalent groups collapse: [A, B, A'] with
    A ≡ A' but B different must solve as three rows — folding A' past B
    would let A''s placements into the plan before B solves, changing
    the usage view (anti-affinity job_count, plan deltas) the sequential
    loop gives B. [A, A', B] collapses the adjacent pair."""
    from nomad_tpu.tpu.solver import SOLVER_PANEL

    def run(order):
        h = Harness()
        for i in range(16):
            node = Node(
                id=f"il-{i}", datacenter="dc1", name=f"n{i}",
                attributes={"kernel.name": "linux", "driver.exec": "1"},
                resources=Resources(cpu=14000, memory_mb=28000,
                                    disk_mb=100_000, iops=1000),
                status=structs.NODE_STATUS_READY,
            )
            h.state.upsert_node(h.next_index(), node)
        tgs = []
        for j, kind in enumerate(order):
            cpu = 5 if kind == "A" else 9
            tgs.append(TaskGroup(
                name=f"g{j}", count=300,
                restart_policy=RestartPolicy(attempts=0, interval=600.0,
                                             delay=1.0),
                tasks=[Task(name="t", driver="exec",
                            resources=Resources(cpu=cpu, memory_mb=16))],
            ))
        job = Job(
            region="global", id=generate_uuid(), name="il",
            type=structs.JOB_TYPE_BATCH, priority=50,
            datacenters=["dc1"], task_groups=tgs,
        )
        h.state.upsert_job(h.next_index(), job)
        ev = Evaluation(
            id=generate_uuid(), priority=50, type=job.type,
            triggered_by=structs.EVAL_TRIGGER_JOB_REGISTER, job_id=job.id,
        )
        s0 = SOLVER_PANEL.solves
        c0 = SOLVER_PANEL.equiv_classes
        h.process("tpu-batch", ev)
        placed = sum(b.n for b in h.plans[0].alloc_batches)
        return placed, SOLVER_PANEL.solves - s0, \
            SOLVER_PANEL.equiv_classes - c0

    placed, solves, classes = run(["A", "B", "A"])
    assert placed == 900
    assert solves == 3 and classes == 0, (solves, classes)

    placed, solves, classes = run(["A", "A", "B"])
    assert placed == 900
    assert solves == 2 and classes == 1, (solves, classes)


# ---------------------------------------------------------------------------
# 5. Delta-rolled device mirror ≡ fresh build (bit-identical)
#
# MirrorCache no longer rebuilds the whole NodeMirror on every node write:
# it rolls the resident mirror forward through the state store's node
# change log (NodeMirror.apply_delta), patching only dirty rows and
# invalidating only affected mask columns. The contract is BIT-IDENTITY:
# after any seeded sequence of upserts/removals/drain flips — including
# the repadding boundary and the log-horizon fallback — the rolled mirror
# must equal a mirror freshly built from the same snapshot, array for
# array, mask for mask, id for id.

N_MIRROR_SEEDS = int(os.environ.get("NOMAD_TPU_FUZZ_SEEDS", 60)) // 2


def _mirror_rand_node(rng, i):
    from nomad_tpu.structs import NODE_STATUS_INIT, NODE_STATUS_READY

    res = Resources(
        cpu=int(rng.integers(500, 8000)),
        memory_mb=int(rng.integers(256, 16384)),
        disk_mb=int(rng.integers(1024, 100_000)),
        iops=int(rng.integers(10, 300)),
    )
    if rng.random() < 0.3:
        res.networks = [NetworkResource(
            device="eth0", cidr="10.0.0.0/8", ip=f"10.0.{i % 250}.1",
            mbits=int(rng.integers(100, 2000)),
        )]
    node = Node(
        id=f"fz-{i:04d}",
        datacenter=str(rng.choice(["dc1", "dc2", "dc3"])),
        name=f"fz-{i}",
        attributes={
            "kernel.name": "linux",
            "driver.exec": str(rng.choice(["1", "0"])),
            "rack": f"r{int(rng.integers(0, 4))}",
        },
        meta={"tier": str(rng.choice(["a", "b"]))},
        status=str(rng.choice(
            [NODE_STATUS_READY] * 4 + [NODE_STATUS_INIT])),
        drain=bool(rng.random() < 0.08),
        resources=res,
    )
    if rng.random() < 0.2:
        node.reserved = Resources(
            cpu=int(rng.integers(0, 200)),
            memory_mb=int(rng.integers(0, 256)),
        )
    return node


_MIRROR_FUZZ_CONSTRAINTS = [
    Constraint(l_target="$attr.kernel.name", r_target="linux", operand="="),
    Constraint(l_target="$attr.rack", r_target="r1", operand="!="),
    Constraint(l_target="$meta.tier", r_target="a", operand="="),
    Constraint(l_target="$node.datacenter", r_target="dc1", operand="="),
]


def _assert_mirror_bit_identical(rolled, fresh, where):
    """Every array + mask + id order must match a fresh build exactly."""
    assert rolled.n == fresh.n, where
    assert rolled.padded == fresh.padded, where
    assert [n.id for n in rolled.nodes] == [n.id for n in fresh.nodes], where
    for attr in ("reserved_np", "bw_reserved", "base_mask"):
        np.testing.assert_array_equal(
            getattr(rolled, attr), getattr(fresh, attr),
            err_msg=f"{where}: {attr}")
    for attr in ("total", "sched_cap", "bw_avail"):
        np.testing.assert_array_equal(
            np.asarray(getattr(rolled, attr)),
            np.asarray(getattr(fresh, attr)),
            err_msg=f"{where}: {attr}")
    np.testing.assert_array_equal(
        rolled.id_array(), fresh.id_array(), err_msg=f"{where}: ids")
    np.testing.assert_array_equal(
        rolled.driver_mask({"exec"}), fresh.driver_mask({"exec"}),
        err_msg=f"{where}: driver_mask")
    for c in _MIRROR_FUZZ_CONSTRAINTS:
        np.testing.assert_array_equal(
            rolled.constraint_mask(None, [c]),
            fresh.constraint_mask(None, [c]),
            err_msg=f"{where}: constraint {c.l_target} {c.operand}")
    got_dev, got_n = rolled.device_mask(
        None, {"exec"}, None, _MIRROR_FUZZ_CONSTRAINTS[:2])
    want_dev, want_n = fresh.device_mask(
        None, {"exec"}, None, _MIRROR_FUZZ_CONSTRAINTS[:2])
    assert got_n == want_n, where
    np.testing.assert_array_equal(
        np.asarray(got_dev), np.asarray(want_dev),
        err_msg=f"{where}: device_mask")
    for got, want, name in zip(rolled.clean_usage(), fresh.clean_usage(),
                               ("used", "job", "tg", "bw")):
        np.testing.assert_array_equal(
            np.asarray(got), np.asarray(want),
            err_msg=f"{where}: clean_usage {name}")


def _mirror_mutate(rng, store, idx, next_id):
    """One random node-table write against the live store. Returns
    (next index, next fresh id)."""
    from nomad_tpu import structs as st

    ids = [n.id for n in store.nodes()]
    op = rng.random()
    idx += 1
    if not ids or op < 0.22:
        store.upsert_node(idx, _mirror_rand_node(rng, next_id))
        return idx, next_id + 1
    nid = str(rng.choice(ids))
    if op < 0.50:
        # In-place rewrite: resource drift and/or mask-surface change.
        node = store.node_by_id(nid).copy()
        which = rng.random()
        if which < 0.5:
            node.resources = node.resources.copy()
            node.resources.cpu = int(rng.integers(500, 8000))
        elif which < 0.7:
            node.attributes["rack"] = f"r{int(rng.integers(0, 4))}"
        elif which < 0.85:
            node.meta["tier"] = str(rng.choice(["a", "b"]))
        else:
            node.reserved = Resources(cpu=int(rng.integers(0, 300)))
        store.upsert_node(idx, node)
    elif op < 0.65:
        store.update_node_drain(
            idx, nid, not store.node_by_id(nid).drain)
    elif op < 0.85:
        store.update_node_status(idx, nid, str(rng.choice(
            [st.NODE_STATUS_READY, st.NODE_STATUS_READY,
             st.NODE_STATUS_DOWN, st.NODE_STATUS_INIT])))
    else:
        store.delete_node(idx, nid)
    return idx, next_id


@pytest.mark.parametrize("seed", range(N_MIRROR_SEEDS))
def test_mirror_delta_roll_bit_identical(seed):
    """Seeded churn (upserts, removals, drain/status flips, fresh
    registrations) rolled through MirrorCache must yield a mirror
    bit-identical to a fresh build at every checkpoint."""
    from nomad_tpu.scheduler.util import ready_nodes_in_dcs
    from nomad_tpu.state import StateStore
    from nomad_tpu.tpu.mirror import MirrorCache, NodeMirror

    rng = np.random.default_rng(40_000 + seed)
    store = StateStore()
    idx = 0
    next_id = 0
    for _ in range(int(rng.integers(6, 70))):
        idx += 1
        store.upsert_node(idx, _mirror_rand_node(rng, next_id))
        next_id += 1
    dcs = ["dc1", "dc2"]
    cache = MirrorCache()
    _n, warm = cache.get(store.snapshot(), dcs)
    # Populate the caches the roll must selectively invalidate.
    warm.driver_mask({"exec"})
    warm.device_mask(None, {"exec"}, None, _MIRROR_FUZZ_CONSTRAINTS[:2])
    warm.clean_usage()
    for step in range(int(rng.integers(3, 9))):
        for _ in range(int(rng.integers(1, 5))):
            idx, next_id = _mirror_mutate(rng, store, idx, next_id)
        snap = store.snapshot()
        _n, rolled = cache.get(snap, dcs)
        fresh = NodeMirror(ready_nodes_in_dcs(snap, dcs))
        _assert_mirror_bit_identical(
            rolled, fresh, where=(seed, step, idx))
    stats = cache.stats()
    assert stats["delta_rolls"] + stats["full_rebuilds"] >= 1, (seed, stats)


def test_mirror_delta_repadding_boundary():
    """Appends inside the padding bucket roll; crossing the power-of-two
    boundary forces (and correctly executes) a full rebuild."""
    from nomad_tpu import structs as st
    from nomad_tpu.scheduler.util import ready_nodes_in_dcs
    from nomad_tpu.state import StateStore
    from nomad_tpu.tpu.mirror import MirrorCache, NodeMirror

    def mk(i):
        return Node(
            id=f"pad-{i:03d}", datacenter="dc1", name=f"pad-{i}",
            attributes={"kernel.name": "linux", "driver.exec": "1"},
            resources=Resources(cpu=1000, memory_mb=1024),
            status=st.NODE_STATUS_READY,
        )

    store = StateStore()
    idx = 0
    for i in range(63):
        idx += 1
        store.upsert_node(idx, mk(i))
    cache = MirrorCache()
    _n, m0 = cache.get(store.snapshot(), ["dc1"])
    assert m0.padded == 64
    # 63 -> 64: same bucket, append roll.
    idx += 1
    store.upsert_node(idx, mk(63))
    snap = store.snapshot()
    _n, m1 = cache.get(snap, ["dc1"])
    _assert_mirror_bit_identical(
        m1, NodeMirror(ready_nodes_in_dcs(snap, ["dc1"])), "64")
    assert cache.stats()["delta_rolls"] == 1
    assert cache.stats()["full_rebuilds"] == 1  # the initial build
    # 64 -> 65: crosses to the 128 bucket, must fully rebuild.
    idx += 1
    store.upsert_node(idx, mk(64))
    snap = store.snapshot()
    _n, m2 = cache.get(snap, ["dc1"])
    assert m2.padded == 128
    _assert_mirror_bit_identical(
        m2, NodeMirror(ready_nodes_in_dcs(snap, ["dc1"])), "65")
    assert cache.stats()["delta_rolls"] == 1
    assert cache.stats()["full_rebuilds"] == 2


def test_mirror_delta_log_horizon_fallback(monkeypatch):
    """Writes past the bounded change log's horizon make
    node_changes_since return None and the cache fall back to one full
    rebuild — never a wrong delta."""
    from nomad_tpu import structs as st
    from nomad_tpu.scheduler.util import ready_nodes_in_dcs
    from nomad_tpu.state import StateStore
    from nomad_tpu.state import store as store_mod
    from nomad_tpu.tpu.mirror import MirrorCache, NodeMirror

    monkeypatch.setattr(store_mod, "NODE_LOG_HORIZON", 4)
    store = StateStore()
    idx = 0
    for i in range(12):
        idx += 1
        store.upsert_node(idx, Node(
            id=f"hz-{i:03d}", datacenter="dc1", name=f"hz-{i}",
            attributes={"kernel.name": "linux"},
            resources=Resources(cpu=1000, memory_mb=1024),
            status=st.NODE_STATUS_READY,
        ))
    cache = MirrorCache()
    _n, _m = cache.get(store.snapshot(), ["dc1"])
    base_index = store.get_index("nodes")
    # > 2 * horizon single-node writes: the log trims past base_index.
    for i in range(10):
        node = store.node_by_id(f"hz-{i % 12:03d}").copy()
        node.resources = node.resources.copy()
        node.resources.cpu += 1
        idx += 1
        store.upsert_node(idx, node)
    snap = store.snapshot()
    assert snap.node_changes_since(base_index) is None
    _n, rolled = cache.get(snap, ["dc1"])
    _assert_mirror_bit_identical(
        rolled, NodeMirror(ready_nodes_in_dcs(snap, ["dc1"])), "horizon")
    stats = cache.stats()
    assert stats["delta_rolls"] == 0
    assert stats["full_rebuilds"] == 2, stats


# ---------------------------------------------------------------------------
# 6. Delta-maintained usage tensors ≡ the full proposed-alloc walk
#
# build_usage now copies a cached, change-log-rolled base and touches only
# the plan's in-flight rows; _build_usage_walk is the original O(cluster)
# reference implementation. They must agree exactly — across alloc-table
# generations (object rows, columnar blocks, evictions) and arbitrary
# plans (placements, evictions of object rows, block members, stale ids).


def _usage_quad(out):
    return [np.asarray(x) for x in out]


@pytest.mark.parametrize("seed", range(N_MIRROR_SEEDS))
def test_usage_delta_matches_full_walk(seed):
    from nomad_tpu import structs as st
    from nomad_tpu.scheduler.context import EvalContext
    from nomad_tpu.scheduler.util import ready_nodes_in_dcs
    from nomad_tpu.state import StateStore
    from nomad_tpu.structs import AllocBatch, Allocation, Plan
    from nomad_tpu.tpu.mirror import MirrorCache, NodeMirror

    rng = np.random.default_rng(50_000 + seed)
    store = StateStore()
    idx = 0
    n0 = int(rng.integers(8, 40))
    for i in range(n0):
        idx += 1
        store.upsert_node(idx, _mirror_rand_node(rng, i))
    dcs = ["dc1", "dc2"]
    cache = MirrorCache()
    cache.get(store.snapshot(), dcs)

    job = Job(
        region="global", id=f"uj-{seed}", name=f"uj-{seed}",
        type=structs.JOB_TYPE_SERVICE, priority=50, datacenters=dcs,
        task_groups=[TaskGroup(
            name="web", count=64,
            tasks=[Task(name="t", driver="exec",
                        resources=Resources(cpu=50, memory_mb=64))],
        )],
    )
    other_job = Job(
        region="global", id=f"uo-{seed}", name=f"uo-{seed}",
        type=structs.JOB_TYPE_SERVICE, priority=50, datacenters=dcs,
        task_groups=job.task_groups,
    )

    def rand_alloc(nid, j, serial, status=st.ALLOC_DESIRED_STATUS_RUN):
        return Allocation(
            id=generate_uuid(), eval_id=generate_uuid(),
            name=f"{j.name}.web[{serial}]", node_id=nid, job_id=j.id,
            job=j, task_group="web",
            resources=Resources(cpu=int(rng.integers(10, 200)),
                                memory_mb=int(rng.integers(16, 256))),
            desired_status=status,
        )

    object_allocs = []
    blocks_batches = []
    for generation in range(int(rng.integers(2, 5))):
        ids = [n.id for n in store.nodes()]
        # Alloc-table churn: object rows (some terminal), plus a columnar
        # block for a random job.
        new_allocs = []
        for s in range(int(rng.integers(1, 6))):
            j = job if rng.random() < 0.6 else other_job
            status = (st.ALLOC_DESIRED_STATUS_RUN
                      if rng.random() < 0.8
                      else st.ALLOC_DESIRED_STATUS_STOP)
            new_allocs.append(
                rand_alloc(str(rng.choice(ids)), j, s, status))
        idx += 1
        store.upsert_allocs(idx, new_allocs)
        object_allocs.extend(new_allocs)
        if rng.random() < 0.6:
            j = job if rng.random() < 0.5 else other_job
            picks = [str(rng.choice(ids))
                     for _ in range(int(rng.integers(1, 4)))]
            counts = [int(rng.integers(1, 5)) for _ in picks]
            batch = AllocBatch(
                eval_id=generate_uuid(), job=j, tg_name="web",
                resources=Resources(cpu=20, memory_mb=32),
                task_resources={"t": Resources(cpu=20, memory_mb=32)},
                metrics=None,
                node_ids=picks,
                node_counts=counts,
                name_idx=np.arange(sum(counts)),
                ids_seed=int(rng.integers(1, 2**63)),
            )
            idx += 1
            store.upsert_alloc_blocks(idx, [batch])
            blocks_batches.append(batch)
        # Cross-node supersede: restamp a live block member onto a
        # DIFFERENT node via an object-row upsert — the member's OLD
        # node silently loses its block usage, and the alloc log must
        # dirty both ends or the rolled base over-counts it.
        if rng.random() < 0.5:
            for blk in store.alloc_blocks():
                if blk.n_live:
                    pos = blk.live_positions()[0]
                    member = blk.materialize_pos(pos)
                    member.node_id = str(rng.choice(ids))
                    member.resources = Resources(
                        cpu=int(rng.integers(10, 100)), memory_mb=32)
                    idx += 1
                    store.upsert_allocs(idx, [member])
                    object_allocs.append(member)
                    break
        # A couple of node writes too: the mirror must roll while the
        # usage base rolls independently through the alloc log.
        for _ in range(int(rng.integers(0, 3))):
            idx, n0 = _mirror_mutate(rng, store, idx, n0 + 1000)

        snap = store.snapshot()
        _n, rolled = cache.get(snap, dcs)
        fresh = NodeMirror(ready_nodes_in_dcs(snap, dcs))

        # Random plan: placements + evictions (object rows, live block
        # members, stale ids).
        plan = Plan(eval_id=generate_uuid())
        mirror_ids = [n.id for n in fresh.nodes]
        if mirror_ids:
            for s in range(int(rng.integers(0, 4))):
                nid = str(rng.choice(mirror_ids))
                plan.node_allocation.setdefault(nid, []).append(
                    rand_alloc(nid, job, 100 + s))
        live_objects = [a for a in object_allocs
                        if store.alloc_object_by_id(a.id) is not None]
        for a in (rng.choice(live_objects, size=min(2, len(live_objects)),
                             replace=False) if live_objects else []):
            plan.node_update.setdefault(a.node_id, []).append(a.copy())
        for blk in snap.alloc_blocks():
            if rng.random() < 0.4 and blk.n_live:
                pos = blk.live_positions()[0]
                member = blk.materialize_pos(pos)
                plan.node_update.setdefault(
                    member.node_id, []).append(member)
                break
        if mirror_ids and rng.random() < 0.5:
            stale = rand_alloc(str(rng.choice(mirror_ids)), job, 999)
            plan.node_update.setdefault(stale.node_id, []).append(stale)

        ctx = EvalContext(snap, plan)
        got = _usage_quad(rolled.build_usage(ctx, job.id, "web"))
        want = _usage_quad(fresh._build_usage_walk(ctx, job.id, "web"))
        for g, w, name in zip(got, want,
                              ("used", "job_count", "tg_count", "bw_used")):
            np.testing.assert_array_equal(
                g, w, err_msg=f"seed {seed} gen {generation}: {name}")


@pytest.mark.parametrize("seed", range(N_MIRROR_SEEDS))
def test_node_table_delta_matches_fresh(seed):
    """The plan applier's columnar node table rolls through the same
    change log (plan_apply._NodeTable.apply_delta); a rolled table must
    equal a fresh build — rows map, columns, liveness — across the same
    churn the mirror fuzz applies."""
    from nomad_tpu.server import plan_apply
    from nomad_tpu.state import StateStore

    rng = np.random.default_rng(60_000 + seed)
    with plan_apply._NODE_TABLE_LOCK:
        plan_apply._NODE_TABLE_CACHE = None
    store = StateStore()
    idx = 0
    next_id = 0
    for _ in range(int(rng.integers(5, 50))):
        idx += 1
        store.upsert_node(idx, _mirror_rand_node(rng, next_id))
        next_id += 1
    plan_apply._node_table(store.snapshot())
    for step in range(int(rng.integers(3, 8))):
        for _ in range(int(rng.integers(1, 4))):
            idx, next_id = _mirror_mutate(rng, store, idx, next_id)
        snap = store.snapshot()
        rolled = plan_apply._node_table(snap)
        fresh = plan_apply._NodeTable(snap)
        where = (seed, step, idx)
        assert rolled.n == fresh.n, where
        assert rolled.rows == fresh.rows, where
        for attr in ("totals", "reserved", "dead", "scalar_only"):
            np.testing.assert_array_equal(
                getattr(rolled, attr), getattr(fresh, attr),
                err_msg=f"{where}: {attr}")


# ---------------------------------------------------------------------------
# 7. Batched plan verification parity (plan_pipeline.evaluate_plans)
# ---------------------------------------------------------------------------

N_BATCH_VERIFY_SEEDS = int(os.environ.get("NOMAD_TPU_FUZZ_SEEDS", 40))


def _pv_alloc(rng, nid, serial, cpu=None):
    return structs.Allocation(
        id=generate_uuid(), eval_id=generate_uuid(),
        name=f"pv.web[{serial}]", node_id=nid, job_id="pv-job",
        task_group="web",
        resources=Resources(
            cpu=int(cpu if cpu is not None else rng.integers(50, 900)),
            memory_mb=int(rng.integers(16, 512)),
        ),
        desired_status=structs.ALLOC_DESIRED_STATUS_RUN,
    )


def _pv_batch(rng, ids, with_net=False):
    """One columnar placement batch over a random node subset — counts
    sized so stacked overlapping batches overflow small nodes."""
    from nomad_tpu.structs import AllocBatch

    picks = [str(rng.choice(ids))
             for _ in range(int(rng.integers(1, 5)))]
    counts = [int(rng.integers(1, 40)) for _ in picks]
    res = Resources(cpu=int(rng.integers(30, 600)),
                    memory_mb=int(rng.integers(16, 256)))
    if with_net:
        res.networks = [NetworkResource(device="eth0", mbits=10)]
    return AllocBatch(
        eval_id=generate_uuid(), job=None, tg_name="web",
        resources=res,
        task_resources={"t": res},
        metrics=None,
        node_ids=picks, node_counts=counts,
        name_idx=np.arange(sum(counts)),
        ids_seed=int(rng.integers(1, 2**63)),
    )


def _pv_decisions(result):
    """The decision content of one PlanResult, in comparable form."""
    return {
        "refresh_index": result.refresh_index,
        "node_allocation": {
            nid: sorted(a.id for a in allocs)
            for nid, allocs in result.node_allocation.items() if allocs
        },
        "node_update": {
            nid: sorted(a.id for a in allocs)
            for nid, allocs in result.node_update.items() if allocs
        },
        "alloc_batches": sorted(
            (tuple(b.node_ids), tuple(int(c) for c in b.node_counts))
            for b in result.alloc_batches
        ),
        "update_batches": len(result.update_batches),
    }


@pytest.mark.parametrize("seed", range(N_BATCH_VERIFY_SEEDS))
def test_batched_plan_verify_matches_sequential(seed):
    """The plan pipeline's K-plan fused tensor verify is DECISION-
    IDENTICAL to K sequential evaluate_plan calls with each committed
    subset rolled into the snapshot between calls — across seeded
    overlapping/disjoint plan sets, block-native existing allocs,
    dead/drained/reserved-network nodes, object-row placements forcing
    the scalar path mid-batch, and delta-rolled node tables."""
    import copy as _copy
    import itertools

    from nomad_tpu.server import plan_apply
    from nomad_tpu.server.plan_apply import evaluate_plan
    from nomad_tpu.server.plan_pipeline import (
        apply_result_to_snapshot,
        evaluate_plans,
    )
    from nomad_tpu.state import StateStore
    from nomad_tpu.structs import Plan

    rng = np.random.default_rng(70_000 + seed)
    with plan_apply._NODE_TABLE_LOCK:
        plan_apply._NODE_TABLE_CACHE = None
    store = StateStore()
    idx = 0
    next_id = 0
    for _ in range(int(rng.integers(6, 30))):
        idx += 1
        store.upsert_node(idx, _mirror_rand_node(rng, next_id))
        next_id += 1
    # Seed a table ancestor so later verifies exercise the delta roll.
    plan_apply._node_table(store.snapshot())

    # Pre-existing columnar blocks (block-native allocs) and sometimes
    # object rows (which force the whole batch down the scalar path).
    ids = [n.id for n in store.nodes()]
    for _ in range(int(rng.integers(0, 4))):
        idx += 1
        store.upsert_alloc_blocks(
            idx, [_pv_batch(rng, ids, with_net=rng.random() < 0.15)])
    if rng.random() < 0.35:
        idx += 1
        store.upsert_allocs(idx, [
            _pv_alloc(rng, str(rng.choice(ids)), s)
            for s in range(int(rng.integers(1, 4)))
        ])
    # Node-table churn after the ancestor build: the rolled-table path.
    for _ in range(int(rng.integers(0, 4))):
        idx, next_id = _mirror_mutate(rng, store, idx, next_id)
    ids = [n.id for n in store.nodes()]
    if not ids:
        return

    k = int(rng.integers(2, 7))
    plans = []
    for p in range(k):
        plan = Plan(eval_id=f"pv-{seed}-{p}", priority=50)
        shape = rng.random()
        if shape < 0.6:
            # Pure columnar: the fused path's home turf. Overlap is the
            # point — batches draw from the same node pool.
            for _ in range(int(rng.integers(1, 3))):
                plan.append_batch(
                    _pv_batch(rng, ids, with_net=rng.random() < 0.1))
        elif shape < 0.85:
            # Object placements (scalar path mid-batch).
            for s in range(int(rng.integers(1, 4))):
                nid = str(rng.choice(ids))
                plan.node_allocation.setdefault(nid, []).append(
                    _pv_alloc(rng, nid, s))
        else:
            # Mixed: a batch plus an eviction of a stale id.
            plan.append_batch(_pv_batch(rng, ids))
            stale = _pv_alloc(rng, str(rng.choice(ids)), 999)
            plan.node_update.setdefault(stale.node_id, []).append(stale)
        plans.append(plan)

    plans_seq = _copy.deepcopy(plans)
    plans_fused = _copy.deepcopy(plans)
    snap_seq = store.snapshot()
    snap_fused = store.snapshot()

    stamp_seq = itertools.count(100_000)
    stamp_fused = itertools.count(100_000)

    want = []
    for plan in plans_seq:
        res = evaluate_plan(snap_seq, plan)
        if not res.is_noop():
            apply_result_to_snapshot(snap_seq, res, next(stamp_seq))
        want.append(_pv_decisions(res))

    got_results = evaluate_plans(
        snap_fused, plans_fused, stamp_index=lambda: next(stamp_fused))
    got = [_pv_decisions(r) for r in got_results]

    assert got == want, f"seed {seed}: fused verify diverged"
    # The rolled stores must agree too: same committed blocks, same
    # object rows.
    def _store_shape(snap):
        return (
            sorted((tuple(b.node_ids), tuple(int(c) for c in b.node_counts))
                   for b in snap.alloc_blocks()),
            sorted(a.id for nid in ids for a in snap.allocs_by_node(nid)),
        )
    assert _store_shape(snap_fused) == _store_shape(snap_seq), (
        f"seed {seed}: rolled snapshots diverged"
    )


@pytest.mark.parametrize("seed", range(N_BATCH_VERIFY_SEEDS))
def test_batched_plan_verify_fused_engagement_parity(seed):
    """Same parity contract on the fused pass's home distribution — all
    nodes live, pure columnar overlapping batches sized so the stacked
    asks overflow small nodes mid-batch (prefix commit + scalar
    resolution of the overflowing plan + re-fuse of the tail). Asserts
    the fused pass actually engaged: a regression that silently sends
    everything down the scalar path fails here, not just in benchmarks."""
    import copy as _copy
    import itertools

    from nomad_tpu.server import plan_apply
    from nomad_tpu.server.plan_apply import evaluate_plan
    from nomad_tpu.server.plan_pipeline import (
        _PipelineTotals,
        apply_result_to_snapshot,
        evaluate_plans,
    )
    from nomad_tpu.state import StateStore
    from nomad_tpu.structs import Node, Plan

    rng = np.random.default_rng(80_000 + seed)
    with plan_apply._NODE_TABLE_LOCK:
        plan_apply._NODE_TABLE_CACHE = None
    store = StateStore()
    idx = 0
    n_nodes = int(rng.integers(5, 25))
    for i in range(n_nodes):
        idx += 1
        store.upsert_node(idx, Node(
            id=f"fp-{i:03d}", datacenter="dc1", name=f"fp{i}",
            status="ready",
            resources=Resources(
                cpu=int(rng.integers(1000, 6000)),
                memory_mb=int(rng.integers(2048, 16384)),
                disk_mb=100_000, iops=10_000,
            ),
        ))
    plan_apply._node_table(store.snapshot())
    ids = [n.id for n in store.nodes()]

    def _mk_batch(hog=False):
        from nomad_tpu.structs import AllocBatch

        picks = [str(rng.choice(ids))
                 for _ in range(int(rng.integers(1, 5)))]
        counts = [int(rng.integers(1, 6)) for _ in picks]
        res = Resources(
            cpu=int(rng.integers(2000, 4000) if hog
                    else rng.integers(10, 80)),
            memory_mb=int(rng.integers(16, 128)),
        )
        return AllocBatch(
            eval_id=generate_uuid(), job=None, tg_name="web",
            resources=res, task_resources={"t": res}, metrics=None,
            node_ids=picks, node_counts=counts,
            name_idx=np.arange(sum(counts)),
            ids_seed=int(rng.integers(1, 2**63)),
        )

    # Existing block pressure so the base usage term is non-trivial.
    for _ in range(int(rng.integers(0, 3))):
        idx += 1
        store.upsert_alloc_blocks(idx, [_mk_batch()])

    k = int(rng.integers(3, 8))
    plans = []
    for p in range(k):
        plan = Plan(eval_id=f"fp-{seed}-{p}", priority=50)
        for _ in range(int(rng.integers(1, 3))):
            # Mostly modest asks that stack and fit (the fused whole-
            # commit run); ~15% hogs that overflow mid-batch and force
            # the prefix break + scalar resolution + tail re-fuse.
            plan.append_batch(_mk_batch(hog=rng.random() < 0.15))
        plans.append(plan)

    plans_seq = _copy.deepcopy(plans)
    plans_fused = _copy.deepcopy(plans)
    snap_seq = store.snapshot()
    snap_fused = store.snapshot()
    stamp_seq = itertools.count(100_000)
    stamp_fused = itertools.count(100_000)

    want = []
    for plan in plans_seq:
        res = evaluate_plan(snap_seq, plan)
        if not res.is_noop():
            apply_result_to_snapshot(snap_seq, res, next(stamp_seq))
        want.append(_pv_decisions(res))

    totals = _PipelineTotals()
    got_results = evaluate_plans(
        snap_fused, plans_fused,
        stamp_index=lambda: next(stamp_fused), totals=totals)
    got = [_pv_decisions(r) for r in got_results]

    assert got == want, f"seed {seed}: fused verify diverged"
    assert totals.fused_plans > 0, (
        f"seed {seed}: fused pass never engaged on its home distribution"
    )
