"""Network client + cluster membership tests.

Covers the client->server network RPC path (reference: client/client.go:
210-253 server list with rotation, node_endpoint.go:328 blocking GetAllocs)
and the serf-lite membership layer (join/force-leave/bootstrap_expect,
reference: nomad/serf.go).
"""

import time

import pytest

from nomad_tpu import mock, structs
from nomad_tpu.client import Client, ClientConfig
from nomad_tpu.server import ServerConfig
from nomad_tpu.server.cluster import (
    ClusterConfig,
    ClusterServer,
    form_cluster,
    wait_for_leader,
)
from nomad_tpu.structs import Job, Resources, RestartPolicy, Task, TaskGroup


def _wait_until(fn, timeout=10.0, interval=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if fn():
            return True
        time.sleep(interval)
    return False


def _mock_job(job_id: str, count: int = 1) -> Job:
    return Job(
        region="global",
        id=job_id,
        name=job_id,
        type=structs.JOB_TYPE_BATCH,
        priority=50,
        datacenters=["dc1"],
        task_groups=[
            TaskGroup(
                name="grp",
                count=count,
                restart_policy=RestartPolicy(attempts=0, interval=60.0, delay=1.0),
                tasks=[
                    Task(
                        name="m",
                        driver="mock_driver",
                        config={"run_for": 0.1, "exit_code": 0},
                        resources=Resources(cpu=100, memory_mb=64),
                    )
                ],
            )
        ],
    )


def test_network_client_end_to_end(tmp_path):
    """A client with only a server address list registers over RPC, watches
    allocations via blocking Node.GetAllocs, runs the task, and syncs the
    terminal status back over Node.UpdateAlloc."""
    (srv,) = form_cluster(
        1, ServerConfig(scheduler_backend="host", num_schedulers=1,
                        min_heartbeat_ttl=30.0)
    )
    try:
        wait_for_leader([srv])
        client = Client(
            ClientConfig(
                state_dir=str(tmp_path / "state"),
                alloc_dir=str(tmp_path / "allocs"),
                node_name="net-client",
                servers=[srv.rpc_addr],
                options={"driver.mock_driver.enable": "1"},
            )
        )
        client.start()
        try:
            assert _wait_until(
                lambda: (
                    srv.state_store.node_by_id(client.node.id) is not None
                    and srv.state_store.node_by_id(client.node.id).status
                    == structs.NODE_STATUS_READY
                )
            ), "client never became ready over the network path"

            job = _mock_job("net-job")
            eval_id, _ = srv.job_register(job)
            ev = srv.wait_for_eval(eval_id, timeout=15.0)
            assert ev.status == structs.EVAL_STATUS_COMPLETE

            allocs = srv.state_store.allocs_by_job(job.id)
            assert len(allocs) == 1
            assert allocs[0].node_id == client.node.id

            assert _wait_until(
                lambda: srv.state_store.allocs_by_job(job.id)[0].client_status
                == structs.ALLOC_CLIENT_STATUS_DEAD,
                timeout=20.0,
            ), srv.state_store.allocs_by_job(job.id)[0]
        finally:
            client.shutdown(destroy_allocs=True)
    finally:
        srv.shutdown()


def test_runtime_join_grows_cluster():
    """A server started with an empty peer set joins at runtime and
    participates in replication (serf join -> peer add, serf.go:76-134)."""
    (first,) = form_cluster(
        1, ServerConfig(scheduler_backend="host", num_schedulers=0)
    )
    second = None
    try:
        wait_for_leader([first])
        cfg = ServerConfig(scheduler_backend="host", num_schedulers=0)
        cfg.node_name = "joiner"
        second = ClusterServer(cfg, ClusterConfig(node_id="joiner"))
        second.start()
        n = second.join(first.rpc_addr)
        assert n >= 1
        assert "joiner" in first.cluster.peers
        assert set(second.cluster.peers) == set(first.cluster.peers)

        # Replication reaches the joiner
        node = mock.node()
        first.node_register(node)
        assert _wait_until(
            lambda: second.state_store.node_by_id(node.id) is not None
        ), "replicated state never reached the joined server"

        # Force-leave removes it everywhere
        first.force_leave("joiner")
        assert "joiner" not in first.cluster.peers
    finally:
        if second is not None:
            second.shutdown()
        first.shutdown()


def test_bootstrap_expect_holds_elections():
    """bootstrap_expect=3 keeps a lone server from electing itself
    (serf.go maybeBootstrap)."""
    cfg = ServerConfig(scheduler_backend="host", num_schedulers=0)
    cfg.node_name = "lonely"
    srv = ClusterServer(
        cfg, ClusterConfig(node_id="lonely", bootstrap_expect=3)
    )
    srv.start()
    try:
        time.sleep(1.0)
        assert not srv.raft.is_leader

        # Two more join -> quorum possible -> leadership emerges
        others = []
        for i in range(2):
            ocfg = ServerConfig(scheduler_backend="host", num_schedulers=0)
            ocfg.node_name = f"peer-{i}"
            other = ClusterServer(
                ocfg,
                ClusterConfig(node_id=f"peer-{i}", bootstrap_expect=3),
            )
            other.start()
            other.join(srv.rpc_addr)
            others.append(other)
        leader = wait_for_leader([srv] + others, timeout=15.0)
        assert leader is not None
    finally:
        for other in others:
            other.shutdown()
        srv.shutdown()


def test_multi_region_federation():
    """Two regions federate via a cross-region join: raft stays per-region,
    region tables converge, and a job whose region differs from the
    receiving server forwards to the owning region (rpc.go:204-228)."""
    cfg_a = ServerConfig(scheduler_backend="host", num_schedulers=1,
                         region="global", min_heartbeat_ttl=30.0)
    cfg_a.node_name = "a-1"
    srv_a = ClusterServer(cfg_a, ClusterConfig(node_id="a-1"))
    cfg_b = ServerConfig(scheduler_backend="host", num_schedulers=1,
                         region="eu", min_heartbeat_ttl=30.0)
    cfg_b.node_name = "b-1"
    srv_b = ClusterServer(cfg_b, ClusterConfig(node_id="b-1"))
    srv_a.start()
    srv_b.start()
    try:
        wait_for_leader([srv_a])
        wait_for_leader([srv_b])
        srv_b.join(srv_a.rpc_addr)

        # Raft membership stays per-region
        assert "b-1" not in srv_a.cluster.peers
        assert "a-1" not in srv_b.cluster.peers
        assert srv_a.regions() == ["eu", "global"]
        assert _wait_until(lambda: srv_b.regions() == ["eu", "global"])

        # Register the eu node on the eu server, then submit an eu job
        # to the GLOBAL server: it must land in eu's state.
        node = mock.node()
        srv_b.node_register(node)
        job = _mock_job("federated")
        job.region = "eu"
        eval_id, _ = srv_a.job_register(job)
        assert eval_id
        assert srv_a.state_store.job_by_id("federated") is None
        assert srv_b.state_store.job_by_id("federated") is not None
        ev = srv_b.wait_for_eval(eval_id, timeout=15.0)
        assert ev.status == structs.EVAL_STATUS_COMPLETE
    finally:
        srv_a.shutdown()
        srv_b.shutdown()
