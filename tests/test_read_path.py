"""Follower read plane tests (nomad_tpu/server/read_path.py).

Covers the consistency-lane contract end to end: stale-lane bound
enforcement and typed refusal, linearizable reads riding the leader
read-index lease (including the deposed-leader safety argument), the
forwarding audit's regression pin (a follower-served stale read makes
ZERO leader RPCs), and the per-follower watch registry surviving
snapshot installs and partition heals with its cap intact.
"""

import threading
import time

import pytest

from nomad_tpu import faults, mock
from nomad_tpu.agent import Agent, AgentConfig
from nomad_tpu.api import ApiClient, QueryOptions
from nomad_tpu.raft import NotLeaderError
from nomad_tpu.server import Server, ServerConfig
from nomad_tpu.server.blocking import blocking_query
from nomad_tpu.server.cluster import form_cluster, wait_for_leader
from nomad_tpu.server.read_path import (
    LANE_DEFAULT,
    LANE_LINEARIZABLE,
    LANE_STALE,
    ROLE_FOLLOWER,
    ROLE_LEADER,
    ReadPath,
    ReadPathConfig,
)
from nomad_tpu.state.store import item_table
from nomad_tpu.structs import (
    REJECT_STALE_BOUND,
    REJECT_WATCH_LIMIT,
    RejectError,
)

from cluster_util import relaxed_cluster_cfg, retry_write


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.get_registry().clear()
    yield
    faults.get_registry().clear()


@pytest.fixture
def cluster3():
    # Quiesce the heap first: a GC pause mid-election is a known stall
    # source for in-process clusters (see tests/test_cluster.py).
    import gc

    gc.collect()
    servers = form_cluster(
        3,
        ServerConfig(
            scheduler_backend="host",
            num_schedulers=1,
            min_heartbeat_ttl=30.0,
        ),
        base_cluster=relaxed_cluster_cfg(),
    )
    yield servers
    for srv in servers:
        srv.shutdown()


def _converged_follower(servers, leader, timeout: float = 20.0):
    """A follower that has heard from the leader and whose applied index
    has caught the leader's commit index."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        commit = leader.raft.commit_index
        for f in servers:
            if f is leader or f.raft.is_leader:
                continue
            if (
                f.raft.last_contact_s() is not None
                and f.raft.applied_index >= commit
            ):
                return f
        time.sleep(0.02)
    raise TimeoutError("no converged follower")


# ---------------------------------------------------------------------------
# Lane mechanics against a fake raft (fast, deterministic)
# ---------------------------------------------------------------------------


class _FakeRaft:
    def __init__(self, is_leader=False, applied=7, contact_s=0.1):
        self.is_leader = is_leader
        self.applied_index = applied
        self.contact_s = contact_s
        self.config = None

    def last_contact_s(self):
        return self.contact_s


class _FakeServer:
    def __init__(self, raft, read_index=None):
        self.raft = raft
        self.read_index_result = read_index

    def confirmed_read_index(self, timeout: float = 2.0):
        if isinstance(self.read_index_result, Exception):
            raise self.read_index_result
        return self.read_index_result


def test_config_parse_validation():
    cfg = ReadPathConfig.parse(None)
    assert cfg.enabled and cfg.default_max_stale_ms == 5000.0
    cfg = ReadPathConfig.parse(
        {"enabled": False, "default_max_stale_ms": 250}
    )
    assert not cfg.enabled and cfg.default_max_stale_ms == 250.0
    with pytest.raises(ValueError, match="unknown read_path config key"):
        ReadPathConfig.parse({"max_stale": 1})
    with pytest.raises(ValueError, match="must be a mapping"):
        ReadPathConfig.parse([1, 2])
    for bad in (
        {"default_max_stale_ms": 0},
        {"read_index_timeout": -1},
        {"apply_wait_timeout": 0},
    ):
        with pytest.raises(ValueError, match="must be > 0"):
            ReadPathConfig.parse(bad)


def test_disabled_read_path_degrades_every_lane_to_default():
    # The contrast-arm posture: lanes OFF serves everything as default —
    # no bound enforcement, no read-index round, no refusals.
    rp = ReadPath(
        _FakeServer(_FakeRaft(contact_s=999.0)),
        ReadPathConfig(enabled=False),
    )
    for lane in (LANE_STALE, LANE_LINEARIZABLE, LANE_DEFAULT):
        meta = rp.enter(lane, max_stale_ms=1.0)
        assert meta["lane"] == LANE_DEFAULT
    snap = rp.snapshot()
    assert snap["served"][ROLE_FOLLOWER][LANE_DEFAULT] == 3
    assert snap["stale"]["refused"] == 0
    assert snap["linearizable"]["refused"] == 0


def test_stale_bound_refusal_is_typed_and_retriable():
    rp = ReadPath(_FakeServer(_FakeRaft(contact_s=1.2)))
    # Within bound: served, age booked, headers carry the measured age.
    meta = rp.enter(LANE_STALE, max_stale_ms=5000.0)
    assert meta["role"] == ROLE_FOLLOWER
    assert meta["last_contact_ms"] == pytest.approx(1200.0)
    assert meta["applied_index"] == 7
    # Past bound: typed retriable refusal with zero side effects.
    with pytest.raises(RejectError) as ei:
        rp.enter(LANE_STALE, max_stale_ms=500.0)
    assert ei.value.reason == REJECT_STALE_BOUND
    assert ei.value.retry_after > 0
    # Never-contacted follower refuses ANY bound (age is unknowable).
    rp2 = ReadPath(_FakeServer(_FakeRaft(contact_s=None)))
    with pytest.raises(RejectError):
        rp2.enter(LANE_STALE, max_stale_ms=10_000_000.0)
    snap = rp.snapshot()
    assert snap["stale"]["refused"] == 1
    assert snap["served"][ROLE_FOLLOWER][LANE_STALE] == 1
    assert snap["stale"]["age_ms"]["max"] == pytest.approx(1200.0)


def test_linearizable_lane_waits_for_read_index():
    # Applied already past the confirmed index: serves immediately and
    # stamps the read index into the response material.
    rp = ReadPath(_FakeServer(_FakeRaft(applied=7), read_index=5))
    meta = rp.enter(LANE_LINEARIZABLE)
    assert meta["read_index"] == 5
    assert meta["applied_index"] >= meta["read_index"]
    # No confirmable leadership anywhere: typed retriable refusal.
    rp2 = ReadPath(
        _FakeServer(_FakeRaft(), read_index=NotLeaderError(None))
    )
    with pytest.raises(RejectError) as ei:
        rp2.enter(LANE_LINEARIZABLE)
    assert ei.value.reason == REJECT_STALE_BOUND
    assert rp2.snapshot()["linearizable"]["refused"] == 1
    # Applied never catches the confirmed index inside the wait budget:
    # refuse rather than serve a value older than the read point.
    rp3 = ReadPath(
        _FakeServer(_FakeRaft(applied=7), read_index=50),
        ReadPathConfig(apply_wait_timeout=0.05),
    )
    with pytest.raises(RejectError):
        rp3.enter(LANE_LINEARIZABLE)


# ---------------------------------------------------------------------------
# Forwarding audit: stale-lane reads never cross the wire
# ---------------------------------------------------------------------------


def _count_pool_calls(srv):
    """Wrap srv.pool.call with a recording shim; returns the log."""
    calls = []
    orig = srv.pool.call

    def recording(addr, method, args, **kw):
        calls.append(method)
        return orig(addr, method, args, **kw)

    srv.pool.call = recording
    return calls


def test_stale_read_zero_leader_rpcs(cluster3):
    # The forwarding-audit regression pin (server/cluster.py): a stale-
    # lane read served by a follower is answered ENTIRELY from its local
    # FSM — zero RPCs to the leader, before, during, or after.
    leader = wait_for_leader(cluster3)
    node = mock.node()
    retry_write(lambda: leader.node_register(node))
    follower = _converged_follower(cluster3, leader)

    calls = _count_pool_calls(follower)
    for _ in range(5):
        meta = follower.read_path.enter(LANE_STALE, max_stale_ms=60_000.0)
        got = follower.state_store.node_by_id(node.id)
        assert got is not None and got.id == node.id
        assert meta["role"] == ROLE_FOLLOWER
        assert meta["applied_index"] > 0
        assert meta["last_contact_ms"] is not None
    assert calls == [], f"stale-lane read crossed the wire: {calls}"
    snap = follower.read_path.snapshot()
    assert snap["served"][ROLE_FOLLOWER][LANE_STALE] == 5

    # Positive control: the LINEARIZABLE lane on the same follower rides
    # exactly one forwarded Raft.ReadIndex — proof the counter works and
    # the one sanctioned read-plane RPC is the read-index fetch.
    meta = follower.read_path.enter(LANE_LINEARIZABLE)
    assert "Raft.ReadIndex" in calls
    assert meta["read_index"] > 0
    assert meta["applied_index"] >= meta["read_index"]


def test_leader_serves_linearizable_from_lease_without_log_write(cluster3):
    leader = wait_for_leader(cluster3)
    retry_write(lambda: leader.node_register(mock.node()))
    # Let a heartbeat round land so the lease is warm.
    time.sleep(leader.raft.config.heartbeat_interval * 3)
    log_len_before = leader.raft.applied_index
    commit_before = leader.raft.commit_index
    meta = leader.read_path.enter(LANE_LINEARIZABLE)
    assert meta["role"] == ROLE_LEADER
    assert meta["read_index"] >= commit_before
    # Lease-riding confirmation books at least one of lease-hit /
    # quorum-confirm; the log grew by AT MOST the once-per-term barrier
    # no-op (never one entry per read).
    stats = leader.read_path.snapshot()["linearizable"]["read_index"]
    assert stats["calls"] >= 1
    assert stats["lease_hits"] + stats["quorum_confirms"] >= 1
    for _ in range(10):
        leader.read_path.enter(LANE_LINEARIZABLE)
    assert leader.raft.applied_index <= log_len_before + 1


# ---------------------------------------------------------------------------
# Lease safety: a deposed leader cannot serve a linearizable read
# ---------------------------------------------------------------------------


def test_deposed_leader_cannot_serve_linearizable_read(cluster3):
    leader = wait_for_leader(cluster3)
    old_id = leader.cluster.node_id
    retry_write(lambda: leader.node_register(mock.node()))

    # Clock-skew guard first: the lease window is strictly inside the
    # election timeout, so a fresh quorum provably predates any new
    # leader's earliest possible election.
    assert leader.raft.lease_window_s() < leader.raft.config.election_timeout_min

    # Fully isolate the old leader (both directions, appends AND votes)
    # without telling it: it keeps believing it leads while the majority
    # moves on — the classic split-brain read hazard.
    faults.get_registry().load({"sites": {
        "raft.append": [
            {"mode": "drop", "probability": 1.0, "match": f"{old_id}->"},
            {"mode": "drop", "probability": 1.0, "match": f"->{old_id}"},
        ],
        "raft.vote": [
            {"mode": "drop", "probability": 1.0, "match": f"{old_id}->"},
            {"mode": "drop", "probability": 1.0, "match": f"->{old_id}"},
        ],
    }})
    try:
        # Majority side elects a new leader and commits in the new term.
        majority = [s for s in cluster3 if s is not leader]
        deadline = time.monotonic() + 30.0
        new_leader = None
        while time.monotonic() < deadline:
            leaders = [s for s in majority if s.raft.is_leader]
            if leaders:
                new_leader = leaders[0]
                break
            time.sleep(0.05)
        assert new_leader is not None, "majority side never elected"
        retry_write(lambda: new_leader.node_register(mock.node()))
        assert new_leader.raft.current_term > 0

        # The deposed leader's lease has long expired (the new election
        # alone outlasts it) and no quorum can confirm it: the
        # linearizable lane must REFUSE, never answer from stale books.
        with pytest.raises((NotLeaderError, TimeoutError)):
            leader.raft.read_index(timeout=0.3)
        with pytest.raises(RejectError) as ei:
            ReadPath(leader).enter(LANE_LINEARIZABLE)
        assert ei.value.reason == REJECT_STALE_BOUND
        # The NEW leader serves: its index covers the new-term commit.
        assert new_leader.raft.read_index() >= new_leader.raft.commit_index
    finally:
        faults.get_registry().clear()

    # Partition heal: the old leader hears the higher term, steps down,
    # and its linearizable lane works again (forwarded read index).
    deadline = time.monotonic() + 30.0
    while time.monotonic() < deadline:
        if not leader.raft.is_leader and leader.raft.last_contact_s() is not None:
            try:
                meta = leader.read_path.enter(LANE_LINEARIZABLE)
                assert meta["applied_index"] >= meta["read_index"]
                break
            except RejectError:
                pass  # re-election still settling; retriable by contract
        time.sleep(0.1)
    else:
        pytest.fail("old leader never rejoined the read plane")


# ---------------------------------------------------------------------------
# Per-follower watch registry: snapshot install, partition heal, caps
# ---------------------------------------------------------------------------


def test_follower_watch_wakes_across_snapshot_install():
    srv = Server(ServerConfig(
        scheduler_backend="host", max_blocking_watchers=8))
    srv.start()
    try:
        srv.node_register(mock.node())
        start_index = srv.fsm.state.get_index("nodes")
        out = {}

        def park():
            idx, n = blocking_query(
                get_store=lambda: srv.fsm.state,
                items=lambda store: [item_table("nodes")],
                run=lambda store: (
                    store.get_index("nodes"), len(store.nodes())),
                min_index=start_index,
                timeout=8.0,
            )
            out["index"], out["nodes"] = idx, n

        t = threading.Thread(target=park)
        t.start()
        time.sleep(0.3)  # let the watcher park
        # Snapshot install rebinds fsm.state to a fresh store. The parked
        # watcher must be woken by the old store's farewell notify and
        # re-park on the NEW store — never sleep through the rebind.
        srv.fsm.restore_bytes(srv.fsm.snapshot_bytes())
        assert srv.fsm.state.watch.max_watchers == 8, \
            "snapshot install silently unbounded the watcher cap"
        time.sleep(0.2)
        srv.node_register(mock.node())  # the write lands on the NEW store
        t.join(timeout=10.0)
        assert not t.is_alive(), "watcher slept through the store rebind"
        assert out["index"] > start_index
        assert out["nodes"] == 2
    finally:
        srv.shutdown()


def test_watcher_cap_is_per_server_not_global():
    a = Server(ServerConfig(scheduler_backend="host",
                            max_blocking_watchers=2))
    b = Server(ServerConfig(scheduler_backend="host",
                            max_blocking_watchers=2))
    a.start()
    b.start()
    try:
        wa, wb = a.fsm.state.watch, b.fsm.state.watch
        t1 = wa.register([item_table("nodes")])
        t2 = wa.register([item_table("jobs")])
        with pytest.raises(RejectError) as ei:
            wa.register([item_table("evals")])
        assert ei.value.reason == REJECT_WATCH_LIMIT
        assert ei.value.retry_after > 0
        # Server B's registry is untouched by A's saturation: the cap is
        # a per-server serving budget, not a fleet-global one.
        t3 = wb.register([item_table("nodes")])
        wa.unregister(t1)
        wa.unregister(t2)
        wb.unregister(t3)
        # A freed slot admits again.
        wa.unregister(wa.register([item_table("nodes")]))
    finally:
        a.shutdown()
        b.shutdown()


def test_follower_event_ring_gapless_after_partition_heal(cluster3):
    # Per-follower watch/SSE serving rests on every member's OWN event
    # ring carrying the same apply stream. Starve one follower behind a
    # partition, write through the leader, heal — the follower's ring
    # must converge to the identical, strictly-index-ordered sequence
    # (the gapless-wake guarantee its blocking watchers ride).
    leader = wait_for_leader(cluster3)
    retry_write(lambda: leader.node_register(mock.node()))
    follower = _converged_follower(cluster3, leader)
    fid = follower.cluster.node_id
    faults.get_registry().load({"sites": {
        "raft.append": [
            {"mode": "drop", "probability": 1.0, "match": f"->{fid}"},
        ],
        "raft.vote": [
            {"mode": "drop", "probability": 1.0, "match": f"{fid}->"},
        ],
    }})
    try:
        for _ in range(4):
            retry_write(lambda: leader.node_register(mock.node()))
    finally:
        faults.get_registry().clear()

    # Heal: wait for a settled leader (the starved follower may force a
    # re-election with its bumped term) and full convergence.
    deadline = time.monotonic() + 30.0
    while time.monotonic() < deadline:
        leaders = [s for s in cluster3 if s.raft.is_leader]
        if len(leaders) == 1:
            settled = leaders[0]
            commit = settled.raft.commit_index
            if all(s.raft.applied_index >= commit for s in cluster3):
                break
        time.sleep(0.05)
    else:
        pytest.fail("cluster never converged after heal")

    # Each member's ring also carries its own LOCAL events (Leader
    # acquisitions/losses), so rings are not byte-identical — but the
    # REPLICATED apply stream (here: Node registrations) must be, in
    # order, on every member.
    def apply_stream(srv):
        return [
            (e.topic, e.type, e.key)
            for e in srv.fsm.events.all_events()
            if e.topic == "Node"
        ]

    assert apply_stream(follower) == apply_stream(settled)
    assert len(apply_stream(follower)) >= 5  # partition-era writes made it
    indexes = [e.index for e in follower.fsm.events.all_events()]
    assert indexes == sorted(indexes)  # gapless, index-ordered wakes
    # Resuming from before the ring head is honest about completeness.
    latest, evs, truncated = follower.fsm.events.events_after(
        indexes[0] - 1)
    assert not truncated and [e.index for e in evs] == indexes


# ---------------------------------------------------------------------------
# HTTP + SDK integration (DevMode agent)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def dev_agent(tmp_path_factory):
    config = AgentConfig.dev()
    config.data_dir = str(tmp_path_factory.mktemp("read_path_agent"))
    config.http_port = 0
    config.scheduler_backend = "host"
    a = Agent(config)
    a.start()
    yield a
    a.shutdown()


def test_http_stamps_freshness_headers_per_lane(dev_agent):
    client = ApiClient(address=dev_agent.http.addr)
    # Default lane: applied index + contact age on every read.
    _, meta = client.nodes().list()
    assert meta.applied_index >= 0
    assert meta.read_index == 0  # not a linearizable response
    # Stale lane: opt-in with bound, same stamps.
    _, meta = client.nodes().list(
        q=QueryOptions(allow_stale=True, max_stale_ms=5000.0))
    assert meta.applied_index >= 0
    assert meta.last_contact == 0.0  # DevMode single node IS the leader
    # Linearizable lane: the confirmed read index rides the response and
    # nothing older than it was served.
    _, meta = client.nodes().list(q=QueryOptions(consistent=True))
    assert meta.read_index >= 0
    assert meta.applied_index >= meta.read_index
    books = dev_agent.server.read_path.snapshot()
    assert books["served"][ROLE_LEADER][LANE_LINEARIZABLE] >= 1
    assert books["served"][ROLE_LEADER][LANE_STALE] >= 1


def test_http_stale_bound_refusal_maps_to_typed_429(dev_agent):
    rp = dev_agent.server.read_path
    orig = rp.last_contact_ms
    rp.last_contact_ms = lambda: 9999.0  # pretend we are a lagged follower
    try:
        client = ApiClient(address=dev_agent.http.addr)
        with pytest.raises(RejectError) as ei:
            client.nodes().list(
                q=QueryOptions(allow_stale=True, max_stale_ms=100.0))
        assert ei.value.reason == REJECT_STALE_BOUND
        assert ei.value.retry_after > 0
    finally:
        rp.last_contact_ms = orig
    assert rp.snapshot()["stale"]["refused"] >= 1


def test_sdk_client_level_stale_default(dev_agent):
    # allow_stale on the CLIENT makes every bare query ride the stale
    # lane with the client-wide bound — no per-call QueryOptions needed.
    client = ApiClient(address=dev_agent.http.addr, allow_stale=True,
                      max_stale_ms=2500.0)
    _, meta = client.jobs().list()
    assert meta.applied_index >= 0
    books = dev_agent.server.read_path.snapshot()
    assert books["served"][ROLE_LEADER][LANE_STALE] >= 1
