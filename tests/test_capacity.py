"""Capacity & solver observatory tests.

Four layers:

- **accountant math** against a raw StateStore: utilization / density /
  lane / fragmentation / stranded accounting on hand-built states, plus
  the DIFFERENTIAL contract — an accountant rolled forward through the
  change logs must report byte-identical aggregates to a fresh one that
  full-rebuilt from the same state (the device mirror's fuzz posture).
- **solver panel** units: padding economy, bucket occupancy, and the
  compile-trigger taxonomy (precompile / bucket_crossing / first_roll).
- **PromText** units: the shared exposition line-builder's sanitation,
  TYPE-once, and conflict guards (the one-sanitizer satellite).
- **live-agent e2e**: /v1/agent/capacity and /v1/agent/solver over HTTP
  + SDK, the debug bundle's new sections, the GOLDEN full-scrape
  exposition test (TYPE-before-sample, no duplicate/conflicting TYPE,
  every name legal), and the structural SDK-parity gate (every
  /v1/agent/* GET route must have an AgentApi accessor — slo/admission/
  express each drifted in late; capacity/solver cannot).
"""

import inspect
import json
import re
import time
import urllib.request

import pytest

from nomad_tpu import mock, structs, telemetry
from nomad_tpu.capacity import (
    CapacityAccountant,
    CapacityConfig,
    DEFAULT_REFERENCE_SHAPES,
    FRAG_BINS,
)
from nomad_tpu.state.store import StateStore
from nomad_tpu.structs import Allocation, Job, Resources

# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------


def _node(i, cpu=4000, memory_mb=8192):
    n = mock.node()
    n.id = f"cap-node-{i:03d}"
    n.resources = Resources(cpu=cpu, memory_mb=memory_mb,
                            disk_mb=100 * 1024, iops=150)
    n.reserved = Resources()
    return n


def _job(job_id, jtype=structs.JOB_TYPE_SERVICE, express=False):
    job = mock.job()
    job.id = job_id
    job.name = job_id
    job.type = jtype
    job.express = express
    return job


def _alloc(job, node_id, cpu=500, memory_mb=256):
    return Allocation(
        id=structs.generate_uuid(),
        eval_id=structs.generate_uuid(),
        name=f"{job.name}.web[0]",
        node_id=node_id,
        job_id=job.id,
        job=job,
        task_group="web",
        resources=Resources(cpu=cpu, memory_mb=memory_mb),
        desired_status=structs.ALLOC_DESIRED_STATUS_RUN,
    )


def _accountant(store, **cfg):
    return CapacityAccountant(
        lambda: store, CapacityConfig.parse(cfg or None)
    )


# ---------------------------------------------------------------------------
# config parsing
# ---------------------------------------------------------------------------


def test_capacity_config_defaults_and_validation():
    cfg = CapacityConfig.parse(None)
    assert cfg.enabled and cfg.poll_interval == 1.0
    assert cfg.reference_shapes == [dict(s)
                                    for s in DEFAULT_REFERENCE_SHAPES]
    assert not CapacityConfig.parse({"enabled": False}).enabled
    with pytest.raises(ValueError):
        CapacityConfig.parse({"poll_intervall": 1.0})  # typo'd key
    with pytest.raises(ValueError):
        CapacityConfig.parse({"poll_interval": 0})
    with pytest.raises(ValueError):
        CapacityConfig.parse({"reference_shapes": []})
    with pytest.raises(ValueError):
        CapacityConfig.parse(
            {"reference_shapes": [{"name": "zero"}]})  # asks for nothing


# ---------------------------------------------------------------------------
# accountant math
# ---------------------------------------------------------------------------


def test_utilization_lanes_and_density():
    store = StateStore()
    store.upsert_nodes(1, [_node(i) for i in range(4)])
    svc = _job("svc")
    bat = _job("bat", jtype=structs.JOB_TYPE_BATCH)
    exp = _job("exp", jtype=structs.JOB_TYPE_BATCH, express=True)
    store.upsert_allocs(2, [
        _alloc(svc, "cap-node-000", cpu=1000, memory_mb=1024),
        _alloc(bat, "cap-node-001", cpu=400, memory_mb=512),
        _alloc(exp, "cap-node-001", cpu=100, memory_mb=128),
    ])
    acct = _accountant(store)
    acct.refresh()
    snap = acct.snapshot()
    assert snap["nodes"] == {"total": 4, "schedulable": 4, "occupied": 2}
    assert snap["total"]["cpu"] == 4 * 4000
    assert snap["used"]["cpu"] == 1500
    assert snap["lanes"]["service"]["used"]["cpu"] == 1000
    assert snap["lanes"]["batch"]["used"]["cpu"] == 400
    assert snap["lanes"]["express"]["used"]["cpu"] == 100
    assert snap["lanes"]["express"]["allocs"] == 1
    assert snap["utilization"]["cpu"] == pytest.approx(1500 / 16000)
    # Density judges only the two occupied nodes' capacity.
    assert snap["binpack_density"]["cpu"] == pytest.approx(1500 / 8000)
    # Fragmentation: the two empty nodes sit in the top decile, the two
    # occupied ones lower.
    assert sum(snap["fragmentation"]["free_fraction"]["cpu"]) == 4
    assert snap["fragmentation"]["free_fraction"]["cpu"][FRAG_BINS - 1] == 2


def test_stranded_capacity_definition():
    """Two nodes: one nearly full (free 300 cpu), one empty. A shape of
    1000 cpu fits only the empty node — the full node's free capacity is
    stranded with respect to it."""
    store = StateStore()
    store.upsert_nodes(1, [_node(0, cpu=4000), _node(1, cpu=4000)])
    job = _job("filler")
    store.upsert_allocs(2, [_alloc(job, "cap-node-000", cpu=3700,
                                   memory_mb=256)])
    acct = _accountant(store, reference_shapes=[
        {"name": "big", "cpu": 1000, "memory_mb": 512},
    ])
    acct.refresh()
    s = acct.snapshot()["stranded"][0]
    assert s["shape"] == "big"
    assert s["nodes_fitting"] == 1
    # free: 300 (node 0, stranded) + 4000 (node 1) = 4300
    assert s["stranded_pct"] == pytest.approx(300 / 4300, abs=1e-5)
    # 4 copies of 1000 cpu fit on the empty node.
    assert s["placeable_count"] == 4


def test_non_schedulable_nodes_excluded():
    store = StateStore()
    nodes = [_node(0), _node(1)]
    nodes[1].drain = True
    store.upsert_nodes(1, nodes)
    acct = _accountant(store)
    acct.refresh()
    snap = acct.snapshot()
    assert snap["nodes"]["total"] == 2
    assert snap["nodes"]["schedulable"] == 1
    assert snap["total"]["cpu"] == 4000


def test_incremental_roll_matches_full_rebuild():
    """The differential contract: after arbitrary node/alloc churn, the
    accountant that ROLLED through the change logs reports the same
    aggregates as a fresh accountant that rebuilt from scratch."""
    store = StateStore()
    store.upsert_nodes(1, [_node(i) for i in range(6)])
    rolled = _accountant(store)
    rolled.refresh()
    assert rolled.rebuilds == 1

    svc = _job("svc")
    bat = _job("bat", jtype=structs.JOB_TYPE_BATCH)
    allocs = [
        _alloc(svc, f"cap-node-{i:03d}", cpu=200 * (i + 1))
        for i in range(4)
    ]
    store.upsert_allocs(2, allocs)
    store.upsert_allocs(3, [_alloc(bat, "cap-node-005", cpu=900)])
    # Node churn too: a drain flip and a deletion.
    store.update_node_drain(4, "cap-node-002", True)
    store.delete_node(5, "cap-node-003")
    # Stop one alloc (its node's usage must roll back down).
    stopped = allocs[0].copy()
    stopped.desired_status = structs.ALLOC_DESIRED_STATUS_STOP
    store.upsert_allocs(6, [stopped])

    rolled.refresh()
    assert rolled.rolls >= 1 and rolled.rebuilds == 1

    fresh = _accountant(store)
    fresh.refresh()
    a, b = rolled.snapshot(), fresh.snapshot()
    for key in ("nodes", "total", "used", "free", "utilization",
                "binpack_density", "lanes", "fragmentation", "stranded"):
        assert a[key] == b[key], key


def test_store_replacement_forces_rebuild():
    store1 = StateStore()
    store1.upsert_nodes(1, [_node(0)])
    holder = {"store": store1}
    acct = CapacityAccountant(lambda: holder["store"],
                              CapacityConfig.parse(None))
    acct.refresh()
    assert acct.rebuilds == 1
    store2 = StateStore()
    store2.upsert_nodes(1, [_node(0), _node(1)])
    holder["store"] = store2
    acct.refresh()
    assert acct.rebuilds == 2
    assert acct.snapshot()["nodes"]["total"] == 2


def test_capacity_event_snapshot_published():
    from nomad_tpu.events import EventBroker, OBSERVER_TOPICS

    store = StateStore()
    store.upsert_nodes(1, [_node(0)])
    broker = EventBroker(register=False)
    acct = CapacityAccountant(lambda: store, CapacityConfig.parse(None),
                              events=broker)
    acct.refresh()
    acct.publish_event()
    events = broker.all_events()
    assert len(events) == 1
    e = events[0]
    assert e.topic == "Capacity" and e.type == "CapacitySnapshot"
    assert e.topic in OBSERVER_TOPICS
    assert "utilization" in e.payload and "stranded" in e.payload
    # The canonical determinism reduction ignores observer topics.
    from nomad_tpu.simcluster.scenario import canonical_events

    assert canonical_events(events)["groups"] == 0


# ---------------------------------------------------------------------------
# solver panel
# ---------------------------------------------------------------------------


def test_solver_panel_economy_and_triggers():
    from nomad_tpu.tpu.solver import SolverPanel

    panel = SolverPanel()
    with panel.precompile():
        panel.record_solve("exact", 100, 128, 8, 8, 0, 50.0)
    panel.record_solve("exact", 100, 128, 8, 8, 8, 1.0)     # warm: no record
    panel.record_solve("exact", 100, 128, 30, 32, 30, 12.0)  # first_roll
    panel.record_solve("waterfill", 900, 1024, 500, 0, 500, 20.0)  # crossing
    snap = panel.snapshot()
    assert snap["solves"] == 4
    assert snap["placed"] == 538
    assert snap["compiles"]["by_trigger"] == {
        "bucket_crossing": 1, "first_roll": 1, "precompile": 1,
    }
    # Padding economy: live/padded over every dispatched row.
    assert snap["node_padding_waste"] == pytest.approx(
        1 - (100 * 3 + 900) / (128 * 3 + 1024), abs=1e-4)
    assert snap["count_padding_waste"] == pytest.approx(
        1 - (8 + 8 + 30) / (8 + 8 + 32), abs=1e-4)
    buckets = {b["bucket"]: b for b in snap["node_buckets"]}
    assert buckets[128]["solves"] == 3
    assert buckets[128]["occupancy"] == pytest.approx(100 / 128, abs=1e-3)
    assert buckets[1024]["solves"] == 1
    assert snap["device_ms_per_placement"] > 0


# ---------------------------------------------------------------------------
# PromText: the one shared exposition builder
# ---------------------------------------------------------------------------


def test_promtext_sanitizes_and_types_once():
    b = telemetry.PromText()
    b.counter("nomad.weird-name.total", 3)
    b.counter("nomad.weird-name.total", 4, labels={"reason": 'a"b\n'})
    b.gauge("9starts_with_digit", 1.5)
    text = b.text()
    assert text.count("# TYPE nomad_weird_name_total counter") == 1
    assert 'reason="a\\"b\\n"' in text
    assert "_9starts_with_digit 1.5" in text


def test_promtext_conflicting_type_raises():
    b = telemetry.PromText()
    b.counter("nomad_x_total", 1)
    with pytest.raises(ValueError):
        b.gauge("nomad_x_total", 2)


# ---------------------------------------------------------------------------
# live agent e2e + golden exposition + SDK parity
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def agent(tmp_path_factory):
    from nomad_tpu.agent import Agent, AgentConfig

    from nomad_tpu.scheduler import wait_for_device

    # The e2e assertions read the solver panel, which only records on
    # the device path: block for the probe so the factory can't fall
    # back to the host scheduler during its first-caller grace.
    assert wait_for_device(timeout=180.0) is not None

    config = AgentConfig.dev()
    config.data_dir = str(tmp_path_factory.mktemp("capacity-agent"))
    config.http_port = 0
    config.enable_debug = True
    config.capacity = {"poll_interval": 0.2, "events_interval": 0.5}
    a = Agent(config)
    a.start()
    # Wait for the dev node to register so the observatory has a cell.
    from nomad_tpu.api import ApiClient

    client = ApiClient(address=a.http.addr)
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        nodes, _ = client.nodes().list()
        if nodes and nodes[0]["status"] == "ready":
            break
        time.sleep(0.1)
    else:
        pytest.fail("dev node never became ready")
    yield a
    a.shutdown()


def _get(agent, path):
    with urllib.request.urlopen(agent.http.addr + path, timeout=10) as r:
        return r.status, r.read()


def _place_one(agent):
    from nomad_tpu.api import ApiClient

    client = ApiClient(address=agent.http.addr)
    job = mock.job()
    job.task_groups[0].count = 2
    job.task_groups[0].tasks[0].driver = "mock_driver"
    job.task_groups[0].tasks[0].config = {"run_for": "20",
                                          "exit_code": "0"}
    job.task_groups[0].tasks[0].resources.networks = []
    eval_id, _ = client.jobs().register(job)
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        ev, _ = client.evaluations().info(eval_id)
        if ev.status == structs.EVAL_STATUS_COMPLETE:
            return
        time.sleep(0.1)
    pytest.fail("eval never completed")


def test_capacity_endpoint_e2e(agent):
    _place_one(agent)
    status, body = _get(agent, "/v1/agent/capacity")
    assert status == 200
    snap = json.loads(body)
    assert snap["nodes"]["total"] >= 1
    assert snap["used"]["cpu"] > 0
    assert {s["shape"] for s in snap["stranded"]} == {
        "small", "medium", "large"}
    # Prometheus face of the same endpoint.
    status, body = _get(agent, "/v1/agent/capacity?format=prometheus")
    assert status == 200
    text = body.decode()
    assert "# TYPE nomad_capacity_utilization gauge" in text
    assert 'nomad_capacity_stranded_pct{shape="large"}' in text
    # SDK accessor parity for the new endpoints.
    from nomad_tpu.api import ApiClient

    api = ApiClient(address=agent.http.addr).agent()
    assert api.capacity()["nodes"] == snap["nodes"]
    solver = api.solver()
    assert solver["panel"]["solves"] >= 1
    assert solver["mirror_cache"]["hits"] >= 0
    assert "roll_ms" in solver["mirror_cache"]
    assert solver["panel"]["compiles"]["total"] >= 1


def test_capacity_events_flow(agent):
    """The periodic Capacity snapshots land on the event stream (and
    only there — the canonical digest reduction skips them)."""
    from nomad_tpu.api import ApiClient

    client = ApiClient(address=agent.http.addr)
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline:
        _idx, events, _trunc = client.events().list(
            topics=["Capacity"])
        if events:
            assert events[0]["type"] == "CapacitySnapshot"
            assert "utilization" in events[0]["payload"]
            return
        time.sleep(0.2)
    pytest.fail("no Capacity snapshot event within 15s")


def test_debug_bundle_carries_capacity_and_solver(agent):
    from nomad_tpu.api import ApiClient
    from nomad_tpu.bundle import BUNDLE_SECTIONS

    bundle = ApiClient(address=agent.http.addr).agent().debug_bundle()
    assert set(BUNDLE_SECTIONS) <= set(bundle)
    assert bundle["capacity"]["nodes"]["total"] >= 1
    assert "stranded" in bundle["capacity"]
    assert bundle["solver"]["solves"] >= 1
    assert "node_padding_waste" in bundle["solver"]


# The Prometheus data-model grammar for metric names.
_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")


def test_golden_prometheus_exposition(agent):
    """Parse the FULL scrape and assert the exposition-format
    invariants every appender must jointly satisfy: a family's # TYPE
    line precedes its first sample, no family carries duplicate or
    conflicting TYPE lines (across appenders!), and every name matches
    the data-model grammar."""
    status, body = _get(agent, "/v1/agent/metrics?format=prometheus")
    assert status == 200
    typed = {}
    seen_sample_names = set()
    for lineno, line in enumerate(body.decode().splitlines(), 1):
        if not line or line.startswith("#"):
            if line.startswith("# TYPE "):
                _, _, name, mtype = line.split(None, 3)
                assert _NAME_RE.match(name), (lineno, name)
                # Duplicate TYPE lines (conflicting or not) are invalid
                # exposition, and TYPE must precede the first sample.
                assert name not in typed, \
                    f"line {lineno}: duplicate TYPE for {name}"
                assert name not in seen_sample_names, \
                    f"line {lineno}: TYPE after first sample of {name}"
                typed[name] = mtype
            continue
        # Sample line: name{labels} value  |  name value
        m = re.match(r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{.*\})?\s+(\S+)$",
                     line)
        assert m, f"line {lineno}: unparseable sample {line!r}"
        name = m.group(1)
        seen_sample_names.add(name)
        float(m.group(3))  # value must parse
        # The family's TYPE must already be declared. Suffixed series
        # (_sum/_count/_bucket/_max summaries+histograms) hang off their
        # base family.
        base_candidates = [name] + [
            name[: -len(sfx)] for sfx in ("_sum", "_count", "_bucket")
            if name.endswith(sfx)
        ]
        assert any(c in typed for c in base_candidates), \
            f"line {lineno}: sample {name} with no preceding TYPE"
    # The observatory families made it onto the main scrape.
    assert "nomad_capacity_utilization" in typed
    assert "nomad_solver_solves_total" in typed


def test_sdk_parity_every_agent_get_route_has_accessor(agent):
    """STRUCTURAL parity gate: every /v1/agent/* route the HTTP server
    registers must be referenced by an AgentApi accessor. slo,
    admission, and express each drifted in one at a time before this
    test; capacity/solver (and whatever comes next) cannot."""
    from nomad_tpu.api.client import AgentApi

    sdk_source = inspect.getsource(AgentApi)
    missing = []
    for pattern, _template, _handler in agent.http.routes:
        path = pattern.pattern
        if not path.startswith(r"^/v1/agent/"):
            continue
        literal = path.lstrip("^").rstrip("$")
        if literal not in sdk_source:
            missing.append(literal)
    assert not missing, (
        f"/v1/agent routes without an AgentApi accessor: {missing}"
    )
