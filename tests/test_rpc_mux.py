"""Stream-multiplexed RPC (yamux-lite, nomad_tpu/rpc.py).

The reference multiplexes msgpack-RPC streams over one connection with
yamux (/root/reference/nomad/rpc.go:120-137, nomad/pool.go); here the seq
field is the stream id. The defining property: a parked long-poll and
control traffic share ONE TCP connection without head-of-line blocking.
"""

import threading
import time

from nomad_tpu.rpc import ConnPool, RPCError, RPCServer, RemoteError


def _server():
    srv = RPCServer()
    gate = threading.Event()

    def slow(args):
        gate.wait(args.get("wait", 5.0))
        return "slow-done"

    srv.register("Test.Slow", slow)
    srv.register("Test.Echo", lambda a: a.get("x"))
    srv.register("Test.Boom", lambda a: 1 / 0)
    srv.start()
    return srv, gate


def test_longpoll_and_control_share_one_connection():
    srv, gate = _server()
    pool = ConnPool(timeout=10.0)
    try:
        out = {}

        def longpoll():
            out["slow"] = pool.call(srv.addr, "Test.Slow", {"wait": 6.0})

        t = threading.Thread(target=longpoll, daemon=True)
        t.start()
        time.sleep(0.2)
        assert t.is_alive()

        # Control traffic completes while the long-poll is parked — on the
        # same pooled connection (the pool holds exactly one per address).
        t0 = time.perf_counter()
        for i in range(20):
            assert pool.call(srv.addr, "Test.Echo", {"x": i}) == i
        assert time.perf_counter() - t0 < 2.0
        assert len(pool._conns) == 1  # one address, one multiplexed conn
        assert t.is_alive()  # long-poll still parked throughout

        gate.set()
        t.join(5.0)
        assert out["slow"] == "slow-done"
    finally:
        pool.shutdown()
        srv.shutdown()


def test_out_of_order_responses_correlate_by_seq():
    srv, gate = _server()
    pool = ConnPool(timeout=10.0)
    try:
        results = {}

        def call(name, method, args):
            results[name] = pool.call(srv.addr, method, args)

        t_slow = threading.Thread(
            target=call, args=("slow", "Test.Slow", {"wait": 6.0}), daemon=True
        )
        t_slow.start()
        time.sleep(0.1)
        t_fast = threading.Thread(
            target=call, args=("fast", "Test.Echo", {"x": "hi"}), daemon=True
        )
        t_fast.start()
        t_fast.join(3.0)
        # The LATER request's response arrives FIRST.
        assert results == {"fast": "hi"}
        gate.set()
        t_slow.join(5.0)
        assert results["slow"] == "slow-done"
    finally:
        pool.shutdown()
        srv.shutdown()


def test_per_call_timeout_keeps_connection_alive():
    srv, gate = _server()
    pool = ConnPool(timeout=10.0)
    try:
        try:
            pool.call(srv.addr, "Test.Slow", {"wait": 30.0}, timeout=0.3)
            raise AssertionError("expected timeout")
        except RPCError as e:
            assert "timed out" in str(e)
        # The shared connection survived the timed-out stream: control
        # traffic keeps flowing with no reconnect.
        mux = pool._conns[srv.addr]
        assert pool.call(srv.addr, "Test.Echo", {"x": 1}) == 1
        assert pool._conns[srv.addr] is mux
    finally:
        gate.set()
        pool.shutdown()
        srv.shutdown()


def test_remote_error_propagates():
    srv, gate = _server()
    pool = ConnPool(timeout=5.0)
    try:
        try:
            pool.call(srv.addr, "Test.Boom", {})
            raise AssertionError("expected RemoteError")
        except RemoteError as e:
            assert "ZeroDivisionError" in str(e)
    finally:
        pool.shutdown()
        srv.shutdown()


def test_transport_failure_fails_all_parked_streams():
    srv, gate = _server()
    pool = ConnPool(timeout=10.0)
    try:
        errors = []

        def parked():
            try:
                pool.call(srv.addr, "Test.Slow", {"wait": 30.0})
            except RPCError as e:
                errors.append(e)

        threads = [threading.Thread(target=parked, daemon=True)
                   for _ in range(3)]
        for t in threads:
            t.start()
        time.sleep(0.3)
        srv.shutdown()  # kills the connection under the parked streams
        for t in threads:
            t.join(5.0)
        assert len(errors) == 3
    finally:
        gate.set()
        pool.shutdown()
