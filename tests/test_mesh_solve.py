"""Node-axis sharded production solve (parallel/mesh.py).

The water-fill kernels that carry the 10k-node x 100k-task load run SPMD
over the configured (evals x nodes) Mesh — the blueprint's scale axis
(SURVEY.md §7 "blockwise/sharded masking and top-k over the node axis";
the reference's analogous scale machinery is the candidate-scan bound,
/root/reference/scheduler/stack.go:94-121). These tests run the REAL
scheduler path end-to-end on the 8-virtual-device CPU mesh (conftest.py)
and assert sharded == single-device placements.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nomad_tpu import mock, structs
from nomad_tpu.parallel import mesh as mesh_lib
from nomad_tpu.structs import Evaluation, generate_uuid

from sched_harness import Harness
from test_coalesce import _direct, _inputs, _submit

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs the 8-device virtual mesh"
)


@pytest.fixture
def node_mesh():
    mesh = mesh_lib.configure_node_sharding(8)
    try:
        yield mesh
    finally:
        mesh_lib.clear_node_sharding()


def test_waterfill_sharded_matches_single_device(node_mesh):
    """The same closed-form water-fill, dispatched with node-axis
    shardings, must produce identical counts."""
    from nomad_tpu.ops.binpack import solve_waterfill

    rng = np.random.default_rng(7)
    for trial in range(5):
        n = 64
        total = np.zeros((n, 4), dtype=np.int32)
        total[:, 0] = rng.integers(500, 8000, n)
        total[:, 1] = rng.integers(512, 16384, n)
        total[:, 2] = 100 * 1024
        total[:, 3] = 150
        inp = dict(
            total=jnp.asarray(total),
            sched_cap=jnp.asarray(total[:, :2].astype(np.float32)),
            used0=jnp.zeros((n, 4), dtype=jnp.int32),
            job_count0=jnp.zeros((n,), dtype=jnp.int32),
            tg_count0=jnp.zeros((n,), dtype=jnp.int32),
            bw_avail=jnp.full((n,), 1000, dtype=jnp.int32),
            bw_used0=jnp.zeros((n,), dtype=jnp.int32),
            eligible=jnp.asarray(rng.random(n) > 0.2),
            ask=jnp.array([100 + 10 * trial, 128, 0, 0], dtype=jnp.int32),
            bw_ask=jnp.int32(0),
            count=int(rng.integers(100, 2000)),
            penalty=10.0,
        )
        # Single-device reference
        d_counts, d_unplaced = _direct(inp)
        # Sharded dispatch of the same args
        args10 = mesh_lib.shard_waterfill_args(node_mesh, (
            inp["total"], inp["sched_cap"], inp["used0"], inp["job_count0"],
            inp["tg_count0"], inp["bw_avail"], inp["bw_used0"],
            inp["eligible"], inp["ask"], inp["bw_ask"],
        ))
        count, penalty = mesh_lib.replicate_on_mesh(
            node_mesh, jnp.int32(inp["count"]), jnp.float32(inp["penalty"])
        )
        counts, remaining = solve_waterfill(
            *args10, count, penalty, False, False
        )
        np.testing.assert_array_equal(np.asarray(counts), d_counts,
                                      err_msg=f"trial {trial}")
        assert int(remaining) == d_unplaced


def test_coalesced_batch_dispatch_on_mesh(node_mesh):
    """The vmapped batched water-fill runs sharded too: concurrent entries
    through the coalescer on the mesh match their individual solves."""
    from nomad_tpu.ops.coalesce import CoalescingSolver

    engine = CoalescingSolver()
    inputs = [_inputs(50 + 10 * i, 200 + 37 * i) for i in range(4)]
    fetches = [_submit(engine, inp) for inp in inputs]
    for inp, fetch in zip(inputs, fetches):
        counts, unplaced = fetch()
        d_counts, d_unplaced = _direct(inp)
        np.testing.assert_array_equal(counts, d_counts)
        assert unplaced == d_unplaced


def _run_big_service_eval(factory):
    """A 32-node cluster and a count=300 service job: count > the exact
    threshold, so the TPU path runs the water-fill production kernel."""
    h = Harness()
    for i in range(32):
        node = mock.node()
        node.resources.cpu = 14000
        node.resources.memory_mb = 28000
        h.state.upsert_node(h.next_index(), node)
    job = mock.job()
    job.task_groups[0].count = 300
    h.state.upsert_job(h.next_index(), job)
    ev = Evaluation(
        id=generate_uuid(),
        priority=job.priority,
        triggered_by=structs.EVAL_TRIGGER_JOB_REGISTER,
        job_id=job.id,
    )
    h.process(factory, ev)
    assert len(h.plans) == 1
    per_node = {}
    for node_id, allocs in h.plans[0].node_allocation.items():
        per_node[node_id] = per_node.get(node_id, 0) + len(allocs)
    for batch in h.plans[0].alloc_batches:
        for node_id, cnt in zip(batch.node_ids, batch.node_counts):
            per_node[node_id] = per_node.get(node_id, 0) + int(cnt)
    return h, per_node


def test_tpu_scheduler_end_to_end_sharded_matches_single_device():
    """TPUGenericScheduler end-to-end over the mesh: same eval, same
    placements as the single-device dispatch, and full placement count."""
    _h0, single = _run_big_service_eval("tpu-service")
    mesh = mesh_lib.configure_node_sharding(8)
    try:
        _h1, sharded = _run_big_service_eval("tpu-service")
    finally:
        mesh_lib.clear_node_sharding()
    assert sum(single.values()) == 300
    # Node identities differ between harnesses (fresh uuids); the placement
    # *distribution* must match exactly: same multiset of per-node counts.
    assert sorted(single.values()) == sorted(sharded.values())


def test_tpu_system_scheduler_on_mesh():
    """The system scheduler's one-dispatch fit check also runs sharded."""
    mesh = mesh_lib.configure_node_sharding(8)
    try:
        h = Harness()
        for i in range(16):
            h.state.upsert_node(h.next_index(), mock.node())
        job = mock.system_job()
        h.state.upsert_job(h.next_index(), job)
        ev = Evaluation(
            id=generate_uuid(),
            priority=job.priority,
            type=structs.JOB_TYPE_SYSTEM,
            triggered_by=structs.EVAL_TRIGGER_JOB_REGISTER,
            job_id=job.id,
        )
        h.process("tpu-system", ev)
        assert len(h.plans) == 1
        placed = sum(
            len(v) for v in h.plans[0].node_allocation.values()
        ) + sum(b.n for b in h.plans[0].alloc_batches)
        assert placed == 16
    finally:
        mesh_lib.clear_node_sharding()


def test_mesh_dispatch_guardrails(node_mesh):
    """Perf guardrails on the sharded production path: a warm eval issues
    exactly one coalesced dispatch, and NO node-axis tensor is resharded
    at dispatch (mirror tensors and usage are born sharded —
    put_node_sharded). A regression that reintroduces per-dispatch
    resharding fails here, not in a profile."""
    from nomad_tpu.ops.coalesce import GLOBAL_SOLVER
    from nomad_tpu.structs import Resources

    h = Harness()
    for i in range(64):
        node = mock.node()
        node.id = f"guard-{i:03d}"
        node.resources.cpu = 14000
        node.resources.memory_mb = 28000
        h.state.upsert_node(h.next_index(), node)
    job = mock.job()
    job.id = "guard-job"
    job.task_groups[0].count = 200  # > threshold: columnar water-fill path
    for t in job.task_groups[0].tasks:
        t.resources = Resources(cpu=50, memory_mb=64)
    h.state.upsert_job(h.next_index(), job)

    # Warm run: compiles, builds the mirror, fills mask caches.
    ev = Evaluation(
        id=generate_uuid(), priority=job.priority, type=job.type,
        triggered_by=structs.EVAL_TRIGGER_JOB_REGISTER, job_id=job.id,
    )
    h.process("tpu-batch", ev)

    # Measured run: same store generation (mirror cache hit), existing
    # allocs present (usage tensorization is NOT the clean fast path).
    mesh_lib.reset_stats()
    d0 = GLOBAL_SOLVER.dispatches
    ev2 = Evaluation(
        id=generate_uuid(), priority=job.priority, type=job.type,
        triggered_by=structs.EVAL_TRIGGER_JOB_REGISTER, job_id=job.id,
    )
    h.process("tpu-batch", ev2)

    assert GLOBAL_SOLVER.dispatches - d0 <= 1, (
        "warm eval issued multiple device dispatches"
    )
    assert mesh_lib.STATS["node_reshards"] == 0, mesh_lib.STATS
    # Usage tensors for this eval are born sharded: a handful of puts, not
    # one per dispatch arg; small-arg replication stays bounded.
    assert mesh_lib.STATS["node_puts"] <= 8, mesh_lib.STATS
    assert mesh_lib.STATS["replications"] <= 6, mesh_lib.STATS


def test_apply_solver_mesh_fallback_and_configure():
    """The server-config face: a mesh the local device set can't satisfy
    falls back transparently (None, solves stay single-device); a
    satisfiable one configures and is indistinguishable from the env/
    explicit path."""
    from nomad_tpu.parallel.mesh import SolverMeshConfig

    cfg = mesh_lib.SolverMeshConfig.parse({"node_shards": 1024})
    assert mesh_lib.apply_solver_mesh(cfg) is None
    assert mesh_lib.node_sharding_mesh() is None

    cfg = SolverMeshConfig.parse({"node_shards": 4, "eval_parallel": 2})
    mesh = mesh_lib.apply_solver_mesh(cfg)
    try:
        assert mesh is not None
        assert mesh.shape[mesh_lib.NODE_AXIS] == 4
        assert mesh.shape[mesh_lib.EVAL_AXIS] == 2
        assert mesh_lib.node_sharding_mesh() is mesh
    finally:
        mesh_lib.clear_node_sharding()

    # Disabled spec: no-op.
    assert mesh_lib.apply_solver_mesh(SolverMeshConfig.parse(None)) is None


def test_sharded_mirror_delta_roll_keeps_node_sharding(node_mesh):
    """The mesh-aware _rows_update: rolling a sharded mirror forward
    through a node write must leave the patched buffers NODE_AXIS-
    sharded (out_shardings-pinned scatter) — a roll that let the output
    sharding float would cost every later solve a full-axis reshard."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from nomad_tpu import mock
    from nomad_tpu.state import StateStore
    from nomad_tpu.tpu.mirror import MirrorCache

    store = StateStore()
    nodes = []
    for i in range(12):
        n = mock.node()
        n.id = f"roll-{i:02d}"
        store.upsert_node(i + 1, n)
        nodes.append(n)
    cache = MirrorCache()
    snap0 = store.snapshot()
    _n0, m0 = cache.get(snap0, ["dc1"])
    want = NamedSharding(node_mesh, P(mesh_lib.NODE_AXIS, None))
    assert m0.total.sharding == want

    # Resource-only rewrite of one resident node: the delta path.
    import copy

    n2 = copy.deepcopy(nodes[3])
    n2.resources.cpu += 111
    store.upsert_node(100, n2)
    rolls0 = cache.delta_rolls
    _n1, m1 = cache.get(store.snapshot(), ["dc1"])
    assert cache.delta_rolls == rolls0 + 1, "write did not take the roll"
    assert m1.total.sharding == want, "roll dropped the node sharding"
    assert m1.sched_cap.sharding == NamedSharding(
        node_mesh, P(mesh_lib.NODE_AXIS, None))
    assert m1.bw_avail.sharding == NamedSharding(
        node_mesh, P(mesh_lib.NODE_AXIS))
    # And the rolled row actually carries the write.
    row = m1.index["roll-03"]
    assert int(np.asarray(m1.total)[row, 0]) == n2.resources.cpu


def test_stacked_exact_dispatch_on_mesh_matches_single_device(node_mesh):
    """The cross-eval batched exact scan runs SPMD too: stacked entries
    through the coalescer on the mesh match their single-device solves
    bit-for-bit."""
    import test_coalesce as tc
    from nomad_tpu.ops.coalesce import CoalescingSolver

    engine = CoalescingSolver()
    inputs = [tc._inputs(50 + 10 * i, 20 + 7 * i) for i in range(4)]
    expected = [tc._direct_exact(inp) for inp in inputs]
    fetches = [tc._submit_exact(engine, inp) for inp in inputs]
    for (idxs, oks), (e_idxs, e_oks) in zip(
        [f() for f in fetches], expected
    ):
        np.testing.assert_array_equal(idxs, e_idxs)
        np.testing.assert_array_equal(oks, e_oks)


def test_mesh_dispatch_count_bounded_for_concurrent_evals(node_mesh):
    """Concurrent solves on the mesh stay correct and bounded: K submits
    cost at most K dispatches (coalescing may merge them into fewer), each
    matching its individual single-device solve."""
    from nomad_tpu.ops.coalesce import CoalescingSolver

    engine = CoalescingSolver()
    inputs = [_inputs(60, 200), _inputs(80, 260), _inputs(40, 120)]
    expected = [_direct(inp) for inp in inputs]
    d0 = engine.dispatches
    fetches = [_submit(engine, inp) for inp in inputs]
    got = [f() for f in fetches]
    for (counts, unplaced), (ecounts, eunplaced) in zip(got, expected):
        np.testing.assert_array_equal(counts, ecounts)
        assert unplaced == eunplaced
    assert engine.dispatches - d0 <= len(inputs)
