"""Rule registry + findings. Rule IDs are STABLE: baselines, allow()
comments, and test fixtures reference them, so an ID is never renumbered
or reused — a retired rule keeps its row with ``retired=True``."""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Dict, Optional


@dataclass(frozen=True)
class Rule:
    id: str
    pass_name: str          # determinism | lockorder | excepts | tracehygiene | observatory | meta
    title: str
    description: str
    retired: bool = False


RULES: Dict[str, Rule] = {
    r.id: r for r in (
        Rule("DET001", "determinism",
             "global random in decision path",
             "Decision-path code must draw from a seeded, name-salted "
             "PRNG stream (random.Random(seed ^ crc32(name)), the "
             "faults.py pattern), never the process-global random module "
             "— a global draw couples replay determinism to every other "
             "caller's draw ordering."),
        Rule("DET002", "determinism",
             "time.time() in decision path",
             "Interval/deadline arithmetic must use time.monotonic() "
             "(wall clock steps under NTP); time.time() is allowed only "
             "for user-facing timestamps, with an allow() reason."),
        Rule("DET003", "determinism",
             "unordered set iteration in decision path",
             "Iterating a set drives decisions in hash order, which "
             "varies across processes (PYTHONHASHSEED for str keys). "
             "Iterate sorted(s) or a list/dict instead."),
        Rule("LCK001", "lockorder",
             "lock-order cycle",
             "The static lock graph contains a cycle: two lock-holding "
             "regions can acquire the participating locks in opposite "
             "orders, which is a deadlock waiting for the right "
             "interleaving."),
        Rule("LCK002", "lockorder",
             "lock acquisition inverts canonical order",
             "A lock-holding region acquires a lock ranked EARLIER in "
             "the committed canonical order (tools/nomadlint/"
             "lock_order.json). Either restructure, or regenerate the "
             "order with --write-lock-order if the canonical order "
             "legitimately changed."),
        Rule("LCK003", "lockorder",
             "lock order drift",
             "The committed lock_order.json does not match a fresh "
             "computation over the current tree (locks added/removed or "
             "graph edges changed). Regenerate with --write-lock-order."),
        Rule("EXC001", "excepts",
             "broad except swallows hot-path error",
             "An `except Exception` in raft/FSM/plan/worker hot paths "
             "must re-raise, count a telemetry metric, or fire a fault "
             "site — a silently eaten raft/FSM error is a state "
             "divergence with no forensics."),
        Rule("EXC002", "excepts",
             "bare except in hot path",
             "Bare `except:` also catches KeyboardInterrupt/SystemExit; "
             "catch a type, or at minimum `except Exception` with "
             "telemetry."),
        Rule("TRC001", "tracehygiene",
             "Python control flow on traced value",
             "`if`/`while`/`for` on a traced argument inside a jitted "
             "function fails under jit or silently burns a retrace per "
             "distinct value; use lax.cond/select/fori_loop or hoist the "
             "branch to a static argument."),
        Rule("TRC002", "tracehygiene",
             "unstable or non-hashable static argument",
             "A static_argnums/static_argnames argument fed an unhashable "
             "value (list/dict/set) raises at call time; one fed an "
             "unstable value (fresh container/varying scalar per call) "
             "recompiles every call."),
        Rule("TRC003", "tracehygiene",
             "jitted function closes over mutable module state",
             "A jit-decorated function reading module state that is "
             "mutated elsewhere bakes the traced-time value into the "
             "compiled executable — later mutations are silently "
             "ignored (the ops/fit.py retrace-counter hazard class)."),
        Rule("OBS001", "observatory",
             "decision path imports the capacity observatory",
             "The capacity observatory (nomad_tpu/capacity.py) is a "
             "READ-ONLY observer of cluster state (Omega's shared-state "
             "posture): scheduler, solver, state, raft, and server "
             "decision paths must never import it — a placement that "
             "consults the observer's books couples decisions to poll "
             "timing and voids the decision-invariance contract the "
             "churn-fragmentation digest arm pins. Only the composition "
             "roots (server/server.py wiring, api/ exposition) may "
             "construct or read it."),
        Rule("META001", "meta",
             "allow() without a reason",
             "`# nomadlint: allow(RULE)` must carry `-- <reason>`: an "
             "unexplained suppression hides the invariant it waives."),
        Rule("META002", "meta",
             "allow() for unknown rule",
             "The allow() names a rule id that does not exist — likely a "
             "typo that suppresses nothing."),
    )
}


@dataclass
class Finding:
    rule_id: str
    file: str               # repo-relative path
    line: int
    qualname: str           # enclosing module/class/function for stable keys
    message: str
    # Baseline identity deliberately excludes the line number: unrelated
    # edits above a grandfathered finding must not read as drift. The
    # stripped source line disambiguates repeated findings in one scope.
    snippet: str = ""
    extra: dict = field(default_factory=dict)

    def key(self) -> str:
        return f"{self.rule_id}|{self.file}|{self.qualname}|{self.snippet}"

    def render(self) -> str:
        return (f"{self.file}:{self.line}: {self.rule_id} "
                f"[{self.qualname}] {self.message}")


# -- allow() directives ------------------------------------------------------

# `# nomadlint: allow(RULE1, RULE2) -- reason` ; the reason is mandatory
# and checked by META001. Matches anywhere in a source line so it can ride
# a trailing comment.
_ALLOW_RE = re.compile(
    r"#\s*nomadlint:\s*allow\(([A-Za-z0-9_,\s]+)\)(?:\s*--\s*(.+?))?\s*$"
)


@dataclass
class Allow:
    rules: tuple
    reason: Optional[str]
    line: int


def parse_allow(source_line: str, lineno: int) -> Optional[Allow]:
    m = _ALLOW_RE.search(source_line)
    if not m:
        return None
    rules = tuple(r.strip() for r in m.group(1).split(",") if r.strip())
    reason = m.group(2).strip() if m.group(2) else None
    return Allow(rules=rules, reason=reason or None, line=lineno)
