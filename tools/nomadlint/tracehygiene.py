"""JAX trace-hygiene pass over ``tpu/`` and ``ops/``.

A jitted function's Python executes only while TRACING; value-dependent
Python control flow either raises (ConcretizationTypeError) or — with
static arguments — silently recompiles per distinct value. ``ops/fit.py``
grew jit_trace telemetry counters to catch the resulting retrace storms
at runtime; this pass catches the hazard classes statically:

- TRC001: ``if``/``while``/``for`` on a traced parameter inside a jitted
  function (uses of ``.shape``/``.ndim``/``.dtype``/``.size`` and
  ``len(x)`` are shape-level and fine).
- TRC002: a call site feeding a list/dict/set literal (or comprehension)
  to a static argument — unhashable, raises at call time.
- TRC003: a jitted function reading module-level mutable state that some
  other code in the module mutates — the traced-time value is baked into
  the executable and later mutations are silently ignored.

Jit detection covers ``@jax.jit``, ``@jit``,
``@partial(jax.jit, ...)``/``@functools.partial(jax.jit, ...)``, and
``name = jax.jit(fn, ...)`` module-level wrapping.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from tools.nomadlint.project import ModuleInfo, Project, qualname_of
from tools.nomadlint.registry import Finding

TRACE_SCOPE = (
    "nomad_tpu/tpu",
    "nomad_tpu/ops",
    "nomad_tpu/parallel",
)

_SHAPE_ATTRS = {"shape", "ndim", "dtype", "size", "aval", "weak_type"}


def _is_jit_expr(node: ast.AST) -> bool:
    """jax.jit / jit as a bare expression."""
    if isinstance(node, ast.Attribute):
        return (node.attr == "jit" and isinstance(node.value, ast.Name)
                and node.value.id == "jax")
    return isinstance(node, ast.Name) and node.id == "jit"


def _jit_call_statics(call: ast.Call) -> Tuple[Set[int], Set[str]]:
    nums: Set[int] = set()
    names: Set[str] = set()
    for kw in call.keywords:
        if kw.arg == "static_argnums":
            nums |= _int_set(kw.value)
        elif kw.arg == "static_argnames":
            names |= _str_set(kw.value)
    return nums, names


def _int_set(expr: ast.AST) -> Set[int]:
    out: Set[int] = set()
    elts = expr.elts if isinstance(expr, (ast.Tuple, ast.List)) else [expr]
    for e in elts:
        if isinstance(e, ast.Constant) and isinstance(e.value, int):
            out.add(e.value)
    return out


def _str_set(expr: ast.AST) -> Set[str]:
    out: Set[str] = set()
    elts = expr.elts if isinstance(expr, (ast.Tuple, ast.List)) else [expr]
    for e in elts:
        if isinstance(e, ast.Constant) and isinstance(e.value, str):
            out.add(e.value)
    return out


def _jit_decoration(fn: ast.FunctionDef) -> Optional[Tuple[Set[int], Set[str]]]:
    """(static_argnums, static_argnames) when fn is jit-decorated."""
    for dec in fn.decorator_list:
        if _is_jit_expr(dec):
            return set(), set()
        if isinstance(dec, ast.Call):
            if _is_jit_expr(dec.func):
                return _jit_call_statics(dec)
            f = dec.func
            is_partial = (
                (isinstance(f, ast.Name) and f.id == "partial")
                or (isinstance(f, ast.Attribute) and f.attr == "partial")
            )
            if is_partial and dec.args and _is_jit_expr(dec.args[0]):
                return _jit_call_statics(dec)
    return None


def _traced_params(fn: ast.FunctionDef, statics: Tuple[Set[int], Set[str]]
                   ) -> Set[str]:
    nums, names = statics
    params = [a.arg for a in fn.args.posonlyargs + fn.args.args]
    traced = set()
    for i, p in enumerate(params):
        if p in ("self", "cls"):
            continue
        if i in nums or p in names:
            continue
        traced.add(p)
    traced |= {a.arg for a in fn.args.kwonlyargs if a.arg not in names}
    return traced


class _ParentedWalk:
    """Name uses with their immediate parent, for shape-attr whitelisting."""

    def __init__(self, root: ast.AST):
        self.parents: Dict[ast.AST, ast.AST] = {}
        for node in ast.walk(root):
            for child in ast.iter_child_nodes(node):
                self.parents[child] = node

    def value_level_names(self, expr: ast.AST, targets: Set[str]) -> List[ast.Name]:
        """Names in ``expr`` matching ``targets`` used as VALUES — not as
        ``x.shape``-style shape access and not inside len()/isinstance()."""
        out = []
        for node in ast.walk(expr):
            if not (isinstance(node, ast.Name) and node.id in targets):
                continue
            parent = self.parents.get(node)
            if (isinstance(parent, ast.Attribute)
                    and parent.value is node
                    and parent.attr in _SHAPE_ATTRS):
                continue
            if (isinstance(parent, ast.Call)
                    and isinstance(parent.func, ast.Name)
                    and parent.func.id in ("len", "isinstance", "type")
                    and node in parent.args):
                continue
            out.append(node)
        return out


def _mutated_globals(mod: ModuleInfo) -> Set[str]:
    """Module-level names bound to mutable containers AND mutated
    somewhere (method mutation, subscript/aug assignment, or
    global-rebind)."""
    mutable: Set[str] = set()
    for node in mod.tree.body:
        if isinstance(node, ast.Assign):
            if isinstance(node.value, (ast.Dict, ast.List, ast.Set,
                                       ast.DictComp, ast.ListComp,
                                       ast.SetComp)) or (
                isinstance(node.value, ast.Call)
                and isinstance(node.value.func, ast.Name)
                and node.value.func.id in ("dict", "list", "set",
                                           "defaultdict", "OrderedDict",
                                           "deque")
            ):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        mutable.add(tgt.id)
    if not mutable:
        return set()
    mutated: Set[str] = set()
    _MUTATORS = {"append", "add", "update", "setdefault", "pop", "popitem",
                 "extend", "insert", "remove", "discard", "clear",
                 "appendleft"}
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Call):
            f = node.func
            if (isinstance(f, ast.Attribute) and f.attr in _MUTATORS
                    and isinstance(f.value, ast.Name)
                    and f.value.id in mutable):
                mutated.add(f.value.id)
        elif isinstance(node, (ast.Assign, ast.AugAssign)):
            tgts = node.targets if isinstance(node, ast.Assign) else [node.target]
            for tgt in tgts:
                if (isinstance(tgt, ast.Subscript)
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id in mutable):
                    mutated.add(tgt.value.id)
        elif isinstance(node, ast.Global):
            mutated |= set(node.names) & mutable
    return mutated


def _wrapped_statics(mod: ModuleInfo) -> Dict[str, Tuple[Set[int], Set[str]]]:
    """fn-name -> statics for ``name = jax.jit(fn, static_...=...)``."""
    out: Dict[str, Tuple[Set[int], Set[str]]] = {}
    for node in ast.walk(mod.tree):
        if not (isinstance(node, ast.Call) and _is_jit_expr(node.func)):
            continue
        if node.args and isinstance(node.args[0], ast.Name):
            out[node.args[0].id] = _jit_call_statics(node)
    return out


def run(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    for mod in project.scoped(TRACE_SCOPE):
        raw: List[Finding] = []
        mutated = _mutated_globals(mod)
        wrapped = _wrapped_statics(mod)
        jitted: List[Tuple[ast.FunctionDef, Tuple[Set[int], Set[str]]]] = []
        static_names_by_fn: Dict[str, Set[str]] = {}

        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.FunctionDef):
                continue
            statics = _jit_decoration(node)
            if statics is None and node.name in wrapped:
                statics = wrapped[node.name]
            if statics is None:
                continue
            jitted.append((node, statics))
            nums, names = statics
            params = [a.arg for a in node.args.posonlyargs + node.args.args]
            resolved = set(names)
            resolved |= {params[i] for i in nums if i < len(params)}
            static_names_by_fn[node.name] = resolved

        for fn, statics in jitted:
            traced = _traced_params(fn, statics)
            pw = _ParentedWalk(fn)
            for node in ast.walk(fn):
                if isinstance(node, (ast.If, ast.While)):
                    hits = pw.value_level_names(node.test, traced)
                    if hits:
                        kind = "if" if isinstance(node, ast.If) else "while"
                        raw.append(Finding(
                            "TRC001", mod.relpath, node.lineno,
                            qualname_of(node),
                            f"Python `{kind}` on traced value(s) "
                            f"{sorted({h.id for h in hits})} inside jitted "
                            f"{fn.name} — use lax.cond/select or make the "
                            "argument static",
                            snippet=mod.snippet(node.lineno),
                        ))
                elif isinstance(node, ast.For):
                    it = node.iter
                    direct = (isinstance(it, ast.Name) and it.id in traced)
                    over_range = (
                        isinstance(it, ast.Call)
                        and isinstance(it.func, ast.Name)
                        and it.func.id == "range"
                        and any(pw.value_level_names(a, traced)
                                for a in it.args)
                    )
                    if direct or over_range:
                        raw.append(Finding(
                            "TRC001", mod.relpath, node.lineno,
                            qualname_of(node),
                            f"Python `for` over traced value inside jitted "
                            f"{fn.name} — use lax.fori_loop/scan or a "
                            "static bound",
                            snippet=mod.snippet(node.lineno),
                        ))
                # TRC003: reads of mutated module-level containers.
                if isinstance(node, ast.Name) and node.id in mutated \
                        and isinstance(node.ctx, ast.Load):
                    raw.append(Finding(
                        "TRC003", mod.relpath, node.lineno,
                        qualname_of(node),
                        f"jitted {fn.name} reads module state "
                        f"{node.id!r} that is mutated elsewhere — the "
                        "traced-time value is baked into the compiled "
                        "executable",
                        snippet=mod.snippet(node.lineno),
                    ))

        # TRC002: unhashable literals at static positions of local calls.
        for node in ast.walk(mod.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id in static_names_by_fn):
                continue
            static_names = static_names_by_fn[node.func.id]
            fn_def = next(f for f, _ in jitted if f.name == node.func.id)
            params = [a.arg for a in fn_def.args.posonlyargs
                      + fn_def.args.args]
            feeds = []
            for i, a in enumerate(node.args):
                if i < len(params) and params[i] in static_names:
                    feeds.append((params[i], a))
            for kw in node.keywords:
                if kw.arg in static_names:
                    feeds.append((kw.arg, kw.value))
            for pname, expr in feeds:
                if isinstance(expr, (ast.List, ast.Dict, ast.Set,
                                     ast.ListComp, ast.DictComp,
                                     ast.SetComp)):
                    raw.append(Finding(
                        "TRC002", mod.relpath, expr.lineno,
                        qualname_of(node),
                        f"static argument {pname!r} of {node.func.id} fed "
                        "an unhashable container literal — jit static "
                        "args must be hashable (tuple it)",
                        snippet=mod.snippet(expr.lineno),
                    ))
        seen = set()
        deduped = []
        for f in raw:
            k = (f.rule_id, f.line, f.message)
            if k not in seen:
                seen.add(k)
                deduped.append(f)
        findings.extend(project.filter_allowed(mod, deduped))
    return findings
