"""Observatory pass (OBS001): the observatories are read-only.

``nomad_tpu/capacity.py`` (the capacity observatory),
``nomad_tpu/raft_observe.py`` (the raft & recovery observatory),
``nomad_tpu/read_observe.py`` (the read-path observatory) and
``nomad_tpu/profile_observe.py`` (the runtime self-observatory) observe
cluster state through change logs and plain-data books, and must stay
invisible to every decision path — the decision-invariance proofs (the
churn-fragmentation observatory-off contrast arm's digest equality; the
steady-10k digest staying byte-equal with the raft observatory on; the
read-storm reads-off contrast arm's digest equality) only mean
something if no placement, verify, or apply path can even *reach* an
observer's books. This pass enforces that
statically: any ``import`` of an observatory module (module-level or
function-local, plain or from-import) inside the decision scope is a
finding.

The composition roots are allowlisted by path: ``server/server.py``
constructs and starts the observers (lifecycle wiring only — the
ServerConfig parse and start/stop calls), and the exposition layer
(``api/``, ``bundle.py``) reads snapshots. Everything else in
scheduler/, server/, state/, raft/, tpu/, and ops/ is barred.
"""

from __future__ import annotations

import ast
from typing import List

from tools.nomadlint.project import Project, qualname_of
from tools.nomadlint.registry import Finding

# Where decisions are made: the solve path (scheduler/tpu/ops), the
# apply path (server/state/raft). The broader DET001 decision scope
# minus the leaf modules that cannot plausibly hold an import of the
# observatory's caliber (structs/network/events/faults are kept IN —
# cheap to check, and events.py importing the accountant would be just
# as much of a layering break).
OBSERVATORY_SCOPE = (
    "nomad_tpu/scheduler",
    "nomad_tpu/server",
    "nomad_tpu/state",
    "nomad_tpu/raft",
    "nomad_tpu/tpu",
    "nomad_tpu/ops",
    "nomad_tpu/structs.py",
    "nomad_tpu/network.py",
    "nomad_tpu/events.py",
    "nomad_tpu/faults.py",
)

# The one legitimate construction site: the server's composition root
# builds the observers and starts/stops them (slo monitor, express
# lane, capacity accountant, raft observatory). It may not READ the
# books either — but that is a review concern; the static bar is the
# import, and the composition root needs exactly that.
COMPOSITION_ROOTS = ("nomad_tpu/server/server.py",)

TARGET_MODULES = ("nomad_tpu.capacity", "nomad_tpu.raft_observe",
                  "nomad_tpu.read_observe", "nomad_tpu.profile_observe")
_TARGET_LEAVES = tuple(m.rsplit(".", 1)[1] for m in TARGET_MODULES)


def _match(name: str):
    for target in TARGET_MODULES:
        if name == target or name.startswith(target + "."):
            return target
    return None


def run(project: Project) -> List[Finding]:
    out: List[Finding] = []
    for mod in project.scoped(OBSERVATORY_SCOPE):
        if mod.relpath in COMPOSITION_ROOTS:
            continue
        findings: List[Finding] = []
        for node in ast.walk(mod.tree):
            hit = None
            if isinstance(node, ast.Import):
                for alias in node.names:
                    if _match(alias.name):
                        hit = alias.name
            elif isinstance(node, ast.ImportFrom):
                m = node.module or ""
                if _match(m):
                    hit = m
                elif m == "nomad_tpu":
                    for alias in node.names:
                        if alias.name in _TARGET_LEAVES:
                            hit = f"nomad_tpu.{alias.name}"
            if hit is not None:
                findings.append(Finding(
                    "OBS001", mod.relpath, node.lineno,
                    qualname_of(node, mod.modname),
                    f"decision-path module imports {hit} — an "
                    "observatory must stay invisible to scheduler/apply "
                    "paths (read-only observer contract)",
                    snippet=mod.snippet(node.lineno),
                ))
        out.extend(project.filter_allowed(mod, findings))
    return out
