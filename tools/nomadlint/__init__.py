"""nomadlint: project-specific static analysis for the tpu-nomad tree.

Five AST-based passes encode the invariants the control plane's
correctness story rests on but nothing previously *checked*:

- **determinism** (DET0xx): scheduler / FSM / plan / simcluster decision
  paths must not draw from the global ``random`` module, stamp intervals
  with ``time.time()``, or iterate unordered sets — the seed-replay
  contract (SIMLOAD event digests, fuzz families) only holds when every
  source of nondeterminism is a name-salted seeded stream (the
  ``faults.py`` pattern) or ``time.monotonic()``.
- **lockorder** (LCK0xx): extracts the whole-program lock graph (which
  locks each function acquires, which lock-holding regions call into
  which modules), computes a canonical acquisition order, and fails on
  cycles or edges that invert the committed order. The static result is
  validated dynamically by ``telemetry.LockWatchdog`` under tests.
- **excepts** (EXC0xx): no bare/broad ``except`` in raft append/apply,
  FSM, plan commit, and worker loops unless the handler re-raises,
  counts a telemetry metric, or fires a fault site — a swallowed raft
  error is a silent divergence, not a recovery.
- **tracehygiene** (TRC0xx): in ``tpu/`` and ``ops/``, Python control
  flow on traced values, unstable ``static_argnums``, and jitted
  functions closing over mutable module state — the retrace hazards
  ``ops/fit.py``'s jit_trace counters were added to catch at runtime.
- **observatory** (OBS0xx): the capacity observatory
  (``nomad_tpu/capacity.py``) is a read-only observer — scheduler /
  solver / state / raft / server decision paths must not import it;
  only the server composition root may construct it.

Findings are suppressed inline with ``# nomadlint: allow(RULE) -- reason``
(the reason is mandatory: an unexplained suppression is itself a finding,
META001) or grandfathered in the committed ``baseline.json``. Run as a
tier-1 gate: ``python -m tools.nomadlint --baseline``.
"""

from __future__ import annotations

from tools.nomadlint.registry import Finding, Rule, RULES  # noqa: F401
from tools.nomadlint.project import Project  # noqa: F401


def run_passes(project: "Project"):
    """Run all passes over ``project`` and return the findings,
    sorted for stable output/baseline comparison."""
    from tools.nomadlint import (
        determinism,
        excepts,
        lockorder,
        observatory,
        tracehygiene,
    )

    findings = []
    findings.extend(determinism.run(project))
    findings.extend(lockorder.run(project))
    findings.extend(excepts.run(project))
    findings.extend(tracehygiene.run(project))
    findings.extend(observatory.run(project))
    findings.extend(project.meta_findings())
    return sorted(findings, key=lambda f: (f.file, f.line, f.rule_id))
