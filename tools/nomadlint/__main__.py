"""nomadlint CLI.

    python -m tools.nomadlint                # report every finding
    python -m tools.nomadlint --baseline     # tier-1 gate: fail only on
                                             # findings not in baseline.json
                                             # (or on stale baseline rows)
    python -m tools.nomadlint --write-baseline    # regenerate baseline.json
    python -m tools.nomadlint --write-lock-order  # regenerate lock_order.json
    python -m tools.nomadlint --rules        # print the rule table
    python -m tools.nomadlint --json         # machine-readable report

Every run also writes the full report to /tmp/nomadlint_report.json so a
failed tier-1 run's debug bundle can embed it (nomad_tpu/bundle.py
``nomadlint`` section) without re-running the analysis in-process.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from nomad_tpu.bundle import NOMADLINT_REPORT_PATH as REPORT_PATH  # noqa: E402
from tools.nomadlint import baseline as baseline_mod  # noqa: E402
from tools.nomadlint import lockorder, run_passes  # noqa: E402
from tools.nomadlint.project import Project  # noqa: E402
from tools.nomadlint.registry import RULES  # noqa: E402


def _report_payload(findings, new, stale, baselined, roots):
    import time

    return {
        "format": "nomadlint-report/v1",
        # Provenance: the report lands at a host-global /tmp path that a
        # debug bundle may embed days later — stamp what tree produced
        # it, when, and over which roots, so a stale, foreign, or
        # partial-coverage report is detectable.
        "repo": REPO,
        "roots": list(roots),
        "generated_at": time.time(),
        "total": len(findings),
        "new": [vars(f) for f in new],
        "baselined": baselined,
        "stale_baseline_keys": stale,
        "by_rule": _by_rule(findings),
    }


def _by_rule(findings):
    out = {}
    for f in findings:
        out[f.rule_id] = out.get(f.rule_id, 0) + 1
    return dict(sorted(out.items()))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="nomadlint")
    ap.add_argument("--baseline", action="store_true",
                    help="gate mode: fail only on non-baselined findings")
    ap.add_argument("--write-baseline", action="store_true")
    ap.add_argument("--write-lock-order", action="store_true")
    ap.add_argument("--rules", action="store_true")
    ap.add_argument("--json", action="store_true")
    ap.add_argument("paths", nargs="*",
                    help="restrict analysis to these repo-relative roots")
    args = ap.parse_args(argv)

    if args.rules:
        for rule in sorted(RULES.values(), key=lambda r: r.id):
            flag = " (retired)" if rule.retired else ""
            print(f"{rule.id}  [{rule.pass_name}]{flag}  {rule.title}")
            print(f"        {rule.description}")
        return 0

    from tools.nomadlint.project import DEFAULT_ROOTS

    if args.paths and (args.baseline or args.write_baseline
                       or args.write_lock_order):
        # The baseline and lock order are whole-tree artifacts: writing
        # either from a subtree would drop every out-of-subtree row, and
        # gating a subtree against them would read out-of-subtree rows
        # as stale/drifted.
        ap.error("--baseline/--write-baseline/--write-lock-order operate "
                 "on the full tree; drop the path restriction")

    project = Project(
        repo=REPO,
        roots=tuple(args.paths) if args.paths else DEFAULT_ROOTS,
    )
    if project.errors:
        for err in project.errors:
            print(f"nomadlint: parse error: {err}", file=sys.stderr)
        return 2

    if args.write_lock_order:
        an = lockorder.analyze(project)
        lockorder.write_committed(an)
        print(f"wrote {lockorder.LOCK_ORDER_PATH} "
              f"({len(an.order)} locks, {len(an.edges)} edges)")
        if an.cycles:
            for cyc in an.cycles:
                print("CYCLE: " + " -> ".join(cyc + [cyc[0]]),
                      file=sys.stderr)
            return 1
        return 0

    findings = run_passes(project)

    if args.write_baseline:
        baseline_mod.save(findings)
        print(f"wrote {baseline_mod.BASELINE_PATH} "
              f"({len(findings)} findings)")
        return 0

    base = baseline_mod.load() if args.baseline else {}
    new, stale = baseline_mod.compare(findings, base)
    baselined = len(findings) - len(new)

    payload = _report_payload(findings, new, stale, baselined, project.roots)
    try:
        with open(REPORT_PATH, "w") as f:
            json.dump(payload, f, indent=1)
    except OSError:
        pass

    if args.json:
        print(json.dumps(payload, indent=1))
    else:
        for f in new:
            print(f.render())
        if stale:
            print(f"nomadlint: {len(stale)} stale baseline entr"
                  f"{'y' if len(stale) == 1 else 'ies'} (fixed findings "
                  "still grandfathered) — prune with --write-baseline:",
                  file=sys.stderr)
            for k in stale:
                print(f"  {k}", file=sys.stderr)
        summary = (f"nomadlint: {len(findings)} finding(s), "
                   f"{baselined} baselined, {len(new)} new")
        print(summary)

    if args.baseline:
        return 1 if (new or stale) else 0
    return 1 if findings else 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except BrokenPipeError:
        # `nomadlint --rules | head` closing stdout is not an error.
        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
        sys.exit(0)
