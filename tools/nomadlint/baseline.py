"""Committed baseline: grandfathered findings.

A baseline entry is a finding's stable key — rule id, file, enclosing
qualname, and the stripped source line (NOT the line number, so edits
above a grandfathered site don't read as drift). ``compare`` returns
(new, fixed): new findings fail the gate; fixed entries are stale
baseline rows that must be pruned (``--write-baseline``), so the
baseline can only ever shrink without an explicit decision."""

from __future__ import annotations

import json
import os
from collections import Counter
from typing import Dict, Iterable, List, Tuple

from tools.nomadlint.registry import Finding

BASELINE_PATH = os.path.join(os.path.dirname(__file__), "baseline.json")


def load(path: str = BASELINE_PATH) -> Dict[str, int]:
    """key -> count (one site can yield the same keyed finding twice,
    e.g. two identical snippets in one function)."""
    try:
        with open(path) as f:
            data = json.load(f)
    except (OSError, ValueError):
        return {}
    return {e["key"]: int(e.get("count", 1)) for e in data.get("findings", [])}


def save(findings: Iterable[Finding], path: str = BASELINE_PATH) -> None:
    counts = Counter(f.key() for f in findings)
    payload = {
        "format": "nomadlint-baseline/v1",
        "findings": [
            {"key": k, "count": n} for k, n in sorted(counts.items())
        ],
    }
    with open(path, "w") as f:
        json.dump(payload, f, indent=1)
        f.write("\n")


def compare(findings: List[Finding], baseline: Dict[str, int]
            ) -> Tuple[List[Finding], List[str]]:
    """(new findings not covered by the baseline, stale baseline keys)."""
    budget = dict(baseline)
    new: List[Finding] = []
    for f in findings:
        k = f.key()
        if budget.get(k, 0) > 0:
            budget[k] -= 1
        else:
            new.append(f)
    stale = sorted(k for k, n in budget.items() if n > 0)
    return new, stale
