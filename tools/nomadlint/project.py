"""Project loader: parse every module once, resolve allow() directives,
and provide the shared lookups the passes run against."""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional

from tools.nomadlint.registry import Allow, Finding, RULES, parse_allow

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# The analyzed tree. tools/ and tests/ are deliberately out: tests drive
# nondeterminism on purpose, and tools are operator-side.
DEFAULT_ROOTS = ("nomad_tpu",)


def _annotate_qualnames(tree: ast.Module, modname: str) -> None:
    """Stamp every node with the dotted scope that encloses it
    (``module.Class.method``) — the stable half of a finding's baseline
    key."""

    def walk(node: ast.AST, qual: str) -> None:
        for child in ast.iter_child_nodes(node):
            child._nl_qualname = qual  # type: ignore[attr-defined]
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                walk(child, f"{qual}.{child.name}")
            else:
                walk(child, qual)

    tree._nl_qualname = modname  # type: ignore[attr-defined]
    walk(tree, modname)


def qualname_of(node: ast.AST, default: str = "?") -> str:
    return getattr(node, "_nl_qualname", default)


@dataclass
class ModuleInfo:
    relpath: str            # repo-relative, forward slashes
    modname: str            # dotted import name
    lines: List[str]
    tree: ast.Module
    allows: Dict[int, Allow] = field(default_factory=dict)

    def line(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""

    def snippet(self, lineno: int) -> str:
        return self.line(lineno).strip()


class Project:
    def __init__(self, repo: str = REPO,
                 roots: Iterable[str] = DEFAULT_ROOTS):
        self.repo = repo
        self.roots = tuple(roots)
        self.modules: Dict[str, ModuleInfo] = {}
        self.errors: List[str] = []
        for root in roots:
            base = os.path.join(repo, root)
            if os.path.isfile(base) and base.endswith(".py"):
                self._load(os.path.relpath(base, repo))
                continue
            for dirpath, dirnames, filenames in os.walk(base):
                dirnames[:] = sorted(
                    d for d in dirnames if d != "__pycache__"
                )
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        self._load(os.path.relpath(
                            os.path.join(dirpath, fn), repo
                        ))

    def _load(self, relpath: str) -> None:
        relpath = relpath.replace(os.sep, "/")
        path = os.path.join(self.repo, relpath)
        try:
            with open(path, encoding="utf-8") as f:
                source = f.read()
            tree = ast.parse(source, filename=relpath)
        except (OSError, SyntaxError) as e:
            self.errors.append(f"{relpath}: {e}")
            return
        modname = relpath[:-3].replace("/", ".")
        if modname.endswith(".__init__"):
            modname = modname[:-len(".__init__")]
        lines = source.splitlines()
        mod = ModuleInfo(relpath=relpath, modname=modname,
                         lines=lines, tree=tree)
        for i, text in enumerate(lines, start=1):
            allow = parse_allow(text, i)
            if allow is not None:
                mod.allows[i] = allow
        _annotate_qualnames(tree, modname)
        self.modules[relpath] = mod

    # -- scoping -------------------------------------------------------------

    def in_scope(self, relpath: str, scope: Iterable[str]) -> bool:
        return any(
            relpath == s or relpath.startswith(s.rstrip("/") + "/")
            for s in scope
        )

    def scoped(self, scope: Iterable[str]) -> List[ModuleInfo]:
        return [m for rp, m in sorted(self.modules.items())
                if self.in_scope(rp, scope)]

    # -- suppression ---------------------------------------------------------

    def allowed(self, mod: ModuleInfo, lineno: int, rule_id: str) -> bool:
        """A finding is suppressed by an allow() on its own line, or
        anywhere in the contiguous comment block directly above it —
        reasons are encouraged to be real prose, which wraps."""
        allow = mod.allows.get(lineno)
        if allow is not None and rule_id in allow.rules:
            return True
        at = lineno - 1
        while at >= 1 and mod.line(at).lstrip().startswith("#"):
            allow = mod.allows.get(at)
            if allow is not None and rule_id in allow.rules:
                return True
            at -= 1
        return False

    def meta_findings(self) -> List[Finding]:
        out: List[Finding] = []
        for relpath, mod in sorted(self.modules.items()):
            for lineno, allow in sorted(mod.allows.items()):
                if allow.reason is None:
                    out.append(Finding(
                        "META001", relpath, lineno, mod.modname,
                        "allow() without `-- <reason>`: "
                        f"allow({', '.join(allow.rules)})",
                        snippet=mod.snippet(lineno),
                    ))
                for rid in allow.rules:
                    if rid not in RULES:
                        out.append(Finding(
                            "META002", relpath, lineno, mod.modname,
                            f"allow() names unknown rule {rid!r}",
                            snippet=mod.snippet(lineno),
                        ))
        return out

    def filter_allowed(self, mod: ModuleInfo,
                       findings: Iterable[Finding]) -> List[Finding]:
        return [f for f in findings
                if not self.allowed(mod, f.line, f.rule_id)]
