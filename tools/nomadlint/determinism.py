"""Determinism pass: decision paths must be a pure function of their
seeds.

Scope rationale: DET001/DET003 cover the modules whose outputs feed the
seed-replay contract (SIMLOAD event digests, fuzz differential families)
— scheduler, server, raft, state, simcluster, device solve, structs,
network, events, faults. Observability modules (telemetry/trace/bundle)
are excluded from DET001/DET003: a reservoir sample or span id draw
cannot change a placement. DET002 (wall clock) additionally covers the
observability modules so every ``time.time()`` in the tree carries an
explicit wall-clock-is-correct reason or gets converted.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set

from tools.nomadlint.project import ModuleInfo, Project, qualname_of
from tools.nomadlint.registry import Finding

DECISION_SCOPE = (
    "nomad_tpu/scheduler",
    "nomad_tpu/server",
    "nomad_tpu/raft",
    "nomad_tpu/state",
    "nomad_tpu/simcluster",
    "nomad_tpu/tpu",
    "nomad_tpu/ops",
    "nomad_tpu/structs.py",
    "nomad_tpu/network.py",
    "nomad_tpu/events.py",
    "nomad_tpu/faults.py",
)

TIME_SCOPE = DECISION_SCOPE + (
    "nomad_tpu/telemetry.py",
    "nomad_tpu/trace.py",
    "nomad_tpu/bundle.py",
    "nomad_tpu/backoff.py",
)

# Importing these names from `random` is fine: an instantiated
# random.Random IS the seeded-stream pattern.
_SEEDED_OK = {"Random", "SystemRandom"}


def _random_aliases(mod: ModuleInfo) -> (Set[str], Set[str]):
    """(names bound to the random MODULE, names bound to its global
    functions via from-imports)."""
    mod_names: Set[str] = set()
    func_names: Set[str] = set()
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name == "random":
                    mod_names.add(alias.asname or "random")
        elif isinstance(node, ast.ImportFrom) and node.module == "random":
            for alias in node.names:
                if alias.name not in _SEEDED_OK:
                    func_names.add(alias.asname or alias.name)
    return mod_names, func_names


def _time_aliases(mod: ModuleInfo) -> (Set[str], Set[str]):
    mod_names: Set[str] = set()
    func_names: Set[str] = set()
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name == "time":
                    mod_names.add(alias.asname or "time")
        elif isinstance(node, ast.ImportFrom) and node.module == "time":
            for alias in node.names:
                if alias.name == "time":
                    func_names.add(alias.asname or "time")
    return mod_names, func_names


def _set_typed_names(fn: ast.AST) -> Set[str]:
    """Names locally provable to be sets inside one function: assigned a
    set literal/comprehension/set()/frozenset() call."""
    names: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and _is_set_expr(node.value):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    names.add(tgt.id)
        elif isinstance(node, ast.AnnAssign) and isinstance(node.target, ast.Name):
            if _is_set_annotation(node.annotation):
                names.add(node.target.id)
    return names


def _is_set_expr(expr: ast.AST) -> bool:
    if isinstance(expr, (ast.Set, ast.SetComp)):
        return True
    if isinstance(expr, ast.Call) and isinstance(expr.func, ast.Name):
        return expr.func.id in ("set", "frozenset")
    return False


def _is_set_annotation(ann: ast.AST) -> bool:
    base = ann.value if isinstance(ann, ast.Subscript) else ann
    if isinstance(base, ast.Name):
        return base.id in ("Set", "set", "FrozenSet", "frozenset")
    if isinstance(base, ast.Attribute):
        return base.attr in ("Set", "FrozenSet")
    return False


def _self_set_attrs(cls: ast.ClassDef) -> Set[str]:
    """Attributes assigned ``self.X = set()/{...}`` anywhere in the
    class, or annotated as sets."""
    attrs: Set[str] = set()
    for node in ast.walk(cls):
        if isinstance(node, ast.Assign) and _is_set_expr(node.value):
            for tgt in node.targets:
                if (isinstance(tgt, ast.Attribute)
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id == "self"):
                    attrs.add(tgt.attr)
        elif (isinstance(node, ast.AnnAssign)
                and isinstance(node.target, ast.Attribute)
                and isinstance(node.target.value, ast.Name)
                and node.target.value.id == "self"
                and _is_set_annotation(node.annotation)):
            attrs.add(node.target.attr)
    return attrs


def run(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    for mod in project.scoped(TIME_SCOPE):
        in_decision = project.in_scope(mod.relpath, DECISION_SCOPE)
        raw: List[Finding] = []
        rand_mods, rand_funcs = _random_aliases(mod)
        time_mods, time_funcs = _time_aliases(mod)

        for node in ast.walk(mod.tree):
            # DET002 applies everywhere in TIME_SCOPE.
            if isinstance(node, ast.Call):
                f = node.func
                if (isinstance(f, ast.Attribute) and f.attr == "time"
                        and isinstance(f.value, ast.Name)
                        and f.value.id in time_mods):
                    raw.append(Finding(
                        "DET002", mod.relpath, node.lineno,
                        qualname_of(node),
                        "time.time() — use time.monotonic() for "
                        "intervals/deadlines; wall clock only for "
                        "user-facing timestamps with an allow() reason",
                        snippet=mod.snippet(node.lineno),
                    ))
                elif (isinstance(f, ast.Name) and f.id in time_funcs):
                    raw.append(Finding(
                        "DET002", mod.relpath, node.lineno,
                        qualname_of(node),
                        "time() imported from time module — same rule "
                        "as time.time()",
                        snippet=mod.snippet(node.lineno),
                    ))
            if not in_decision:
                continue
            # DET001: draws from the process-global random module.
            if isinstance(node, ast.Attribute):
                if (isinstance(node.value, ast.Name)
                        and node.value.id in rand_mods
                        and node.attr not in _SEEDED_OK):
                    raw.append(Finding(
                        "DET001", mod.relpath, node.lineno,
                        qualname_of(node),
                        f"global random.{node.attr} in a decision path — "
                        "use a name-salted seeded stream "
                        "(random.Random(seed ^ crc32(name)))",
                        snippet=mod.snippet(node.lineno),
                    ))
            elif isinstance(node, ast.Name) and node.id in rand_funcs:
                if isinstance(getattr(node, "ctx", None), ast.Load):
                    raw.append(Finding(
                        "DET001", mod.relpath, node.lineno,
                        qualname_of(node),
                        f"{node.id}() from the global random module in a "
                        "decision path — use a seeded Random instance",
                        snippet=mod.snippet(node.lineno),
                    ))
            # DET003: iteration over provable sets.
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                raw.extend(_set_iteration(mod, node))
        # _set_iteration runs per FunctionDef, and ast.walk hands us nested
        # functions both standalone and within their parent — dedupe.
        seen = set()
        deduped = []
        for f in raw:
            k = (f.rule_id, f.line, f.message)
            if k not in seen:
                seen.add(k)
                deduped.append(f)
        findings.extend(project.filter_allowed(mod, deduped))
    return findings


def _set_iteration(mod: ModuleInfo, fn: ast.AST) -> List[Finding]:
    out: List[Finding] = []
    local_sets = _set_typed_names(fn)
    cls = _enclosing_class_attrs(mod, fn)

    def is_set_target(it: ast.AST) -> Optional[str]:
        if _is_set_expr(it):
            return "a set expression"
        if isinstance(it, ast.Name) and it.id in local_sets:
            return f"local set {it.id!r}"
        if (isinstance(it, ast.Attribute)
                and isinstance(it.value, ast.Name)
                and it.value.id == "self" and it.attr in cls):
            return f"set attribute self.{it.attr}"
        return None

    for node in ast.walk(fn):
        iters: List[ast.AST] = []
        if isinstance(node, ast.For):
            iters.append(node.iter)
        elif isinstance(node, (ast.ListComp, ast.SetComp, ast.DictComp,
                               ast.GeneratorExp)):
            iters.extend(g.iter for g in node.generators)
        for it in iters:
            what = is_set_target(it)
            if what is not None:
                out.append(Finding(
                    "DET003", mod.relpath, it.lineno, qualname_of(node),
                    f"iteration over {what}: set order is hash order, "
                    "which varies across processes — iterate sorted(...) "
                    "or keep a list/dict",
                    snippet=mod.snippet(it.lineno),
                ))
    return out


def _enclosing_class_attrs(mod: ModuleInfo, fn: ast.AST) -> Set[str]:
    # A method's stamped qualname is its ENCLOSING scope — i.e. the
    # class's full dotted name — so the class is the ClassDef whose own
    # qualname + name equals it. The attr set is memoized on the ClassDef
    # node itself (dies with the AST; a process-global cache keyed by
    # node id could alias a recycled id across Projects).
    qual = qualname_of(fn, mod.modname)
    for node in ast.walk(mod.tree):
        if (isinstance(node, ast.ClassDef)
                and f"{qualname_of(node, mod.modname)}.{node.name}" == qual):
            attrs = getattr(node, "_nl_set_attrs", None)
            if attrs is None:
                attrs = node._nl_set_attrs = _self_set_attrs(node)
            return attrs
    return set()
