"""Lock-order pass: extract the whole-program lock graph and enforce a
canonical acquisition order.

Model:

- A **lock node** is a construction site, named by its owning scope:
  ``module.Class.attr`` for ``self.attr = threading.Lock()`` and
  ``module.NAME`` for module-level locks. Instances of one class share a
  node (instance identity is invisible statically), so self-edges L->L
  are skipped rather than reported.
- ``threading.Condition(self._lock)`` is an **alias** of the lock it
  wraps: acquiring the condition acquires that lock.
- An **edge** L -> M means some region holding L acquires M — directly
  (nested ``with``), or transitively through calls the resolver can
  follow (self-methods, same-module functions, project-module imports,
  and attributes whose class is inferable from constructor assignments).

Checks: LCK001 (cycle in the current graph), LCK002 (a current edge that
inverts the committed canonical order in ``lock_order.json``), LCK003
(the committed file does not match a fresh computation — regenerate with
``--write-lock-order``).

The same analysis feeds ``telemetry.LockWatchdog``: ``analyze()`` returns
construction sites (file, line) per lock and the transitive closure of
the edge set, which the watchdog asserts against real acquisitions under
tests — the static result validated dynamically.
"""

from __future__ import annotations

import ast
import json
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from tools.nomadlint.project import ModuleInfo, Project, qualname_of
from tools.nomadlint.registry import Finding

LOCK_ORDER_PATH = os.path.join(os.path.dirname(__file__), "lock_order.json")

_LOCK_CTORS = ("Lock", "RLock")
_MAX_CALL_DEPTH = 8


def _annotation_class(ann: Optional[ast.AST]) -> Optional[str]:
    """Bare class name from a parameter annotation: ``FSM``,
    ``"FSM"`` (quoted), ``Optional[FSM]``, ``mod.FSM``."""
    if ann is None:
        return None
    if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
        name = ann.value.strip()
        return name.split(".")[-1] if name.isidentifier() or "." in name \
            else None
    if isinstance(ann, ast.Subscript):
        base = ann.value
        if (isinstance(base, ast.Name) and base.id == "Optional") or (
                isinstance(base, ast.Attribute) and base.attr == "Optional"):
            return _annotation_class(ann.slice)
        return None
    if isinstance(ann, ast.Name):
        return ann.id
    if isinstance(ann, ast.Attribute):
        return ann.attr
    return None


def _ctor_classes(value: Optional[ast.AST],
                  global_types: Dict[str, str]) -> List[str]:
    """Class names an assigned expression may construct: a direct
    ``C(...)`` call, a module-level instance's class, or either arm of an
    ``x if cond else C()`` default-injection idiom."""
    if value is None:
        return []
    if isinstance(value, ast.IfExp):
        return (_ctor_classes(value.body, global_types)
                + _ctor_classes(value.orelse, global_types))
    if isinstance(value, ast.Call) and isinstance(value.func, ast.Name):
        return [value.func.id]
    if isinstance(value, ast.Name) and value.id in global_types:
        return [global_types[value.id]]
    return []


def _is_threading_call(node: ast.AST, names: Tuple[str, ...]) -> Optional[str]:
    """'Lock'/'RLock'/'Condition' when node is threading.X(...) or a
    bare X(...) imported from threading."""
    if not isinstance(node, ast.Call):
        return None
    f = node.func
    if (isinstance(f, ast.Attribute) and f.attr in names
            and isinstance(f.value, ast.Name)
            and f.value.id in ("threading", "_threading")):
        return f.attr
    if isinstance(f, ast.Name) and f.id in names:
        return f.id
    return None


@dataclass
class LockNode:
    lock_id: str
    file: str
    line: int
    kind: str                      # Lock | RLock | Condition


@dataclass
class Edge:
    src: str
    dst: str
    file: str
    line: int
    via: str                       # qualname of the holding function


@dataclass
class Analysis:
    locks: Dict[str, LockNode] = field(default_factory=dict)
    aliases: Dict[str, str] = field(default_factory=dict)   # alias id -> lock id
    edges: Dict[Tuple[str, str], Edge] = field(default_factory=dict)
    order: List[str] = field(default_factory=list)
    cycles: List[List[str]] = field(default_factory=list)

    def closure(self) -> Set[Tuple[str, str]]:
        """Transitive closure of the edge set (small graph; Floyd-style)."""
        succ: Dict[str, Set[str]] = {}
        for (a, b) in self.edges:
            succ.setdefault(a, set()).add(b)
        closed: Set[Tuple[str, str]] = set()
        for start in succ:
            stack, seen = [start], set()
            while stack:
                cur = stack.pop()
                for nxt in succ.get(cur, ()):
                    if nxt not in seen:
                        seen.add(nxt)
                        stack.append(nxt)
            closed.update((start, n) for n in seen)
        return closed

    def sites(self) -> Dict[Tuple[str, int], str]:
        """(file, line) of each lock/alias construction -> lock id — the
        LockWatchdog's runtime mapping."""
        out = {(n.file, n.line): self.aliases.get(n.lock_id, n.lock_id)
               for n in self.locks.values()}
        return out


class _ModuleEnv:
    """Per-module name resolution: imports of project modules, classes,
    module-level instance types, and per-class attribute types."""

    def __init__(self, mod: ModuleInfo, project_mods: Set[str]):
        self.mod = mod
        self.import_map: Dict[str, str] = {}      # local name -> module
        self.from_map: Dict[str, Tuple[str, str]] = {}  # name -> (module, orig)
        self.classes: Dict[str, ast.ClassDef] = {}
        self.global_types: Dict[str, str] = {}    # NAME -> ClassName
        self.attr_types: Dict[Tuple[str, str], str] = {}  # (Class, attr) -> ClassName
        self.functions: Dict[str, ast.FunctionDef] = {}   # module-level funcs
        # (Class, attr) -> method names: `self._handlers = {...: self._m}`
        # dispatch tables, so indirect handler calls stay in the graph.
        self.method_tables: Dict[Tuple[str, str], Set[str]] = {}

        for node in mod.tree.body:
            if isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.name in project_mods:
                        self.import_map[alias.asname
                                        or alias.name.split(".")[0]] = alias.name
            elif isinstance(node, ast.ImportFrom) and node.module:
                base = node.module
                if node.level:
                    parts = mod.modname.split(".")
                    base = ".".join(parts[:-node.level] + [node.module])
                for alias in node.names:
                    full = f"{base}.{alias.name}"
                    if full in project_mods:
                        self.import_map[alias.asname or alias.name] = full
                    elif base in project_mods:
                        self.from_map[alias.asname or alias.name] = (
                            base, alias.name
                        )
            elif isinstance(node, ast.ClassDef):
                self.classes[node.name] = node
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.functions[node.name] = node
            elif isinstance(node, ast.Assign):
                if (isinstance(node.value, ast.Call)
                        and isinstance(node.value.func, ast.Name)):
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            self.global_types[tgt.id] = node.value.func.id

        for cls in self.classes.values():
            for sub in ast.walk(cls):
                if isinstance(sub, ast.AnnAssign):
                    targets = [sub.target] if sub.value is not None else []
                    value = sub.value
                elif isinstance(sub, ast.Assign):
                    targets = sub.targets
                    value = sub.value
                else:
                    continue
                self_targets = [
                    t for t in targets
                    if (isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self")
                ]
                if not self_targets:
                    continue
                for value_cls in _ctor_classes(value, self.global_types):
                    for tgt in self_targets:
                        self.attr_types[(cls.name, tgt.attr)] = value_cls
                if isinstance(value, ast.Dict):
                    methods = {
                        v.attr for v in value.values
                        if (isinstance(v, ast.Attribute)
                            and isinstance(v.value, ast.Name)
                            and v.value.id == "self")
                    }
                    if methods:
                        for tgt in self_targets:
                            self.method_tables[(cls.name, tgt.attr)] = methods
            # `def __init__(self, fsm: FSM)` + `self.fsm = fsm`: the
            # annotation types the attribute. Collaborator objects are
            # usually INJECTED, not constructed — without this, every
            # lock-holding call through an injected dependency (e.g.
            # InProcRaft holding _lock while calling self.fsm.apply) is
            # invisible to the edge extraction.
            for item in cls.body:
                if not isinstance(item, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                    continue
                ann: Dict[str, str] = {}
                args = (item.args.posonlyargs + item.args.args
                        + item.args.kwonlyargs)
                for a in args:
                    cname = _annotation_class(a.annotation)
                    if cname is not None:
                        ann[a.arg] = cname
                if not ann:
                    continue
                for sub in ast.walk(item):
                    if not isinstance(sub, ast.Assign):
                        continue
                    names = [sub.value] if isinstance(sub.value, ast.Name) \
                        else ([sub.value.body, sub.value.orelse]
                              if isinstance(sub.value, ast.IfExp) else [])
                    param = next(
                        (n.id for n in names
                         if isinstance(n, ast.Name) and n.id in ann), None,
                    )
                    if param is None:
                        continue
                    for tgt in sub.targets:
                        if (isinstance(tgt, ast.Attribute)
                                and isinstance(tgt.value, ast.Name)
                                and tgt.value.id == "self"):
                            self.attr_types.setdefault(
                                (cls.name, tgt.attr), ann[param]
                            )


def _collect_locks(mod: ModuleInfo, env: _ModuleEnv, an: Analysis) -> None:
    def lock_expr_id(expr: ast.AST, cls_name: Optional[str]) -> Optional[str]:
        """The lock id an expression names when used as a Condition's
        backing lock (self.X in the same class, or a module global)."""
        if (cls_name and isinstance(expr, ast.Attribute)
                and isinstance(expr.value, ast.Name)
                and expr.value.id == "self"):
            return f"{mod.modname}.{cls_name}.{expr.attr}"
        if isinstance(expr, ast.Name):
            return f"{mod.modname}.{expr.id}"
        return None

    def visit(body, cls_name: Optional[str]):
        for node in body:
            if isinstance(node, ast.ClassDef):
                visit(node.body, node.name)
                continue
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                visit(node.body, cls_name)
                continue
            for sub in ast.walk(node):
                if not isinstance(sub, ast.Assign):
                    continue
                kind = _is_threading_call(sub.value, _LOCK_CTORS + ("Condition",))
                if kind is None:
                    continue
                for tgt in sub.targets:
                    owner = None
                    if (isinstance(tgt, ast.Attribute)
                            and isinstance(tgt.value, ast.Name)
                            and tgt.value.id == "self" and cls_name):
                        owner = f"{mod.modname}.{cls_name}.{tgt.attr}"
                    elif isinstance(tgt, ast.Name):
                        owner = f"{mod.modname}.{tgt.id}"
                    if owner is None:
                        continue
                    an.locks[owner] = LockNode(
                        owner, mod.relpath, sub.value.lineno, kind
                    )
                    if kind == "Condition" and sub.value.args:
                        backing = lock_expr_id(sub.value.args[0], cls_name)
                        if backing is not None:
                            an.aliases[owner] = backing

    visit(mod.tree.body, None)


class _Resolver:
    """Cross-module call + lock-expression resolution."""

    def __init__(self, project: Project, envs: Dict[str, _ModuleEnv],
                 an: Analysis):
        self.project = project
        self.envs = envs
        self.an = an
        # qualname -> FunctionDef for every function/method in scope
        self.funcs: Dict[str, ast.AST] = {}
        # ClassName -> [qual prefix] (classes may share names across modules)
        self.class_quals: Dict[str, List[str]] = {}
        for modname, env in envs.items():
            for fname, fnode in env.functions.items():
                self.funcs[f"{modname}.{fname}"] = fnode
            for cname, cnode in env.classes.items():
                self.class_quals.setdefault(cname, []).append(
                    f"{modname}.{cname}"
                )
                for item in cnode.body:
                    if isinstance(item, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        self.funcs[f"{modname}.{cname}.{item.name}"] = item
        self._locks_of: Dict[str, Set[str]] = {}

    def canon(self, lock_id: Optional[str]) -> Optional[str]:
        if lock_id is None:
            return None
        lock_id = self.an.aliases.get(lock_id, lock_id)
        return lock_id if lock_id in self.an.locks else None

    def resolve_lock_expr(self, expr: ast.AST, env: _ModuleEnv,
                          cls_name: Optional[str]) -> Optional[str]:
        if isinstance(expr, ast.Name):
            return self.canon(f"{env.mod.modname}.{expr.id}")
        if not isinstance(expr, ast.Attribute):
            return None
        base = expr.value
        if isinstance(base, ast.Name):
            if base.id == "self" and cls_name:
                got = self.canon(f"{env.mod.modname}.{cls_name}.{expr.attr}")
                if got is not None:
                    return got
                # Base classes in the same project (single level).
                cnode = env.classes.get(cls_name)
                if cnode is not None:
                    for b in cnode.bases:
                        bname = b.id if isinstance(b, ast.Name) else None
                        for q in self.class_quals.get(bname or "", []):
                            got = self.canon(f"{q}.{expr.attr}")
                            if got is not None:
                                return got
                return None
            if base.id in env.import_map:
                return self.canon(f"{env.import_map[base.id]}.{expr.attr}")
            cls = env.global_types.get(base.id)
            if cls is not None:
                for q in self.class_quals.get(cls, []):
                    got = self.canon(f"{q}.{expr.attr}")
                    if got is not None:
                        return got
        elif (isinstance(base, ast.Attribute)
                and isinstance(base.value, ast.Name)
                and base.value.id == "self" and cls_name):
            cls = env.attr_types.get((cls_name, base.attr))
            if cls is not None:
                for q in self.class_quals.get(cls, []):
                    got = self.canon(f"{q}.{expr.attr}")
                    if got is not None:
                        return got
        return None

    def resolve_call(self, call: ast.Call, env: _ModuleEnv,
                     cls_name: Optional[str]) -> Optional[str]:
        f = call.func
        if isinstance(f, ast.Name):
            if f.id in env.from_map:
                m, orig = env.from_map[f.id]
                qual = f"{m}.{orig}"
                if qual in self.funcs:
                    return qual
                # from X import Class — constructor call: __init__
                if f"{qual}.__init__" in self.funcs:
                    return f"{qual}.__init__"
                return None
            qual = f"{env.mod.modname}.{f.id}"
            if qual in self.funcs:
                return qual
            if f.id in env.classes:
                q = f"{env.mod.modname}.{f.id}.__init__"
                return q if q in self.funcs else None
            return None
        if not isinstance(f, ast.Attribute):
            return None
        base = f.value
        if isinstance(base, ast.Name):
            if base.id in ("self", "cls") and cls_name:
                for q in self.class_quals.get(cls_name, []):
                    if q.startswith(env.mod.modname + "."):
                        cand = f"{q}.{f.attr}"
                        if cand in self.funcs:
                            return cand
                cnode = env.classes.get(cls_name)
                if cnode is not None:
                    for b in cnode.bases:
                        bname = b.id if isinstance(b, ast.Name) else None
                        for q in self.class_quals.get(bname or "", []):
                            cand = f"{q}.{f.attr}"
                            if cand in self.funcs:
                                return cand
                return None
            if base.id in env.import_map:
                cand = f"{env.import_map[base.id]}.{f.attr}"
                return cand if cand in self.funcs else None
            cls = env.global_types.get(base.id)
            if cls is not None:
                for q in self.class_quals.get(cls, []):
                    cand = f"{q}.{f.attr}"
                    if cand in self.funcs:
                        return cand
        elif (isinstance(base, ast.Attribute)
                and isinstance(base.value, ast.Name)
                and base.value.id == "self" and cls_name):
            cls = env.attr_types.get((cls_name, base.attr))
            if cls is not None:
                for q in self.class_quals.get(cls, []):
                    cand = f"{q}.{f.attr}"
                    if cand in self.funcs:
                        return cand
        return None

    # -- transitive lock sets ------------------------------------------------

    def locks_of(self, qual: str, _depth: int = 0,
                 _stack: Optional[Set[str]] = None) -> Set[str]:
        """Every lock ``qual`` may acquire, directly or through resolvable
        calls (over-approximate, memoized)."""
        if qual in self._locks_of:
            return self._locks_of[qual]
        if _depth > _MAX_CALL_DEPTH:
            return set()
        stack = _stack or set()
        if qual in stack:
            return set()
        fn = self.funcs.get(qual)
        if fn is None:
            return set()
        env, cls_name = self._context_of(qual)
        # Dispatch-table indirection: `h = self._handlers.get(k); h(...)`
        # (or a direct `self._handlers[k](...)`) may call any method the
        # table references — without this the FSM's entire apply fan-out
        # would be invisible to the graph.
        table_vars: Dict[str, Set[str]] = {}
        if cls_name is not None:
            for node in ast.walk(fn):
                if not isinstance(node, ast.Assign):
                    continue
                methods = self._table_methods(node.value, env, cls_name)
                if methods:
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            table_vars[tgt.id] = methods
        out: Set[str] = set()

        def dispatch(methods: Set[str]) -> None:
            for m in sorted(methods):
                for q in self.class_quals.get(cls_name or "", []):
                    cand = f"{q}.{m}"
                    if cand in self.funcs and cand != qual:
                        out.update(self.locks_of(
                            cand, _depth + 1, stack | {qual}
                        ))

        for node in ast.walk(fn):
            if isinstance(node, ast.With):
                for item in node.items:
                    lock = self.resolve_lock_expr(
                        item.context_expr, env, cls_name
                    )
                    if lock is not None:
                        out.add(lock)
            elif isinstance(node, ast.Call):
                callee = self.resolve_call(node, env, cls_name)
                if callee is not None and callee != qual:
                    out |= self.locks_of(
                        callee, _depth + 1, stack | {qual}
                    )
                elif (isinstance(node.func, ast.Name)
                        and node.func.id in table_vars):
                    dispatch(table_vars[node.func.id])
                else:
                    methods = self._table_methods(node.func, env, cls_name)
                    if methods:
                        dispatch(methods)
        self._locks_of[qual] = out
        return out

    def _table_methods(self, expr: ast.AST, env: _ModuleEnv,
                       cls_name: Optional[str]) -> Set[str]:
        """Method names reachable through ``self.<table>.get(...)`` /
        ``self.<table>[...]`` when <table> is a recorded dispatch dict."""
        if cls_name is None:
            return set()

        def table_of(base: ast.AST) -> Set[str]:
            if (isinstance(base, ast.Attribute)
                    and isinstance(base.value, ast.Name)
                    and base.value.id == "self"):
                return env.method_tables.get((cls_name, base.attr), set())
            return set()

        if (isinstance(expr, ast.Call)
                and isinstance(expr.func, ast.Attribute)
                and expr.func.attr == "get"):
            return table_of(expr.func.value)
        if isinstance(expr, ast.Subscript):
            return table_of(expr.value)
        return set()

    def _context_of(self, qual: str) -> Tuple[_ModuleEnv, Optional[str]]:
        parts = qual.split(".")
        for cut in range(len(parts) - 1, 0, -1):
            modname = ".".join(parts[:cut])
            env = self.envs.get(modname)
            if env is not None:
                rest = parts[cut:]
                cls = rest[0] if len(rest) == 2 else None
                return env, cls
        raise KeyError(qual)


def analyze(project: Project) -> Analysis:
    an = Analysis()
    envs: Dict[str, _ModuleEnv] = {}
    project_mods = {m.modname for m in project.modules.values()}
    for relpath, mod in sorted(project.modules.items()):
        envs[mod.modname] = _ModuleEnv(mod, project_mods)
        _collect_locks(mod, envs[mod.modname], an)

    resolver = _Resolver(project, envs, an)

    # Edges: for every with-region, locks acquired inside the body.
    for qual, fn in sorted(resolver.funcs.items()):
        env, cls_name = resolver._context_of(qual)
        for node in ast.walk(fn):
            if not isinstance(node, ast.With):
                continue
            held = [
                resolver.resolve_lock_expr(item.context_expr, env, cls_name)
                for item in node.items
            ]
            held = [h for h in held if h is not None]
            if not held:
                continue
            # `with A, B:` orders A before B.
            for i in range(len(held) - 1):
                _add_edge(an, held[i], held[i + 1], env.mod.relpath,
                          node.lineno, qual)
            inner: Set[str] = set()
            for body_node in node.body:
                for sub in ast.walk(body_node):
                    if isinstance(sub, ast.With):
                        for item in sub.items:
                            lock = resolver.resolve_lock_expr(
                                item.context_expr, env, cls_name
                            )
                            if lock is not None:
                                inner.add(lock)
                    elif isinstance(sub, ast.Call):
                        callee = resolver.resolve_call(sub, env, cls_name)
                        if callee is not None:
                            inner |= resolver.locks_of(callee)
            for h in held:
                for m in inner:
                    _add_edge(an, h, m, env.mod.relpath, node.lineno, qual)

    _order_and_cycles(an)
    return an


def _add_edge(an: Analysis, src: str, dst: str, file: str, line: int,
              via: str) -> None:
    src = an.aliases.get(src, src)
    dst = an.aliases.get(dst, dst)
    if src == dst:
        return  # instance identity unknown statically; see module doc
    an.edges.setdefault((src, dst), Edge(src, dst, file, line, via))


def _order_and_cycles(an: Analysis) -> None:
    """Kahn topological sort with lexicographic tie-break; unsortable
    leftovers are the cycle participants (reported via SCC walk)."""
    nodes = sorted(an.locks)
    nodes = [n for n in nodes if n not in an.aliases]
    succ: Dict[str, Set[str]] = {n: set() for n in nodes}
    pred: Dict[str, Set[str]] = {n: set() for n in nodes}
    for (a, b) in an.edges:
        if a in succ and b in succ:
            succ[a].add(b)
            pred[b].add(a)
    ready = sorted(n for n in nodes if not pred[n])
    order: List[str] = []
    pred = {n: set(p) for n, p in pred.items()}
    while ready:
        n = ready.pop(0)
        order.append(n)
        newly = []
        for m in sorted(succ[n]):
            pred[m].discard(n)
            if not pred[m]:
                newly.append(m)
        ready = sorted(set(ready) | set(newly))
    an.order = order
    leftover = [n for n in nodes if n not in set(order)]
    if leftover:
        an.cycles = _sccs(leftover, succ)


def _sccs(nodes: List[str], succ: Dict[str, Set[str]]) -> List[List[str]]:
    """Tarjan over the leftover (cyclic) subgraph."""
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on_stack: Set[str] = set()
    stack: List[str] = []
    counter = [0]
    out: List[List[str]] = []
    nodeset = set(nodes)

    def strong(v: str) -> None:
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on_stack.add(v)
        for w in sorted(succ.get(v, ())):
            if w not in nodeset:
                continue
            if w not in index:
                strong(w)
                low[v] = min(low[v], low[w])
            elif w in on_stack:
                low[v] = min(low[v], index[w])
        if low[v] == index[v]:
            comp = []
            while True:
                w = stack.pop()
                on_stack.discard(w)
                comp.append(w)
                if w == v:
                    break
            if len(comp) > 1:
                out.append(sorted(comp))

    for v in sorted(nodes):
        if v not in index:
            strong(v)
    return out


# -- committed order ---------------------------------------------------------

def load_committed(path: str = LOCK_ORDER_PATH) -> Optional[dict]:
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def committed_payload(an: Analysis) -> dict:
    """Line-number-free so unrelated edits don't read as drift."""
    return {
        "order": an.order,
        "edges": sorted([a, b] for (a, b) in an.edges),
        "aliases": dict(sorted(an.aliases.items())),
    }


def write_committed(an: Analysis, path: str = LOCK_ORDER_PATH) -> None:
    with open(path, "w") as f:
        json.dump(committed_payload(an), f, indent=1, sort_keys=True)
        f.write("\n")


def run(project: Project) -> List[Finding]:
    an = analyze(project)
    findings: List[Finding] = []
    for cyc in an.cycles:
        first = next(
            (an.edges[(a, b)] for a in cyc for b in cyc
             if (a, b) in an.edges), None,
        )
        findings.append(Finding(
            "LCK001", first.file if first else "tools/nomadlint",
            first.line if first else 0,
            first.via if first else "lockorder",
            "lock-order cycle: " + " -> ".join(cyc + [cyc[0]]),
            snippet="cycle:" + ",".join(cyc),
        ))
    from tools.nomadlint.project import DEFAULT_ROOTS

    if tuple(project.roots) != tuple(DEFAULT_ROOTS):
        # A path-restricted analysis sees only a partial lock graph:
        # comparing it against the whole-tree committed order would
        # read every out-of-scope lock as drift. Cycles (above) are
        # still real; the committed-order checks need the full tree.
        return findings
    committed = load_committed()
    if committed is None:
        findings.append(Finding(
            "LCK003", "tools/nomadlint/lock_order.json", 0, "lockorder",
            "no committed lock order — generate with --write-lock-order",
            snippet="missing",
        ))
        return findings
    committed_edges = {tuple(e) for e in committed.get("edges", [])}
    committed_closure = _close(committed_edges)
    for (a, b), edge in sorted(an.edges.items()):
        if (b, a) in committed_closure and (a, b) not in committed_edges:
            findings.append(Finding(
                "LCK002", edge.file, edge.line, edge.via,
                f"acquisition {a} -> {b} inverts the committed canonical "
                f"order ({b} precedes {a})",
                snippet=f"{a}->{b}",
            ))
    if committed != committed_payload(an):
        findings.append(Finding(
            "LCK003", "tools/nomadlint/lock_order.json", 0, "lockorder",
            "committed lock order drifted from a fresh computation — "
            "regenerate with --write-lock-order",
            snippet="drift",
        ))
    return findings


def _close(edges: Set[Tuple[str, str]]) -> Set[Tuple[str, str]]:
    succ: Dict[str, Set[str]] = {}
    for (a, b) in edges:
        succ.setdefault(a, set()).add(b)
    out: Set[Tuple[str, str]] = set()
    for start in succ:
        stack, seen = [start], set()
        while stack:
            cur = stack.pop()
            for nxt in succ.get(cur, ()):
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append(nxt)
        out.update((start, n) for n in seen)
    return out
