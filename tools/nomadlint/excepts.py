"""Exception-hygiene pass: broad handlers in the replication/scheduling
hot path must leave evidence.

Scope: raft append/apply, the FSM, plan verification/commit, and the
worker/broker loops — the modules where an eaten exception is a silent
state divergence. A broad handler (``except Exception`` / bare
``except:``) passes iff it does at least one of:

- re-raises (``raise``),
- propagates the caught exception object into a future/response
  (``set_exception(e)`` / ``respond(..., e)``),
- counts a telemetry metric (``telemetry.incr_counter``/``add_sample``/
  ``measure_since``/``set_gauge``),
- fires a fault site (``faults.fire``).

``logger.error(...)`` alone deliberately does NOT pass: logs rot in
buffers nobody greps; metrics alarm.
"""

from __future__ import annotations

import ast
from typing import List

from tools.nomadlint.project import Project, qualname_of
from tools.nomadlint.registry import Finding

HOT_SCOPE = (
    "nomad_tpu/raft",
    "nomad_tpu/server/fsm.py",
    "nomad_tpu/server/plan_pipeline.py",
    "nomad_tpu/server/plan_apply.py",
    "nomad_tpu/server/plan_queue.py",
    "nomad_tpu/server/worker.py",
    "nomad_tpu/server/eval_broker.py",
)

_TELEMETRY_FUNCS = ("incr_counter", "add_sample", "measure_since", "set_gauge")
_PROPAGATORS = ("set_exception", "respond")

_BROAD = ("Exception", "BaseException")


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True
    if isinstance(t, ast.Name):
        return t.id in _BROAD
    if isinstance(t, ast.Tuple):
        return any(isinstance(e, ast.Name) and e.id in _BROAD for e in t.elts)
    return False


def _handler_ok(handler: ast.ExceptHandler) -> bool:
    caught = handler.name  # `except Exception as e` -> "e"
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return True
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if isinstance(f, ast.Attribute):
            if (f.attr in _TELEMETRY_FUNCS
                    and isinstance(f.value, ast.Name)
                    and f.value.id == "telemetry"):
                return True
            if (f.attr == "fire" and isinstance(f.value, ast.Name)
                    and f.value.id == "faults"):
                return True
            if f.attr in _PROPAGATORS and caught:
                if any(isinstance(a, ast.Name) and a.id == caught
                       for a in node.args):
                    return True
        elif isinstance(f, ast.Name) and f.id in _TELEMETRY_FUNCS:
            return True
    return False


def run(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    for mod in project.scoped(HOT_SCOPE):
        raw: List[Finding] = []
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not _is_broad(node):
                continue
            if _handler_ok(node):
                continue
            rule = "EXC002" if node.type is None else "EXC001"
            what = ("bare except" if node.type is None
                    else "broad `except Exception`")
            raw.append(Finding(
                rule, mod.relpath, node.lineno, qualname_of(node),
                f"{what} in a hot path neither re-raises, propagates the "
                "error, counts telemetry, nor fires a fault site",
                snippet=mod.snippet(node.lineno),
            ))
        findings.extend(project.filter_allowed(mod, raw))
    return findings
