"""Compiled-pallas proof: execute the Mosaic water-fill kernel on a real
TPU backend and differentially verify it against the jnp path, per shape.

The interpret-mode suite (tests/test_pallas_solve.py) proves kernel
SEMANTICS on CPU; this proves the compiled artifact — Mosaic lowering,
VMEM residency, and on-device execution — which can only happen where a
TPU backend exists. Invoked by tools/bench_watch.py the moment the device
relay answers (after a successful bench capture), or standalone:

    python tools/pallas_proof.py        # emits ONE JSON line, rc 0 if all match

Per (node-bucket, batch) shape it runs differential seeds from the same
corpus as the interpret suite and times both paths, so the capture also
answers whether the kernel BEATS the jnp lowering on hardware. A shape
that fails to lower is reported per-shape, not fatally — that is exactly
the prove-before-trust posture of the production coalescer
(ops/coalesce.py _pallas_dispatch).
"""

from __future__ import annotations

import json
import os
import statistics
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "tests"))

SHAPES = ((64, 1), (1024, 1), (4096, 1), (16384, 1), (1024, 4), (4096, 4))
SEEDS = int(os.environ.get("NOMAD_TPU_PALLAS_PROOF_SEEDS", "6"))
TRIALS = 5


def _time_fn(fn) -> float:
    import jax

    times = []
    for _ in range(TRIALS):
        t = time.perf_counter()
        jax.block_until_ready(fn())
        times.append(time.perf_counter() - t)
    return statistics.median(times) * 1000


def run_proof(shapes=SHAPES, seeds: int = SEEDS) -> dict:
    """Differential + timing proof of the compiled kernel on the current
    backend. Returns a report dict; report['ok'] means every shape that
    lowered matched the jnp path bit-for-bit on every seed AND at least
    one shape lowered."""
    os.environ.setdefault("NOMAD_TPU_PALLAS", "compiled")
    import jax
    import jax.numpy as jnp
    import numpy as np

    from nomad_tpu.ops import pallas_solve
    from nomad_tpu.ops.binpack import solve_waterfill
    from nomad_tpu.ops.coalesce import solve_waterfill_batched
    from test_pallas_solve import random_instance

    # interpret=True only when explicitly requested (harness smoke on CPU);
    # the real proof is the compiled Mosaic artifact.
    interp = os.environ.get("NOMAD_TPU_PALLAS", "").lower() == "interpret"
    backend = jax.default_backend()
    report = {
        "kind": "pallas_proof",
        "backend": backend,
        "compiled": not interp,
        "seeds_per_shape": seeds,
        "shapes": [],
    }

    for n, b in shapes:
        row = {"n_nodes": n, "batch": b, "matched": 0, "mismatched": 0}
        rng = np.random.default_rng(20_000 + n + b)
        try:
            for s in range(seeds):
                rows = [random_instance(rng, n) for _ in range(b)]
                if b == 1:
                    args = rows[0]
                    c0, r0 = solve_waterfill(*args, False, False)
                    c1, r1 = pallas_solve.solve_waterfill_pallas(
                        *args, False, False, interpret=interp
                    )
                    match = (
                        np.array_equal(np.asarray(c0), np.asarray(c1))
                        and int(r0) == int(r1)
                    )
                else:
                    cols = list(zip(*(r[:10] for r in rows)))
                    stacked = [jnp.stack(c) for c in cols]
                    counts = jnp.asarray(
                        [int(r[10]) for r in rows], dtype=jnp.int32)
                    pens = jnp.asarray(
                        [float(r[11]) for r in rows], dtype=jnp.float32)
                    c0, r0 = solve_waterfill_batched(
                        *stacked, counts, pens, False, False)
                    c1, r1 = pallas_solve.solve_waterfill_pallas_batched(
                        *stacked, counts, pens, False, False,
                        interpret=interp)
                    match = (
                        np.array_equal(np.asarray(c0), np.asarray(c1))
                        and np.array_equal(np.asarray(r0), np.asarray(r1))
                    )
                row["matched" if match else "mismatched"] += 1
                if s == seeds - 1 and row["mismatched"] == 0:
                    # Timing on the last instance: both programs warm.
                    if b == 1:
                        row["pallas_ms_p50"] = round(_time_fn(
                            lambda: pallas_solve.solve_waterfill_pallas(
                                *args, False, False,
                                interpret=interp)), 3)
                        row["jnp_ms_p50"] = round(_time_fn(
                            lambda: solve_waterfill(*args, False, False)), 3)
                    else:
                        row["pallas_ms_p50"] = round(_time_fn(
                            lambda: pallas_solve.solve_waterfill_pallas_batched(
                                *stacked, counts, pens, False, False,
                                interpret=interp)), 3)
                        row["jnp_ms_p50"] = round(_time_fn(
                            lambda: solve_waterfill_batched(
                                *stacked, counts, pens, False, False)), 3)
        except Exception as e:
            # Lowering/execution failure for this shape — the per-shape
            # outcome IS the data (which buckets Mosaic accepts).
            row["error"] = f"{type(e).__name__}: {str(e)[:300]}"
        report["shapes"].append(row)

    lowered = [r for r in report["shapes"] if "error" not in r]
    # ANY mismatch is fatal — including on a shape that later also raised
    # (a wrong-answer kernel must never be reported as proven just because
    # it subsequently crashed).
    report["ok"] = (
        bool(lowered)
        and all(r["mismatched"] == 0 for r in report["shapes"])
        and all(r["matched"] == seeds for r in lowered)
    )
    report["lowered_shapes"] = len(lowered)
    report["proven"] = [
        [r["n_nodes"], r["batch"]] for r in lowered if r["matched"] == seeds
    ]
    return report


def main() -> int:
    # Bound device acquisition the same way the bench does: the manager's
    # subprocess probe, never a bare in-process jax.devices() that can
    # wedge on a dead relay.
    from nomad_tpu.scheduler import device_probe_status, wait_for_device

    solver = wait_for_device(timeout=float(
        os.environ.get("NOMAD_TPU_BENCH_DEVICE_WAIT", "300")))
    status = device_probe_status()
    if solver is None:
        print(json.dumps({
            "kind": "pallas_proof", "ok": False,
            "error": f"device unavailable: {status}",
        }), flush=True)
        return 1
    report = run_proof()
    report["probe_backend"] = str(status.get("backend", ""))
    print(json.dumps(report), flush=True)
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
