#!/usr/bin/env python
"""Timeboxed deviceless Mosaic lowering attempt for the pallas water-fill.

VERDICT r5 item 2: the flagship kernel (ops/pallas_solve.py
solve_waterfill_pallas_batched) has only ever run in interpret mode —
the suite pins the cpu backend and the device relay has been dark since
2026-07-30. This tool attempts the one validation path that does not
need the relay: ahead-of-time lowering/compilation against a TPU target
with NO attached device, in a killable child process (the
scheduler/device_probe.py pattern — a wedged backend import can never
take the session down; default leash 120s, NOMAD_TPU_MOSAIC_TIMEOUT).

Stages the child reports (JSON lines on stdout):

  import      jax + jaxlib versions
  args        tiny batched solve inputs built (B=1, N=8)
  topology    jax.experimental.topologies.get_topology_desc('tpu', ...)
              across several topology spellings — requires libtpu; each
              failure is recorded with its exception head
  export      jax.export.export(..., platforms=['tpu']) — cross-platform
              StableHLO lowering; for a pallas_call this is where Mosaic
              runs (the kernel serializes into a tpu_custom_call) and it
              needs no device
  artifact    the lowered module's text: size, sha256, whether
              tpu_custom_call/mosaic markers are present; head saved
  compile     lowered.compile() against the topology (needs the TPU
              compiler => expected to fail deviceless; the failure stage
              IS the finding)

Output: MOSAIC_LOWER_<ts>.json (or --out) with every stage, plus the
lowered-module head alongside when export succeeded. Exit 0 if the
export stage succeeded (the kernel LOWERED for TPU), 1 otherwise.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

TIMEOUT = float(os.environ.get("NOMAD_TPU_MOSAIC_TIMEOUT", "120"))

_CHILD_SRC = r'''
import hashlib, json, os, sys, time

sys.path.insert(0, os.environ["NOMAD_TPU_REPO"])
t0 = time.monotonic()

def emit(**kw):
    kw.setdefault("elapsed_s", round(time.monotonic() - t0, 2))
    print(json.dumps(kw), flush=True)

def err_head(e, n=400):
    return f"{type(e).__name__}: {str(e)[:n]}"

import jax, jaxlib
emit(stage="import", jax=jax.__version__, jaxlib=jaxlib.__version__,
     default_backend_env=os.environ.get("JAX_PLATFORMS"))

import jax.numpy as jnp
from functools import partial
from nomad_tpu.ops.pallas_solve import solve_waterfill_pallas_batched

B, N, D = 1, 8, 4
args = (
    jnp.ones((B, N, D), jnp.int32) * 1000,        # total
    jnp.ones((B, N, 2), jnp.float32) * 1000.0,    # sched_cap
    jnp.zeros((B, N, D), jnp.int32),              # used0
    jnp.zeros((B, N), jnp.int32),                 # job_count0
    jnp.zeros((B, N), jnp.int32),                 # tg_count0
    jnp.ones((B, N), jnp.int32) * 100,            # bw_avail
    jnp.zeros((B, N), jnp.int32),                 # bw_used0
    jnp.ones((B, N), bool),                       # eligible
    jnp.ones((B, D), jnp.int32),                  # ask
    jnp.zeros((B,), jnp.int32),                   # bw_ask
    jnp.ones((B,), jnp.int32) * 4,                # count
    jnp.zeros((B,), jnp.float32),                 # penalty
)
emit(stage="args", shapes=[list(a.shape) for a in args])

# --- deviceless cross-platform lowering FIRST: Mosaic runs HERE, and it
# --- must not be robbed by a wedging topology probe (observed: the
# --- image's tpu platform plugin blocks inside get_topology_desc when
# --- the device relay is dark — the same single-shot backend-init hang
# --- scheduler/device_probe.py isolates).
fn = partial(solve_waterfill_pallas_batched,
             job_distinct=False, tg_distinct=False)
exported = None
try:
    from jax import export as jax_export

    exported = jax_export.export(jax.jit(fn), platforms=("tpu",))(*args)
    emit(stage="export", ok=True)
except Exception as e:
    emit(stage="export", ok=False, error=err_head(e, 1200))

if exported is not None:
    try:
        text = exported.mlir_module()
        digest = hashlib.sha256(text.encode()).hexdigest()
        emit(stage="artifact", ok=True, bytes=len(text), sha256=digest,
             has_tpu_custom_call="tpu_custom_call" in text,
             has_mosaic="mosaic" in text.lower(),
             head=text[:1500])
        out = os.environ.get("NOMAD_TPU_MOSAIC_MLIR_OUT")
        if out:
            with open(out, "w") as f:
                f.write(text)
    except Exception as e:
        emit(stage="artifact", ok=False, error=err_head(e))

# --- topology: needs libtpu; every spelling's failure is the record.
# --- Runs LAST because a dark relay wedges the plugin's topology init
# --- (the parent's leash then kills the child with the export already
# --- banked, and "stopped at topology" is the pinned failure stage).
topo = None
topo_tried = []
if os.environ.get("NOMAD_TPU_MOSAIC_SKIP_TOPOLOGY") != "1":
    try:
        from jax.experimental import topologies
        for name, kwargs in (
            ("v5e:1x1", {}),
            ("v5litepod-1", {}),
            ("v4:2x2x1", {}),
        ):
            emit(stage="topology_attempt", name=name)
            try:
                topo = topologies.get_topology_desc(name, "tpu", **kwargs)
                topo_tried.append({"name": name, "ok": True})
                break
            except Exception as e:
                topo_tried.append({"name": name, "ok": False,
                                   "error": err_head(e)})
    except Exception as e:
        topo_tried.append({"name": "<module>", "ok": False,
                           "error": err_head(e)})
    emit(stage="topology", ok=topo is not None, tried=topo_tried)

# --- AOT compile: needs the TPU compiler (libtpu) --------------------
if exported is not None:
    try:
        if topo is not None:
            lowered = jax.jit(fn).lower(*args)
            compiled = lowered.compile()
            emit(stage="compile", ok=True, via="topology")
        else:
            emit(stage="compile", ok=False, skipped=True,
                 reason="no topology description (libtpu absent or "
                        "topology init wedged); AOT compile has no TPU "
                        "compiler to target")
    except Exception as e:
        emit(stage="compile", ok=False, error=err_head(e, 1200))

emit(stage="done")
'''


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out", default=None)
    ap.add_argument("--timeout", type=float, default=TIMEOUT)
    args = ap.parse_args()
    out_path = args.out or os.path.join(
        REPO, "MOSAIC_LOWER_r06.json"
    )
    mlir_out = os.path.splitext(out_path)[0] + ".stablehlo.mlir"

    env = {**os.environ,
           "NOMAD_TPU_REPO": REPO,
           "NOMAD_TPU_MOSAIC_MLIR_OUT": mlir_out,
           # The lowering target is named explicitly (platforms=('tpu',));
           # the process backend stays cpu so nothing touches a (dead)
           # relay during jax init.
           "JAX_PLATFORMS": "cpu"}
    stages, stderr_tail = [], []
    start = time.monotonic()
    proc = subprocess.Popen(
        [sys.executable, "-c", _CHILD_SRC],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True, env=env,
    )

    def pump_out():
        for line in proc.stdout:
            line = line.strip()
            if not line:
                continue
            try:
                stages.append(json.loads(line))
            except ValueError:
                stderr_tail.append(line)

    def pump_err():
        for line in proc.stderr:
            stderr_tail.append(line.rstrip())

    t1 = threading.Thread(target=pump_out, daemon=True)
    t2 = threading.Thread(target=pump_err, daemon=True)
    t1.start()
    t2.start()
    killed = False
    try:
        rc = proc.wait(timeout=args.timeout)
    except subprocess.TimeoutExpired:
        killed = True
        proc.kill()
        rc = -1
    t1.join(timeout=2)
    t2.join(timeout=2)

    export_stage = next(
        (s for s in stages if s.get("stage") == "export"), None)
    ok = bool(export_stage and export_stage.get("ok"))
    report = {
        "tool": "mosaic_lower",
        "timestamp": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "timeout_s": args.timeout,
        "killed": killed,
        "rc": rc,
        "elapsed_s": round(time.monotonic() - start, 2),
        "lowered_for_tpu": ok,
        "stages": stages,
        "stderr_tail": stderr_tail[-8:],
        "mlir_path": mlir_out if ok and os.path.exists(mlir_out) else None,
    }
    with open(out_path, "w") as f:
        json.dump(report, f, indent=2)
        f.write("\n")
    print(json.dumps({
        "lowered_for_tpu": ok,
        "last_stage": stages[-1].get("stage") if stages else "spawn",
        "killed": killed,
        "artifact": out_path,
    }))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
