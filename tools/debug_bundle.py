#!/usr/bin/env python
"""Capture an operator debug bundle from a live agent.

The CLI face of the flight recorder (the ``nomad operator debug`` analog):
hits ``/v1/agent/debug/bundle`` on a running agent and writes the single
JSON artifact — metrics snapshot + cumulative series, recent traces,
last-K events, redacted config, armed fault plan, breaker state,
capacity-observatory and solver-efficiency snapshots (the utilization
picture: fragmentation, stranded-capacity %, padding waste), and thread
stacks — that you attach when a bench or chaos run goes sideways.

Usage::

    python tools/debug_bundle.py [-a http://127.0.0.1:4646] [-o out.json]
    python tools/debug_bundle.py --local   # no agent: process-local bundle

The agent must run with ``enable_debug`` (the bundle rides the debug-gated
introspection surface). ``--local`` skips the HTTP hop and collects the
process-local subset — what tools/tier1.py does on a red run.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="capture an operator debug bundle")
    parser.add_argument(
        "-a", "--address", default="http://127.0.0.1:4646",
        help="agent HTTP address (default %(default)s)")
    parser.add_argument(
        "-o", "--output", default="-",
        help="output path ('-' = stdout, the default)")
    parser.add_argument(
        "--events", type=int, default=512,
        help="max events to include (default %(default)s)")
    parser.add_argument(
        "--local", action="store_true",
        help="collect a process-local bundle instead of hitting an agent")
    args = parser.parse_args(argv)

    if args.local:
        from nomad_tpu.bundle import collect

        bundle = collect(agent=None, last_events=args.events)
    else:
        from nomad_tpu.api.client import ApiClient, ApiError

        try:
            bundle = ApiClient(address=args.address).agent().debug_bundle(
                events=args.events
            )
        except ApiError as e:
            hint = (
                " (is the agent running with enable_debug?)"
                if e.code == 404 else ""
            )
            print(f"debug_bundle: {e}{hint}", file=sys.stderr)
            return 1

    text = json.dumps(bundle, indent=2, default=str)
    if args.output == "-":
        print(text)
    else:
        with open(args.output, "w") as f:
            f.write(text + "\n")
        print(f"debug_bundle: wrote {args.output} "
              f"({len(text)} bytes, {len(bundle.get('events') or [])} events)",
              file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
