#!/usr/bin/env python
"""Simcluster load-generation CLI: run a named scale scenario and bank
the artifact.

    python tools/simload.py --scenario steady-10k --seed 42
    python tools/simload.py --scenario steady-1k --verify-determinism
    python tools/simload.py --list

Writes ``SIMLOAD_<scenario>_s<seed>.json`` (override with --out) and
prints one JSON summary line (the bench.py one-line contract) so drivers
that keep only stdout still capture the headline numbers.

``--verify-determinism`` runs the scenario TWICE with the same seed and
asserts the canonical event digests (sorted multiset of per-key
event-type sequences, nomad_tpu/simcluster/scenario.py:canonical_events)
match; the artifact records both digests and the verdict. Scenarios whose
spec sets ``deterministic=False`` (node-failure churn: which nodes host
allocs is not pinned by the seed) refuse verification instead of
reporting a vacuous pass.
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--scenario", default="steady-1k")
    ap.add_argument("--seed", type=int, default=42)
    ap.add_argument("--nodes", type=int, default=None,
                    help="override the scenario's fleet size")
    ap.add_argument("--out", default=None,
                    help="artifact path (default SIMLOAD_<name>_s<seed>.json)")
    ap.add_argument("--verify-determinism", action="store_true",
                    help="run twice with the same seed and assert the "
                         "canonical event digests match")
    ap.add_argument("--overhead-arm", action="store_true",
                    help="additionally run the scenario with the "
                         "attribution layer OFF (tracer disabled, SLO "
                         "monitor off) and stamp the plan-p50 overhead "
                         "of the enabled layer into the artifact")
    ap.add_argument("--list", action="store_true", help="list scenarios")
    ap.add_argument("-v", "--verbose", action="store_true")
    args = ap.parse_args()

    logging.basicConfig(
        level=logging.INFO if args.verbose else logging.WARNING,
        format="%(asctime)s %(name)s %(levelname)s %(message)s",
    )

    from nomad_tpu.simcluster import SCENARIOS, run_scenario

    if args.list:
        for name, spec in sorted(SCENARIOS.items()):
            print(f"{name:12s} n_nodes={spec.n_nodes:<6d} {spec.description}")
        return 0

    spec = SCENARIOS.get(args.scenario)
    if spec is None:
        print(f"unknown scenario {args.scenario!r}; "
              f"have {sorted(SCENARIOS)}", file=sys.stderr)
        return 2

    out_path = args.out or os.path.join(
        REPO, f"SIMLOAD_{args.scenario}_s{args.seed}.json"
    )
    artifact = run_scenario(args.scenario, seed=args.seed,
                            n_nodes=args.nodes)

    if args.verify_determinism:
        if not spec.deterministic:
            print(f"scenario {args.scenario!r} does not carry the "
                  "per-entity determinism contract "
                  "(spec.deterministic=False); refusing a vacuous verify",
                  file=sys.stderr)
            return 2
        # Main arms only: the contrast (admission-off) arm's digest is
        # not part of the determinism contract and re-running it here
        # would double the verification cost for nothing.
        second = run_scenario(args.scenario, seed=args.seed,
                              n_nodes=args.nodes, contrast=False)
        match = (artifact["events"]["digest"] == second["events"]["digest"]
                 and artifact["events"]["by_type"]
                 == second["events"]["by_type"])
        artifact["determinism"] = {
            "verified": bool(match),
            "runs": 2,
            "digests": [artifact["events"]["digest"],
                        second["events"]["digest"]],
        }
        if not match:
            with open(out_path, "w") as f:
                json.dump(artifact, f, indent=2, sort_keys=True)
            print(json.dumps({"error": "determinism check FAILED",
                              "artifact": out_path}))
            return 1

    if args.overhead_arm:
        # The layer must be latency-free on the hot path: re-run with the
        # tracer + SLO monitor off and compare plan p50. Enabled p50 is
        # the best of the runs already taken (noise reduction — a single
        # p50 sample at ~20ms jitters more than the <5% bar); every raw
        # number is recorded so the reduction is auditable.
        baseline = run_scenario(args.scenario, seed=args.seed,
                                n_nodes=args.nodes, attribution_layer=False,
                                contrast=False)
        enabled_p50s = [artifact["plan_latency_ms"].get("p50_ms")]
        det = artifact.get("determinism")
        if args.verify_determinism and det and det.get("verified"):
            enabled_p50s.append(second["plan_latency_ms"].get("p50_ms"))
        enabled_p50s = [p for p in enabled_p50s if p is not None]
        disabled_p50 = baseline["plan_latency_ms"].get("p50_ms")
        overhead = None
        if enabled_p50s and disabled_p50:
            overhead = round(min(enabled_p50s) / disabled_p50 - 1.0, 4)
        artifact["latency_attribution"]["tracing_overhead"] = {
            "enabled_plan_p50_ms": enabled_p50s,
            "disabled_plan_p50_ms": disabled_p50,
            "disabled_digest_matches": (
                baseline["events"]["digest"] == artifact["events"]["digest"]
            ),
            "overhead_fraction": overhead,
        }

    with open(out_path, "w") as f:
        json.dump(artifact, f, indent=2, sort_keys=True)
        f.write("\n")

    admission = artifact.get("admission", {})
    print(json.dumps({
        "metric": f"simload.{args.scenario}",
        "seed": args.seed,
        "n_nodes": artifact["n_nodes"],
        "offered": admission.get("injector", {}).get("offered"),
        "rejected": admission.get("injector", {}).get("rejected"),
        "caps_respected": admission.get("caps_respected"),
        "placed": artifact["placements"]["placed"],
        "placements_per_sec": artifact["placements"]["placements_per_sec"],
        "plan_latency_ms_p50": artifact["plan_latency_ms"].get("p50_ms"),
        "plan_latency_ms_p95": artifact["plan_latency_ms"].get("p95_ms"),
        "device_dispatches": artifact["placements"]["device_dispatches"],
        "determinism_verified": artifact.get("determinism", {}).get(
            "verified"),
        "backend": artifact["backend"],
        "artifact": out_path,
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
