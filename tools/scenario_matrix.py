#!/usr/bin/env python
"""Scenario matrix sweep: run a declared grid of compiled scenarios,
bank one SIMLOAD artifact per cell, and diff the matrix against the
previous banked round.

    python tools/scenario_matrix.py --round 17
    python tools/scenario_matrix.py --round 17 --verify-determinism
    python tools/scenario_matrix.py --scenarios rack-failure,partition-flap \
        --seeds 42,43 --round 17

The grid is scenarios x seeds (default: the three chaos families x seed
42 — the declared chaos matrix). Each cell shells out to tools/simload.py
so a cell run is EXACTLY a banked run (same artifact schema, same
determinism verification, same one-line summary), banked as
``SIMLOAD_<scenario>_s<seed>_r<round>.json`` — the round-suffixed family
naming tools/bench_watch.py's gates already scan. After the sweep the
matrix diff compares every cell against the newest earlier round of the
same family (headline placement/latency numbers, canonical digest
equality, chaos verdicts, recovery metrics) and writes
``SIMLOAD_MATRIX_r<round>.json`` plus one JSON line per cell.

A cell whose scenario run FAILS (violated chaos invariant, determinism
mismatch, crash) is banked as a failed cell and the sweep continues —
the matrix is an observatory, one dead cell must not hide the others —
but the exit code reports the failure.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

DEFAULT_SCENARIOS = ["rack-failure", "partition-flap",
                     "follower-crash-rejoin"]

_ROUND_RE = re.compile(r"_r(\d+)\.json$")


def _previous_artifact(family: str, current_round: int):
    """Newest banked artifact of ``family`` (= ``<scenario>_s<seed>``)
    from a round before ``current_round``; an unsuffixed bank counts as
    the oldest round."""
    best = None  # (round, path)
    for path in glob.glob(os.path.join(REPO, f"SIMLOAD_{family}*.json")):
        base = os.path.basename(path)
        if not base.startswith(f"SIMLOAD_{family}"):
            continue
        tail = base[len(f"SIMLOAD_{family}"):]
        m = _ROUND_RE.match(tail) if tail != ".json" else None
        if tail == ".json":
            rnd = -1
        elif m:
            rnd = int(m.group(1))
        else:
            continue  # some other family sharing the prefix
        if rnd >= current_round:
            continue
        if best is None or rnd > best[0]:
            best = (rnd, path)
    return best


def _rel(new, old):
    if new is None or old is None or not old:
        return None
    return round(new / old - 1.0, 4)


def _cell_headline(artifact: dict) -> dict:
    chaos = artifact.get("chaos") or {}
    return {
        "placed": (artifact.get("placements") or {}).get("placed"),
        "placements_per_sec": (artifact.get("placements") or {}).get(
            "placements_per_sec"),
        "plan_p50_ms": (artifact.get("plan_latency_ms") or {}).get("p50_ms"),
        "plan_p95_ms": (artifact.get("plan_latency_ms") or {}).get("p95_ms"),
        "digest": (artifact.get("events") or {}).get("digest"),
        "determinism_verified": (artifact.get("determinism") or {}).get(
            "verified"),
        "chaos_ok": chaos.get("ok"),
        "chaos_checks": sum(1 for c in chaos.get("checks", ())
                            if c.get("ok")),
        "time_to_rejoin_ms": chaos.get("time_to_rejoin_ms"),
        "expiry_replacement_p95_ms": (chaos.get("expiry_replacement_ms")
                                      or {}).get("p95_ms"),
    }


def _diff_cell(new: dict, old: dict) -> dict:
    nh, oh = _cell_headline(new), _cell_headline(old)
    return {
        "placed_delta": ((nh["placed"] - oh["placed"])
                         if None not in (nh["placed"], oh["placed"])
                         else None),
        "placements_per_sec_rel": _rel(nh["placements_per_sec"],
                                       oh["placements_per_sec"]),
        "plan_p95_ms_rel": _rel(nh["plan_p95_ms"], oh["plan_p95_ms"]),
        "digest_match": (nh["digest"] == oh["digest"]
                         if nh["digest"] and oh["digest"] else None),
        "time_to_rejoin_ms_rel": _rel(nh["time_to_rejoin_ms"],
                                      oh["time_to_rejoin_ms"]),
        "expiry_replacement_p95_ms_rel": _rel(
            nh["expiry_replacement_p95_ms"],
            oh["expiry_replacement_p95_ms"]),
    }


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--scenarios", default=",".join(DEFAULT_SCENARIOS),
                    help="comma-separated scenario names (the grid rows)")
    ap.add_argument("--seeds", default="42",
                    help="comma-separated seeds (the grid columns)")
    ap.add_argument("--round", type=int, required=True, dest="round_",
                    help="round number to bank under (_rNN suffix)")
    ap.add_argument("--verify-determinism", action="store_true",
                    help="pass through to simload: run each cell twice "
                         "and assert canonical digests match")
    ap.add_argument("--timeout", type=float, default=900.0,
                    help="per-cell wall clock budget (seconds)")
    ap.add_argument("--out", default=None,
                    help="matrix path (default SIMLOAD_MATRIX_r<NN>.json)")
    args = ap.parse_args()

    scenarios = [s for s in args.scenarios.split(",") if s]
    seeds = [int(s) for s in args.seeds.split(",") if s]

    from nomad_tpu.simcluster import SCENARIOS
    unknown = sorted(set(scenarios) - set(SCENARIOS))
    if unknown:
        print(f"unknown scenario(s) {unknown}; have {sorted(SCENARIOS)}",
              file=sys.stderr)
        return 2

    cells = []
    failed = 0
    for name in scenarios:
        for seed in seeds:
            family = f"{name}_s{seed}"
            out_path = os.path.join(
                REPO, f"SIMLOAD_{family}_r{args.round_:02d}.json")
            cmd = [sys.executable, os.path.join(REPO, "tools/simload.py"),
                   "--scenario", name, "--seed", str(seed),
                   "--out", out_path]
            if args.verify_determinism:
                cmd.append("--verify-determinism")
            cell = {"scenario": name, "seed": seed, "family": family,
                    "artifact": out_path, "round": args.round_}
            try:
                proc = subprocess.run(
                    cmd, capture_output=True, text=True,
                    timeout=args.timeout, cwd=REPO)
                cell["exit_code"] = proc.returncode
                if proc.returncode != 0:
                    failed += 1
                    cell["error"] = (proc.stderr or proc.stdout
                                     or "").strip()[-2000:]
            except subprocess.TimeoutExpired:
                failed += 1
                cell["exit_code"] = None
                cell["error"] = f"cell timed out after {args.timeout}s"
            if os.path.exists(out_path):
                with open(out_path) as f:
                    artifact = json.load(f)
                cell["headline"] = _cell_headline(artifact)
                prev = _previous_artifact(family, args.round_)
                if prev is not None:
                    prev_round, prev_path = prev
                    with open(prev_path) as f:
                        old = json.load(f)
                    cell["previous"] = {
                        "round": prev_round,
                        "artifact": os.path.basename(prev_path),
                    }
                    cell["diff"] = _diff_cell(artifact, old)
            cells.append(cell)
            print(json.dumps({
                "metric": "scenario_matrix.cell",
                "family": family,
                "ok": cell.get("exit_code") == 0,
                "chaos_ok": (cell.get("headline") or {}).get("chaos_ok"),
                "digest_match_prev": (cell.get("diff") or {}).get(
                    "digest_match"),
            }))

    matrix = {
        "round": args.round_,
        "grid": {"scenarios": scenarios, "seeds": seeds},
        "cells": cells,
        "failed_cells": failed,
    }
    matrix_path = args.out or os.path.join(
        REPO, f"SIMLOAD_MATRIX_r{args.round_:02d}.json")
    with open(matrix_path, "w") as f:
        json.dump(matrix, f, indent=2, sort_keys=True)
        f.write("\n")
    print(json.dumps({
        "metric": "scenario_matrix",
        "round": args.round_,
        "cells": len(cells),
        "failed_cells": failed,
        "matrix": matrix_path,
    }))
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
