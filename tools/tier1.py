#!/usr/bin/env python
"""Tier-1 suite wrapper: the ROADMAP verify command with failure forensics.

Runs the exact tier-1 pytest invocation (ROADMAP.md "Tier-1 verify") but
always captures ``-rf`` (failed-test summary) and ``--junitxml`` so a
flaky full run leaves NAMED evidence instead of an anonymous red — the
round-5 verdict's "unnamed 1-in-3 full-suite flake" existed precisely
because full runs were thrown away. Artifacts per run:

    /tmp/tier1_<N>.log          full pytest output (tee'd to stdout)
    /tmp/tier1_<N>.xml          junit XML: machine-greppable failed names
    /tmp/tier1_<N>_bundle.json  debug bundle, written ONLY on a failed
                                run: fetched from a live agent when
                                NOMAD_TPU_DEBUG_AGENT is set, else the
                                process-local capture (nomad_tpu.bundle)
                                — red runs ship flight-recorder data

Usage: ``python tools/tier1.py [repeat]`` — repeat defaults to 1; pass 3
to hunt a 1-in-3 flake. Exit code: 0 only if every run passed. After the
runs, prints one summary line per run plus every distinct failed test id
seen across runs (collection errors excluded: the suite tolerates them
via --continue-on-collection-errors, e.g. test_jobspec.py's dependency on
the /root/reference checkout that CI containers lack).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import xml.etree.ElementTree as ET

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def capture_bundle(path: str) -> str:
    """Write a debug bundle next to the junitxml of a failed run.

    NOMAD_TPU_DEBUG_AGENT (an http://host:port) targets a live test
    agent's /v1/agent/debug/bundle; otherwise the bundle is the
    process-local capture — the suite ran as a SUBPROCESS, so that
    fallback records the wrapper process only (its threads, plus any
    registries the harness itself armed), NOT the dead suite's state.
    The bundle is stamped with its capture scope so empty sections read
    as "wrong process", never as "nothing happened". Best-effort:
    forensics must never fail the report."""
    try:
        addr = os.environ.get("NOMAD_TPU_DEBUG_AGENT", "")
        if addr:
            from nomad_tpu.api.client import ApiClient

            bundle = ApiClient(address=addr).agent().debug_bundle()
            bundle["source"] = {"kind": "live-agent", "address": addr}
        else:
            from nomad_tpu.bundle import collect

            bundle = collect(agent=None)
            bundle["source"] = {
                "kind": "process-local",
                "process": "tier1-wrapper",
                "note": "suite ran as a subprocess; set "
                        "NOMAD_TPU_DEBUG_AGENT to capture a live agent",
            }
        latest = _latest_simload_artifact()
        if latest:
            try:
                with open(latest) as f:
                    bundle["simload_artifact"] = {
                        "path": latest, "data": json.load(f),
                    }
                # SLO verdicts over the embedded artifact: the bundle's
                # own `slo`/`timelines` sections capture THIS process
                # (live state), while the artifact check records whether
                # the last banked control-plane run was inside the
                # objectives — both views ride a red run.
                att = bundle["simload_artifact"]["data"].get(
                    "latency_attribution")
                if att:
                    from nomad_tpu.slo import evaluate_artifact

                    bundle["simload_artifact"]["slo_check"] = (
                        evaluate_artifact(att))
            except (OSError, ValueError) as e:
                bundle["simload_artifact"] = {"path": latest,
                                              "error": str(e)}
        with open(path, "w") as f:
            json.dump(bundle, f, indent=2, default=str)
        return path
    except Exception as e:  # noqa: BLE001 - forensics are best-effort
        print(f"tier1: debug bundle capture failed: {e}", file=sys.stderr)
        return ""


def _latest_simload_artifact() -> str:
    """Newest SIMLOAD_*.json (repo root, then /tmp): a failed run's bundle
    carries the most recent control-plane scale capture, so a regression
    hunt can compare the red run's environment against the last-known
    pipeline throughput without re-running the scenario."""
    import glob

    def mtime(p):
        # /tmp is shared: an artifact deleted between glob and stat must
        # not abort the WHOLE bundle capture for an optional attachment.
        try:
            return os.path.getmtime(p)
        except OSError:
            return 0.0

    candidates = sorted(
        glob.glob(os.path.join(REPO, "SIMLOAD_*.json"))
        + glob.glob("/tmp/SIMLOAD_*.json"),
        key=mtime, reverse=True,
    )
    return candidates[0] if candidates else ""

PYTEST_ARGS = [
    "-m", "pytest", "tests/", "-q", "-m", "not slow",
    "--continue-on-collection-errors",
    "-p", "no:cacheprovider", "-p", "no:xdist", "-p", "no:randomly",
    "-rf",
]
TIMEOUT_S = 870  # the ROADMAP tier-1 budget


def run_nomadlint() -> int:
    """The static-analysis gate, run BEFORE pytest: any nomadlint finding
    outside the committed baseline fails tier-1 without spending the test
    budget. The run also refreshes /tmp/nomadlint_report.json, which a
    failed run's debug bundle embeds (nomad_tpu.bundle `nomadlint`
    section) — red-run forensics carry the gate's view of the tree."""
    print("=== nomadlint gate ===")
    try:
        proc = subprocess.run(
            [sys.executable, "-m", "tools.nomadlint", "--baseline"],
            cwd=REPO, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, timeout=120,
        )
        out, rc = proc.stdout, proc.returncode
    except subprocess.TimeoutExpired as e:
        out = ((e.stdout or "") if isinstance(e.stdout, str)
               else (e.stdout or b"").decode("utf-8", "replace"))
        out += "\nnomadlint gate TIMED OUT after 120s\n"
        rc = 1
    sys.stdout.write(out)
    with open("/tmp/tier1_nomadlint.log", "w") as f:
        f.write(out)
    return rc


def run_once(n: int) -> dict:
    import threading

    log_path = f"/tmp/tier1_{n}.log"
    xml_path = f"/tmp/tier1_{n}.xml"
    # A wedged run that gets killed never writes its junitxml; a stale
    # file from a previous invocation would silently masquerade as this
    # run's forensics.
    try:
        os.remove(xml_path)
    except FileNotFoundError:
        pass
    with open(log_path, "w") as logf:
        proc = subprocess.Popen(
            [sys.executable, *PYTEST_ARGS, f"--junitxml={xml_path}"],
            cwd=REPO, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )

        # Pump output on a thread so the TIMEOUT_S budget is enforced by
        # proc.wait below even when a wedged run never closes stdout — a
        # hung suite is exactly the scenario this wrapper must outlive.
        def pump():
            for line in proc.stdout:
                sys.stdout.write(line)
                logf.write(line)

        reader = threading.Thread(target=pump, daemon=True)
        reader.start()
        try:
            rc = proc.wait(timeout=TIMEOUT_S)
        except subprocess.TimeoutExpired:
            proc.kill()
            rc = -1
        reader.join(timeout=5)
    failed, collect_errors = [], []
    xml_ok = False
    try:
        for case in ET.parse(xml_path).getroot().iter("testcase"):
            if case.find("failure") is None and case.find("error") is None:
                continue
            if not case.get("classname"):
                # Collection error (junit records it as a classname-less
                # testcase): tolerated per --continue-on-collection-errors.
                collect_errors.append(case.get("name", ""))
            else:
                failed.append(
                    f"{case.get('classname', '')}::{case.get('name', '')}"
                )
    except (OSError, ET.ParseError):
        pass
    else:
        xml_ok = True
    return {"run": n, "rc": rc, "failed": failed,
            "collect_errors": collect_errors, "xml_ok": xml_ok,
            "log": log_path, "xml": xml_path}


def main() -> int:
    repeat = int(sys.argv[1]) if len(sys.argv) > 1 else 1
    if run_nomadlint() != 0:
        capture_bundle("/tmp/tier1_nomadlint_bundle.json")
        print("tier1: nomadlint gate FAILED — fix the findings, suppress "
              "with `# nomadlint: allow(RULE) -- reason`, or grandfather "
              "with `python -m tools.nomadlint --write-baseline` "
              "(log: /tmp/tier1_nomadlint.log)")
        return 1
    results = [run_once(n) for n in range(1, repeat + 1)]
    print("\n=== tier1 summary ===")
    all_failed: dict = {}
    ok = True
    for r in results:
        # rc==1 with zero failed testcases is the tolerated
        # collection-error posture (--continue-on-collection-errors) —
        # but ONLY when the junitxml parsed: rc==1 without forensics
        # (corrupt/missing xml) must read as a failure, not a pass.
        passed = (
            not r["failed"]
            and (r["rc"] == 0 or (r["rc"] == 1 and r["xml_ok"]))
        )
        status = "PASS" if passed else "FAIL"
        bundle = ""
        if not passed:
            ok = False
            bundle = capture_bundle(f"/tmp/tier1_{r['run']}_bundle.json")
        artifacts = ", ".join(p for p in (r["log"], r["xml"], bundle) if p)
        print(f"run {r['run']}: {status} rc={r['rc']} "
              f"failed={len(r['failed'])} "
              f"collect_errors={len(r['collect_errors'])} "
              f"({artifacts})")
        for name in r["failed"]:
            all_failed.setdefault(name, []).append(r["run"])
    if all_failed:
        print("distinct failures across runs:")
        for name, runs in sorted(all_failed.items()):
            print(f"  {name}  (runs {runs})")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
