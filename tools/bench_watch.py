"""Opportunistic TPU capture watcher.

The axon device relay is flaky: it has come up for minutes-long windows and
died mid-session on every prior round, so end-of-round benches keep missing
it. This watcher inverts the timing problem: it scans the relay's loopback
ports continuously, and the moment a subprocess probe child reports the
device claim completing, it immediately runs the full ``bench.py`` suite at
the CURRENT commit (plus the compiled-pallas proof, when present) and
appends the capture to ``BENCH_SELF_r{N}.json``. Every scan is also logged
to ``BENCH_WATCH_r{N}.jsonl`` so a relay that never comes up all round is
provable from the log, not asserted.

Runs as a detached background process for the whole session:

    python tools/bench_watch.py >> bench_watch.log 2>&1 &

Re-captures when HEAD moves (so the newest solver gets proven) or after a
cooldown, whichever comes first; the first capture in a window is the
urgent one — the relay has historically died within minutes of answering.
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

def _round_tag() -> str:
    """Current round, inferred from the bench artifacts on disk (one
    past the max completed driver round, never behind an existing
    self-capture tag), overridable via NOMAD_TPU_ROUND (accepts "5" or
    "r05"). Keeps the watcher edit-free across rounds."""
    env = os.environ.get("NOMAD_TPU_ROUND", "").lstrip("rR")
    if env:
        return f"r{int(env):02d}"
    import re

    # Driver files name COMPLETED rounds; self-capture/watch files name
    # the round that produced them (a round whose driver bench never
    # landed still leaves these). The round in progress is one past the
    # max driver round, but never behind an existing self-capture tag.
    driver = [
        int(m.group(1))
        for f in os.listdir(REPO)
        for m in [re.match(r"BENCH_r(\d+)\.json$", f)]
        if m
    ]
    selfcap = [
        int(m.group(1))
        for f in os.listdir(REPO)
        for m in [re.match(r"BENCH_(?:SELF|WATCH)_r(\d+)\.", f)]
        if m
    ]
    cur = max(
        (max(driver) + 1 if driver else 1),
        (max(selfcap) if selfcap else 1),
    )
    return f"r{cur:02d}"


_TAG = _round_tag()
WATCH_LOG = os.path.join(REPO, f"BENCH_WATCH_{_TAG}.jsonl")
CAPTURE_FILE = os.path.join(REPO, f"BENCH_SELF_{_TAG}.json")
SCAN_INTERVAL_S = 45.0
# Wider than device_probe's default candidate list: relay listeners have
# been observed anywhere in 8080..8117.
SCAN_PORTS = list(range(8080, 8121))
BENCH_TIMEOUT_S = 2700.0  # > bench.py's own 2400s watchdog
PROOF_TIMEOUT_S = 1500.0
RECAPTURE_COOLDOWN_S = 30 * 60.0
# Stage-1 fast capture: headline config only, 3 runs, no breakdown. Relay
# windows have historically lasted minutes; this banks a TPU number in
# <60s of bench time before the full suite gambles the rest of the window.
# The device wait stays generous (300s): the 07-31 window was missed by a
# short claim leash, and the fast stage's savings must come from doing
# less bench work, not from giving up on a queued claim.
FAST_TIMEOUT_S = 660.0
FAST_ENV = {
    "NOMAD_TPU_BENCH_HEADLINE_ONLY": "1",
    "NOMAD_TPU_BENCH_RUNS": "3",
    "NOMAD_TPU_BENCH_BREAKDOWN": "0",
    "NOMAD_TPU_BENCH_DEVICE_WAIT": "300",
    "NOMAD_TPU_BENCH_WATCHDOG": "600",
}


def now() -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())


def log(event: str, **kw) -> None:
    rec = {"ts": now(), "event": event, **kw}
    with open(WATCH_LOG, "a") as f:
        f.write(json.dumps(rec) + "\n")


def scan_ports(host: str = "127.0.0.1") -> list:
    open_ports = []
    for p in SCAN_PORTS:
        s = socket.socket()
        s.settimeout(0.5)
        try:
            s.connect((host, p))
            open_ports.append(p)
        except OSError:
            pass
        finally:
            s.close()
    return open_ports


def head_commit() -> str:
    try:
        return subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"], cwd=REPO,
            capture_output=True, text=True, timeout=10,
        ).stdout.strip()
    except Exception:
        return "unknown"


def append_capture(entry: dict) -> None:
    doc = {
        "note": (
            f"SELF-REPORTED opportunistic TPU captures from the {_TAG} "
            "builder session (tools/bench_watch.py): the relay is scanned "
            "continuously and bench.py runs the moment a probe child "
            f"reports ready. BENCH_WATCH_{_TAG}.jsonl holds the full scan "
            f"log; the driver-captured BENCH_{_TAG}.json is the source of "
            "truth."
        ),
        "runs": [],
    }
    if os.path.exists(CAPTURE_FILE):
        try:
            with open(CAPTURE_FILE) as f:
                doc = json.load(f)
        except Exception:
            pass
    doc.setdefault("runs", []).append(entry)
    tmp = CAPTURE_FILE + f".tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=2)
    os.replace(tmp, CAPTURE_FILE)


def last_json_line(text: str):
    for line in reversed(text.strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line)
            except ValueError:
                continue
    return None


def run_capture(kind: str, argv: list, timeout: float,
                extra_env: dict | None = None) -> dict:
    commit = head_commit()
    start = time.monotonic()
    try:
        proc = subprocess.run(
            argv, cwd=REPO, capture_output=True, text=True, timeout=timeout,
            env={
                **os.environ,
                "NOMAD_TPU_BENCH_DEVICE_WAIT": "300",
                # keep the probe child's reachability diagnostic scanning
                # the same ports the watcher scans
                "NOMAD_TPU_RELAY_PORTS": ",".join(map(str, SCAN_PORTS)),
                **(extra_env or {}),
            },
        )
        rc, out, err = proc.returncode, proc.stdout, proc.stderr
    except subprocess.TimeoutExpired as e:
        # POSIX CPython raises TimeoutExpired with the raw captured BYTES
        # even under text=True (Popen._communicate joins before decoding)
        rc, out, err = -1, (e.stdout or ""), (e.stderr or "")
        if isinstance(out, bytes):
            out = out.decode(errors="replace")
        if isinstance(err, bytes):
            err = err.decode(errors="replace")
    result = last_json_line(out)
    # bench.py emits a JSON line even on failure — success means the run
    # exited clean AND the payload is not an error payload
    ok = rc == 0 and isinstance(result, dict) and "error" not in result
    entry = {
        "captured_at": now(),
        "kind": kind,
        "command": " ".join(argv),
        "commit": commit,
        "rc": rc,
        "ok": ok,
        "wall_s": round(time.monotonic() - start, 1),
        "result": result,
        "stderr_tail": "\n".join(err.strip().splitlines()[-12:]),
    }
    append_capture(entry)
    log("capture", kind=kind, rc=rc, commit=commit, ok=ok)
    if ok:
        # A successful capture is a milestone worth landing immediately —
        # the session may die before any manual commit, and the round tag
        # in the message ties the artifact to the round that produced it.
        try:
            # Add (the first capture creates the file untracked, which a
            # bare commit pathspec would reject) then commit with the
            # SAME pathspec: only the capture artifacts land, never
            # whatever the interactive session happens to have staged.
            paths = [os.path.basename(CAPTURE_FILE),
                     os.path.basename(WATCH_LOG)]
            add = subprocess.run(
                ["git", "add", "--"] + paths,
                cwd=REPO, capture_output=True, text=True, timeout=30,
            )
            cm = subprocess.run(
                ["git", "commit", "-m",
                 f"Device capture ({_TAG} {kind}): {commit}", "--"] + paths,
                cwd=REPO, capture_output=True, text=True, timeout=30,
            )
            if add.returncode != 0 or cm.returncode != 0:
                # A persistently failing auto-commit (index.lock
                # contention, rebase in progress, unset identity) must be
                # visible in the watch log, not silently defeated.
                log("autocommit-failed",
                    add_rc=add.returncode, commit_rc=cm.returncode,
                    stderr="\n".join(
                        (add.stderr or "").strip().splitlines()[-3:]
                        + (cm.stderr or "").strip().splitlines()[-3:]
                    ))
            else:
                log("autocommit", kind=kind, commit=commit)
        except Exception as e:
            # A capture must never be lost to a git hiccup — but the
            # hiccup itself must be loggable evidence.
            log("autocommit-error", error=f"{type(e).__name__}: {e}")
    return entry


# ---------------------------------------------------------------------------
# SLO regression gate: banked SIMLOAD artifacts vs their previous round
# ---------------------------------------------------------------------------

# Latency-percentile regression tolerance: a new artifact that is inside
# its SLO threshold never fails the gate; one outside it fails only when
# it is ALSO >25% worse than the banked baseline (p50-scale numbers at
# ~20ms jitter a few percent run-to-run; 25% is a real regression).
SLO_GATE_TOLERANCE = 0.25


def _attribution_of(artifact: dict) -> dict:
    """A SIMLOAD artifact's latency percentiles in evaluate_artifact
    shape. Pre-r08 artifacts carry no ``latency_attribution`` — but their
    ``plan_latency_ms`` IS submit→placed (EvalUpdated(pending) → first
    PlanApplied, the same event anchors), so a banked r07 baseline still
    gates the placed-side objectives."""
    att = artifact.get("latency_attribution")
    if att:
        return att
    return {"submit_to_placed_ms": artifact.get("plan_latency_ms") or {}}


def _objectives_for(artifact: dict) -> dict | None:
    """Objective set for one artifact family: scenario-scoped overrides
    first (slo.SCENARIO_OBJECTIVES — e.g. churn-fragmentation's probe
    wave races a deregistration stop storm by design and is judged
    against its own declared bound, not the 250ms steady-state SLO),
    plus the express lane's own target (express_placed_p50_ms < 1ms)
    when the artifact carries express observations — the express-mix
    family gates ABSOLUTELY on its headline number instead of skipping
    it. None = the default set (evaluate_artifact's convention)."""
    from nomad_tpu.slo import (
        DEFAULT_OBJECTIVES,
        EXPRESS_OBJECTIVES,
        SCENARIO_OBJECTIVES,
    )

    objectives = SCENARIO_OBJECTIVES.get(artifact.get("scenario") or "")
    if _attribution_of(artifact).get("express_placed_ms"):
        return {**(objectives or DEFAULT_OBJECTIVES), **EXPRESS_OBJECTIVES}
    return objectives


def slo_gate(new_artifact: dict, baseline_artifact: dict,
             objectives: dict | None = None,
             tolerance: float = SLO_GATE_TOLERANCE) -> dict:
    """Gate a fresh SIMLOAD artifact against a banked baseline: for each
    SLO objective (nomad_tpu.slo; default set when ``objectives`` is
    None), FAIL when the new run misses an objective the baseline met, or
    when its observed percentile is outside the threshold AND more than
    ``tolerance`` worse than the baseline. Objectives neither run can
    observe (no samples) are reported, not failed."""
    from nomad_tpu.slo import evaluate_artifact

    new_checks = evaluate_artifact(_attribution_of(new_artifact), objectives)
    base_checks = {
        c["objective"]: c
        for c in evaluate_artifact(_attribution_of(baseline_artifact),
                                   objectives)
    }
    checks, ok = [], True
    for c in new_checks:
        base = base_checks.get(c["objective"], {})
        verdict = dict(c)
        verdict["baseline_ms"] = base.get("observed_ms")
        regressed = False
        if c["met"] is False:
            if base.get("met"):
                regressed = True          # objective newly broken
            elif (base.get("observed_ms")
                    and c["observed_ms"]
                    > base["observed_ms"] * (1.0 + tolerance)):
                regressed = True          # already-out objective worsened
        verdict["regressed"] = regressed
        ok = ok and not regressed
        checks.append(verdict)
    return {"ok": ok, "tolerance": tolerance, "checks": checks}


def _banked_simload_pairs() -> list:
    """(scenario, newest artifact path, previous-round path or None) for
    every banked ``SIMLOAD_<scenario>_s<seed>[_rNN].json`` family.
    Un-suffixed artifacts count as round 0. Single-round families (a
    freshly introduced scenario — e.g. overdrive-100k's first bank) pair
    with None: the gate then checks the artifact ABSOLUTELY against its
    declared objectives instead of skipping it silently."""
    import re

    fams: dict = {}
    for f in sorted(os.listdir(REPO)):
        m = re.match(r"SIMLOAD_(.+_s\d+?)(?:_r(\d+))?\.json$", f)
        if m:
            fams.setdefault(m.group(1), []).append(
                (int(m.group(2) or 0), os.path.join(REPO, f))
            )
    out = []
    for fam, rounds in sorted(fams.items()):
        rounds.sort()
        out.append((fam, rounds[-1][1],
                    rounds[-2][1] if len(rounds) >= 2 else None))
    return out


def slo_gate_absolute(new_artifact: dict,
                      objectives: dict | None = None) -> dict:
    """First-round gate (no banked baseline yet): every OBSERVED
    objective must be met outright. Unobserved objectives (no samples —
    e.g. no running acks in an ack_cap=0 scenario) are reported, not
    failed."""
    from nomad_tpu.slo import evaluate_artifact

    checks = []
    ok = True
    for c in evaluate_artifact(_attribution_of(new_artifact), objectives):
        verdict = dict(c)
        verdict["baseline_ms"] = None
        verdict["regressed"] = c["met"] is False
        ok = ok and not verdict["regressed"]
        checks.append(verdict)
    return {"ok": ok, "tolerance": None, "checks": checks}


# Solver-economy gate tolerance: the panel's device-time-per-placement
# is box-noise-sensitive (rider-attributed walls under coalescing), so
# the regression bar is deliberately loose — it exists to catch a real
# batching/padding regression (2x-class), not scheduler jitter.
SOLVER_GATE_TOLERANCE = 0.5


def solver_gate(new_artifact: dict, baseline_artifact: dict,
                tolerance: float = SOLVER_GATE_TOLERANCE) -> dict | None:
    """Gate the solver panel's measured-window economy newest-vs-
    previous: FAIL when device-time-per-placement worsened more than
    ``tolerance`` relative. Also reports the batch-width histogram and
    the amortized per-eval device wall (the cross-eval batching win) so
    a gate log shows WHERE a regression came from. None when either
    artifact predates the solver_panel window section."""
    new_w = (new_artifact.get("solver_panel") or {}).get("window") or {}
    base_w = (baseline_artifact.get("solver_panel") or {}).get(
        "window") or {}
    new_v = new_w.get("device_ms_per_placement")
    base_v = base_w.get("device_ms_per_placement")
    # `is None`, not truthiness: a legitimate 0.0 baseline (sub-precision
    # walls) must keep the gate armed, not read as a pre-panel artifact.
    if new_v is None or base_v is None:
        return None
    if not base_v:
        base_v = 1e-9  # zero baseline: any measurable cost is a regression
    regressed = new_v > base_v * (1.0 + tolerance)
    return {
        "ok": not regressed,
        "tolerance": tolerance,
        "device_ms_per_placement": new_v,
        "baseline_ms_per_placement": base_v,
        "batch_widths": new_w.get("batch_widths"),
        "equiv": new_w.get("equiv"),
    }


# Recovery-gate tolerance: restart downtime and replay rates are box-
# noise-sensitive (re-election jitter alone spans 150-300ms), so the
# newest-vs-previous bar is deliberately loose — it exists to catch a
# real recovery regression (2x-class), not scheduler jitter.
RECOVERY_GATE_TOLERANCE = 0.5


def recovery_gate(new_artifact: dict, baseline_artifact: dict | None,
                  tolerance: float = RECOVERY_GATE_TOLERANCE) -> dict | None:
    """Gate a restart-family artifact's recovery story. ABSOLUTE (every
    round, baseline or not): the mid-load leader kill must have lost
    nothing — ``placements_survived`` is the digest-survival contract,
    not a statistic. RELATIVE (newest-vs-previous when a prior bank
    carries a restart section): replay rate (entries/s) must not drop
    more than ``tolerance``, and time-to-serving must not grow more than
    ``tolerance``. None when the artifact has no restart section (not a
    restart family)."""
    raft = new_artifact.get("raft") or {}
    restart = raft.get("restart")
    if not restart:
        return None
    recovery = raft.get("recovery") or {}
    survived = restart.get("placements_survived") is True
    checks = [{
        "check": "placements_survived",
        "value": restart.get("placements_survived"),
        "baseline": None,
        "regressed": not survived,
    }]
    ok = survived
    base_raft = (baseline_artifact or {}).get("raft") or {}
    base_recovery = base_raft.get("recovery") or {}
    if base_raft.get("restart"):
        new_rate = recovery.get("replay_entries_per_s")
        base_rate = base_recovery.get("replay_entries_per_s")
        if new_rate is not None and base_rate:
            regressed = new_rate < base_rate * (1.0 - tolerance)
            checks.append({"check": "replay_entries_per_s",
                           "value": new_rate, "baseline": base_rate,
                           "regressed": regressed})
            ok = ok and not regressed
        new_tts = recovery.get("time_to_serving_ms")
        base_tts = base_recovery.get("time_to_serving_ms")
        if new_tts is not None and base_tts:
            regressed = new_tts > base_tts * (1.0 + tolerance)
            checks.append({"check": "time_to_serving_ms",
                           "value": new_tts, "baseline": base_tts,
                           "regressed": regressed})
            ok = ok and not regressed
    return {"ok": ok, "tolerance": tolerance, "checks": checks}


# Read-gate tolerance: serving latency under an impolite read fleet is
# box-noise-sensitive (GIL contention with the placement path is the
# scenario's POINT), so the newest-vs-previous bar is deliberately loose
# — it exists to catch a real serving regression (2x-class), not
# scheduler jitter.
READ_GATE_TOLERANCE = 0.5


def read_gate(new_artifact: dict, baseline_artifact: dict | None,
              tolerance: float = READ_GATE_TOLERANCE) -> dict | None:
    """Gate a read-carrying family's serving story (the read-path
    observatory's artifact section, nomad_tpu/read_observe.py). Scoped:
    None when the artifact's reads section is absent or disabled — only
    families that actually drove a read fleet gate here. RELATIVE
    newest-vs-previous when the prior bank also carries an enabled reads
    section: the worst per-route read latency p95 must not grow more
    than ``tolerance``, and the staleness distribution's p99 (raft
    entries behind the leader commit) must not grow more than
    ``tolerance`` plus a 2-entry absolute slack (the distribution sits
    at 0-1 entries on a healthy single-member cell, where a pure
    relative bar would fail on noise). First-round families report the
    observed values without failing — there is no declared absolute
    bound for read latency; the family's write-path SLOs gate
    separately."""
    reads = new_artifact.get("reads") or {}
    if not reads.get("enabled"):
        return None

    def worst_p95(r: dict):
        vals = [(ep.get("latency_ms") or {}).get("p95")
                for ep in (r.get("endpoints") or {}).values()]
        vals = [v for v in vals if v is not None]
        return max(vals) if vals else None

    def staleness_p99(r: dict):
        return ((r.get("freshness") or {}).get("staleness_entries")
                or {}).get("p99")

    base_reads = (baseline_artifact or {}).get("reads") or {}
    if not base_reads.get("enabled"):
        base_reads = {}
    checks, ok = [], True
    for name, fn, slack in (
        ("read_latency_p95_ms", worst_p95, 0.0),
        ("staleness_age_p99_entries", staleness_p99, 2.0),
    ):
        value = fn(reads)
        if value is None:
            continue
        baseline = fn(base_reads) if base_reads else None
        regressed = (baseline is not None
                     and value > baseline * (1.0 + tolerance) + slack)
        checks.append({"check": name, "value": value,
                       "baseline": baseline, "regressed": regressed})
        ok = ok and not regressed
    return {"ok": ok, "tolerance": tolerance, "checks": checks}


# Read-lane gate: the consistency-lane contract checks on an artifact's
# ``reads.lanes`` section (nomad_tpu/server/read_path.py; objective
# vocabulary in slo.READ_LANE_OBJECTIVES). Mostly ABSOLUTE per-run
# invariants — stale age p95 inside the client bound, follower serve
# share >= the floor, zero linearizable violations / missing stamps —
# plus one main-vs-contrast row: with followers serving, the leader's
# plan p50 must stay within tolerance of the leader-only contrast arm
# (the read plane must relieve the leader, never tax the write path).
# The tolerance is CLIFF-scaled, not noise-scaled: the contrast arm
# doubles as the digest-invariance proof, so it runs observatory-OFF,
# and the observatory itself prices ~19% of plan p50 on this box (r16
# leader-only: 137.5 vs 116.0; r19 follower-serving: 968.8 vs 814.1 —
# the SAME ratio, i.e. the follower plane adds nothing on top). The row
# exists to catch the leader-pile-up cliff (multiples of contrast when
# read serving lands on the write path), so the bar sits above the
# measured observatory cost but far below any pile-up. The absolute
# slack covers sub-150ms p50s riding box scheduling noise.
READ_LANE_PLAN_TOLERANCE = 0.25
READ_LANE_PLAN_SLACK_MS = 50.0


def read_lane_gate(new_artifact: dict) -> dict | None:
    """Gate a read-lane-carrying artifact (reads.lanes present and
    enabled; the r19+ read-storm shape). Self-contained per run: rows
    come from slo.evaluate_read_lanes plus the contrast plan-p50
    comparison against the artifact's OWN leader-only arm — no banked
    baseline needed, so the contract binds from the first round."""
    from nomad_tpu.slo import evaluate_read_lanes

    rows = evaluate_read_lanes(new_artifact)
    if not rows:
        return None
    checks = [{"check": r["objective"], "value": r["observed"],
               "threshold": r["threshold"],
               "regressed": r["met"] is False} for r in rows]
    main_p50 = (new_artifact.get("plan_latency_ms") or {}).get("p50_ms")
    contrast = new_artifact.get("contrast") or {}
    contrast_p50 = (contrast.get("plan_latency_ms") or {}).get("p50_ms")
    if main_p50 is not None and contrast_p50 is not None:
        ceiling = (contrast_p50 * (1.0 + READ_LANE_PLAN_TOLERANCE)
                   + READ_LANE_PLAN_SLACK_MS)
        checks.append({
            "check": "leader_plan_p50_vs_contrast_ms",
            "value": main_p50, "threshold": round(ceiling, 2),
            "regressed": main_p50 > ceiling,
        })
    ok = not any(c["regressed"] for c in checks)
    return {"ok": ok, "checks": checks}


# Runtime-gate tolerance: RSS rides allocator noise and per-row mirror
# bytes only move when buffer/dtype layout changes, so the bar is loose
# — it exists to catch a real footprint regression (a new per-row
# buffer, a float64 slip, a leak past the bounded rings), not GC
# timing. Lock-wait p95 is scheduler-noisy at sim scale for the same
# reason.
RUNTIME_GATE_TOLERANCE = 0.5


def runtime_gate(new_artifact: dict, baseline_artifact: dict | None,
                 tolerance: float = RUNTIME_GATE_TOLERANCE) -> dict | None:
    """Gate a family's runtime economy (the runtime self-observatory's
    artifact section, nomad_tpu/profile_observe.py). Scoped: None when
    the artifact's profile section is absent or disabled. RELATIVE
    newest-vs-previous when the prior bank also carries an enabled
    profile section: peak RSS, the mirror's measured bytes-per-row (the
    1M-node projection's slope), and the worst per-site lock-wait p95
    must not grow more than ``tolerance``. First-round families report
    the observed values without failing."""
    prof = new_artifact.get("profile") or {}
    if not prof.get("enabled"):
        return None

    def rss_peak(p: dict):
        return ((p.get("bytes") or {}).get("rss") or {}).get("peak_bytes")

    def mirror_per_row(p: dict):
        return ((p.get("bytes") or {}).get("mirror")
                or {}).get("per_row_bytes")

    def worst_lock_wait_p95(p: dict):
        rows = (p.get("locks") or {}).get("contention") or []
        vals = [(r.get("wait_ms") or {}).get("p95") for r in rows]
        vals = [v for v in vals if v is not None]
        return max(vals) if vals else None

    base_prof = (baseline_artifact or {}).get("profile") or {}
    if not base_prof.get("enabled"):
        base_prof = {}
    checks, ok = [], True
    for name, fn in (
        ("rss_peak_bytes", rss_peak),
        ("mirror_per_row_bytes", mirror_per_row),
        ("lock_wait_p95_ms", worst_lock_wait_p95),
    ):
        value = fn(prof)
        if value is None:
            continue
        baseline = fn(base_prof) if base_prof else None
        regressed = (baseline is not None and baseline > 0
                     and value > baseline * (1.0 + tolerance))
        checks.append({"check": name, "value": value,
                       "baseline": baseline, "regressed": regressed})
        ok = ok and not regressed
    return {"ok": ok, "tolerance": tolerance, "checks": checks}


# Chaos-gate tolerance: rejoin and expiry-replacement times ride TTL
# jitter, snapshot transfer and re-election noise, so the newest-vs-
# previous bar is deliberately loose — it exists to catch a real
# recovery regression (2x-class), not scheduler jitter. The invariant
# half of the gate (exactly-once, digest equality) is absolute.
CHAOS_GATE_TOLERANCE = 0.5


def chaos_gate(new_artifact: dict, baseline_artifact: dict | None,
               tolerance: float = CHAOS_GATE_TOLERANCE) -> dict | None:
    """Gate a chaos-family artifact (nomad_tpu/simcluster/chaos.py).
    ABSOLUTE (every round, baseline or not): every declared chaos check
    — exactly-once re-placement, no duplicate PlanApplied, leader
    stability, flap-transition books, rejoin digest equality — must
    hold; the runner refuses to even bank a violating artifact, so a
    banked artifact with a failed check means someone hand-edited the
    bank. RELATIVE (newest-vs-previous when the prior bank carries the
    same metric): time-to-rejoin and the expiry->re-placement p95 must
    not grow more than ``tolerance``. None when the artifact has no
    chaos section (not a chaos family)."""
    chaos = new_artifact.get("chaos")
    if not chaos:
        return None
    failed = [c["check"] for c in chaos.get("checks", ())
              if not c.get("ok")]
    checks = [{
        "check": "chaos_invariants",
        "value": len(chaos.get("checks", ())) - len(failed),
        "baseline": None,
        "regressed": bool(failed) or chaos.get("ok") is not True,
        "failed": failed,
    }]
    ok = not checks[0]["regressed"]
    base_chaos = (baseline_artifact or {}).get("chaos") or {}

    def rejoin_ms(c: dict):
        return c.get("time_to_rejoin_ms")

    def expiry_p95(c: dict):
        return (c.get("expiry_replacement_ms") or {}).get("p95_ms")

    for name, fn in (("time_to_rejoin_ms", rejoin_ms),
                     ("expiry_replacement_p95_ms", expiry_p95)):
        value = fn(chaos)
        if value is None:
            continue
        baseline = fn(base_chaos)
        regressed = (baseline is not None and baseline > 0
                     and value > baseline * (1.0 + tolerance))
        checks.append({"check": name, "value": value,
                       "baseline": baseline, "regressed": regressed})
        ok = ok and not regressed
    return {"ok": ok, "tolerance": tolerance, "checks": checks}


def _cell_members(artifact: dict) -> int:
    """Cluster size the artifact's wall-clock numbers were measured on.
    The lanes section carries it explicitly (r19+); pre-lane artifacts
    are single-member cells."""
    lanes = ((artifact.get("reads") or {}).get("lanes") or {})
    try:
        return int(lanes.get("members") or 1)
    except (TypeError, ValueError):
        return 1


def slo_gate_scan(log=log) -> bool:
    """Run the SLO gate over every banked artifact family: newest-vs-
    previous where a prior round exists, absolute-against-objectives for
    first-round families; log one verdict per family. Families whose
    artifacts carry the solver-panel window additionally gate on the
    device-solve economy (solver_gate). A round that changes the
    family's CELL TOPOLOGY (single-member -> replicated cell, as
    read-storm did when the follower read plane landed) re-banks: its
    wall-clock numbers are measured on different machinery than the
    prior round's, so the newest-vs-previous comparison is
    apples-to-oranges and the family is judged absolutely against its
    declared objectives instead — logged, never silent. Returns overall
    pass."""
    ok = True
    for fam, new_path, base_path in _banked_simload_pairs():
        try:
            with open(new_path) as f:
                new = json.load(f)
            objectives = _objectives_for(new)
            if base_path is not None:
                with open(base_path) as f:
                    base_probe = json.load(f)
                if _cell_members(new) != _cell_members(base_probe):
                    log("slo-gate-rebank", family=fam,
                        new_members=_cell_members(new),
                        baseline_members=_cell_members(base_probe),
                        baseline=os.path.basename(base_path))
                    base_path = None
            if base_path is None:
                verdict = slo_gate_absolute(new, objectives)
                solver_verdict = None
                recovery_verdict = recovery_gate(new, None)
                read_verdict = read_gate(new, None)
                runtime_verdict = runtime_gate(new, None)
                chaos_verdict = chaos_gate(new, None)
            else:
                with open(base_path) as f:
                    base = json.load(f)
                verdict = slo_gate(new, base, objectives)
                solver_verdict = solver_gate(new, base)
                recovery_verdict = recovery_gate(new, base)
                read_verdict = read_gate(new, base)
                runtime_verdict = runtime_gate(new, base)
                chaos_verdict = chaos_gate(new, base)
        except (OSError, ValueError, KeyError) as e:
            log("slo-gate-error", family=fam, error=str(e))
            ok = False
            continue
        log("slo-gate", family=fam,
            new=os.path.basename(new_path),
            baseline=(os.path.basename(base_path) if base_path
                      else "<absolute>"),
            ok=verdict["ok"],
            regressed=[c["objective"] for c in verdict["checks"]
                       if c["regressed"]])
        ok = ok and verdict["ok"]
        if solver_verdict is not None:
            log("solver-gate", family=fam, ok=solver_verdict["ok"],
                device_ms_per_placement=solver_verdict[
                    "device_ms_per_placement"],
                baseline=solver_verdict["baseline_ms_per_placement"])
            ok = ok and solver_verdict["ok"]
        if recovery_verdict is not None:
            log("recovery-gate", family=fam, ok=recovery_verdict["ok"],
                regressed=[c["check"] for c in recovery_verdict["checks"]
                           if c["regressed"]])
            ok = ok and recovery_verdict["ok"]
        if read_verdict is not None:
            log("read-gate", family=fam, ok=read_verdict["ok"],
                regressed=[c["check"] for c in read_verdict["checks"]
                           if c["regressed"]])
            ok = ok and read_verdict["ok"]
        lane_verdict = read_lane_gate(new)
        if lane_verdict is not None:
            log("read-lane-gate", family=fam, ok=lane_verdict["ok"],
                regressed=[c["check"] for c in lane_verdict["checks"]
                           if c["regressed"]])
            ok = ok and lane_verdict["ok"]
        if runtime_verdict is not None:
            log("runtime-gate", family=fam, ok=runtime_verdict["ok"],
                regressed=[c["check"] for c in runtime_verdict["checks"]
                           if c["regressed"]])
            ok = ok and runtime_verdict["ok"]
        if chaos_verdict is not None:
            log("chaos-gate", family=fam, ok=chaos_verdict["ok"],
                regressed=[c["check"] for c in chaos_verdict["checks"]
                           if c["regressed"]])
            ok = ok and chaos_verdict["ok"]
    return ok


PIDFILE = os.path.join(REPO, ".bench_watch.pid")


def _default_probe():
    from nomad_tpu.scheduler import device_probe

    # claim_timeout chosen deliberately: we only probe after the port
    # scan saw listeners, so the relay stage will report reachable and
    # the leash extends. Killing a queued claim at 150s is how the 07-31
    # window was missed — a long single claimer beats fast kill/retry
    # here (kills can orphan grants).
    return device_probe.probe_once(
        timeout=150,
        claim_timeout=420,
        env={"NOMAD_TPU_RELAY_PORTS": ",".join(map(str, SCAN_PORTS))},
    )


class CaptureWatcher:
    """The capture state machine, one relay-scan cycle per ``cycle()``.

    Separated from main() so the ordering/once-per-window invariants are
    unit-testable with a stubbed prober and fake capture commands
    (tests/test_bench_watch.py):

    - staged capture order within a window: fast -> proof -> full — bank
      the cheapest TPU number first, the window may die any minute;
    - fast and proof each bank at most once per window, and only on
      SUCCESS (a transient failure retries while the relay is still up);
    - a failed fast stage does not gate the proof (the probe already
      proved a live device);
    - only a successful FULL bench closes the window (cooldown + commit
      marker); and a dark scan resets the per-window stage markers.
    """

    def __init__(self, scan=scan_ports, probe=_default_probe,
                 capture=run_capture, head=head_commit,
                 proof_path=None, clock=time.monotonic, log=log):
        self.scan = scan
        self.probe = probe
        self.capture = capture
        self.head = head
        self.proof_path = (
            proof_path if proof_path is not None
            else os.path.join(REPO, "tools", "pallas_proof.py")
        )
        self.clock = clock
        self.log = log
        self.last_capture_t = 0.0
        self.last_capture_commit = ""
        # Per-window stage markers: reset when the relay goes dark so the
        # next window re-banks a fresh fast number, but within one window
        # a retrying full bench never re-spends time on a banked stage.
        self.window_fast_ok = False
        self.window_proof_done = False

    def cycle(self) -> None:
        open_ports = self.scan()
        self.log("scan", open_ports=open_ports)
        if not open_ports:
            self.window_fast_ok = False
            self.window_proof_done = False
            return
        commit = self.head()
        fresh_window = (
            self.clock() - self.last_capture_t > RECAPTURE_COOLDOWN_S
        )
        if not fresh_window and commit == self.last_capture_commit:
            return
        report = self.probe()
        self.log("probe", ok=report.ok, last_stage=report.last_stage,
                 backend=report.backend, error=report.error)
        if not (report.ok and report.backend != "cpu"):
            return
        # Relay answered with a real device. Staged capture: bank the
        # cheapest TPU number FIRST (headline only, 3 runs, ~1 min), then
        # the pallas proof, then the full suite — a window that dies
        # mid-full-suite has still produced a driver-verifiable number.
        if not self.window_fast_ok:
            fast = self.capture(
                "bench-fast", [sys.executable, "bench.py"],
                FAST_TIMEOUT_S, extra_env=FAST_ENV,
            )
            self.window_fast_ok = fast["ok"]
        # The probe already proved a live device, so the proof is NOT
        # gated on the fast stage's outcome — a fast-stage timeout must
        # not cost the window its only compiled-pallas evidence. Only a
        # SUCCESSFUL proof banks the stage (mirroring window_fast_ok).
        if not self.window_proof_done and os.path.exists(self.proof_path):
            proof_cap = self.capture(
                "pallas_proof", [sys.executable, self.proof_path],
                PROOF_TIMEOUT_S,
            )
            self.window_proof_done = proof_cap["ok"]
        bench = self.capture(
            "bench", [sys.executable, "bench.py"], BENCH_TIMEOUT_S,
        )
        # Only a SUCCESSFUL full bench closes the window; a failed one
        # must keep retrying while the relay is still up — that window is
        # the whole point.
        if bench["ok"]:
            self.last_capture_t = self.clock()
            self.last_capture_commit = commit
            # A closed window is also the moment the banked SIMLOAD story
            # gets re-checked: the SLO gate compares every artifact
            # family's newest round against its previous one, so a
            # capture session that banked a regressed r0N is flagged in
            # the same log that proves the capture.
            slo_gate_scan(log=self.log)


def main() -> None:
    # One-shot CI mode: `python tools/bench_watch.py --slo-gate` runs the
    # SLO regression gate over the banked SIMLOAD families and exits —
    # the path tools/tier1.py and release checks call, no watcher loop.
    if "--slo-gate" in sys.argv[1:]:
        def stdout_log(event: str, **kw) -> None:
            print(json.dumps({"event": event, **kw}))
        sys.exit(0 if slo_gate_scan(log=stdout_log) else 1)
    # Single-instance guard: two overlapping watchers would race the
    # capture file's read-modify-write and double-claim the device window.
    if os.path.exists(PIDFILE):
        try:
            old = int(open(PIDFILE).read().strip())
            os.kill(old, 0)  # raises if the pid is gone
            # Guard against OS pid recycling: only defer to a live pid
            # that is actually a bench_watch process.
            with open(f"/proc/{old}/cmdline", "rb") as f:
                cmdline = f.read().decode(errors="replace")
            if "bench_watch" in cmdline:
                log("duplicate-exit", existing_pid=old, pid=os.getpid())
                return
        except (ValueError, OSError):
            pass
    with open(PIDFILE, "w") as f:
        f.write(str(os.getpid()))
    log("start", pid=os.getpid(), ports=f"{SCAN_PORTS[0]}-{SCAN_PORTS[-1]}")
    watcher = CaptureWatcher()
    while True:
        try:
            watcher.cycle()
        except Exception as e:  # never let one bad cycle kill the watcher
            log("error", error=f"{type(e).__name__}: {e}")
        time.sleep(SCAN_INTERVAL_S)


if __name__ == "__main__":
    main()
